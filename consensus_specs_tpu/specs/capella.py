"""Capella spec: withdrawals, BLS-to-execution changes, historical summaries.

From-scratch implementation of /root/reference/specs/capella/beacon-chain.md
as a BellatrixSpec subclass.
"""
from ..ssz import (
    uint64, uint256, Bitvector, Vector, List, Container, ByteList,
    ByteVector, Bytes4, Bytes20, Bytes32, Bytes48, Bytes96,
    hash_tree_root,
)
from .bellatrix import BellatrixSpec


class CapellaSpec(BellatrixSpec):
    fork = "capella"

    def _build_constants(self) -> None:
        super()._build_constants()
        self.DOMAIN_BLS_TO_EXECUTION_CHANGE = Bytes4("0x0A000000")
        self.WithdrawalIndex = uint64

    def _build_types(self) -> None:
        super()._build_types()
        p = self

        class Withdrawal(Container):
            index: uint64
            validator_index: uint64
            address: Bytes20
            amount: uint64

        class BLSToExecutionChange(Container):
            validator_index: uint64
            from_bls_pubkey: Bytes48
            to_execution_address: Bytes20

        class SignedBLSToExecutionChange(Container):
            message: BLSToExecutionChange
            signature: Bytes96

        class HistoricalSummary(Container):
            block_summary_root: Bytes32
            state_summary_root: Bytes32

        class ExecutionPayload(Container):
            parent_hash: Bytes32
            fee_recipient: Bytes20
            state_root: Bytes32
            receipts_root: Bytes32
            logs_bloom: ByteVector[p.BYTES_PER_LOGS_BLOOM]
            prev_randao: Bytes32
            block_number: uint64
            gas_limit: uint64
            gas_used: uint64
            timestamp: uint64
            extra_data: ByteList[p.MAX_EXTRA_DATA_BYTES]
            base_fee_per_gas: uint256
            block_hash: Bytes32
            transactions: List[p.Transaction, p.MAX_TRANSACTIONS_PER_PAYLOAD]
            withdrawals: List[Withdrawal, p.MAX_WITHDRAWALS_PER_PAYLOAD]

        class ExecutionPayloadHeader(Container):
            parent_hash: Bytes32
            fee_recipient: Bytes20
            state_root: Bytes32
            receipts_root: Bytes32
            logs_bloom: ByteVector[p.BYTES_PER_LOGS_BLOOM]
            prev_randao: Bytes32
            block_number: uint64
            gas_limit: uint64
            gas_used: uint64
            timestamp: uint64
            extra_data: ByteList[p.MAX_EXTRA_DATA_BYTES]
            base_fee_per_gas: uint256
            block_hash: Bytes32
            transactions_root: Bytes32
            withdrawals_root: Bytes32

        class BeaconBlockBody(Container):
            randao_reveal: Bytes96
            eth1_data: p.Eth1Data
            graffiti: Bytes32
            proposer_slashings: List[p.ProposerSlashing, p.MAX_PROPOSER_SLASHINGS]
            attester_slashings: List[p.AttesterSlashing, p.MAX_ATTESTER_SLASHINGS]
            attestations: List[p.Attestation, p.MAX_ATTESTATIONS]
            deposits: List[p.Deposit, p.MAX_DEPOSITS]
            voluntary_exits: List[p.SignedVoluntaryExit, p.MAX_VOLUNTARY_EXITS]
            sync_aggregate: p.SyncAggregate
            execution_payload: ExecutionPayload
            bls_to_execution_changes: List[SignedBLSToExecutionChange, p.MAX_BLS_TO_EXECUTION_CHANGES]

        class BeaconBlock(Container):
            slot: uint64
            proposer_index: uint64
            parent_root: Bytes32
            state_root: Bytes32
            body: BeaconBlockBody

        class SignedBeaconBlock(Container):
            message: BeaconBlock
            signature: Bytes96

        class BeaconState(Container):
            genesis_time: uint64
            genesis_validators_root: Bytes32
            slot: uint64
            fork: p.Fork
            latest_block_header: p.BeaconBlockHeader
            block_roots: Vector[Bytes32, p.SLOTS_PER_HISTORICAL_ROOT]
            state_roots: Vector[Bytes32, p.SLOTS_PER_HISTORICAL_ROOT]
            historical_roots: List[Bytes32, p.HISTORICAL_ROOTS_LIMIT]
            eth1_data: p.Eth1Data
            eth1_data_votes: List[p.Eth1Data, p.EPOCHS_PER_ETH1_VOTING_PERIOD * p.SLOTS_PER_EPOCH]
            eth1_deposit_index: uint64
            validators: List[p.Validator, p.VALIDATOR_REGISTRY_LIMIT]
            balances: List[uint64, p.VALIDATOR_REGISTRY_LIMIT]
            randao_mixes: Vector[Bytes32, p.EPOCHS_PER_HISTORICAL_VECTOR]
            slashings: Vector[uint64, p.EPOCHS_PER_SLASHINGS_VECTOR]
            previous_epoch_participation: List[p.ParticipationFlags, p.VALIDATOR_REGISTRY_LIMIT]
            current_epoch_participation: List[p.ParticipationFlags, p.VALIDATOR_REGISTRY_LIMIT]
            justification_bits: Bitvector[p.JUSTIFICATION_BITS_LENGTH]
            previous_justified_checkpoint: p.Checkpoint
            current_justified_checkpoint: p.Checkpoint
            finalized_checkpoint: p.Checkpoint
            inactivity_scores: List[uint64, p.VALIDATOR_REGISTRY_LIMIT]
            current_sync_committee: p.SyncCommittee
            next_sync_committee: p.SyncCommittee
            latest_execution_payload_header: ExecutionPayloadHeader
            next_withdrawal_index: uint64
            next_withdrawal_validator_index: uint64
            historical_summaries: List[HistoricalSummary, p.HISTORICAL_ROOTS_LIMIT]

        for name, cls in list(locals().items()):
            if isinstance(cls, type) and issubclass(cls, Container):
                setattr(self, name, cls)

    # ------------------------------------------------------------------
    # withdrawal predicates & sweep
    # ------------------------------------------------------------------
    def has_eth1_withdrawal_credential(self, validator) -> bool:
        return bytes(validator.withdrawal_credentials)[:1] \
            == self.ETH1_ADDRESS_WITHDRAWAL_PREFIX

    def is_fully_withdrawable_validator(self, validator, balance,
                                        epoch) -> bool:
        return (self.has_eth1_withdrawal_credential(validator)
                and validator.withdrawable_epoch <= epoch
                and balance > 0)

    def is_partially_withdrawable_validator(self, validator,
                                            balance) -> bool:
        has_max_effective_balance = (
            validator.effective_balance == self.MAX_EFFECTIVE_BALANCE)
        has_excess_balance = balance > self.MAX_EFFECTIVE_BALANCE
        return (self.has_eth1_withdrawal_credential(validator)
                and has_max_effective_balance and has_excess_balance)

    def get_expected_withdrawals(self, state):
        epoch = self.get_current_epoch(state)
        withdrawal_index = int(state.next_withdrawal_index)
        validator_index = int(state.next_withdrawal_validator_index)
        withdrawals = []
        bound = min(len(state.validators),
                    self.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)
        for _ in range(bound):
            validator = state.validators[validator_index]
            balance = state.balances[validator_index]
            address = Bytes20(
                bytes(validator.withdrawal_credentials)[12:])
            if self.is_fully_withdrawable_validator(validator, balance,
                                                    epoch):
                withdrawals.append(self.Withdrawal(
                    index=withdrawal_index,
                    validator_index=validator_index,
                    address=address,
                    amount=balance))
                withdrawal_index += 1
            elif self.is_partially_withdrawable_validator(validator,
                                                          balance):
                withdrawals.append(self.Withdrawal(
                    index=withdrawal_index,
                    validator_index=validator_index,
                    address=address,
                    amount=uint64(balance - self.MAX_EFFECTIVE_BALANCE)))
                withdrawal_index += 1
            if len(withdrawals) == self.MAX_WITHDRAWALS_PER_PAYLOAD:
                break
            validator_index = (validator_index + 1) % len(state.validators)
        return withdrawals

    def process_withdrawals(self, state, payload) -> None:
        expected_withdrawals = self.get_expected_withdrawals(state)
        assert len(payload.withdrawals) == len(expected_withdrawals)
        for expected, actual in zip(expected_withdrawals,
                                    payload.withdrawals):
            assert actual == expected
        for withdrawal in expected_withdrawals:
            self.decrease_balance(state, withdrawal.validator_index,
                                  withdrawal.amount)

        # advance the sweep cursors
        if len(expected_withdrawals) > 0:
            state.next_withdrawal_index = uint64(
                expected_withdrawals[-1].index + 1)
        if len(expected_withdrawals) == self.MAX_WITHDRAWALS_PER_PAYLOAD:
            # full payload: resume right after the last withdrawn validator
            next_validator_index = uint64(
                (expected_withdrawals[-1].validator_index + 1)
                % len(state.validators))
        else:
            # swept the bound without filling the payload
            next_index = (int(state.next_withdrawal_validator_index)
                          + self.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)
            next_validator_index = uint64(
                next_index % len(state.validators))
        state.next_withdrawal_validator_index = next_validator_index

    # ------------------------------------------------------------------
    # block processing
    # ------------------------------------------------------------------
    def process_block(self, state, block) -> None:
        self.process_block_header(state, block)
        # [Modified in Capella] no is_execution_enabled gate: withdrawals
        # and payload processing are unconditional post-capella
        self.process_withdrawals(state, block.body.execution_payload)
        self.process_execution_payload(
            state, block.body, self.EXECUTION_ENGINE)
        self.process_randao(state, block.body)
        self.process_eth1_data(state, block.body)
        self.process_operations(state, block.body)
        self.process_sync_aggregate(state, block.body.sync_aggregate)

    def process_execution_payload(self, state, body,
                                  execution_engine) -> None:
        payload = body.execution_payload
        # [Modified in Capella] parent-hash check is unconditional
        assert payload.parent_hash == \
            state.latest_execution_payload_header.block_hash
        assert payload.prev_randao == self.get_randao_mix(
            state, self.get_current_epoch(state))
        assert payload.timestamp == self.compute_timestamp_at_slot(
            state, state.slot)
        assert execution_engine.verify_and_notify_new_payload(payload)
        state.latest_execution_payload_header = \
            self.build_execution_payload_header(payload)

    def process_operations(self, state, body) -> None:
        super().process_operations(state, body)
        for operation in body.bls_to_execution_changes:
            self.process_bls_to_execution_change(state, operation)

    def process_bls_to_execution_change(self, state,
                                        signed_address_change) -> None:
        address_change = signed_address_change.message
        assert address_change.validator_index < len(state.validators)
        validator = state.validators[address_change.validator_index]
        assert bytes(validator.withdrawal_credentials)[:1] \
            == self.BLS_WITHDRAWAL_PREFIX
        assert bytes(validator.withdrawal_credentials)[1:] \
            == bytes(self.hash(address_change.from_bls_pubkey))[1:]
        # signed against the genesis domain so changes survive forks
        domain = self.compute_domain(
            self.DOMAIN_BLS_TO_EXECUTION_CHANGE,
            genesis_validators_root=state.genesis_validators_root)
        signing_root = self.compute_signing_root(address_change, domain)
        assert self.bls_verify(address_change.from_bls_pubkey, signing_root,
                               signed_address_change.signature)
        validator.withdrawal_credentials = (
            self.ETH1_ADDRESS_WITHDRAWAL_PREFIX + b"\x00" * 11
            + bytes(address_change.to_execution_address))

    def build_execution_payload_header(self, payload):
        header = super().build_execution_payload_header(payload)
        header.withdrawals_root = hash_tree_root(payload.withdrawals)
        return header

    # ------------------------------------------------------------------
    # epoch processing: historical summaries replace historical roots
    # ------------------------------------------------------------------
    def process_epoch(self, state) -> None:
        from . import epoch_fast
        if epoch_fast.fused_epoch(self, state):
            self.process_eth1_data_reset(state)
            self.process_slashings_reset(state)
            self.process_randao_mixes_reset(state)
            self.process_historical_summaries_update(state)
            self.process_participation_flag_updates(state)
            self.process_sync_committee_updates(state)
            return
        self.process_justification_and_finalization(state)
        self.process_inactivity_updates(state)
        self.process_rewards_and_penalties(state)
        self.process_registry_updates(state)
        self.process_slashings(state)
        self.process_eth1_data_reset(state)
        self.process_effective_balance_updates(state)
        self.process_slashings_reset(state)
        self.process_randao_mixes_reset(state)
        self.process_historical_summaries_update(state)
        self.process_participation_flag_updates(state)
        self.process_sync_committee_updates(state)

    def process_historical_summaries_update(self, state) -> None:
        next_epoch = uint64(self.get_current_epoch(state) + 1)
        if next_epoch % (self.SLOTS_PER_HISTORICAL_ROOT
                         // self.SLOTS_PER_EPOCH) == 0:
            historical_summary = self.HistoricalSummary(
                block_summary_root=hash_tree_root(state.block_roots),
                state_summary_root=hash_tree_root(state.state_roots))
            state.historical_summaries.append(historical_summary)

    # ------------------------------------------------------------------
    # fork upgrade (capella/fork.md)
    # ------------------------------------------------------------------
    def genesis_fork_versions(self):
        return (Bytes4(self.config.BELLATRIX_FORK_VERSION),
                Bytes4(self.config.CAPELLA_FORK_VERSION))

    def upgrade_from(self, pre):
        epoch = self.get_current_epoch(pre)
        pre_header = pre.latest_execution_payload_header
        post_header = self.ExecutionPayloadHeader(
            parent_hash=pre_header.parent_hash,
            fee_recipient=pre_header.fee_recipient,
            state_root=pre_header.state_root,
            receipts_root=pre_header.receipts_root,
            logs_bloom=pre_header.logs_bloom,
            prev_randao=pre_header.prev_randao,
            block_number=pre_header.block_number,
            gas_limit=pre_header.gas_limit,
            gas_used=pre_header.gas_used,
            timestamp=pre_header.timestamp,
            extra_data=pre_header.extra_data,
            base_fee_per_gas=pre_header.base_fee_per_gas,
            block_hash=pre_header.block_hash,
            transactions_root=pre_header.transactions_root,
            # withdrawals_root stays zeroed
        )
        post = self.BeaconState(
            genesis_time=pre.genesis_time,
            genesis_validators_root=pre.genesis_validators_root,
            slot=pre.slot,
            fork=self.Fork(
                previous_version=pre.fork.current_version,
                current_version=Bytes4(self.config.CAPELLA_FORK_VERSION),
                epoch=epoch),
            latest_block_header=pre.latest_block_header,
            block_roots=list(pre.block_roots),
            state_roots=list(pre.state_roots),
            historical_roots=list(pre.historical_roots),
            eth1_data=pre.eth1_data,
            eth1_data_votes=list(pre.eth1_data_votes),
            eth1_deposit_index=pre.eth1_deposit_index,
            validators=list(pre.validators),
            balances=list(pre.balances),
            randao_mixes=list(pre.randao_mixes),
            slashings=list(pre.slashings),
            previous_epoch_participation=list(
                pre.previous_epoch_participation),
            current_epoch_participation=list(
                pre.current_epoch_participation),
            justification_bits=list(pre.justification_bits),
            previous_justified_checkpoint=pre.previous_justified_checkpoint,
            current_justified_checkpoint=pre.current_justified_checkpoint,
            finalized_checkpoint=pre.finalized_checkpoint,
            inactivity_scores=list(pre.inactivity_scores),
            current_sync_committee=pre.current_sync_committee,
            next_sync_committee=pre.next_sync_committee,
            latest_execution_payload_header=post_header,
            next_withdrawal_index=0,
            next_withdrawal_validator_index=0,
            # historical_summaries starts empty
        )
        return post
