"""EIP-7732 (ePBS) fork choice: (block, slot, payload-present) voting.

From-scratch implementation of
/root/reference/specs/_features/eip7732/fork-choice.md: the store tracks
empty/full intermediate states per consensus block plus PTC votes;
LMD-GHOST runs over ChildNode triples (root, slot, is_payload_present)
with three boosts (proposer, builder-reveal, builder-withhold); new
handlers on_execution_payload and on_payload_attestation_message.
Mixed into Eip7732Spec ahead of the phase0 fork choice in the MRO.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

from ..ssz import Bytes32, hash_tree_root, uint64
from ..txn import transactional
from .fork_choice import Store as BaseStore


@dataclass
class LatestMessageBySlot:
    """EIP-7732 LatestMessage tracks the SLOT (not the epoch)."""
    slot: int
    root: bytes


@dataclass
class ChildNode:
    """(block, slot, bool) LMD voting unit (fork-choice.md:55-63)."""
    root: bytes
    slot: int
    is_payload_present: bool


@dataclass
class Eip7732Store(BaseStore):
    # [New in EIP-7732]
    payload_withhold_boost_root: bytes = Bytes32()
    payload_withhold_boost_full: bool = True
    payload_reveal_boost_root: bytes = Bytes32()
    execution_payload_states: Dict[bytes, object] = field(
        default_factory=dict)
    ptc_vote: Dict[bytes, list] = field(default_factory=dict)


class Eip7732ForkChoice:
    INTERVALS_PER_SLOT = 4              # [modified in EIP-7732]
    PROPOSER_SCORE_BOOST_PCT = 20       # [modified in EIP-7732]
    PAYLOAD_WITHHOLD_BOOST_PCT = 40
    PAYLOAD_REVEAL_BOOST_PCT = 40

    Store = Eip7732Store
    LatestMessage = LatestMessageBySlot
    ChildNode = ChildNode

    @property
    def PAYLOAD_TIMELY_THRESHOLD(self) -> int:
        return int(self.PTC_SIZE) // 2

    # ------------------------------------------------------------------
    # store construction
    # ------------------------------------------------------------------
    def get_forkchoice_store(self, anchor_state, anchor_block):
        assert anchor_block.state_root == hash_tree_root(anchor_state)
        anchor_root = hash_tree_root(anchor_block)
        anchor_epoch = self.get_current_epoch(anchor_state)
        justified = self.Checkpoint(epoch=anchor_epoch, root=anchor_root)
        finalized = self.Checkpoint(epoch=anchor_epoch, root=anchor_root)
        return Eip7732Store(
            time=int(anchor_state.genesis_time
                     + self.config.SECONDS_PER_SLOT * anchor_state.slot),
            genesis_time=int(anchor_state.genesis_time),
            justified_checkpoint=justified,
            finalized_checkpoint=finalized,
            unrealized_justified_checkpoint=justified,
            unrealized_finalized_checkpoint=finalized,
            proposer_boost_root=Bytes32(),
            blocks={anchor_root: anchor_block.copy()},
            block_states={anchor_root: anchor_state.copy()},
            checkpoint_states={justified: anchor_state.copy()},
            unrealized_justifications={anchor_root: justified},
            execution_payload_states={anchor_root: anchor_state.copy()},
            ptc_vote={anchor_root: [self.PAYLOAD_ABSENT]
                      * int(self.PTC_SIZE)},
        )

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------
    def update_latest_messages(self, store, attesting_indices,
                               attestation) -> None:
        # keyed by SLOT (fork-choice.md:77-88)
        slot = attestation.data.slot
        root = attestation.data.beacon_block_root
        for i in attesting_indices:
            if i in store.equivocating_indices:
                continue
            if i not in store.latest_messages or \
                    slot > store.latest_messages[i].slot:
                store.latest_messages[i] = LatestMessageBySlot(
                    slot=int(slot), root=bytes(root))

    def notify_ptc_messages(self, store, state,
                            payload_attestations) -> None:
        """Apply in-block payload attestations (no signature checks —
        the block carried them)."""
        if state.slot == 0:
            return
        for payload_attestation in payload_attestations:
            indexed = self.get_indexed_payload_attestation(
                state, uint64(int(state.slot) - 1), payload_attestation)
            for idx in indexed.attesting_indices:
                self.on_payload_attestation_message(
                    store,
                    self.PayloadAttestationMessage(
                        validator_index=idx,
                        data=payload_attestation.data,
                        signature=b"\x00" * 96),
                    is_from_block=True)

    def is_payload_present(self, store, beacon_block_root) -> bool:
        assert beacon_block_root in store.ptc_vote
        return store.ptc_vote[beacon_block_root].count(
            self.PAYLOAD_PRESENT) > self.PAYLOAD_TIMELY_THRESHOLD

    def is_parent_node_full(self, store, block) -> bool:
        parent = store.blocks[block.parent_root]
        parent_block_hash = \
            block.body.signed_execution_payload_header.message.parent_block_hash
        message_block_hash = \
            parent.body.signed_execution_payload_header.message.block_hash
        return bytes(parent_block_hash) == bytes(message_block_hash)

    def get_ancestor(self, store, root, slot) -> ChildNode:
        """Ancestor WITH payload status (fork-choice.md:195-213)."""
        block = store.blocks[root]
        if block.slot <= slot:
            return ChildNode(
                root=bytes(root), slot=int(slot),
                is_payload_present=self.is_payload_present(store, root))
        parent = store.blocks[block.parent_root]
        if parent.slot > slot:
            return self.get_ancestor(store, block.parent_root, slot)
        return ChildNode(
            root=bytes(block.parent_root), slot=int(parent.slot),
            is_payload_present=self.is_parent_node_full(store, block))

    def get_checkpoint_block(self, store, root, epoch) -> bytes:
        epoch_first_slot = self.compute_start_slot_at_epoch(epoch)
        return self.get_ancestor(store, root, epoch_first_slot).root

    def is_supporting_vote(self, store, node: ChildNode, message) -> bool:
        if bytes(node.root) == bytes(message.root):
            return node.slot <= message.slot
        message_block = store.blocks[message.root]
        if node.slot >= message_block.slot:
            return False
        ancestor = self.get_ancestor(store, message.root, node.slot)
        return (bytes(node.root) == bytes(ancestor.root)
                and node.is_payload_present == ancestor.is_payload_present)

    # ------------------------------------------------------------------
    # boosts
    # ------------------------------------------------------------------
    def _committee_boost(self, state, percent) -> int:
        committee_weight = self.get_total_active_balance(state) \
            // self.SLOTS_PER_EPOCH
        return uint64((committee_weight * percent) // 100)

    def compute_proposer_boost(self, store, state, node: ChildNode) -> int:
        if store.proposer_boost_root == Bytes32():
            return uint64(0)
        ancestor = self.get_ancestor(store, store.proposer_boost_root,
                                     node.slot)
        if bytes(ancestor.root) != bytes(node.root):
            return uint64(0)
        proposer_boost_slot = \
            store.blocks[store.proposer_boost_root].slot
        if node.slot > proposer_boost_slot:
            return uint64(0)   # not applied after skipped slots
        if (node.slot < proposer_boost_slot
                and ancestor.is_payload_present
                != node.is_payload_present):
            return uint64(0)
        return self._committee_boost(state,
                                     self.PROPOSER_SCORE_BOOST_PCT)

    def compute_withhold_boost(self, store, state,
                               node: ChildNode) -> int:
        if store.payload_withhold_boost_root == Bytes32():
            return uint64(0)
        ancestor = self.get_ancestor(
            store, store.payload_withhold_boost_root, node.slot)
        if bytes(ancestor.root) != bytes(node.root):
            return uint64(0)
        if node.slot >= \
                store.blocks[store.payload_withhold_boost_root].slot:
            ancestor.is_payload_present = store.payload_withhold_boost_full
        if ancestor.is_payload_present != node.is_payload_present:
            return uint64(0)
        return self._committee_boost(state,
                                     self.PAYLOAD_WITHHOLD_BOOST_PCT)

    def compute_reveal_boost(self, store, state, node: ChildNode) -> int:
        if store.payload_reveal_boost_root == Bytes32():
            return uint64(0)
        ancestor = self.get_ancestor(
            store, store.payload_reveal_boost_root, node.slot)
        if bytes(ancestor.root) != bytes(node.root):
            return uint64(0)
        if node.slot >= store.blocks[store.payload_reveal_boost_root].slot:
            ancestor.is_payload_present = True
        if ancestor.is_payload_present != node.is_payload_present:
            return uint64(0)
        return self._committee_boost(state,
                                     self.PAYLOAD_REVEAL_BOOST_PCT)

    # ------------------------------------------------------------------
    # weights & head
    # ------------------------------------------------------------------
    def get_weight(self, store, node: ChildNode) -> int:
        state = store.checkpoint_states[store.justified_checkpoint]
        unslashed_and_active = [
            i for i in self.get_active_validator_indices(
                state, self.get_current_epoch(state))
            if not state.validators[i].slashed]
        attestation_score = sum(
            int(state.validators[i].effective_balance)
            for i in unslashed_and_active
            if (i in store.latest_messages
                and i not in store.equivocating_indices
                and self.is_supporting_vote(
                    store, node, store.latest_messages[i])))
        return uint64(attestation_score
                      + self.compute_proposer_boost(store, state, node)
                      + self.compute_reveal_boost(store, state, node)
                      + self.compute_withhold_boost(store, state, node))

    def _root_node(self, store, root) -> ChildNode:
        """Adapt a bare block root to its ChildNode (the block at its
        own slot with its PTC-voted payload status) — for the inherited
        root-based proposer-reorg helpers (is_head_weak /
        is_parent_strong), which predate (block, slot, bool) voting."""
        block = store.blocks[root]
        return ChildNode(
            root=bytes(root), slot=int(block.slot),
            is_payload_present=self.is_payload_present(store, root))

    def is_head_weak(self, store, head_root) -> bool:
        justified_state = store.checkpoint_states[
            store.justified_checkpoint]
        reorg_threshold = self.calculate_committee_fraction(
            justified_state, self.config.REORG_HEAD_WEIGHT_THRESHOLD)
        return self.get_weight(
            store, self._root_node(store, head_root)) < reorg_threshold

    def is_parent_strong(self, store, parent_root) -> bool:
        justified_state = store.checkpoint_states[
            store.justified_checkpoint]
        parent_threshold = self.calculate_committee_fraction(
            justified_state, self.config.REORG_PARENT_WEIGHT_THRESHOLD)
        return self.get_weight(
            store, self._root_node(store, parent_root)) > parent_threshold

    def get_head(self, store) -> ChildNode:
        blocks = self.get_filtered_block_tree(store)
        justified_root = bytes(store.justified_checkpoint.root)
        justified_block = store.blocks[justified_root]
        best_child = ChildNode(
            root=justified_root, slot=int(justified_block.slot),
            is_payload_present=self.is_payload_present(store,
                                                       justified_root))
        while True:
            children = [
                ChildNode(root=bytes(root), slot=int(block.slot),
                          is_payload_present=present)
                for (root, block) in blocks.items()
                if bytes(block.parent_root) == best_child.root
                and block.slot > best_child.slot
                and (best_child.root == justified_root
                     or self.is_parent_node_full(store, block)
                     == best_child.is_payload_present)
                for present in (True, False)
                if root in store.execution_payload_states or not present
            ]
            if len(children) == 0:
                return best_child
            highest_child_slot = max(c.slot for c in children)
            children.append(ChildNode(
                root=best_child.root, slot=best_child.slot + 1,
                is_payload_present=best_child.is_payload_present))
            new_best_child = max(children, key=lambda child: (
                int(self.get_weight(store, child)),
                int(blocks[child.root].slot),
                self.is_payload_present(store, child.root),
                child.is_payload_present,
                child.root))
            if new_best_child.root == best_child.root and \
                    new_best_child.slot >= highest_child_slot:
                return new_best_child
            best_child = new_best_child

    # ------------------------------------------------------------------
    # handlers
    # ------------------------------------------------------------------
    @transactional
    def on_block(self, store, signed_block) -> None:
        block = signed_block.message
        assert block.parent_root in store.block_states

        parent_block = store.blocks[block.parent_root]
        header = block.body.signed_execution_payload_header.message
        parent_header = \
            parent_block.body.signed_execution_payload_header.message
        if self.is_parent_node_full(store, block):
            assert block.parent_root in store.execution_payload_states
            state = store.execution_payload_states[
                block.parent_root].copy()
        else:
            assert bytes(header.parent_block_hash) == \
                bytes(parent_header.parent_block_hash)
            state = store.block_states[block.parent_root].copy()

        current_slot = self.get_current_slot(store)
        assert current_slot >= block.slot
        finalized_slot = self.compute_start_slot_at_epoch(
            store.finalized_checkpoint.epoch)
        assert block.slot > finalized_slot
        finalized_checkpoint_block = self.get_checkpoint_block(
            store, block.parent_root, store.finalized_checkpoint.epoch)
        assert bytes(store.finalized_checkpoint.root) == \
            bytes(finalized_checkpoint_block)

        block_root = hash_tree_root(block)
        self.state_transition(state, signed_block, True)

        # Mutation phase, new-block insertion LAST (same torn-store
        # defense as the phase0 on_block): the in-block PTC
        # notifications and the boost/checkpoint updates only touch
        # ancestor entries (a payload attestation targets the previous
        # slot's block), so a crash between any two mutations never
        # leaves a half-visible block.
        self.notify_ptc_messages(store, state,
                                 block.body.payload_attestations)

        time_into_slot = (store.time - store.genesis_time) \
            % self.config.SECONDS_PER_SLOT
        is_before_attesting_interval = time_into_slot < \
            self.config.SECONDS_PER_SLOT // self.INTERVALS_PER_SLOT
        is_timely = self.get_current_slot(store) == block.slot \
            and is_before_attesting_interval
        store.block_timeliness[block_root] = is_timely
        if is_timely and store.proposer_boost_root == Bytes32():
            store.proposer_boost_root = block_root

        self.update_checkpoints(store, state.current_justified_checkpoint,
                                state.finalized_checkpoint)
        self._apply_pulled_up_tip(store, block_root, block, state)
        store.blocks[block_root] = block
        store.block_states[block_root] = state
        store.ptc_vote[block_root] = \
            [self.PAYLOAD_ABSENT] * int(self.PTC_SIZE)

    @transactional
    def on_execution_payload(self, store, signed_envelope) -> None:
        """New handler: a revealed SignedExecutionPayloadEnvelope
        produces the block's FULL state (fork-choice.md:450-476)."""
        envelope = signed_envelope.message
        assert envelope.beacon_block_root in store.block_states
        assert self.is_data_available(envelope.beacon_block_root,
                                      envelope.blob_kzg_commitments)
        state = store.block_states[envelope.beacon_block_root].copy()
        self.process_execution_payload(state, signed_envelope,
                                       self.EXECUTION_ENGINE)
        store.execution_payload_states[envelope.beacon_block_root] = state

    def seconds_into_slot(self, store) -> int:
        return (store.time - store.genesis_time) \
            % self.config.SECONDS_PER_SLOT

    def on_tick_per_slot(self, store, time) -> None:
        previous_slot = self.get_current_slot(store)
        store.time = int(time)
        current_slot = self.get_current_slot(store)
        if current_slot > previous_slot:
            store.proposer_boost_root = Bytes32()
        elif self.seconds_into_slot(store) >= \
                self.config.SECONDS_PER_SLOT // self.INTERVALS_PER_SLOT:
            # attestation time: reset the payload boosts
            store.payload_withhold_boost_root = Bytes32()
            store.payload_withhold_boost_full = False
            store.payload_reveal_boost_root = Bytes32()
        if current_slot > previous_slot and \
                self.compute_slots_since_epoch_start(current_slot) == 0:
            self.update_checkpoints(
                store, store.unrealized_justified_checkpoint,
                store.unrealized_finalized_checkpoint)

    def gossip_payload_attestation_check(self, store, ptc_message):
        """(pubkeys, signing_root, signature) that
        `on_payload_attestation_message` will verify for a non-block
        message — the read-only collection hook the gossip micro-batcher
        uses (gossip/collect.py).  Mirrors
        is_valid_indexed_payload_attestation for a single-validator
        indexed attestation; the handler's own call flows through the
        bls_fast_aggregate_verify seam, so a batch verdict collected
        from this tuple substitutes at the exact inline call site."""
        data = ptc_message.data
        state = store.block_states[data.beacon_block_root]
        pubkey = state.validators[ptc_message.validator_index].pubkey
        domain = self.get_domain(state, self.DOMAIN_PTC_ATTESTER, None)
        signing_root = self.compute_signing_root(data, domain)
        return (pubkey,), signing_root, ptc_message.signature

    @transactional
    def on_payload_attestation_message(self, store, ptc_message,
                                       is_from_block: bool = False) -> None:
        data = ptc_message.data
        state = store.block_states[data.beacon_block_root]
        ptc = self.get_ptc(state, data.slot)
        if data.slot != state.slot:
            return
        assert ptc_message.validator_index in ptc

        if not is_from_block:
            assert data.slot == self.get_current_slot(store)
            assert self.is_valid_indexed_payload_attestation(
                state,
                self.IndexedPayloadAttestation(
                    attesting_indices=[ptc_message.validator_index],
                    data=data,
                    signature=ptc_message.signature))

        ptc_index = list(ptc).index(ptc_message.validator_index)
        ptc_vote = store.ptc_vote[data.beacon_block_root]
        ptc_vote[ptc_index] = data.payload_status

        if is_from_block and int(data.slot) + 1 != \
                int(self.get_current_slot(store)):
            return
        time_into_slot = (store.time - store.genesis_time) \
            % self.config.SECONDS_PER_SLOT
        if is_from_block and time_into_slot >= \
                self.config.SECONDS_PER_SLOT // self.INTERVALS_PER_SLOT:
            return

        if ptc_vote.count(self.PAYLOAD_PRESENT) > \
                self.PAYLOAD_TIMELY_THRESHOLD:
            store.payload_reveal_boost_root = bytes(
                data.beacon_block_root)
        if ptc_vote.count(self.PAYLOAD_WITHHELD) > \
                self.PAYLOAD_TIMELY_THRESHOLD:
            block = store.blocks[data.beacon_block_root]
            store.payload_withhold_boost_root = bytes(block.parent_root)
            store.payload_withhold_boost_full = \
                self.is_parent_node_full(store, block)
