"""Bellatrix spec: execution payloads and the merge transition.

From-scratch implementation of /root/reference/specs/bellatrix/
{beacon-chain.md,fork.md,fork-choice.md,validator.md} as an AltairSpec
subclass.  The ExecutionEngine is the spec's process boundary to the
execution layer; the NoopExecutionEngine stub answers True to everything
(the reference's pysetup/spec_builders/bellatrix.py:39-64 pattern).
"""
from dataclasses import dataclass, field

from ..ssz import (
    uint64, uint256, Bitvector, Vector, List, Container, ByteList,
    ByteVector, Bytes4, Bytes20, Bytes32, Bytes48, Bytes96,
    hash_tree_root,
)
from .altair import AltairSpec
from .optimistic_sync import OptimisticSync


@dataclass
class PowBlockData:
    block_hash: bytes = b"\x00" * 32
    parent_hash: bytes = b"\x00" * 32
    total_difficulty: int = 0


class NoopExecutionEngine:
    """Stub engine: all verifications pass, no payloads are built."""

    def notify_new_payload(self, execution_payload,
                           parent_beacon_block_root=None) -> bool:
        return True

    def notify_forkchoice_updated(self, head_block_hash,
                                  safe_block_hash,
                                  finalized_block_hash,
                                  payload_attributes) -> object:
        return None

    def get_payload(self, payload_id):
        raise NotImplementedError("no payload building in the noop engine")

    def is_valid_block_hash(self, execution_payload,
                            parent_beacon_block_root=None) -> bool:
        return True

    def is_valid_versioned_hashes(self, new_payload_request) -> bool:
        return True

    def verify_and_notify_new_payload(self, new_payload_request) -> bool:
        return True


class BellatrixSpec(OptimisticSync, AltairSpec):
    fork = "bellatrix"

    def _build_constants(self) -> None:
        super()._build_constants()
        self.Transaction = ByteList[self.MAX_BYTES_PER_TRANSACTION]
        self.ExecutionAddress = Bytes20
        self.EXECUTION_ENGINE = NoopExecutionEngine()
        # stubbed pow-chain view for merge-transition tests (per instance)
        self.pow_chain = {}

    def _build_types(self) -> None:
        super()._build_types()
        p = self

        class ExecutionPayload(Container):
            parent_hash: Bytes32
            fee_recipient: Bytes20
            state_root: Bytes32
            receipts_root: Bytes32
            logs_bloom: ByteVector[p.BYTES_PER_LOGS_BLOOM]
            prev_randao: Bytes32
            block_number: uint64
            gas_limit: uint64
            gas_used: uint64
            timestamp: uint64
            extra_data: ByteList[p.MAX_EXTRA_DATA_BYTES]
            base_fee_per_gas: uint256
            block_hash: Bytes32
            transactions: List[p.Transaction, p.MAX_TRANSACTIONS_PER_PAYLOAD]

        class ExecutionPayloadHeader(Container):
            parent_hash: Bytes32
            fee_recipient: Bytes20
            state_root: Bytes32
            receipts_root: Bytes32
            logs_bloom: ByteVector[p.BYTES_PER_LOGS_BLOOM]
            prev_randao: Bytes32
            block_number: uint64
            gas_limit: uint64
            gas_used: uint64
            timestamp: uint64
            extra_data: ByteList[p.MAX_EXTRA_DATA_BYTES]
            base_fee_per_gas: uint256
            block_hash: Bytes32
            transactions_root: Bytes32

        class BeaconBlockBody(Container):
            randao_reveal: Bytes96
            eth1_data: p.Eth1Data
            graffiti: Bytes32
            proposer_slashings: List[p.ProposerSlashing, p.MAX_PROPOSER_SLASHINGS]
            attester_slashings: List[p.AttesterSlashing, p.MAX_ATTESTER_SLASHINGS]
            attestations: List[p.Attestation, p.MAX_ATTESTATIONS]
            deposits: List[p.Deposit, p.MAX_DEPOSITS]
            voluntary_exits: List[p.SignedVoluntaryExit, p.MAX_VOLUNTARY_EXITS]
            sync_aggregate: p.SyncAggregate
            execution_payload: ExecutionPayload

        class BeaconBlock(Container):
            slot: uint64
            proposer_index: uint64
            parent_root: Bytes32
            state_root: Bytes32
            body: BeaconBlockBody

        class SignedBeaconBlock(Container):
            message: BeaconBlock
            signature: Bytes96

        class BeaconState(Container):
            genesis_time: uint64
            genesis_validators_root: Bytes32
            slot: uint64
            fork: p.Fork
            latest_block_header: p.BeaconBlockHeader
            block_roots: Vector[Bytes32, p.SLOTS_PER_HISTORICAL_ROOT]
            state_roots: Vector[Bytes32, p.SLOTS_PER_HISTORICAL_ROOT]
            historical_roots: List[Bytes32, p.HISTORICAL_ROOTS_LIMIT]
            eth1_data: p.Eth1Data
            eth1_data_votes: List[p.Eth1Data, p.EPOCHS_PER_ETH1_VOTING_PERIOD * p.SLOTS_PER_EPOCH]
            eth1_deposit_index: uint64
            validators: List[p.Validator, p.VALIDATOR_REGISTRY_LIMIT]
            balances: List[uint64, p.VALIDATOR_REGISTRY_LIMIT]
            randao_mixes: Vector[Bytes32, p.EPOCHS_PER_HISTORICAL_VECTOR]
            slashings: Vector[uint64, p.EPOCHS_PER_SLASHINGS_VECTOR]
            previous_epoch_participation: List[p.ParticipationFlags, p.VALIDATOR_REGISTRY_LIMIT]
            current_epoch_participation: List[p.ParticipationFlags, p.VALIDATOR_REGISTRY_LIMIT]
            justification_bits: Bitvector[p.JUSTIFICATION_BITS_LENGTH]
            previous_justified_checkpoint: p.Checkpoint
            current_justified_checkpoint: p.Checkpoint
            finalized_checkpoint: p.Checkpoint
            inactivity_scores: List[uint64, p.VALIDATOR_REGISTRY_LIMIT]
            current_sync_committee: p.SyncCommittee
            next_sync_committee: p.SyncCommittee
            latest_execution_payload_header: ExecutionPayloadHeader

        class PowBlock(Container):
            block_hash: Bytes32
            parent_hash: Bytes32
            total_difficulty: uint256

        for name, cls in list(locals().items()):
            if isinstance(cls, type) and issubclass(cls, Container):
                setattr(self, name, cls)

    # ------------------------------------------------------------------
    # merge predicates
    # ------------------------------------------------------------------
    def is_merge_transition_complete(self, state) -> bool:
        return state.latest_execution_payload_header \
            != self.ExecutionPayloadHeader()

    def is_merge_transition_block(self, state, body) -> bool:
        return (not self.is_merge_transition_complete(state)
                and body.execution_payload != self.ExecutionPayload())

    def is_execution_enabled(self, state, body) -> bool:
        return self.is_merge_transition_block(state, body) \
            or self.is_merge_transition_complete(state)

    def compute_timestamp_at_slot(self, state, slot) -> int:
        slots_since_genesis = slot - self.GENESIS_SLOT
        return uint64(state.genesis_time
                      + slots_since_genesis * self.config.SECONDS_PER_SLOT)

    def get_pow_block(self, block_hash):
        return self.pow_chain.get(bytes(block_hash))

    def is_valid_terminal_pow_block(self, block, parent) -> bool:
        ttd = int(self.config.TERMINAL_TOTAL_DIFFICULTY)
        is_total_difficulty_reached = block.total_difficulty >= ttd
        is_parent_total_difficulty_valid = parent.total_difficulty < ttd
        return is_total_difficulty_reached \
            and is_parent_total_difficulty_valid

    def validate_merge_transition_block(self, pre_state, block) -> None:
        """on_block hook (bellatrix/fork-choice.md): the first block
        carrying an execution payload must descend from a valid
        terminal PoW block."""
        if self.is_merge_transition_block(pre_state, block.body):
            self.validate_merge_block(block)

    def validate_merge_block(self, block) -> None:
        terminal_hash = bytes.fromhex(
            str(self.config.TERMINAL_BLOCK_HASH)[2:])
        if terminal_hash != b"\x00" * 32:
            assert self.compute_epoch_at_slot(block.slot) >= int(
                self.config.TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH)
            assert bytes(block.body.execution_payload.parent_hash) \
                == terminal_hash
            return
        pow_block = self.get_pow_block(
            block.body.execution_payload.parent_hash)
        assert pow_block is not None
        pow_parent = self.get_pow_block(pow_block.parent_hash)
        assert pow_parent is not None
        assert self.is_valid_terminal_pow_block(pow_block, pow_parent)

    # ------------------------------------------------------------------
    # block processing
    # ------------------------------------------------------------------
    def process_block(self, state, block) -> None:
        self.process_block_header(state, block)
        if self.is_execution_enabled(state, block.body):
            self.process_execution_payload(
                state, block.body, self.EXECUTION_ENGINE)
        self.process_randao(state, block.body)
        self.process_eth1_data(state, block.body)
        self.process_operations(state, block.body)
        self.process_sync_aggregate(state, block.body.sync_aggregate)

    def process_execution_payload(self, state, body, execution_engine) -> None:
        payload = body.execution_payload
        if self.is_merge_transition_complete(state):
            assert payload.parent_hash == \
                state.latest_execution_payload_header.block_hash
        assert payload.prev_randao == self.get_randao_mix(
            state, self.get_current_epoch(state))
        assert payload.timestamp == self.compute_timestamp_at_slot(
            state, state.slot)
        assert execution_engine.verify_and_notify_new_payload(payload)
        state.latest_execution_payload_header = \
            self.build_execution_payload_header(payload)

    def build_execution_payload_header(self, payload):
        return self.ExecutionPayloadHeader(
            parent_hash=payload.parent_hash,
            fee_recipient=payload.fee_recipient,
            state_root=payload.state_root,
            receipts_root=payload.receipts_root,
            logs_bloom=payload.logs_bloom,
            prev_randao=payload.prev_randao,
            block_number=payload.block_number,
            gas_limit=payload.gas_limit,
            gas_used=payload.gas_used,
            timestamp=payload.timestamp,
            extra_data=payload.extra_data,
            base_fee_per_gas=payload.base_fee_per_gas,
            block_hash=payload.block_hash,
            transactions_root=hash_tree_root(payload.transactions))

    # quotients
    def inactivity_penalty_quotient(self) -> int:
        return self.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX

    def min_slashing_penalty_quotient(self) -> int:
        return self.MIN_SLASHING_PENALTY_QUOTIENT_BELLATRIX

    def proportional_slashing_multiplier(self) -> int:
        return self.PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX

    # fork-choice extension (fork_choice/safe-block.md + bellatrix/fork-choice.md)
    def get_safe_execution_block_hash(self, store):
        safe_block_root = self.get_safe_beacon_block_root(store)
        safe_block = store.blocks[safe_block_root]
        if self.is_execution_enabled(
                store.block_states[safe_block_root], safe_block.body):
            return safe_block.body.execution_payload.block_hash
        return Bytes32()

    def should_override_forkchoice_update(self, store, head_root) -> bool:
        head_block = store.blocks[head_root]
        parent_root = head_block.parent_root
        proposal_slot = uint64(head_block.slot + 1)
        current_slot = self.get_current_slot(store)

        head_late = self.is_head_late(store, head_root)
        shuffling_stable = self.is_shuffling_stable(proposal_slot)
        ffg_competitive = self.is_ffg_competitive(store, head_root,
                                                  parent_root)
        finalization_ok = self.is_finalization_ok(store, proposal_slot)
        proposing_reorg_slot = current_slot == head_block.slot or \
            current_slot == proposal_slot
        parent_block = store.blocks[parent_root]
        parent_slot_ok = parent_block.slot + 1 == head_block.slot
        proposing_on_time = (self.is_proposing_on_time(store)
                             if current_slot == proposal_slot else True)
        if not all([head_late, shuffling_stable, ffg_competitive,
                    finalization_ok, proposing_reorg_slot, parent_slot_ok,
                    proposing_on_time]):
            return False
        # only consult weights once the head slot's attestations have been
        # counted; before that, assume the reorg conditions hold
        head_weak = True
        parent_strong = True
        if current_slot > head_block.slot:
            head_weak = self.is_head_weak(store, head_root)
            parent_strong = self.is_parent_strong(store, parent_root)
        return head_weak and parent_strong

    # ------------------------------------------------------------------
    # fork upgrade (bellatrix/fork.md)
    # ------------------------------------------------------------------
    def genesis_fork_versions(self):
        return (Bytes4(self.config.ALTAIR_FORK_VERSION),
                Bytes4(self.config.BELLATRIX_FORK_VERSION))

    def upgrade_from(self, pre):
        epoch = self.get_current_epoch(pre)
        post = self.BeaconState(
            genesis_time=pre.genesis_time,
            genesis_validators_root=pre.genesis_validators_root,
            slot=pre.slot,
            fork=self.Fork(
                previous_version=pre.fork.current_version,
                current_version=Bytes4(self.config.BELLATRIX_FORK_VERSION),
                epoch=epoch),
            latest_block_header=pre.latest_block_header,
            block_roots=list(pre.block_roots),
            state_roots=list(pre.state_roots),
            historical_roots=list(pre.historical_roots),
            eth1_data=pre.eth1_data,
            eth1_data_votes=list(pre.eth1_data_votes),
            eth1_deposit_index=pre.eth1_deposit_index,
            validators=list(pre.validators),
            balances=list(pre.balances),
            randao_mixes=list(pre.randao_mixes),
            slashings=list(pre.slashings),
            previous_epoch_participation=list(
                pre.previous_epoch_participation),
            current_epoch_participation=list(
                pre.current_epoch_participation),
            justification_bits=list(pre.justification_bits),
            previous_justified_checkpoint=pre.previous_justified_checkpoint,
            current_justified_checkpoint=pre.current_justified_checkpoint,
            finalized_checkpoint=pre.finalized_checkpoint,
            inactivity_scores=list(pre.inactivity_scores),
            current_sync_committee=pre.current_sync_committee,
            next_sync_committee=pre.next_sync_committee,
            # latest_execution_payload_header stays default (pre-merge)
        )
        return post
