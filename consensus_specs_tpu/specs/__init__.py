"""Spec registry: one cached spec instance per (fork, preset, config).

The counterpart of the reference's spec_targets
(/root/reference/tests/core/pyspec/eth2spec/test/helpers/specs.py).
"""
from __future__ import annotations

import importlib
import importlib.util

_BUILTIN_FORKS = [
    ("phase0", "Phase0Spec"),
    ("altair", "AltairSpec"),
    ("bellatrix", "BellatrixSpec"),
    ("capella", "CapellaSpec"),
    ("deneb", "DenebSpec"),
    ("electra", "ElectraSpec"),
    ("fulu", "FuluSpec"),
    ("whisk", "WhiskSpec"),
    ("eip7732", "Eip7732Spec"),
    ("eip6800", "Eip6800Spec"),
]

_REGISTRY: dict = {}
_INSTANCES: dict = {}
_loaded = False


def register(fork_name: str, cls) -> None:
    _ensure_loaded()
    _REGISTRY[fork_name] = cls


def available_forks() -> list:
    _ensure_loaded()
    return list(_REGISTRY)


def get_spec(fork_name: str, preset_name: str = "mainnet", config=None):
    """Spec instance for (fork, preset); instances with default config are
    cached, custom configs build fresh."""
    _ensure_loaded()
    if fork_name not in _REGISTRY:
        raise KeyError(f"unknown fork {fork_name!r}; have {list(_REGISTRY)}")
    if config is not None:
        return _REGISTRY[fork_name](preset_name, config)
    key = (fork_name, preset_name)
    if key not in _INSTANCES:
        _INSTANCES[key] = _REGISTRY[fork_name](preset_name)
    return _INSTANCES[key]


def _ensure_loaded() -> None:
    global _loaded
    if _loaded:
        return
    _loaded = True
    for fork_name, class_name in _BUILTIN_FORKS:
        # skip forks whose module doesn't exist yet; genuine import errors
        # inside an existing module must propagate
        if importlib.util.find_spec(f"{__name__}.{fork_name}") is None:
            continue
        module = importlib.import_module(f"{__name__}.{fork_name}")
        _REGISTRY[fork_name] = getattr(module, class_name)
