"""Phase0 beacon-chain spec.

From-scratch implementation of the phase0 consensus rules
(/root/reference/specs/phase0/beacon-chain.md — function-by-function parity;
docstrings cite the section names).  Organized as a spec class: SSZ container
classes are built per preset in _build_types, functions are methods.

The oracle path mirrors spec semantics exactly (mutable views, asserts for
invalid transitions).  Vectorized/TPU epoch processing plugs in as method
overrides (ops/, later rounds).

NOTE: no `from __future__ import annotations` here — SSZ Container fields
are declared via class annotations and must stay live types (PEP 563 would
stringify them).
"""
from ..ssz import (
    uint8, uint32, uint64, boolean, Bitlist, Bitvector, ByteVector, ByteList,
    Vector, List, Container, Bytes4, Bytes32, Bytes48, Bytes96,
    hash_tree_root, serialize, uint_to_bytes,
)
from ..ssz import incremental as ssz_incremental
from ..ssz.merkle import is_valid_merkle_branch as _merkle_branch_ok
from ..utils import bls
from ..utils.hash import hash as sha256_hash
from .base import BaseSpec
from .fork_choice import Phase0ForkChoice
from .validator_duties import Phase0ValidatorDuties


def integer_squareroot(n: int) -> int:
    """Largest x with x*x <= n (beacon-chain.md "integer_squareroot")."""
    if n < 0:
        raise ValueError("negative input")
    x = int(n)
    y = (x + 1) // 2
    while y < x:
        x = y
        y = (x + n // x) // 2
    return uint64(x)


def xor(a: bytes, b: bytes) -> Bytes32:
    return Bytes32(bytes(x ^ y for x, y in zip(a, b)))


def bytes_to_uint64(data: bytes) -> uint64:
    return uint64(int.from_bytes(data, "little"))


class Phase0Spec(Phase0ForkChoice, Phase0ValidatorDuties, BaseSpec):
    fork = "phase0"

    # ------------------------------------------------------------------
    # constants (beacon-chain.md "Constants" tables)
    # ------------------------------------------------------------------
    def _build_constants(self) -> None:
        super()._build_constants()
        self.GENESIS_SLOT = uint64(0)
        self.GENESIS_EPOCH = uint64(0)
        self.FAR_FUTURE_EPOCH = uint64(2**64 - 1)
        self.BASE_REWARDS_PER_EPOCH = uint64(4)
        self.DEPOSIT_CONTRACT_TREE_DEPTH = 2**5
        self.JUSTIFICATION_BITS_LENGTH = 4
        self.ENDIANNESS = "little"
        self.BLS_WITHDRAWAL_PREFIX = b"\x00"
        self.ETH1_ADDRESS_WITHDRAWAL_PREFIX = b"\x01"
        self.DOMAIN_BEACON_PROPOSER = Bytes4("0x00000000")
        self.DOMAIN_BEACON_ATTESTER = Bytes4("0x01000000")
        self.DOMAIN_RANDAO = Bytes4("0x02000000")
        self.DOMAIN_DEPOSIT = Bytes4("0x03000000")
        self.DOMAIN_VOLUNTARY_EXIT = Bytes4("0x04000000")
        self.DOMAIN_SELECTION_PROOF = Bytes4("0x05000000")
        self.DOMAIN_AGGREGATE_AND_PROOF = Bytes4("0x06000000")
        self.DOMAIN_APPLICATION_MASK = Bytes4("0x00000001")
        # validator.md
        self.TARGET_AGGREGATORS_PER_COMMITTEE = 2**4
        # p2p-interface.md
        self.ATTESTATION_SUBNET_COUNT = 64
        self.EPOCHS_PER_SUBNET_SUBSCRIPTION = 2**8
        self.SUBNETS_PER_NODE = 2
        self.NODE_ID_BITS = 256
        # custom "types" (aliases; all uint64 / bytes)
        self.Slot = uint64
        self.Epoch = uint64
        self.CommitteeIndex = uint64
        self.ValidatorIndex = uint64
        self.Gwei = uint64
        self.Root = Bytes32
        self.Hash32 = Bytes32
        self.Version = Bytes4
        self.DomainType = Bytes4
        self.ForkDigest = Bytes4
        self.Domain = Bytes32
        self.BLSPubkey = Bytes48
        self.BLSSignature = Bytes96

    # ------------------------------------------------------------------
    # SSZ containers (beacon-chain.md "Containers")
    # ------------------------------------------------------------------
    def _build_types(self) -> None:
        super()._build_types()
        p = self

        class Fork(Container):
            previous_version: Bytes4
            current_version: Bytes4
            epoch: uint64

        class ForkData(Container):
            current_version: Bytes4
            genesis_validators_root: Bytes32

        class Checkpoint(Container):
            epoch: uint64
            root: Bytes32

        class Validator(Container):
            pubkey: Bytes48
            withdrawal_credentials: Bytes32
            effective_balance: uint64
            slashed: boolean
            activation_eligibility_epoch: uint64
            activation_epoch: uint64
            exit_epoch: uint64
            withdrawable_epoch: uint64

        class AttestationData(Container):
            slot: uint64
            index: uint64
            beacon_block_root: Bytes32
            source: Checkpoint
            target: Checkpoint

        class IndexedAttestation(Container):
            attesting_indices: List[uint64, p.MAX_VALIDATORS_PER_COMMITTEE]
            data: AttestationData
            signature: Bytes96

        class PendingAttestation(Container):
            aggregation_bits: Bitlist[p.MAX_VALIDATORS_PER_COMMITTEE]
            data: AttestationData
            inclusion_delay: uint64
            proposer_index: uint64

        class Eth1Data(Container):
            deposit_root: Bytes32
            deposit_count: uint64
            block_hash: Bytes32

        class HistoricalBatch(Container):
            block_roots: Vector[Bytes32, p.SLOTS_PER_HISTORICAL_ROOT]
            state_roots: Vector[Bytes32, p.SLOTS_PER_HISTORICAL_ROOT]

        class DepositMessage(Container):
            pubkey: Bytes48
            withdrawal_credentials: Bytes32
            amount: uint64

        class DepositData(Container):
            pubkey: Bytes48
            withdrawal_credentials: Bytes32
            amount: uint64
            signature: Bytes96

        class BeaconBlockHeader(Container):
            slot: uint64
            proposer_index: uint64
            parent_root: Bytes32
            state_root: Bytes32
            body_root: Bytes32

        class SigningData(Container):
            object_root: Bytes32
            domain: Bytes32

        class SignedBeaconBlockHeader(Container):
            message: BeaconBlockHeader
            signature: Bytes96

        class ProposerSlashing(Container):
            signed_header_1: SignedBeaconBlockHeader
            signed_header_2: SignedBeaconBlockHeader

        class AttesterSlashing(Container):
            attestation_1: IndexedAttestation
            attestation_2: IndexedAttestation

        class Attestation(Container):
            aggregation_bits: Bitlist[p.MAX_VALIDATORS_PER_COMMITTEE]
            data: AttestationData
            signature: Bytes96

        class Deposit(Container):
            proof: Vector[Bytes32, p.DEPOSIT_CONTRACT_TREE_DEPTH + 1]
            data: DepositData

        class VoluntaryExit(Container):
            epoch: uint64
            validator_index: uint64

        class SignedVoluntaryExit(Container):
            message: VoluntaryExit
            signature: Bytes96

        class BeaconBlockBody(Container):
            randao_reveal: Bytes96
            eth1_data: Eth1Data
            graffiti: Bytes32
            proposer_slashings: List[ProposerSlashing, p.MAX_PROPOSER_SLASHINGS]
            attester_slashings: List[AttesterSlashing, p.MAX_ATTESTER_SLASHINGS]
            attestations: List[Attestation, p.MAX_ATTESTATIONS]
            deposits: List[Deposit, p.MAX_DEPOSITS]
            voluntary_exits: List[SignedVoluntaryExit, p.MAX_VOLUNTARY_EXITS]

        class BeaconBlock(Container):
            slot: uint64
            proposer_index: uint64
            parent_root: Bytes32
            state_root: Bytes32
            body: BeaconBlockBody

        class SignedBeaconBlock(Container):
            message: BeaconBlock
            signature: Bytes96

        class BeaconState(Container):
            genesis_time: uint64
            genesis_validators_root: Bytes32
            slot: uint64
            fork: Fork
            latest_block_header: BeaconBlockHeader
            block_roots: Vector[Bytes32, p.SLOTS_PER_HISTORICAL_ROOT]
            state_roots: Vector[Bytes32, p.SLOTS_PER_HISTORICAL_ROOT]
            historical_roots: List[Bytes32, p.HISTORICAL_ROOTS_LIMIT]
            eth1_data: Eth1Data
            eth1_data_votes: List[Eth1Data, p.EPOCHS_PER_ETH1_VOTING_PERIOD * p.SLOTS_PER_EPOCH]
            eth1_deposit_index: uint64
            validators: List[Validator, p.VALIDATOR_REGISTRY_LIMIT]
            balances: List[uint64, p.VALIDATOR_REGISTRY_LIMIT]
            randao_mixes: Vector[Bytes32, p.EPOCHS_PER_HISTORICAL_VECTOR]
            slashings: Vector[uint64, p.EPOCHS_PER_SLASHINGS_VECTOR]
            previous_epoch_attestations: List[PendingAttestation, p.MAX_ATTESTATIONS * p.SLOTS_PER_EPOCH]
            current_epoch_attestations: List[PendingAttestation, p.MAX_ATTESTATIONS * p.SLOTS_PER_EPOCH]
            justification_bits: Bitvector[p.JUSTIFICATION_BITS_LENGTH]
            previous_justified_checkpoint: Checkpoint
            current_justified_checkpoint: Checkpoint
            finalized_checkpoint: Checkpoint

        class Eth1Block(Container):
            timestamp: uint64
            deposit_root: Bytes32
            deposit_count: uint64

        class AggregateAndProof(Container):
            aggregator_index: uint64
            aggregate: Attestation
            selection_proof: Bytes96

        class SignedAggregateAndProof(Container):
            message: AggregateAndProof
            signature: Bytes96

        for name, cls in list(locals().items()):
            if isinstance(cls, type) and issubclass(cls, Container):
                setattr(self, name, cls)

    # ------------------------------------------------------------------
    # math / crypto helpers
    # ------------------------------------------------------------------
    integer_squareroot = staticmethod(integer_squareroot)
    xor = staticmethod(xor)
    bytes_to_uint64 = staticmethod(bytes_to_uint64)
    uint_to_bytes = staticmethod(uint_to_bytes)
    hash = staticmethod(sha256_hash)
    hash_tree_root = staticmethod(hash_tree_root)
    serialize = staticmethod(serialize)
    bls = bls

    @staticmethod
    def is_valid_merkle_branch(leaf, branch, depth, index, root) -> bool:
        return _merkle_branch_ok(bytes(leaf), [bytes(b) for b in branch],
                                 int(depth), int(index), bytes(root))

    # ------------------------------------------------------------------
    # predicates (beacon-chain.md "Predicates")
    # ------------------------------------------------------------------
    def is_active_validator(self, validator, epoch) -> bool:
        return validator.activation_epoch <= epoch < validator.exit_epoch

    def is_eligible_for_activation_queue(self, validator) -> bool:
        return (validator.activation_eligibility_epoch == self.FAR_FUTURE_EPOCH
                and validator.effective_balance == self.MAX_EFFECTIVE_BALANCE)

    def is_eligible_for_activation(self, state, validator) -> bool:
        return (validator.activation_eligibility_epoch
                <= state.finalized_checkpoint.epoch
                and validator.activation_epoch == self.FAR_FUTURE_EPOCH)

    def is_slashable_validator(self, validator, epoch) -> bool:
        return (not validator.slashed
                and validator.activation_epoch <= epoch < validator.withdrawable_epoch)

    def is_slashable_attestation_data(self, data_1, data_2) -> bool:
        # double vote or surround vote
        return ((data_1 != data_2 and data_1.target.epoch == data_2.target.epoch)
                or (data_1.source.epoch < data_2.source.epoch
                    and data_2.target.epoch < data_1.target.epoch))

    def is_valid_indexed_attestation(self, state, indexed_attestation) -> bool:
        indices = list(indexed_attestation.attesting_indices)
        if len(indices) == 0 or indices != sorted(set(int(i) for i in indices)):
            return False
        pubkeys = [state.validators[i].pubkey for i in indices]
        domain = self.get_domain(state, self.DOMAIN_BEACON_ATTESTER,
                                 indexed_attestation.data.target.epoch)
        signing_root = self.compute_signing_root(indexed_attestation.data, domain)
        return self.bls_fast_aggregate_verify(pubkeys, signing_root,
                                              indexed_attestation.signature)

    # ------------------------------------------------------------------
    # misc computations (beacon-chain.md "Misc" helpers)
    # ------------------------------------------------------------------
    def compute_shuffled_index(self, index: int, index_count: int, seed) -> int:
        """Swap-or-not shuffle, SHUFFLE_ROUND_COUNT rounds."""
        assert index < index_count
        for current_round in range(self.SHUFFLE_ROUND_COUNT):
            pivot = bytes_to_uint64(self.hash(
                bytes(seed) + uint_to_bytes(uint8(current_round)))[0:8]) % index_count
            flip = (pivot + index_count - index) % index_count
            position = max(index, flip)
            source = self.hash(
                bytes(seed) + uint_to_bytes(uint8(current_round))
                + uint_to_bytes(uint32(position // 256)))
            byte_val = source[(position % 256) // 8]
            bit = (byte_val >> (position % 8)) % 2
            index = flip if bit else index
        return uint64(index)

    _SHUFFLE_CACHE_SIZE = 8

    def _shuffle_permutation(self, seed, index_count: int):
        """Full swap-or-not permutation for (seed, n), LRU-cached per spec
        instance — the batched counterpart of the reference's per-index LRU
        (pysetup/spec_builders/phase0.py:59-62).  Bounded: a fresh seed per
        epoch in a long-running generator would otherwise grow ~8n bytes
        per epoch forever."""
        from .shuffle import shuffle_permutation
        cache = self._caches.setdefault("shuffle_perm_lru", {})
        key = (bytes(seed), int(index_count))
        if key not in cache:
            if len(cache) >= self._SHUFFLE_CACHE_SIZE:
                cache.pop(next(iter(cache)))
            cache[key] = shuffle_permutation(
                bytes(seed), int(index_count), self.SHUFFLE_ROUND_COUNT)
        else:
            cache[key] = cache.pop(key)   # refresh LRU order
        return cache[key]

    def compute_proposer_index(self, state, indices, seed) -> int:
        """Balance-weighted rejection sampling over a shuffled candidate list."""
        assert len(indices) > 0
        MAX_RANDOM_BYTE = 2**8 - 1
        i = 0
        total = len(indices)
        perm = self._shuffle_permutation(seed, total)
        while True:
            candidate_index = indices[int(perm[i % total])]
            random_byte = self.hash(bytes(seed) + uint_to_bytes(uint64(i // 32)))[i % 32]
            effective_balance = state.validators[candidate_index].effective_balance
            if (effective_balance * MAX_RANDOM_BYTE
                    >= self.MAX_EFFECTIVE_BALANCE * random_byte):
                return uint64(candidate_index)
            i += 1

    def compute_committee(self, indices, seed, index: int, count: int):
        start = len(indices) * index // count
        end = len(indices) * (index + 1) // count
        perm = self._shuffle_permutation(seed, len(indices))
        return [indices[int(perm[i])] for i in range(start, end)]

    def compute_epoch_at_slot(self, slot) -> int:
        return uint64(slot // self.SLOTS_PER_EPOCH)

    def compute_start_slot_at_epoch(self, epoch) -> int:
        return uint64(epoch * self.SLOTS_PER_EPOCH)

    def compute_activation_exit_epoch(self, epoch) -> int:
        return uint64(epoch + 1 + self.MAX_SEED_LOOKAHEAD)

    def compute_fork_data_root(self, current_version, genesis_validators_root):
        return hash_tree_root(self.ForkData(
            current_version=current_version,
            genesis_validators_root=genesis_validators_root))

    def compute_fork_digest(self, current_version, genesis_validators_root):
        return Bytes4(self.compute_fork_data_root(
            current_version, genesis_validators_root)[:4])

    def compute_fork_version(self, epoch):
        """Fork version active at `epoch`, over this spec's fork ladder
        (each fork's fork.md compute_fork_version, generalized)."""
        ladder = ["fulu", "electra", "deneb", "capella", "bellatrix",
                  "altair"]
        for name in ladder:
            if not self.is_post(name):
                continue
            fork_epoch = self.config.get(
                f"{name.upper()}_FORK_EPOCH", 2**64 - 1)
            if epoch >= fork_epoch:
                return Bytes4(
                    getattr(self.config, f"{name.upper()}_FORK_VERSION"))
        return Bytes4(self.config.GENESIS_FORK_VERSION)

    def compute_domain(self, domain_type, fork_version=None,
                       genesis_validators_root=None):
        if fork_version is None:
            fork_version = Bytes4(self.config.GENESIS_FORK_VERSION)
        if genesis_validators_root is None:
            genesis_validators_root = Bytes32()
        fork_data_root = self.compute_fork_data_root(
            fork_version, genesis_validators_root)
        return Bytes32(bytes(domain_type) + bytes(fork_data_root)[:28])

    def compute_signing_root(self, ssz_object, domain):
        return hash_tree_root(self.SigningData(
            object_root=hash_tree_root(ssz_object), domain=domain))

    # ------------------------------------------------------------------
    # accessors (beacon-chain.md "Beacon state accessors")
    # ------------------------------------------------------------------
    def get_current_epoch(self, state) -> int:
        return self.compute_epoch_at_slot(state.slot)

    def get_previous_epoch(self, state) -> int:
        current = self.get_current_epoch(state)
        return self.GENESIS_EPOCH if current == self.GENESIS_EPOCH \
            else uint64(current - 1)

    def get_block_root(self, state, epoch):
        return self.get_block_root_at_slot(
            state, self.compute_start_slot_at_epoch(epoch))

    def get_block_root_at_slot(self, state, slot):
        assert slot < state.slot <= slot + self.SLOTS_PER_HISTORICAL_ROOT
        return state.block_roots[slot % self.SLOTS_PER_HISTORICAL_ROOT]

    def get_randao_mix(self, state, epoch):
        return state.randao_mixes[epoch % self.EPOCHS_PER_HISTORICAL_VECTOR]

    def get_active_validator_indices(self, state, epoch):
        return [uint64(i) for i, v in enumerate(state.validators)
                if self.is_active_validator(v, epoch)]

    def get_validator_churn_limit(self, state) -> int:
        active = self.get_active_validator_indices(
            state, self.get_current_epoch(state))
        return uint64(max(self.config.MIN_PER_EPOCH_CHURN_LIMIT,
                          len(active) // self.config.CHURN_LIMIT_QUOTIENT))

    def get_seed(self, state, epoch, domain_type):
        mix = self.get_randao_mix(
            state, uint64(epoch + self.EPOCHS_PER_HISTORICAL_VECTOR
                          - self.MIN_SEED_LOOKAHEAD - 1))
        return self.hash(bytes(domain_type) + uint_to_bytes(uint64(epoch))
                         + bytes(mix))

    def get_committee_count_per_slot(self, state, epoch) -> int:
        active = len(self.get_active_validator_indices(state, epoch))
        return uint64(max(1, min(
            self.MAX_COMMITTEES_PER_SLOT,
            active // self.SLOTS_PER_EPOCH // self.TARGET_COMMITTEE_SIZE)))

    def get_beacon_committee(self, state, slot, index):
        epoch = self.compute_epoch_at_slot(slot)
        committees_per_slot = self.get_committee_count_per_slot(state, epoch)
        return self.compute_committee(
            indices=self.get_active_validator_indices(state, epoch),
            seed=self.get_seed(state, epoch, self.DOMAIN_BEACON_ATTESTER),
            index=(slot % self.SLOTS_PER_EPOCH) * committees_per_slot + index,
            count=committees_per_slot * self.SLOTS_PER_EPOCH)

    def get_beacon_proposer_index(self, state) -> int:
        epoch = self.get_current_epoch(state)
        seed = self.hash(
            bytes(self.get_seed(state, epoch, self.DOMAIN_BEACON_PROPOSER))
            + uint_to_bytes(uint64(state.slot)))
        indices = self.get_active_validator_indices(state, epoch)
        return self.compute_proposer_index(state, indices, seed)

    def get_total_balance(self, state, indices) -> int:
        return uint64(max(
            self.EFFECTIVE_BALANCE_INCREMENT,
            sum(int(state.validators[i].effective_balance) for i in indices)))

    def get_total_active_balance(self, state) -> int:
        return self.get_total_balance(
            state, set(self.get_active_validator_indices(
                state, self.get_current_epoch(state))))

    def get_domain(self, state, domain_type, epoch=None):
        epoch = self.get_current_epoch(state) if epoch is None else epoch
        fork_version = (state.fork.previous_version if epoch < state.fork.epoch
                        else state.fork.current_version)
        return self.compute_domain(domain_type, fork_version,
                                   state.genesis_validators_root)

    def get_indexed_attestation(self, state, attestation):
        attesting_indices = self.get_attesting_indices(state, attestation)
        return self.IndexedAttestation(
            attesting_indices=sorted(int(i) for i in attesting_indices),
            data=attestation.data,
            signature=attestation.signature)

    def get_attesting_indices(self, state, attestation):
        committee = self.get_beacon_committee(
            state, attestation.data.slot, attestation.data.index)
        return set(index for i, index in enumerate(committee)
                   if attestation.aggregation_bits[i])

    # ------------------------------------------------------------------
    # mutators (beacon-chain.md "Beacon state mutators")
    # ------------------------------------------------------------------
    def increase_balance(self, state, index, delta) -> None:
        state.balances[index] = uint64(state.balances[index] + delta)

    def decrease_balance(self, state, index, delta) -> None:
        bal = state.balances[index]
        state.balances[index] = uint64(0 if delta > bal else bal - delta)

    def initiate_validator_exit(self, state, index) -> None:
        validator = state.validators[index]
        if validator.exit_epoch != self.FAR_FUTURE_EPOCH:
            return
        exit_epochs = [int(v.exit_epoch) for v in state.validators
                       if v.exit_epoch != self.FAR_FUTURE_EPOCH]
        exit_queue_epoch = max(exit_epochs + [int(
            self.compute_activation_exit_epoch(self.get_current_epoch(state)))])
        exit_queue_churn = len([v for v in state.validators
                                if v.exit_epoch == exit_queue_epoch])
        if exit_queue_churn >= self.get_validator_churn_limit(state):
            exit_queue_epoch += 1
        validator.exit_epoch = uint64(exit_queue_epoch)
        validator.withdrawable_epoch = uint64(
            validator.exit_epoch
            + self.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY)

    def slash_validator(self, state, slashed_index,
                        whistleblower_index=None) -> None:
        epoch = self.get_current_epoch(state)
        self.initiate_validator_exit(state, slashed_index)
        validator = state.validators[slashed_index]
        validator.slashed = True
        validator.withdrawable_epoch = uint64(max(
            int(validator.withdrawable_epoch),
            int(epoch + self.EPOCHS_PER_SLASHINGS_VECTOR)))
        state.slashings[epoch % self.EPOCHS_PER_SLASHINGS_VECTOR] = uint64(
            state.slashings[epoch % self.EPOCHS_PER_SLASHINGS_VECTOR]
            + validator.effective_balance)
        slashing_penalty = validator.effective_balance \
            // self.min_slashing_penalty_quotient()
        self.decrease_balance(state, slashed_index, slashing_penalty)

        proposer_index = self.get_beacon_proposer_index(state)
        if whistleblower_index is None:
            whistleblower_index = proposer_index
        whistleblower_reward = uint64(
            validator.effective_balance
            // self.whistleblower_reward_quotient())
        proposer_reward = self.slashing_proposer_reward(whistleblower_reward)
        self.increase_balance(state, proposer_index, proposer_reward)
        self.increase_balance(state, whistleblower_index,
                              uint64(whistleblower_reward - proposer_reward))

    # fork-overridable pieces of slash_validator
    def min_slashing_penalty_quotient(self) -> int:
        return self.MIN_SLASHING_PENALTY_QUOTIENT

    def whistleblower_reward_quotient(self) -> int:
        return self.WHISTLEBLOWER_REWARD_QUOTIENT

    def slashing_proposer_reward(self, whistleblower_reward) -> int:
        return uint64(whistleblower_reward // self.PROPOSER_REWARD_QUOTIENT)

    # ------------------------------------------------------------------
    # genesis (beacon-chain.md "Genesis")
    # ------------------------------------------------------------------
    def initialize_beacon_state_from_eth1(self, eth1_block_hash,
                                          eth1_timestamp, deposits):
        # per-fork genesis versions: each fork's builder in the
        # reference rewrites this initializer with its own version pair
        # (pysetup/spec_builders); here the overridable
        # genesis_fork_versions() carries that role
        previous_version, current_version = self.genesis_fork_versions()
        fork = self.Fork(
            previous_version=previous_version,
            current_version=current_version,
            epoch=self.GENESIS_EPOCH)
        state = self.BeaconState(
            genesis_time=uint64(eth1_timestamp + self.config.GENESIS_DELAY),
            fork=fork,
            eth1_data=self.Eth1Data(block_hash=eth1_block_hash,
                                    deposit_count=len(deposits)),
            latest_block_header=self.BeaconBlockHeader(
                body_root=hash_tree_root(self.BeaconBlockBody())),
            randao_mixes=[eth1_block_hash] * self.EPOCHS_PER_HISTORICAL_VECTOR)

        # process genesis deposits
        leaves = [d.data for d in deposits]
        deposit_list_type = List[self.DepositData,
                                 2**self.DEPOSIT_CONTRACT_TREE_DEPTH]
        for index, deposit in enumerate(deposits):
            deposit_data_list = deposit_list_type(leaves[:index + 1])
            state.eth1_data.deposit_root = hash_tree_root(deposit_data_list)
            self.process_deposit(state, deposit)

        # activate bootstrap validators
        for index, validator in enumerate(state.validators):
            balance = state.balances[index]
            validator.effective_balance = uint64(min(
                int(balance) - int(balance) % self.EFFECTIVE_BALANCE_INCREMENT,
                self.MAX_EFFECTIVE_BALANCE))
            if validator.effective_balance == self.MAX_EFFECTIVE_BALANCE:
                validator.activation_eligibility_epoch = self.GENESIS_EPOCH
                validator.activation_epoch = self.GENESIS_EPOCH

        state.genesis_validators_root = hash_tree_root(state.validators)
        return state

    def genesis_fork_versions(self):
        """(previous_version, current_version) for a state born at this
        fork — used by mock-genesis fixtures; later forks override."""
        v = Bytes4(self.config.GENESIS_FORK_VERSION)
        return (v, v)

    def is_valid_genesis_state(self, state) -> bool:
        if state.genesis_time < self.config.MIN_GENESIS_TIME:
            return False
        active = self.get_active_validator_indices(state, self.GENESIS_EPOCH)
        return len(active) >= self.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT

    # ------------------------------------------------------------------
    # state transition (beacon-chain.md "Beacon chain state transition")
    # ------------------------------------------------------------------
    def state_transition(self, state, signed_block,
                         validate_result: bool = True) -> None:
        # opt-in incremental merkleization (ssz/incremental.py): track
        # the hot state so every hash_tree_root below re-hashes only the
        # block's dirty cone in one ssz.merkle_sweep dispatch (no-op
        # while the mode is disabled)
        ssz_incremental.track(state)
        block = signed_block.message
        self.process_slots(state, block.slot)
        # opt-in deferred signature pipeline: precompute one batch verdict
        # per signature check; the per-operation seams consume them at the
        # inline call sites (scalar path when disabled)
        from ..sigpipe import verify as sigpipe_verify
        with sigpipe_verify.block_scope(self, state, signed_block):
            if validate_result:
                assert self.verify_block_signature(state, signed_block)
            self.process_block(state, block)
        if validate_result:
            assert block.state_root == hash_tree_root(state)

    def verify_block_signature(self, state, signed_block) -> bool:
        proposer = state.validators[signed_block.message.proposer_index]
        signing_root = self.compute_signing_root(
            signed_block.message,
            self.get_domain(state, self.DOMAIN_BEACON_PROPOSER))
        return self.bls_verify(proposer.pubkey, signing_root,
                               signed_block.signature)

    def process_slots(self, state, slot) -> None:
        assert state.slot < slot
        ssz_incremental.track(state)
        while state.slot < slot:
            self.process_slot(state)
            if (state.slot + 1) % self.SLOTS_PER_EPOCH == 0:
                self.process_epoch(state)
            state.slot = uint64(state.slot + 1)

    def process_slot(self, state) -> None:
        previous_state_root = hash_tree_root(state)
        state.state_roots[state.slot % self.SLOTS_PER_HISTORICAL_ROOT] = \
            previous_state_root
        if state.latest_block_header.state_root == Bytes32():
            state.latest_block_header.state_root = previous_state_root
        previous_block_root = hash_tree_root(state.latest_block_header)
        state.block_roots[state.slot % self.SLOTS_PER_HISTORICAL_ROOT] = \
            previous_block_root

    # ------------------------------------------------------------------
    # epoch processing (beacon-chain.md "Epoch processing")
    # ------------------------------------------------------------------
    def process_epoch(self, state) -> None:
        from . import epoch_fast
        if epoch_fast.fused_epoch(self, state):
            # the fused ONE-dispatch sweep handled justification through
            # the effective-balance update; only the cheap tail resets
            # remain (eth1_data_reset commutes past the sweep: it clears
            # vote bookkeeping no fused pass reads or writes)
            self.process_eth1_data_reset(state)
            self.process_slashings_reset(state)
            self.process_randao_mixes_reset(state)
            self.process_historical_roots_update(state)
            self.process_participation_record_updates(state)
            return
        self.process_justification_and_finalization(state)
        self.process_rewards_and_penalties(state)
        self.process_registry_updates(state)
        self.process_slashings(state)
        self.process_eth1_data_reset(state)
        self.process_effective_balance_updates(state)
        self.process_slashings_reset(state)
        self.process_randao_mixes_reset(state)
        self.process_historical_roots_update(state)
        self.process_participation_record_updates(state)

    # -- attestation matching helpers
    def get_matching_source_attestations(self, state, epoch):
        assert epoch in (self.get_previous_epoch(state),
                         self.get_current_epoch(state))
        return (state.current_epoch_attestations
                if epoch == self.get_current_epoch(state)
                else state.previous_epoch_attestations)

    def get_matching_target_attestations(self, state, epoch):
        return [a for a in self.get_matching_source_attestations(state, epoch)
                if a.data.target.root == self.get_block_root(state, epoch)]

    def get_matching_head_attestations(self, state, epoch):
        return [a for a in self.get_matching_target_attestations(state, epoch)
                if a.data.beacon_block_root
                == self.get_block_root_at_slot(state, a.data.slot)]

    def get_unslashed_attesting_indices(self, state, attestations):
        output = set()
        for a in attestations:
            output |= self.get_attesting_indices(state, a)
        return set(filter(lambda i: not state.validators[i].slashed, output))

    def get_attesting_balance(self, state, attestations) -> int:
        return self.get_total_balance(
            state, self.get_unslashed_attesting_indices(state, attestations))

    def process_justification_and_finalization(self, state) -> None:
        # no processing within the first two epochs
        if self.get_current_epoch(state) <= self.GENESIS_EPOCH + 1:
            return
        previous_attestations = self.get_matching_target_attestations(
            state, self.get_previous_epoch(state))
        current_attestations = self.get_matching_target_attestations(
            state, self.get_current_epoch(state))
        total_active_balance = self.get_total_active_balance(state)
        previous_target_balance = self.get_attesting_balance(
            state, previous_attestations)
        current_target_balance = self.get_attesting_balance(
            state, current_attestations)
        self.weigh_justification_and_finalization(
            state, total_active_balance,
            previous_target_balance, current_target_balance)

    def weigh_justification_and_finalization(self, state, total_active_balance,
                                             previous_epoch_target_balance,
                                             current_epoch_target_balance):
        previous_epoch = self.get_previous_epoch(state)
        current_epoch = self.get_current_epoch(state)
        old_previous_justified = state.previous_justified_checkpoint
        old_current_justified = state.current_justified_checkpoint

        # process justifications
        state.previous_justified_checkpoint = state.current_justified_checkpoint
        bits = state.justification_bits
        for i in range(len(bits) - 1, 0, -1):
            bits[i] = bits[i - 1]
        bits[0] = False
        if previous_epoch_target_balance * 3 >= total_active_balance * 2:
            state.current_justified_checkpoint = self.Checkpoint(
                epoch=previous_epoch,
                root=self.get_block_root(state, previous_epoch))
            bits[1] = True
        if current_epoch_target_balance * 3 >= total_active_balance * 2:
            state.current_justified_checkpoint = self.Checkpoint(
                epoch=current_epoch,
                root=self.get_block_root(state, current_epoch))
            bits[0] = True

        # process finalizations
        # 2nd/3rd/4th most recent epochs justified, 2nd is source
        if all(bits[1:4]) and old_previous_justified.epoch + 3 == current_epoch:
            state.finalized_checkpoint = old_previous_justified
        if all(bits[1:3]) and old_previous_justified.epoch + 2 == current_epoch:
            state.finalized_checkpoint = old_previous_justified
        if all(bits[0:3]) and old_current_justified.epoch + 2 == current_epoch:
            state.finalized_checkpoint = old_current_justified
        if all(bits[0:2]) and old_current_justified.epoch + 1 == current_epoch:
            state.finalized_checkpoint = old_current_justified

    # -- rewards & penalties
    def get_base_reward(self, state, index) -> int:
        total_balance = self.get_total_active_balance(state)
        effective_balance = state.validators[index].effective_balance
        return uint64(effective_balance * self.BASE_REWARD_FACTOR
                      // integer_squareroot(total_balance)
                      // self.BASE_REWARDS_PER_EPOCH)

    def get_proposer_reward(self, state, attesting_index) -> int:
        return uint64(self.get_base_reward(state, attesting_index)
                      // self.PROPOSER_REWARD_QUOTIENT)

    def get_finality_delay(self, state) -> int:
        return uint64(self.get_previous_epoch(state)
                      - state.finalized_checkpoint.epoch)

    def is_in_inactivity_leak(self, state) -> bool:
        return self.get_finality_delay(state) \
            > self.MIN_EPOCHS_TO_INACTIVITY_PENALTY

    def get_eligible_validator_indices(self, state):
        previous_epoch = self.get_previous_epoch(state)
        return [uint64(index) for index, v in enumerate(state.validators)
                if self.is_active_validator(v, previous_epoch)
                or (v.slashed and previous_epoch + 1 < v.withdrawable_epoch)]

    def get_attestation_component_deltas(self, state, attestations):
        """Helper for source/target/head reward components."""
        n = len(state.validators)
        rewards = [uint64(0)] * n
        penalties = [uint64(0)] * n
        total_balance = self.get_total_active_balance(state)
        unslashed_attesting_indices = self.get_unslashed_attesting_indices(
            state, attestations)
        attesting_balance = self.get_total_balance(
            state, unslashed_attesting_indices)
        for index in self.get_eligible_validator_indices(state):
            if index in unslashed_attesting_indices:
                increment = self.EFFECTIVE_BALANCE_INCREMENT
                if self.is_in_inactivity_leak(state):
                    # optimal participation receives full base reward
                    # compensation here; the inactivity penalty cancels it
                    rewards[index] = uint64(
                        rewards[index] + self.get_base_reward(state, index))
                else:
                    reward_numerator = (self.get_base_reward(state, index)
                                        * (attesting_balance // increment))
                    rewards[index] = uint64(
                        rewards[index]
                        + reward_numerator // (total_balance // increment))
            else:
                penalties[index] = uint64(
                    penalties[index] + self.get_base_reward(state, index))
        return rewards, penalties

    def get_source_deltas(self, state):
        return self.get_attestation_component_deltas(
            state, self.get_matching_source_attestations(
                state, self.get_previous_epoch(state)))

    def get_target_deltas(self, state):
        return self.get_attestation_component_deltas(
            state, self.get_matching_target_attestations(
                state, self.get_previous_epoch(state)))

    def get_head_deltas(self, state):
        return self.get_attestation_component_deltas(
            state, self.get_matching_head_attestations(
                state, self.get_previous_epoch(state)))

    def get_inclusion_delay_deltas(self, state):
        n = len(state.validators)
        rewards = [uint64(0)] * n
        matching_source = self.get_matching_source_attestations(
            state, self.get_previous_epoch(state))
        for index in self.get_unslashed_attesting_indices(
                state, matching_source):
            attestation = min(
                (a for a in matching_source
                 if index in self.get_attesting_indices(state, a)),
                key=lambda a: a.inclusion_delay)
            rewards[attestation.proposer_index] = uint64(
                rewards[attestation.proposer_index]
                + self.get_proposer_reward(state, index))
            max_attester_reward = uint64(
                self.get_base_reward(state, index)
                - self.get_proposer_reward(state, index))
            rewards[index] = uint64(
                rewards[index]
                + max_attester_reward // attestation.inclusion_delay)
        return rewards, [uint64(0)] * n

    def get_inactivity_penalty_deltas(self, state):
        n = len(state.validators)
        penalties = [uint64(0)] * n
        if self.is_in_inactivity_leak(state):
            matching_target_attestations = \
                self.get_matching_target_attestations(
                    state, self.get_previous_epoch(state))
            matching_target_attesting_indices = \
                self.get_unslashed_attesting_indices(
                    state, matching_target_attestations)
            for index in self.get_eligible_validator_indices(state):
                base_reward = self.get_base_reward(state, index)
                penalties[index] = uint64(
                    penalties[index]
                    + self.BASE_REWARDS_PER_EPOCH * base_reward
                    - self.get_proposer_reward(state, index))
                if index not in matching_target_attesting_indices:
                    effective_balance = \
                        state.validators[index].effective_balance
                    penalties[index] = uint64(
                        penalties[index]
                        + effective_balance * self.get_finality_delay(state)
                        // self.INACTIVITY_PENALTY_QUOTIENT)
        return [uint64(0)] * n, penalties

    def get_attestation_deltas(self, state):
        source_rewards, source_penalties = self.get_source_deltas(state)
        target_rewards, target_penalties = self.get_target_deltas(state)
        head_rewards, head_penalties = self.get_head_deltas(state)
        inclusion_rewards, _ = self.get_inclusion_delay_deltas(state)
        _, inactivity_penalties = self.get_inactivity_penalty_deltas(state)
        rewards = [uint64(a + b + c + d) for a, b, c, d in zip(
            source_rewards, target_rewards, head_rewards, inclusion_rewards)]
        penalties = [uint64(a + b + c + d) for a, b, c, d in zip(
            source_penalties, target_penalties, head_penalties,
            inactivity_penalties)]
        return rewards, penalties

    def process_rewards_and_penalties(self, state) -> None:
        # no rewards in GENESIS_EPOCH (no previous epoch to attest to)
        if self.get_current_epoch(state) == self.GENESIS_EPOCH:
            return
        rewards, penalties = self.get_attestation_deltas(state)
        for index in range(len(state.validators)):
            self.increase_balance(state, index, rewards[index])
            self.decrease_balance(state, index, penalties[index])

    # -- registry & leftovers
    def process_registry_updates(self, state) -> None:
        # eligibility and ejections
        for index, validator in enumerate(state.validators):
            if self.is_eligible_for_activation_queue(validator):
                validator.activation_eligibility_epoch = uint64(
                    self.get_current_epoch(state) + 1)
            if (self.is_active_validator(validator,
                                         self.get_current_epoch(state))
                    and validator.effective_balance
                    <= self.config.EJECTION_BALANCE):
                self.initiate_validator_exit(state, index)

        # dequeue activations up to churn limit, ordered by eligibility epoch
        activation_queue = sorted(
            [index for index, validator in enumerate(state.validators)
             if self.is_eligible_for_activation(state, validator)],
            key=lambda index: (
                int(state.validators[index].activation_eligibility_epoch),
                index))
        for index in activation_queue[:self.get_validator_churn_limit(state)]:
            validator = state.validators[index]
            validator.activation_epoch = self.compute_activation_exit_epoch(
                self.get_current_epoch(state))

    def process_slashings(self, state) -> None:
        epoch = self.get_current_epoch(state)
        total_balance = self.get_total_active_balance(state)
        adjusted_total_slashing_balance = min(
            sum(int(x) for x in state.slashings)
            * self.proportional_slashing_multiplier(),
            int(total_balance))
        for index, validator in enumerate(state.validators):
            if (validator.slashed
                    and epoch + self.EPOCHS_PER_SLASHINGS_VECTOR // 2
                    == validator.withdrawable_epoch):
                increment = self.EFFECTIVE_BALANCE_INCREMENT
                penalty_numerator = (validator.effective_balance // increment
                                     * adjusted_total_slashing_balance)
                penalty = penalty_numerator // total_balance * increment
                self.decrease_balance(state, index, uint64(penalty))

    def proportional_slashing_multiplier(self) -> int:
        return self.PROPORTIONAL_SLASHING_MULTIPLIER

    def process_eth1_data_reset(self, state) -> None:
        next_epoch = uint64(self.get_current_epoch(state) + 1)
        if next_epoch % self.EPOCHS_PER_ETH1_VOTING_PERIOD == 0:
            state.eth1_data_votes = type(state.eth1_data_votes)()

    def process_effective_balance_updates(self, state) -> None:
        for index, validator in enumerate(state.validators):
            balance = state.balances[index]
            hysteresis_increment = uint64(
                self.EFFECTIVE_BALANCE_INCREMENT // self.HYSTERESIS_QUOTIENT)
            downward_threshold = uint64(
                hysteresis_increment * self.HYSTERESIS_DOWNWARD_MULTIPLIER)
            upward_threshold = uint64(
                hysteresis_increment * self.HYSTERESIS_UPWARD_MULTIPLIER)
            if (balance + downward_threshold < validator.effective_balance
                    or validator.effective_balance + upward_threshold
                    < balance):
                validator.effective_balance = uint64(min(
                    int(balance)
                    - int(balance) % self.EFFECTIVE_BALANCE_INCREMENT,
                    self.max_effective_balance_for_validator(validator)))

    def max_effective_balance_for_validator(self, validator) -> int:
        return self.MAX_EFFECTIVE_BALANCE

    def process_slashings_reset(self, state) -> None:
        next_epoch = uint64(self.get_current_epoch(state) + 1)
        state.slashings[next_epoch % self.EPOCHS_PER_SLASHINGS_VECTOR] = \
            uint64(0)

    def process_randao_mixes_reset(self, state) -> None:
        current_epoch = self.get_current_epoch(state)
        next_epoch = uint64(current_epoch + 1)
        state.randao_mixes[next_epoch % self.EPOCHS_PER_HISTORICAL_VECTOR] = \
            self.get_randao_mix(state, current_epoch)

    def process_historical_roots_update(self, state) -> None:
        next_epoch = uint64(self.get_current_epoch(state) + 1)
        if next_epoch % (self.SLOTS_PER_HISTORICAL_ROOT
                         // self.SLOTS_PER_EPOCH) == 0:
            historical_batch = self.HistoricalBatch(
                block_roots=list(state.block_roots),
                state_roots=list(state.state_roots))
            state.historical_roots.append(hash_tree_root(historical_batch))

    def process_participation_record_updates(self, state) -> None:
        state.previous_epoch_attestations = state.current_epoch_attestations
        state.current_epoch_attestations = \
            type(state.current_epoch_attestations)()

    # ------------------------------------------------------------------
    # block processing (beacon-chain.md "Block processing")
    # ------------------------------------------------------------------
    def process_block(self, state, block) -> None:
        self.process_block_header(state, block)
        self.process_randao(state, block.body)
        self.process_eth1_data(state, block.body)
        self.process_operations(state, block.body)

    def process_block_header(self, state, block) -> None:
        # slot/proposer/parent consistency
        assert block.slot == state.slot
        assert block.slot > state.latest_block_header.slot
        assert block.proposer_index == self.get_beacon_proposer_index(state)
        assert block.parent_root == hash_tree_root(state.latest_block_header)
        state.latest_block_header = self.BeaconBlockHeader(
            slot=block.slot,
            proposer_index=block.proposer_index,
            parent_root=block.parent_root,
            state_root=Bytes32(),  # overwritten next process_slot
            body_root=hash_tree_root(block.body))
        proposer = state.validators[block.proposer_index]
        assert not proposer.slashed

    def process_randao(self, state, body) -> None:
        epoch = self.get_current_epoch(state)
        proposer = state.validators[self.get_beacon_proposer_index(state)]
        signing_root = self.compute_signing_root(
            uint64(epoch), self.get_domain(state, self.DOMAIN_RANDAO))
        assert self.bls_verify(proposer.pubkey, signing_root,
                               body.randao_reveal)
        mix = xor(self.get_randao_mix(state, epoch),
                  self.hash(bytes(body.randao_reveal)))
        state.randao_mixes[epoch % self.EPOCHS_PER_HISTORICAL_VECTOR] = mix

    def process_eth1_data(self, state, body) -> None:
        state.eth1_data_votes.append(body.eth1_data)
        votes = [v for v in state.eth1_data_votes if v == body.eth1_data]
        if (len(votes) * 2 > self.EPOCHS_PER_ETH1_VOTING_PERIOD
                * self.SLOTS_PER_EPOCH):
            state.eth1_data = body.eth1_data

    def process_operations(self, state, body) -> None:
        # all outstanding deposits must be processed, up to the block cap
        assert len(body.deposits) == min(
            self.MAX_DEPOSITS,
            int(state.eth1_data.deposit_count - state.eth1_deposit_index))
        for operation in body.proposer_slashings:
            self.process_proposer_slashing(state, operation)
        for operation in body.attester_slashings:
            self.process_attester_slashing(state, operation)
        for operation in body.attestations:
            self.process_attestation(state, operation)
        for operation in body.deposits:
            self.process_deposit(state, operation)
        for operation in body.voluntary_exits:
            self.process_voluntary_exit(state, operation)

    def process_proposer_slashing(self, state, proposer_slashing) -> None:
        header_1 = proposer_slashing.signed_header_1.message
        header_2 = proposer_slashing.signed_header_2.message
        assert header_1.slot == header_2.slot
        assert header_1.proposer_index == header_2.proposer_index
        assert header_1 != header_2
        proposer = state.validators[header_1.proposer_index]
        assert self.is_slashable_validator(
            proposer, self.get_current_epoch(state))
        for signed_header in (proposer_slashing.signed_header_1,
                              proposer_slashing.signed_header_2):
            domain = self.get_domain(
                state, self.DOMAIN_BEACON_PROPOSER,
                self.compute_epoch_at_slot(signed_header.message.slot))
            signing_root = self.compute_signing_root(
                signed_header.message, domain)
            assert self.bls_verify(proposer.pubkey, signing_root,
                                   signed_header.signature)
        self.slash_validator(state, header_1.proposer_index)

    def process_attester_slashing(self, state, attester_slashing) -> None:
        attestation_1 = attester_slashing.attestation_1
        attestation_2 = attester_slashing.attestation_2
        assert self.is_slashable_attestation_data(
            attestation_1.data, attestation_2.data)
        assert self.is_valid_indexed_attestation(state, attestation_1)
        assert self.is_valid_indexed_attestation(state, attestation_2)

        slashed_any = False
        indices = set(int(i) for i in attestation_1.attesting_indices) \
            & set(int(i) for i in attestation_2.attesting_indices)
        for index in sorted(indices):
            if self.is_slashable_validator(state.validators[index],
                                           self.get_current_epoch(state)):
                self.slash_validator(state, index)
                slashed_any = True
        assert slashed_any

    def process_attestation(self, state, attestation) -> None:
        data = attestation.data
        assert data.target.epoch in (self.get_previous_epoch(state),
                                     self.get_current_epoch(state))
        assert data.target.epoch == self.compute_epoch_at_slot(data.slot)
        assert (data.slot + self.MIN_ATTESTATION_INCLUSION_DELAY
                <= state.slot <= data.slot + self.SLOTS_PER_EPOCH)
        assert data.index < self.get_committee_count_per_slot(
            state, data.target.epoch)

        committee = self.get_beacon_committee(state, data.slot, data.index)
        assert len(attestation.aggregation_bits) == len(committee)

        pending_attestation = self.PendingAttestation(
            data=data,
            aggregation_bits=list(attestation.aggregation_bits),
            inclusion_delay=uint64(state.slot - data.slot),
            proposer_index=self.get_beacon_proposer_index(state))

        if data.target.epoch == self.get_current_epoch(state):
            assert data.source == state.current_justified_checkpoint
            state.current_epoch_attestations.append(pending_attestation)
        else:
            assert data.source == state.previous_justified_checkpoint
            state.previous_epoch_attestations.append(pending_attestation)

        # committee signature
        assert self.is_valid_indexed_attestation(
            state, self.get_indexed_attestation(state, attestation))

    def get_validator_from_deposit(self, pubkey, withdrawal_credentials,
                                   amount):
        effective_balance = uint64(min(
            int(amount) - int(amount) % self.EFFECTIVE_BALANCE_INCREMENT,
            self.MAX_EFFECTIVE_BALANCE))
        return self.Validator(
            pubkey=pubkey,
            withdrawal_credentials=withdrawal_credentials,
            activation_eligibility_epoch=self.FAR_FUTURE_EPOCH,
            activation_epoch=self.FAR_FUTURE_EPOCH,
            exit_epoch=self.FAR_FUTURE_EPOCH,
            withdrawable_epoch=self.FAR_FUTURE_EPOCH,
            effective_balance=effective_balance)

    def add_validator_to_registry(self, state, pubkey,
                                  withdrawal_credentials, amount) -> None:
        state.validators.append(self.get_validator_from_deposit(
            pubkey, withdrawal_credentials, amount))
        state.balances.append(amount)

    def apply_deposit(self, state, pubkey, withdrawal_credentials, amount,
                      signature) -> None:
        validator_pubkeys = [v.pubkey for v in state.validators]
        if pubkey not in validator_pubkeys:
            # new validator: the deposit signature (proof of possession)
            # is verified against the *deposit* domain, not the state fork
            deposit_message = self.DepositMessage(
                pubkey=pubkey,
                withdrawal_credentials=withdrawal_credentials,
                amount=amount)
            domain = self.compute_domain(self.DOMAIN_DEPOSIT)
            signing_root = self.compute_signing_root(deposit_message, domain)
            if self.bls_verify(pubkey, signing_root, signature):
                self.add_validator_to_registry(
                    state, pubkey, withdrawal_credentials, amount)
        else:
            index = validator_pubkeys.index(pubkey)
            self.increase_balance(state, index, amount)

    def process_deposit(self, state, deposit) -> None:
        assert self.is_valid_merkle_branch(
            leaf=hash_tree_root(deposit.data),
            branch=deposit.proof,
            depth=self.DEPOSIT_CONTRACT_TREE_DEPTH + 1,  # +1 for length mix-in
            index=state.eth1_deposit_index,
            root=state.eth1_data.deposit_root)
        state.eth1_deposit_index = uint64(state.eth1_deposit_index + 1)
        self.apply_deposit(
            state,
            pubkey=deposit.data.pubkey,
            withdrawal_credentials=deposit.data.withdrawal_credentials,
            amount=deposit.data.amount,
            signature=deposit.data.signature)

    def process_voluntary_exit(self, state, signed_voluntary_exit) -> None:
        voluntary_exit = signed_voluntary_exit.message
        validator = state.validators[voluntary_exit.validator_index]
        assert self.is_active_validator(validator,
                                        self.get_current_epoch(state))
        assert self.get_current_epoch(state) >= voluntary_exit.epoch
        assert validator.exit_epoch == self.FAR_FUTURE_EPOCH
        assert (self.get_current_epoch(state) >= validator.activation_epoch
                + self.config.SHARD_COMMITTEE_PERIOD)
        domain = self.voluntary_exit_domain(state, voluntary_exit)
        signing_root = self.compute_signing_root(voluntary_exit, domain)
        assert self.bls_verify(validator.pubkey, signing_root,
                               signed_voluntary_exit.signature)
        self.initiate_validator_exit(state, voluntary_exit.validator_index)

    def voluntary_exit_domain(self, state, voluntary_exit):
        # deneb pins this to the capella fork version; phase0 uses the state
        return self.get_domain(state, self.DOMAIN_VOLUNTARY_EXIT,
                               voluntary_exit.epoch)
