"""Validator duties, weak subjectivity, and p2p helper functions.

From-scratch implementations of the executable parts of
/root/reference/specs/phase0/validator.md, weak-subjectivity.md, and
p2p-interface.md (compute_subscribed_subnets).  Mixed into Phase0Spec.
"""
from __future__ import annotations

from ..ssz import uint8, uint32, uint64, Bytes32, hash_tree_root, uint_to_bytes
from ..utils import bls

ETH_TO_GWEI = 10**9
SAFETY_DECAY = 10


class Phase0ValidatorDuties:

    # ------------------------------------------------------------------
    # validator.md
    # ------------------------------------------------------------------
    def check_if_validator_active(self, state, validator_index) -> bool:
        return self.is_active_validator(state.validators[validator_index],
                                        self.get_current_epoch(state))

    def get_committee_assignment(self, state, epoch, validator_index):
        """(committee, committee_index, slot) for the validator, or None."""
        next_epoch = uint64(self.get_current_epoch(state) + 1)
        assert epoch <= next_epoch
        start_slot = self.compute_start_slot_at_epoch(epoch)
        committee_count_per_slot = self.get_committee_count_per_slot(
            state, epoch)
        for slot in range(start_slot, start_slot + self.SLOTS_PER_EPOCH):
            for index in range(committee_count_per_slot):
                committee = self.get_beacon_committee(
                    state, uint64(slot), uint64(index))
                if validator_index in committee:
                    return committee, uint64(index), uint64(slot)
        return None

    def is_proposer(self, state, validator_index) -> bool:
        return self.get_beacon_proposer_index(state) == validator_index

    def get_epoch_signature(self, state, block, privkey):
        domain = self.get_domain(state, self.DOMAIN_RANDAO,
                                 self.compute_epoch_at_slot(block.slot))
        signing_root = self.compute_signing_root(
            uint64(self.compute_epoch_at_slot(block.slot)), domain)
        return bls.Sign(privkey, signing_root)

    def compute_time_at_slot(self, state, slot) -> int:
        return uint64(state.genesis_time
                      + slot * self.config.SECONDS_PER_SLOT)

    def voting_period_start_time(self, state) -> int:
        eth1_voting_period_start_slot = uint64(
            state.slot - state.slot % (self.EPOCHS_PER_ETH1_VOTING_PERIOD
                                       * self.SLOTS_PER_EPOCH))
        return self.compute_time_at_slot(state,
                                         eth1_voting_period_start_slot)

    def is_candidate_block(self, block, period_start) -> bool:
        follow = self.config.SECONDS_PER_ETH1_BLOCK \
            * self.config.ETH1_FOLLOW_DISTANCE
        return (block.timestamp + follow <= period_start
                and block.timestamp + follow * 2 >= period_start)

    def get_eth1_data(self, block):
        """Stub eth1-chain accessor (tests inject block.deposit_* directly)."""
        return self.Eth1Data(deposit_root=block.deposit_root,
                             deposit_count=block.deposit_count,
                             block_hash=hash_tree_root(block))

    def get_eth1_vote(self, state, eth1_chain):
        period_start = self.voting_period_start_time(state)
        votes_to_consider = [
            self.get_eth1_data(block) for block in eth1_chain
            if (self.is_candidate_block(block, period_start)
                and self.get_eth1_data(block).deposit_count
                >= state.eth1_data.deposit_count)]
        valid_votes = [vote for vote in state.eth1_data_votes
                       if vote in votes_to_consider]
        # default: smallest-distance candidate, else current eth1_data
        default_vote = (votes_to_consider[len(votes_to_consider) - 1]
                        if any(votes_to_consider) else state.eth1_data)
        return max(
            valid_votes,
            key=lambda v: (valid_votes.count(v),
                           -valid_votes.index(v)),  # earliest wins ties
            default=default_vote)

    def compute_new_state_root(self, state, block):
        temp_state = state.copy()
        signed_block = self.SignedBeaconBlock(message=block)
        self.state_transition(temp_state, signed_block,
                              validate_result=False)
        return hash_tree_root(temp_state)

    def get_block_signature(self, state, block, privkey):
        domain = self.get_domain(state, self.DOMAIN_BEACON_PROPOSER,
                                 self.compute_epoch_at_slot(block.slot))
        return bls.Sign(privkey,
                        self.compute_signing_root(block, domain))

    def get_attestation_signature(self, state, attestation_data, privkey):
        domain = self.get_domain(state, self.DOMAIN_BEACON_ATTESTER,
                                 attestation_data.target.epoch)
        return bls.Sign(privkey, self.compute_signing_root(
            attestation_data, domain))

    def compute_subnet_for_attestation(self, committees_per_slot, slot,
                                       committee_index) -> int:
        slots_since_epoch_start = uint64(slot % self.SLOTS_PER_EPOCH)
        committees_since_epoch_start = \
            committees_per_slot * slots_since_epoch_start
        return uint64((committees_since_epoch_start + committee_index)
                      % self.ATTESTATION_SUBNET_COUNT)

    def get_slot_signature(self, state, slot, privkey):
        domain = self.get_domain(state, self.DOMAIN_SELECTION_PROOF,
                                 self.compute_epoch_at_slot(slot))
        return bls.Sign(privkey,
                        self.compute_signing_root(uint64(slot), domain))

    def is_aggregator(self, state, slot, index, slot_signature) -> bool:
        committee = self.get_beacon_committee(state, slot, index)
        modulo = max(1, len(committee)
                     // self.TARGET_AGGREGATORS_PER_COMMITTEE)
        from .phase0 import bytes_to_uint64
        return bytes_to_uint64(
            self.hash(bytes(slot_signature))[0:8]) % modulo == 0

    def get_aggregate_signature(self, attestations):
        return bls.Aggregate([a.signature for a in attestations])

    def get_aggregate_and_proof(self, state, aggregator_index, aggregate,
                                privkey):
        return self.AggregateAndProof(
            aggregator_index=aggregator_index,
            aggregate=aggregate,
            selection_proof=self.get_slot_signature(
                state, aggregate.data.slot, privkey))

    def get_aggregate_and_proof_signature(self, state, aggregate_and_proof,
                                          privkey):
        aggregate = aggregate_and_proof.aggregate
        domain = self.get_domain(
            state, self.DOMAIN_AGGREGATE_AND_PROOF,
            self.compute_epoch_at_slot(aggregate.data.slot))
        return bls.Sign(privkey, self.compute_signing_root(
            aggregate_and_proof, domain))

    # ------------------------------------------------------------------
    # weak-subjectivity.md
    # ------------------------------------------------------------------
    def compute_weak_subjectivity_period(self, state) -> int:
        ws_period = int(self.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY)
        n = len(self.get_active_validator_indices(
            state, self.get_current_epoch(state)))
        t = int(self.get_total_active_balance(state)) // n // ETH_TO_GWEI
        T = self.MAX_EFFECTIVE_BALANCE // ETH_TO_GWEI
        delta = int(self.get_validator_churn_limit(state))
        Delta = self.MAX_DEPOSITS * self.SLOTS_PER_EPOCH
        D = SAFETY_DECAY
        if T * (200 + 3 * D) < t * (200 + 12 * D):
            epochs_for_validator_set_churn = (
                n * (t * (200 + 12 * D) - T * (200 + 3 * D))
                // (600 * delta * (2 * t + T)))
            epochs_for_balance_top_ups = (
                n * (200 + 3 * D) // (600 * Delta))
            ws_period += max(epochs_for_validator_set_churn,
                             epochs_for_balance_top_ups)
        else:
            ws_period += 3 * n * D * t // (200 * Delta * (T - t))
        return uint64(ws_period)

    def is_within_weak_subjectivity_period(self, store, ws_state,
                                           ws_checkpoint) -> bool:
        assert ws_state.latest_block_header.state_root == ws_checkpoint.root
        assert self.compute_epoch_at_slot(ws_state.slot) \
            == ws_checkpoint.epoch
        ws_period = self.compute_weak_subjectivity_period(ws_state)
        ws_state_epoch = self.compute_epoch_at_slot(ws_state.slot)
        current_epoch = self.compute_epoch_at_slot(
            self.get_current_slot(store))
        return current_epoch <= ws_state_epoch + ws_period

    # ------------------------------------------------------------------
    # p2p-interface.md (executable helpers)
    # ------------------------------------------------------------------
    ATTESTATION_SUBNET_EXTRA_BITS = 0

    @property
    def ATTESTATION_SUBNET_PREFIX_BITS(self) -> int:
        return (self.ATTESTATION_SUBNET_COUNT - 1).bit_length() \
            + self.ATTESTATION_SUBNET_EXTRA_BITS

    def compute_subscribed_subnet(self, node_id, epoch, index) -> int:
        node_id_prefix = int(node_id) >> (self.NODE_ID_BITS
                                          - self.ATTESTATION_SUBNET_PREFIX_BITS)
        node_offset = int(node_id) % self.EPOCHS_PER_SUBNET_SUBSCRIPTION
        permutation_seed = self.hash(uint_to_bytes(uint64(
            (int(epoch) + node_offset)
            // self.EPOCHS_PER_SUBNET_SUBSCRIPTION)))
        permutated_prefix = self.compute_shuffled_index(
            node_id_prefix, 1 << self.ATTESTATION_SUBNET_PREFIX_BITS,
            permutation_seed)
        return uint64((permutated_prefix + index)
                      % self.ATTESTATION_SUBNET_COUNT)

    def compute_subscribed_subnets(self, node_id, epoch):
        return [self.compute_subscribed_subnet(node_id, epoch, index)
                for index in range(self.SUBNETS_PER_NODE)]
