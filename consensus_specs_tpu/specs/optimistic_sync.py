"""Optimistic sync (merge-era partial sync without execution verification).

From-scratch implementation of the reference's /root/reference/sync/optimistic.md:
OptimisticStore, candidate rules (is_optimistic_candidate_block), the
NOT_VALIDATED -> VALID / INVALIDATED retrospective transitions (with
ancestor/descendant propagation), latestValidHash invalidation rules, and
the optimistic fork-choice filter (INVALIDATED weight exclusion).

Mixed into BellatrixSpec and later forks; the payload-status plumbing is a
small state machine over block roots, so it is host-side Python (no TPU
compute lives here — the heavy work stays in state_transition).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, Set

from ..ssz import Bytes32, hash_tree_root


class PayloadStatus(Enum):
    """Collapsed PayloadStatusV1 statuses (optimistic.md "Helpers")."""
    VALID = "VALID"
    NOT_VALIDATED = "NOT_VALIDATED"   # SYNCING | ACCEPTED
    INVALIDATED = "INVALIDATED"       # INVALID | INVALID_BLOCK_HASH


@dataclass
class OptimisticStore:
    optimistic_roots: Set[bytes] = field(default_factory=set)
    head_block_root: bytes = b"\x00" * 32
    blocks: Dict[bytes, object] = field(default_factory=dict)
    block_states: Dict[bytes, object] = field(default_factory=dict)
    invalidated_roots: Set[bytes] = field(default_factory=set)


class OptimisticSync:
    """Mixin providing the optimistic-sync mechanics."""

    SAFE_SLOTS_TO_IMPORT_OPTIMISTICALLY = 128

    OptimisticStore = OptimisticStore
    PayloadStatus = PayloadStatus

    # ------------------------------------------------------------------
    # helpers (optimistic.md "Helpers")
    # ------------------------------------------------------------------
    def get_optimistic_store(self, anchor_state, anchor_block) -> OptimisticStore:
        anchor_root = hash_tree_root(anchor_block)
        return OptimisticStore(
            optimistic_roots=set(),
            head_block_root=anchor_root,
            blocks={anchor_root: anchor_block.copy()},
            block_states={anchor_root: anchor_state.copy()},
        )

    def is_optimistic(self, opt_store: OptimisticStore, block) -> bool:
        return bytes(hash_tree_root(block)) in opt_store.optimistic_roots

    def latest_verified_ancestor(self, opt_store: OptimisticStore, block):
        # caller guarantees `block` is never INVALIDATED
        while True:
            if (not self.is_optimistic(opt_store, block)
                    or block.parent_root == Bytes32()):
                return block
            block = opt_store.blocks[bytes(block.parent_root)]

    def is_execution_block(self, block) -> bool:
        return block.body.execution_payload != self.ExecutionPayload()

    def is_optimistic_candidate_block(self, opt_store: OptimisticStore,
                                      current_slot, block) -> bool:
        if self.is_execution_block(opt_store.blocks[bytes(block.parent_root)]):
            return True
        if block.slot + self.SAFE_SLOTS_TO_IMPORT_OPTIMISTICALLY \
                <= current_slot:
            return True
        return False

    # ------------------------------------------------------------------
    # import path (optimistic.md "How to optimistically import blocks")
    # ------------------------------------------------------------------
    def optimistically_import_block(self, opt_store: OptimisticStore,
                                    current_slot, signed_block,
                                    payload_status: PayloadStatus,
                                    post_state=None) -> None:
        """Import one block given the engine's payload status.

        INVALIDATED responses are rejected outright; NOT_VALIDATED imports
        record the root as optimistic; a VALID import is final immediately
        and (per optimistic.md) also validates every NOT_VALIDATED ancestor.
        """
        block = signed_block.message
        if payload_status is PayloadStatus.INVALIDATED:
            raise AssertionError("INVALIDATED payload must not be imported")
        parent_root = bytes(block.parent_root)
        assert parent_root not in opt_store.invalidated_roots, \
            "parent has an INVALIDATED payload"
        if payload_status is PayloadStatus.NOT_VALIDATED:
            assert self.is_optimistic_candidate_block(
                opt_store, current_slot, block)
        block_root = bytes(hash_tree_root(block))
        opt_store.blocks[block_root] = block.copy()
        if post_state is not None:
            opt_store.block_states[block_root] = post_state.copy()
        if payload_status is PayloadStatus.NOT_VALIDATED:
            opt_store.optimistic_roots.add(block_root)
        else:  # VALID: ancestors transition NOT_VALIDATED -> VALID too
            self.validate_optimistic_block(opt_store, block_root)

    # ------------------------------------------------------------------
    # retrospective transitions
    # ------------------------------------------------------------------
    def _descendants(self, opt_store: OptimisticStore, root: bytes):
        children: Dict[bytes, list] = {}
        for r, b in opt_store.blocks.items():
            children.setdefault(bytes(b.parent_root), []).append(bytes(r))
        out = []
        frontier = [root]
        while frontier:
            kids = children.get(frontier.pop(), ())
            out.extend(kids)
            frontier.extend(kids)
        return out

    def validate_optimistic_block(self, opt_store: OptimisticStore,
                                  block_root: bytes) -> None:
        """NOT_VALIDATED -> VALID: the block and all its ancestors leave
        the optimistic set."""
        block_root = bytes(block_root)
        assert block_root not in opt_store.invalidated_roots
        block = opt_store.blocks[block_root]
        while True:
            opt_store.optimistic_roots.discard(
                bytes(hash_tree_root(block)))
            parent = bytes(block.parent_root)
            if parent not in opt_store.blocks:
                return
            block = opt_store.blocks[parent]

    def invalidate_optimistic_block(self, opt_store: OptimisticStore,
                                    block_root: bytes) -> None:
        """NOT_VALIDATED -> INVALIDATED: the block and all its descendants
        are invalidated and removed from the optimistic set.

        A VALID -> INVALIDATED transition is impossible per optimistic.md
        ("Transitioning from VALID -> INVALIDATED"): seeing one means the
        execution engine contradicted itself, which is surfaced as a hard
        error rather than applied silently.
        """
        block_root = bytes(block_root)
        for root in [block_root] + self._descendants(opt_store, block_root):
            if (root not in opt_store.optimistic_roots
                    and root not in opt_store.invalidated_roots):
                raise RuntimeError(
                    "execution engine inconsistency: VALID block "
                    f"{root.hex()} reported INVALIDATED")
            opt_store.optimistic_roots.discard(root)
            opt_store.invalidated_roots.add(root)

    def process_invalid_payload_response(self, opt_store: OptimisticStore,
                                         block_root: bytes,
                                         latest_valid_hash) -> None:
        """Apply latestValidHash semantics (optimistic.md table):

        - meaningful hash -> invalidate the *child* of the block whose
          payload has that hash, in the chain containing `block_root`
        - all-zero hash   -> invalidate from the first execution block
        - None            -> invalidate `block_root` itself
        Unknown meaningful hashes degrade to the None behaviour.
        """
        block_root = bytes(block_root)
        chain = [block_root]  # ancestors from block_root to anchor
        b = opt_store.blocks[block_root]
        while bytes(b.parent_root) in opt_store.blocks:
            chain.append(bytes(b.parent_root))
            b = opt_store.blocks[bytes(b.parent_root)]

        invalid_root = block_root
        if latest_valid_hash is None:
            pass
        elif bytes(latest_valid_hash) == bytes(Bytes32()):
            # earliest NOT_VALIDATED execution block in the chain (searched
            # root-ward).  VALID ancestors — e.g. a post-merge checkpoint
            # anchor — are certified already and cannot be invalidated.
            for root in reversed(chain):
                if (root in opt_store.optimistic_roots
                        and self.is_execution_block(opt_store.blocks[root])):
                    invalid_root = root
                    break
        else:
            # child of the block carrying latestValidHash; the carrying
            # block itself is thereby certified VALID along with its
            # ancestors (engine says it is the latest *valid* payload)
            for child, parent in zip(chain[:-1], chain[1:]):
                payload = opt_store.blocks[parent].body.execution_payload
                if bytes(payload.block_hash) == bytes(latest_valid_hash):
                    invalid_root = child
                    self.validate_optimistic_block(opt_store, parent)
                    break
        self.invalidate_optimistic_block(opt_store, invalid_root)

    # ------------------------------------------------------------------
    # fork-choice interaction
    # ------------------------------------------------------------------
    def get_optimistic_head(self, opt_store: OptimisticStore, store):
        """Fork choice with INVALIDATED blocks removed (optimistic.md "Fork
        Choice"): invalidated blocks are pruned from the block tree and the
        votes cast for them carry no weight, so the heaviest *valid* branch
        wins — not merely the nearest valid ancestor of the poisoned head.
        """
        invalid = opt_store.invalidated_roots
        if not invalid:
            head = self.get_head(store)
        else:
            # rebuilt only when the store or invalidated set changed since
            # the last call; afterwards the pruned view is reused
            key = (len(invalid), len(store.blocks),
                   len(store.latest_messages),
                   bytes(store.proposer_boost_root))
            cached = getattr(opt_store, "_pruned_cache", None)
            if cached is not None and cached[0] == key:
                pruned = cached[1]
            else:
                from dataclasses import replace
                pruned = replace(
                    store,
                    blocks={r: b for r, b in store.blocks.items()
                            if bytes(r) not in invalid},
                    block_states={r: s for r, s in store.block_states.items()
                                  if bytes(r) not in invalid},
                    latest_messages={
                        i: m for i, m in store.latest_messages.items()
                        if bytes(m.root) not in invalid},
                    proposer_boost_root=(
                        Bytes32()
                        if bytes(store.proposer_boost_root) in invalid
                        else store.proposer_boost_root),
                )
                opt_store._pruned_cache = (key, pruned)
            head = self.get_head(pruned)
        # eip7732's fork choice returns a (root, slot, payload) node;
        # unwrap to the root every other consumer expects
        head = getattr(head, "root", head)
        opt_store.head_block_root = bytes(head)
        return head

    def is_optimistic_node(self, opt_store: OptimisticStore, head) -> bool:
        return self.is_optimistic(opt_store, opt_store.blocks[bytes(head)]) \
            if bytes(head) in opt_store.blocks else False
