"""Light-client sync protocol (altair + capella/deneb/electra upgrades).

From-scratch implementation of
/root/reference/specs/altair/light-client/{sync-protocol.md,full-node.md}
with the capella execution-header extension
(specs/capella/light-client/sync-protocol.md), the deneb blob-field rules
(specs/deneb/light-client/sync-protocol.md) and the electra generalized-
index migration (specs/electra/light-client/sync-protocol.md).

Mixed into AltairSpec so every post-altair spec instance carries the
protocol; container shapes and generalized indices adapt per fork.

NOTE: SSZ Container fields are live class annotations (no PEP 563 here).
"""
from dataclasses import dataclass, field
from typing import Optional

from ..ssz import (
    Container, Vector, Bytes32, Bytes96, hash_tree_root, uint64,
)
from ..ssz.merkle import is_valid_merkle_branch
from ..ssz.proofs import compute_merkle_proof, get_generalized_index


def floorlog2(x: int) -> int:
    assert x > 0
    return int(x).bit_length() - 1


@dataclass
class LightClientStore:
    """altair/light-client/sync-protocol.md:157"""
    finalized_header: object
    current_sync_committee: object
    next_sync_committee: object
    best_valid_update: Optional[object]
    optimistic_header: object
    previous_max_active_participants: int
    current_max_active_participants: int


@dataclass
class LightClientDataStore:
    """Server-side LC data collection: bootstraps by finalized block
    root, the best update per sync-committee period, and the latest
    finality/optimistic updates."""
    bootstraps: dict = field(default_factory=dict)
    best_updates: dict = field(default_factory=dict)
    latest_finality_update: object = None
    latest_optimistic_update: object = None


class LightClientMixin:
    # frozen pre-electra constants (sync-protocol.md:72-78; electra
    # sync-protocol.md "Frozen constants")
    FINALIZED_ROOT_GINDEX = 105
    CURRENT_SYNC_COMMITTEE_GINDEX = 54
    NEXT_SYNC_COMMITTEE_GINDEX = 55

    # ------------------------------------------------------------------
    # fork-dependent generalized indices
    # ------------------------------------------------------------------
    def _own_state_gindex(self, *path) -> int:
        key = ("lc_state_gindex", path)
        return self._cached(key, lambda: get_generalized_index(
            self.BeaconState, *path))

    def execution_payload_gindex(self) -> int:
        """capella/light-client/sync-protocol.md EXECUTION_PAYLOAD_GINDEX
        (= 25)."""
        return self._cached(
            ("lc_exec_gindex",),
            lambda: get_generalized_index(self.BeaconBlockBody,
                                          "execution_payload"))

    def latest_finalized_root_gindex(self) -> int:
        """This fork's own gindex (electra sync-protocol.md
        *_GINDEX_ELECTRA; frozen constants before)."""
        if self.is_post("electra"):
            return self._own_state_gindex("finalized_checkpoint", "root")
        return self.FINALIZED_ROOT_GINDEX

    def latest_current_sync_committee_gindex(self) -> int:
        if self.is_post("electra"):
            return self._own_state_gindex("current_sync_committee")
        return self.CURRENT_SYNC_COMMITTEE_GINDEX

    def latest_next_sync_committee_gindex(self) -> int:
        if self.is_post("electra"):
            return self._own_state_gindex("next_sync_committee")
        return self.NEXT_SYNC_COMMITTEE_GINDEX

    def finalized_root_gindex_at_slot(self, slot) -> int:
        epoch = self.compute_epoch_at_slot(slot)
        if self.is_post("electra") and \
                epoch >= self.config.ELECTRA_FORK_EPOCH:
            return self._own_state_gindex("finalized_checkpoint", "root")
        return self.FINALIZED_ROOT_GINDEX

    def current_sync_committee_gindex_at_slot(self, slot) -> int:
        epoch = self.compute_epoch_at_slot(slot)
        if self.is_post("electra") and \
                epoch >= self.config.ELECTRA_FORK_EPOCH:
            return self._own_state_gindex("current_sync_committee")
        return self.CURRENT_SYNC_COMMITTEE_GINDEX

    def next_sync_committee_gindex_at_slot(self, slot) -> int:
        epoch = self.compute_epoch_at_slot(slot)
        if self.is_post("electra") and \
                epoch >= self.config.ELECTRA_FORK_EPOCH:
            return self._own_state_gindex("next_sync_committee")
        return self.NEXT_SYNC_COMMITTEE_GINDEX

    # ------------------------------------------------------------------
    # container types (built lazily; shapes depend on the spec's fork)
    # ------------------------------------------------------------------
    def _lc(self) -> dict:
        def build():
            p = self
            fin_len = floorlog2(self.latest_finalized_root_gindex())
            csc_len = floorlog2(
                self.latest_current_sync_committee_gindex())
            nsc_len = floorlog2(self.latest_next_sync_committee_gindex())

            if self.is_post("capella"):
                exec_len = floorlog2(self.execution_payload_gindex())

                class LightClientHeader(Container):
                    beacon: p.BeaconBlockHeader
                    execution: p.ExecutionPayloadHeader
                    execution_branch: Vector[Bytes32, exec_len]
            else:
                class LightClientHeader(Container):
                    beacon: p.BeaconBlockHeader

            class LightClientBootstrap(Container):
                header: LightClientHeader
                current_sync_committee: p.SyncCommittee
                current_sync_committee_branch: Vector[Bytes32, csc_len]

            class LightClientUpdate(Container):
                attested_header: LightClientHeader
                next_sync_committee: p.SyncCommittee
                next_sync_committee_branch: Vector[Bytes32, nsc_len]
                finalized_header: LightClientHeader
                finality_branch: Vector[Bytes32, fin_len]
                sync_aggregate: p.SyncAggregate
                signature_slot: uint64

            class LightClientFinalityUpdate(Container):
                attested_header: LightClientHeader
                finalized_header: LightClientHeader
                finality_branch: Vector[Bytes32, fin_len]
                sync_aggregate: p.SyncAggregate
                signature_slot: uint64

            class LightClientOptimisticUpdate(Container):
                attested_header: LightClientHeader
                sync_aggregate: p.SyncAggregate
                signature_slot: uint64

            types = {
                "LightClientHeader": LightClientHeader,
                "LightClientBootstrap": LightClientBootstrap,
                "LightClientUpdate": LightClientUpdate,
                "LightClientFinalityUpdate": LightClientFinalityUpdate,
                "LightClientOptimisticUpdate": LightClientOptimisticUpdate,
            }
            for name, cls in types.items():
                setattr(self, name, cls)
            return types
        return self._cached(("lc_types",), build)

    # ------------------------------------------------------------------
    # header validity (capella/deneb/electra deltas folded in)
    # ------------------------------------------------------------------
    def get_lc_execution_root(self, header):
        """capella/light-client/sync-protocol.md get_lc_execution_root,
        with the electra-era historical dispatch."""
        if not self.is_post("capella"):
            return Bytes32()
        epoch = self.compute_epoch_at_slot(header.beacon.slot)
        if epoch < self.config.CAPELLA_FORK_EPOCH:
            return Bytes32()
        if self.is_post("deneb") and \
                epoch < self.config.DENEB_FORK_EPOCH:
            # historical capella-era header: hash with the capella shape
            from . import get_spec
            capella_type = get_spec(
                "capella", self.preset_name).ExecutionPayloadHeader
            fields = {name: getattr(header.execution, name)
                      for name in capella_type.fields()}
            return hash_tree_root(capella_type(**fields))
        return hash_tree_root(header.execution)

    def is_valid_light_client_header(self, header) -> bool:
        if not self.is_post("capella"):
            return True
        epoch = self.compute_epoch_at_slot(header.beacon.slot)
        if epoch < self.config.CAPELLA_FORK_EPOCH:
            return (header.execution == self.ExecutionPayloadHeader()
                    and header.execution_branch == type(
                        header.execution_branch)())
        if self.is_post("deneb") and epoch < self.config.DENEB_FORK_EPOCH:
            # deneb LC: blob-gas fields must be zero before deneb
            if (header.execution.blob_gas_used != 0
                    or header.execution.excess_blob_gas != 0):
                return False
        gindex = self.execution_payload_gindex()
        return is_valid_merkle_branch(
            bytes(self.get_lc_execution_root(header)),
            [bytes(b) for b in header.execution_branch],
            floorlog2(gindex),
            gindex % 2**floorlog2(gindex),
            bytes(header.beacon.body_root))

    # ------------------------------------------------------------------
    # predicates & small helpers (sync-protocol.md:210-325)
    # ------------------------------------------------------------------
    def is_sync_committee_update(self, update) -> bool:
        return update.next_sync_committee_branch != \
            type(update.next_sync_committee_branch)()

    def is_finality_update(self, update) -> bool:
        return update.finality_branch != type(update.finality_branch)()

    def is_next_sync_committee_known(self, store) -> bool:
        return store.next_sync_committee != self.SyncCommittee()

    def get_safety_threshold(self, store) -> int:
        return max(store.previous_max_active_participants,
                   store.current_max_active_participants) // 2

    def is_valid_normalized_merkle_branch(self, leaf, branch, gindex,
                                          root) -> bool:
        depth = floorlog2(gindex)
        index = gindex % 2**depth
        num_extra = len(branch) - depth
        for i in range(num_extra):
            if bytes(branch[i]) != bytes(32):
                return False
        return is_valid_merkle_branch(
            bytes(leaf), [bytes(b) for b in branch[num_extra:]],
            depth, index, bytes(root))

    def compute_sync_committee_period_at_slot(self, slot) -> int:
        return self.compute_sync_committee_period(
            self.compute_epoch_at_slot(slot))

    def is_better_update(self, new_update, old_update) -> bool:
        """Update preference order (sync-protocol.md:227)."""
        max_active_participants = len(
            new_update.sync_aggregate.sync_committee_bits)
        new_num = sum(bool(b) for b in
                      new_update.sync_aggregate.sync_committee_bits)
        old_num = sum(bool(b) for b in
                      old_update.sync_aggregate.sync_committee_bits)
        new_has_supermajority = new_num * 3 >= max_active_participants * 2
        old_has_supermajority = old_num * 3 >= max_active_participants * 2
        if new_has_supermajority != old_has_supermajority:
            return new_has_supermajority
        if not new_has_supermajority and new_num != old_num:
            return new_num > old_num

        period = self.compute_sync_committee_period_at_slot
        new_has_relevant = self.is_sync_committee_update(new_update) and (
            period(new_update.attested_header.beacon.slot)
            == period(new_update.signature_slot))
        old_has_relevant = self.is_sync_committee_update(old_update) and (
            period(old_update.attested_header.beacon.slot)
            == period(old_update.signature_slot))
        if new_has_relevant != old_has_relevant:
            return new_has_relevant

        new_has_finality = self.is_finality_update(new_update)
        old_has_finality = self.is_finality_update(old_update)
        if new_has_finality != old_has_finality:
            return new_has_finality

        if new_has_finality:
            new_sc_finality = (
                period(new_update.finalized_header.beacon.slot)
                == period(new_update.attested_header.beacon.slot))
            old_sc_finality = (
                period(old_update.finalized_header.beacon.slot)
                == period(old_update.attested_header.beacon.slot))
            if new_sc_finality != old_sc_finality:
                return new_sc_finality

        if new_num != old_num:
            return new_num > old_num

        if new_update.attested_header.beacon.slot \
                != old_update.attested_header.beacon.slot:
            return new_update.attested_header.beacon.slot \
                < old_update.attested_header.beacon.slot
        return new_update.signature_slot < old_update.signature_slot

    # ------------------------------------------------------------------
    # initialization (sync-protocol.md:334)
    # ------------------------------------------------------------------
    def initialize_light_client_store(self, trusted_block_root,
                                      bootstrap) -> LightClientStore:
        self._lc()
        assert self.is_valid_light_client_header(bootstrap.header)
        assert hash_tree_root(bootstrap.header.beacon) == trusted_block_root
        assert self.is_valid_normalized_merkle_branch(
            hash_tree_root(bootstrap.current_sync_committee),
            bootstrap.current_sync_committee_branch,
            self.current_sync_committee_gindex_at_slot(
                bootstrap.header.beacon.slot),
            bootstrap.header.beacon.state_root)
        return LightClientStore(
            finalized_header=bootstrap.header,
            current_sync_committee=bootstrap.current_sync_committee,
            next_sync_committee=self.SyncCommittee(),
            best_valid_update=None,
            optimistic_header=bootstrap.header,
            previous_max_active_participants=0,
            current_max_active_participants=0)

    # ------------------------------------------------------------------
    # update validation / application (sync-protocol.md:368-533)
    # ------------------------------------------------------------------
    def validate_light_client_update(self, store, update, current_slot,
                                     genesis_validators_root) -> None:
        sync_aggregate = update.sync_aggregate
        assert sum(bool(b) for b in sync_aggregate.sync_committee_bits) \
            >= self.MIN_SYNC_COMMITTEE_PARTICIPANTS

        assert self.is_valid_light_client_header(update.attested_header)
        update_attested_slot = update.attested_header.beacon.slot
        update_finalized_slot = update.finalized_header.beacon.slot
        assert (current_slot >= update.signature_slot
                > update_attested_slot >= update_finalized_slot)
        store_period = self.compute_sync_committee_period_at_slot(
            store.finalized_header.beacon.slot)
        update_signature_period = \
            self.compute_sync_committee_period_at_slot(
                update.signature_slot)
        if self.is_next_sync_committee_known(store):
            assert update_signature_period in (store_period,
                                               store_period + 1)
        else:
            assert update_signature_period == store_period

        update_attested_period = \
            self.compute_sync_committee_period_at_slot(update_attested_slot)
        update_has_next_sync_committee = (
            not self.is_next_sync_committee_known(store)
            and self.is_sync_committee_update(update)
            and update_attested_period == store_period)
        assert (update_attested_slot > store.finalized_header.beacon.slot
                or update_has_next_sync_committee)

        if not self.is_finality_update(update):
            assert update.finalized_header == self.LightClientHeader()
        else:
            if update_finalized_slot == self.GENESIS_SLOT:
                assert update.finalized_header == self.LightClientHeader()
                finalized_root = Bytes32()
            else:
                assert self.is_valid_light_client_header(
                    update.finalized_header)
                finalized_root = hash_tree_root(
                    update.finalized_header.beacon)
            assert self.is_valid_normalized_merkle_branch(
                finalized_root,
                update.finality_branch,
                self.finalized_root_gindex_at_slot(update_attested_slot),
                update.attested_header.beacon.state_root)

        if not self.is_sync_committee_update(update):
            assert update.next_sync_committee == self.SyncCommittee()
        else:
            if update_attested_period == store_period and \
                    self.is_next_sync_committee_known(store):
                assert update.next_sync_committee == \
                    store.next_sync_committee
            assert self.is_valid_normalized_merkle_branch(
                hash_tree_root(update.next_sync_committee),
                update.next_sync_committee_branch,
                self.next_sync_committee_gindex_at_slot(
                    update_attested_slot),
                update.attested_header.beacon.state_root)

        if update_signature_period == store_period:
            sync_committee = store.current_sync_committee
        else:
            sync_committee = store.next_sync_committee
        participant_pubkeys = [
            pubkey for (bit, pubkey)
            in zip(sync_aggregate.sync_committee_bits,
                   sync_committee.pubkeys) if bit]
        fork_version_slot = uint64(max(int(update.signature_slot), 1) - 1)
        fork_version = self.compute_fork_version(
            self.compute_epoch_at_slot(fork_version_slot))
        domain = self.compute_domain(self.DOMAIN_SYNC_COMMITTEE,
                                     fork_version, genesis_validators_root)
        signing_root = self.compute_signing_root(
            update.attested_header.beacon, domain)
        assert self.bls_fast_aggregate_verify(
            participant_pubkeys, signing_root,
            sync_aggregate.sync_committee_signature)

    def apply_light_client_update(self, store, update) -> None:
        store_period = self.compute_sync_committee_period_at_slot(
            store.finalized_header.beacon.slot)
        update_finalized_period = \
            self.compute_sync_committee_period_at_slot(
                update.finalized_header.beacon.slot)
        if not self.is_next_sync_committee_known(store):
            assert update_finalized_period == store_period
            store.next_sync_committee = update.next_sync_committee
        elif update_finalized_period == store_period + 1:
            store.current_sync_committee = store.next_sync_committee
            store.next_sync_committee = update.next_sync_committee
            store.previous_max_active_participants = \
                store.current_max_active_participants
            store.current_max_active_participants = 0
        if update.finalized_header.beacon.slot \
                > store.finalized_header.beacon.slot:
            store.finalized_header = update.finalized_header
            if store.finalized_header.beacon.slot \
                    > store.optimistic_header.beacon.slot:
                store.optimistic_header = store.finalized_header

    def process_light_client_store_force_update(self, store,
                                                current_slot) -> None:
        if (current_slot > store.finalized_header.beacon.slot
                + self.UPDATE_TIMEOUT
                and store.best_valid_update is not None):
            if store.best_valid_update.finalized_header.beacon.slot \
                    <= store.finalized_header.beacon.slot:
                store.best_valid_update.finalized_header = \
                    store.best_valid_update.attested_header
            self.apply_light_client_update(store,
                                           store.best_valid_update)
            store.best_valid_update = None

    def process_light_client_update(self, store, update, current_slot,
                                    genesis_validators_root) -> None:
        self.validate_light_client_update(
            store, update, current_slot, genesis_validators_root)

        sync_committee_bits = update.sync_aggregate.sync_committee_bits
        num_participants = sum(bool(b) for b in sync_committee_bits)

        if (store.best_valid_update is None
                or self.is_better_update(update,
                                         store.best_valid_update)):
            store.best_valid_update = update

        store.current_max_active_participants = max(
            store.current_max_active_participants, num_participants)

        if (num_participants > self.get_safety_threshold(store)
                and update.attested_header.beacon.slot
                > store.optimistic_header.beacon.slot):
            store.optimistic_header = update.attested_header

        update_has_finalized_next_sync_committee = (
            not self.is_next_sync_committee_known(store)
            and self.is_sync_committee_update(update)
            and self.is_finality_update(update)
            and (self.compute_sync_committee_period_at_slot(
                    update.finalized_header.beacon.slot)
                 == self.compute_sync_committee_period_at_slot(
                    update.attested_header.beacon.slot)))
        if (num_participants * 3 >= len(sync_committee_bits) * 2
                and (update.finalized_header.beacon.slot
                     > store.finalized_header.beacon.slot
                     or update_has_finalized_next_sync_committee)):
            self.apply_light_client_update(store, update)
            store.best_valid_update = None

    def process_light_client_finality_update(
            self, store, finality_update, current_slot,
            genesis_validators_root) -> None:
        types = self._lc()
        update = types["LightClientUpdate"](
            attested_header=finality_update.attested_header,
            finalized_header=finality_update.finalized_header,
            finality_branch=finality_update.finality_branch,
            sync_aggregate=finality_update.sync_aggregate,
            signature_slot=finality_update.signature_slot)
        self.process_light_client_update(
            store, update, current_slot, genesis_validators_root)

    def process_light_client_optimistic_update(
            self, store, optimistic_update, current_slot,
            genesis_validators_root) -> None:
        types = self._lc()
        update = types["LightClientUpdate"](
            attested_header=optimistic_update.attested_header,
            sync_aggregate=optimistic_update.sync_aggregate,
            signature_slot=optimistic_update.signature_slot)
        self.process_light_client_update(
            store, update, current_slot, genesis_validators_root)

    # ------------------------------------------------------------------
    # full-node data derivation (full-node.md:40-171)
    # ------------------------------------------------------------------
    def block_to_light_client_header(self, block):
        types = self._lc()
        message = block.message
        beacon = self.BeaconBlockHeader(
            slot=message.slot,
            proposer_index=message.proposer_index,
            parent_root=message.parent_root,
            state_root=message.state_root,
            body_root=hash_tree_root(message.body))
        if not self.is_post("capella"):
            return types["LightClientHeader"](beacon=beacon)

        epoch = self.compute_epoch_at_slot(message.slot)
        if epoch < self.config.CAPELLA_FORK_EPOCH:
            return types["LightClientHeader"](beacon=beacon)
        payload = message.body.execution_payload
        execution_header = self.build_execution_payload_header(payload)
        execution_branch = compute_merkle_proof(
            message.body, self.execution_payload_gindex())
        return types["LightClientHeader"](
            beacon=beacon,
            execution=execution_header,
            execution_branch=execution_branch)

    # ------------------------------------------------------------------
    # cross-fork data upgrades (capella/deneb/electra light-client/
    # fork.md upgrade_lc_*_to_*): a post-fork store can still process
    # pre-fork data after locally upgrading it.  One generic family per
    # object — field-compatible copies, new fields at their defaults —
    # replaces the reference's per-fork triplication.
    # ------------------------------------------------------------------
    @staticmethod
    def normalize_merkle_branch(branch, gindex):
        """electra/light-client/fork.md:27: left-pad a shallower branch
        with zero hashes up to the gindex's depth."""
        depth = floorlog2(int(gindex))
        num_extra = depth - len(branch)
        return [Bytes32()] * num_extra + [Bytes32(b) for b in branch]

    def upgrade_lc_header_from(self, pre):
        """capella/light-client/fork.md:25 upgrade_lc_header_to_capella,
        deneb fork.md:25 (blob-gas fields default to 0), electra."""
        types = self._lc()
        header_cls = types["LightClientHeader"]
        if not self.is_post("capella") or not hasattr(pre, "execution"):
            # pre-capella data: no execution info to carry over
            return header_cls(beacon=pre.beacon)
        eh_cls = self.ExecutionPayloadHeader
        common = [n for n in eh_cls._field_names
                  if n in type(pre.execution)._field_names]
        execution = eh_cls(**{n: getattr(pre.execution, n)
                              for n in common})
        return header_cls(beacon=pre.beacon, execution=execution,
                          execution_branch=pre.execution_branch)

    def upgrade_lc_bootstrap_from(self, pre):
        types = self._lc()
        return types["LightClientBootstrap"](
            header=self.upgrade_lc_header_from(pre.header),
            current_sync_committee=pre.current_sync_committee,
            current_sync_committee_branch=self.normalize_merkle_branch(
                pre.current_sync_committee_branch,
                self.latest_current_sync_committee_gindex()))

    def upgrade_lc_update_from(self, pre):
        types = self._lc()
        return types["LightClientUpdate"](
            attested_header=self.upgrade_lc_header_from(
                pre.attested_header),
            next_sync_committee=pre.next_sync_committee,
            next_sync_committee_branch=self.normalize_merkle_branch(
                pre.next_sync_committee_branch,
                self.latest_next_sync_committee_gindex()),
            finalized_header=self.upgrade_lc_header_from(
                pre.finalized_header),
            finality_branch=self.normalize_merkle_branch(
                pre.finality_branch,
                self.latest_finalized_root_gindex()),
            sync_aggregate=pre.sync_aggregate,
            signature_slot=pre.signature_slot)

    def upgrade_lc_finality_update_from(self, pre):
        types = self._lc()
        return types["LightClientFinalityUpdate"](
            attested_header=self.upgrade_lc_header_from(
                pre.attested_header),
            finalized_header=self.upgrade_lc_header_from(
                pre.finalized_header),
            finality_branch=self.normalize_merkle_branch(
                pre.finality_branch,
                self.latest_finalized_root_gindex()),
            sync_aggregate=pre.sync_aggregate,
            signature_slot=pre.signature_slot)

    def upgrade_lc_optimistic_update_from(self, pre):
        types = self._lc()
        return types["LightClientOptimisticUpdate"](
            attested_header=self.upgrade_lc_header_from(
                pre.attested_header),
            sync_aggregate=pre.sync_aggregate,
            signature_slot=pre.signature_slot)

    def upgrade_lc_store_from(self, pre):
        """capella/light-client/fork.md:78 upgrade_lc_store_to_capella
        (and the deneb/electra equivalents)."""
        best_valid_update = (
            None if pre.best_valid_update is None
            else self.upgrade_lc_update_from(pre.best_valid_update))
        return LightClientStore(
            finalized_header=self.upgrade_lc_header_from(
                pre.finalized_header),
            current_sync_committee=pre.current_sync_committee,
            next_sync_committee=pre.next_sync_committee,
            best_valid_update=best_valid_update,
            optimistic_header=self.upgrade_lc_header_from(
                pre.optimistic_header),
            previous_max_active_participants=(
                pre.previous_max_active_participants),
            current_max_active_participants=(
                pre.current_max_active_participants))

    def create_light_client_bootstrap(self, state, block):
        types = self._lc()
        assert self.compute_epoch_at_slot(state.slot) \
            >= self.config.ALTAIR_FORK_EPOCH
        assert state.slot == state.latest_block_header.slot
        header = state.latest_block_header.copy()
        header.state_root = hash_tree_root(state)
        assert hash_tree_root(header) == hash_tree_root(block.message)
        return types["LightClientBootstrap"](
            header=self.block_to_light_client_header(block),
            current_sync_committee=state.current_sync_committee,
            current_sync_committee_branch=compute_merkle_proof(
                state,
                self.current_sync_committee_gindex_at_slot(state.slot)))

    def create_light_client_update(self, state, block, attested_state,
                                   attested_block, finalized_block):
        types = self._lc()
        assert self.compute_epoch_at_slot(attested_state.slot) \
            >= self.config.ALTAIR_FORK_EPOCH
        assert sum(bool(b) for b in
                   block.message.body.sync_aggregate.sync_committee_bits) \
            >= self.MIN_SYNC_COMMITTEE_PARTICIPANTS

        assert state.slot == state.latest_block_header.slot
        header = state.latest_block_header.copy()
        header.state_root = hash_tree_root(state)
        assert hash_tree_root(header) == hash_tree_root(block.message)
        update_signature_period = \
            self.compute_sync_committee_period_at_slot(block.message.slot)

        assert attested_state.slot == \
            attested_state.latest_block_header.slot
        attested_header = attested_state.latest_block_header.copy()
        attested_header.state_root = hash_tree_root(attested_state)
        assert hash_tree_root(attested_header) \
            == hash_tree_root(attested_block.message) \
            == block.message.parent_root
        update_attested_period = \
            self.compute_sync_committee_period_at_slot(
                attested_block.message.slot)

        update = types["LightClientUpdate"]()
        update.attested_header = \
            self.block_to_light_client_header(attested_block)

        if update_attested_period == update_signature_period:
            update.next_sync_committee = attested_state.next_sync_committee
            update.next_sync_committee_branch = compute_merkle_proof(
                attested_state,
                self.next_sync_committee_gindex_at_slot(
                    attested_state.slot))

        if finalized_block is not None:
            if finalized_block.message.slot != self.GENESIS_SLOT:
                update.finalized_header = \
                    self.block_to_light_client_header(finalized_block)
                assert hash_tree_root(update.finalized_header.beacon) \
                    == attested_state.finalized_checkpoint.root
            else:
                assert attested_state.finalized_checkpoint.root == Bytes32()
            update.finality_branch = compute_merkle_proof(
                attested_state,
                self.finalized_root_gindex_at_slot(attested_state.slot))

        update.sync_aggregate = block.message.body.sync_aggregate
        update.signature_slot = block.message.slot
        return update

    def create_light_client_finality_update(self, update):
        types = self._lc()
        return types["LightClientFinalityUpdate"](
            attested_header=update.attested_header,
            finalized_header=update.finalized_header,
            finality_branch=update.finality_branch,
            sync_aggregate=update.sync_aggregate,
            signature_slot=update.signature_slot)

    def create_light_client_optimistic_update(self, update):
        types = self._lc()
        return types["LightClientOptimisticUpdate"](
            attested_header=update.attested_header,
            sync_aggregate=update.sync_aggregate,
            signature_slot=update.signature_slot)

    # ------------------------------------------------------------------
    # light-client data collection (the LC SERVER side; reference
    # capability: test/helpers/light_client_data_collection.py + the
    # p2p LightClientUpdatesByRange/Bootstrap request semantics)
    # ------------------------------------------------------------------
    # p2p request bound (reference config MAX_REQUEST_LIGHT_CLIENT_UPDATES)
    MAX_REQUEST_LIGHT_CLIENT_UPDATES = 128

    def new_light_client_data_store(self):
        return LightClientDataStore()

    def lc_data_on_block(self, store: "LightClientDataStore", state,
                         block, attested_state, attested_block,
                         finalized_block=None) -> None:
        """Feed one imported head block into the collection: derive the
        update whose attested header is the parent, keep the best per
        sync-committee period (is_better_update), and refresh the
        latest finality/optimistic updates by attested slot."""
        try:
            update = self.create_light_client_update(
                state, block, attested_state, attested_block,
                finalized_block)
        except AssertionError:
            # not update material (low participation, pre-altair
            # attested epoch): a server simply collects nothing, it
            # does not fail the import
            return
        period = self.compute_sync_committee_period_at_slot(
            update.attested_header.beacon.slot)
        best = store.best_updates.get(period)
        if best is None or self.is_better_update(update, best):
            store.best_updates[period] = update

        att_slot = int(update.attested_header.beacon.slot)
        if self.is_finality_update(update) and (
                store.latest_finality_update is None
                or att_slot > int(store.latest_finality_update
                                  .attested_header.beacon.slot)):
            store.latest_finality_update = \
                self.create_light_client_finality_update(update)
        if store.latest_optimistic_update is None or att_slot > int(
                store.latest_optimistic_update
                .attested_header.beacon.slot):
            store.latest_optimistic_update = \
                self.create_light_client_optimistic_update(update)

    def lc_data_on_finalized(self, store: "LightClientDataStore", state,
                             block) -> None:
        """A finalized block becomes bootstrap material
        (LightClientBootstrap request semantics)."""
        root = hash_tree_root(block.message)
        store.bootstraps[bytes(root)] = \
            self.create_light_client_bootstrap(state, block)

    def get_light_client_updates(self, store: "LightClientDataStore",
                                 start_period: int, count: int) -> list:
        """LightClientUpdatesByRange: best updates for up to
        MAX_REQUEST_LIGHT_CLIENT_UPDATES consecutive periods, stopping
        at the first gap."""
        out = []
        capped = min(int(count), self.MAX_REQUEST_LIGHT_CLIENT_UPDATES)
        for period in range(int(start_period),
                            int(start_period) + capped):
            update = store.best_updates.get(period)
            if update is None:
                break
            out.append(update)
        return out

    def get_light_client_bootstrap(self, store: "LightClientDataStore",
                                   block_root: bytes):
        return store.bootstraps.get(bytes(block_root))
