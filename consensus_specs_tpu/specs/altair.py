"""Altair spec: participation flags, sync committees, inactivity leak.

From-scratch implementation of /root/reference/specs/altair/
{beacon-chain.md,fork.md,validator.md} as a Phase0Spec subclass — each
method override is one fork delta (the reference's combine_spec_objects
overlay, expressed as inheritance).

NOTE: SSZ Container fields must stay live annotations (no PEP 563 here).
"""
from ..ssz import (
    uint8, uint64, boolean, Bitlist, Bitvector, Vector, List, Container,
    Bytes4, Bytes32, Bytes48, Bytes96, hash_tree_root, uint_to_bytes,
)
from ..utils import bls
from .light_client import LightClientMixin
from .phase0 import Phase0Spec, integer_squareroot


class AltairSpec(LightClientMixin, Phase0Spec):
    fork = "altair"

    # ------------------------------------------------------------------
    # constants (altair/beacon-chain.md tables)
    # ------------------------------------------------------------------
    def _build_constants(self) -> None:
        super()._build_constants()
        self.TIMELY_SOURCE_FLAG_INDEX = 0
        self.TIMELY_TARGET_FLAG_INDEX = 1
        self.TIMELY_HEAD_FLAG_INDEX = 2
        self.TIMELY_SOURCE_WEIGHT = uint64(14)
        self.TIMELY_TARGET_WEIGHT = uint64(26)
        self.TIMELY_HEAD_WEIGHT = uint64(14)
        self.SYNC_REWARD_WEIGHT = uint64(2)
        self.PROPOSER_WEIGHT = uint64(8)
        self.WEIGHT_DENOMINATOR = uint64(64)
        self.PARTICIPATION_FLAG_WEIGHTS = [
            self.TIMELY_SOURCE_WEIGHT,
            self.TIMELY_TARGET_WEIGHT,
            self.TIMELY_HEAD_WEIGHT,
        ]
        self.DOMAIN_SYNC_COMMITTEE = Bytes4("0x07000000")
        self.DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF = Bytes4("0x08000000")
        self.DOMAIN_CONTRIBUTION_AND_PROOF = Bytes4("0x09000000")
        self.G2_POINT_AT_INFINITY = Bytes96(b"\xc0" + b"\x00" * 95)
        self.ParticipationFlags = uint8
        # validator.md
        self.TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE = 2**4
        self.SYNC_COMMITTEE_SUBNET_COUNT = 4

    # ------------------------------------------------------------------
    # types (altair/beacon-chain.md "Containers")
    # ------------------------------------------------------------------
    def _build_types(self) -> None:
        super()._build_types()
        p = self

        class SyncAggregate(Container):
            sync_committee_bits: Bitvector[p.SYNC_COMMITTEE_SIZE]
            sync_committee_signature: Bytes96

        class SyncCommittee(Container):
            pubkeys: Vector[Bytes48, p.SYNC_COMMITTEE_SIZE]
            aggregate_pubkey: Bytes48

        class BeaconBlockBody(Container):
            randao_reveal: Bytes96
            eth1_data: p.Eth1Data
            graffiti: Bytes32
            proposer_slashings: List[p.ProposerSlashing, p.MAX_PROPOSER_SLASHINGS]
            attester_slashings: List[p.AttesterSlashing, p.MAX_ATTESTER_SLASHINGS]
            attestations: List[p.Attestation, p.MAX_ATTESTATIONS]
            deposits: List[p.Deposit, p.MAX_DEPOSITS]
            voluntary_exits: List[p.SignedVoluntaryExit, p.MAX_VOLUNTARY_EXITS]
            sync_aggregate: SyncAggregate

        class BeaconBlock(Container):
            slot: uint64
            proposer_index: uint64
            parent_root: Bytes32
            state_root: Bytes32
            body: BeaconBlockBody

        class SignedBeaconBlock(Container):
            message: BeaconBlock
            signature: Bytes96

        class BeaconState(Container):
            genesis_time: uint64
            genesis_validators_root: Bytes32
            slot: uint64
            fork: p.Fork
            latest_block_header: p.BeaconBlockHeader
            block_roots: Vector[Bytes32, p.SLOTS_PER_HISTORICAL_ROOT]
            state_roots: Vector[Bytes32, p.SLOTS_PER_HISTORICAL_ROOT]
            historical_roots: List[Bytes32, p.HISTORICAL_ROOTS_LIMIT]
            eth1_data: p.Eth1Data
            eth1_data_votes: List[p.Eth1Data, p.EPOCHS_PER_ETH1_VOTING_PERIOD * p.SLOTS_PER_EPOCH]
            eth1_deposit_index: uint64
            validators: List[p.Validator, p.VALIDATOR_REGISTRY_LIMIT]
            balances: List[uint64, p.VALIDATOR_REGISTRY_LIMIT]
            randao_mixes: Vector[Bytes32, p.EPOCHS_PER_HISTORICAL_VECTOR]
            slashings: Vector[uint64, p.EPOCHS_PER_SLASHINGS_VECTOR]
            previous_epoch_participation: List[uint8, p.VALIDATOR_REGISTRY_LIMIT]
            current_epoch_participation: List[uint8, p.VALIDATOR_REGISTRY_LIMIT]
            justification_bits: Bitvector[p.JUSTIFICATION_BITS_LENGTH]
            previous_justified_checkpoint: p.Checkpoint
            current_justified_checkpoint: p.Checkpoint
            finalized_checkpoint: p.Checkpoint
            inactivity_scores: List[uint64, p.VALIDATOR_REGISTRY_LIMIT]
            current_sync_committee: SyncCommittee
            next_sync_committee: SyncCommittee

        # validator.md containers
        class SyncCommitteeMessage(Container):
            slot: uint64
            beacon_block_root: Bytes32
            validator_index: uint64
            signature: Bytes96

        class SyncCommitteeContribution(Container):
            slot: uint64
            beacon_block_root: Bytes32
            subcommittee_index: uint64
            aggregation_bits: Bitvector[p.SYNC_COMMITTEE_SIZE // p.SYNC_COMMITTEE_SUBNET_COUNT]
            signature: Bytes96

        class ContributionAndProof(Container):
            aggregator_index: uint64
            contribution: SyncCommitteeContribution
            selection_proof: Bytes96

        class SignedContributionAndProof(Container):
            message: ContributionAndProof
            signature: Bytes96

        class SyncAggregatorSelectionData(Container):
            slot: uint64
            subcommittee_index: uint64

        for name, cls in list(locals().items()):
            if isinstance(cls, type) and issubclass(cls, Container):
                setattr(self, name, cls)

    # ------------------------------------------------------------------
    # participation-flag helpers
    # ------------------------------------------------------------------
    def add_flag(self, flags, flag_index):
        return uint8(flags | (2**flag_index))

    def has_flag(self, flags, flag_index) -> bool:
        flag = 2**flag_index
        return flags & flag == flag

    # ------------------------------------------------------------------
    # sync committee machinery
    # ------------------------------------------------------------------
    def get_next_sync_committee_indices(self, state):
        """Balance-weighted rejection sampling for the *next* period."""
        epoch = uint64(self.get_current_epoch(state) + 1)
        MAX_RANDOM_BYTE = 2**8 - 1
        active_validator_indices = self.get_active_validator_indices(
            state, epoch)
        active_validator_count = len(active_validator_indices)
        seed = self.get_seed(state, epoch, self.DOMAIN_SYNC_COMMITTEE)
        i = 0
        sync_committee_indices = []
        while len(sync_committee_indices) < self.SYNC_COMMITTEE_SIZE:
            shuffled_index = self.compute_shuffled_index(
                i % active_validator_count, active_validator_count, seed)
            candidate_index = active_validator_indices[shuffled_index]
            random_byte = self.hash(
                bytes(seed) + uint_to_bytes(uint64(i // 32)))[i % 32]
            effective_balance = \
                state.validators[candidate_index].effective_balance
            if (effective_balance * MAX_RANDOM_BYTE
                    >= self.MAX_EFFECTIVE_BALANCE * random_byte):
                sync_committee_indices.append(candidate_index)
            i += 1
        return sync_committee_indices

    def get_next_sync_committee(self, state):
        indices = self.get_next_sync_committee_indices(state)
        pubkeys = [state.validators[index].pubkey for index in indices]
        aggregate_pubkey = self.eth_aggregate_pubkeys(pubkeys)
        return self.SyncCommittee(pubkeys=pubkeys,
                                  aggregate_pubkey=aggregate_pubkey)

    def eth_aggregate_pubkeys(self, pubkeys):
        assert len(pubkeys) > 0
        # pure point addition (no pairing): always computed for real so the
        # state's sync-committee aggregate pubkey is correct even when the
        # harness stubs signature checks
        from ..crypto import bls12_381 as native
        return Bytes48(native.AggregatePKs([bytes(pk) for pk in pubkeys]))

    def eth_fast_aggregate_verify(self, pubkeys, message, signature) -> bool:
        if len(pubkeys) == 0 and signature == self.G2_POINT_AT_INFINITY:
            return True
        return self.bls_fast_aggregate_verify(pubkeys, message, signature)

    # ------------------------------------------------------------------
    # accessors / rewards
    # ------------------------------------------------------------------
    def get_base_reward_per_increment(self, state):
        return uint64(self.EFFECTIVE_BALANCE_INCREMENT
                      * self.BASE_REWARD_FACTOR
                      // integer_squareroot(
                          self.get_total_active_balance(state)))

    def get_base_reward(self, state, index):
        increments = state.validators[index].effective_balance \
            // self.EFFECTIVE_BALANCE_INCREMENT
        return uint64(increments * self.get_base_reward_per_increment(state))

    def get_unslashed_participating_indices(self, state, flag_index, epoch):
        assert epoch in (self.get_previous_epoch(state),
                         self.get_current_epoch(state))
        if epoch == self.get_current_epoch(state):
            epoch_participation = state.current_epoch_participation
        else:
            epoch_participation = state.previous_epoch_participation
        active_validator_indices = self.get_active_validator_indices(
            state, epoch)
        participating_indices = [
            i for i in active_validator_indices
            if self.has_flag(epoch_participation[i], flag_index)]
        return set(filter(
            lambda index: not state.validators[index].slashed,
            participating_indices))

    def get_attestation_participation_flag_indices(self, state, data,
                                                   inclusion_delay):
        if data.target.epoch == self.get_current_epoch(state):
            justified_checkpoint = state.current_justified_checkpoint
        else:
            justified_checkpoint = state.previous_justified_checkpoint

        is_matching_source = data.source == justified_checkpoint
        is_matching_target = (
            is_matching_source
            and data.target.root == self.get_block_root(state,
                                                        data.target.epoch))
        is_matching_head = (
            is_matching_target
            and data.beacon_block_root
            == self.get_block_root_at_slot(state, data.slot))
        assert is_matching_source

        participation_flag_indices = []
        if (is_matching_source and inclusion_delay
                <= integer_squareroot(self.SLOTS_PER_EPOCH)):
            participation_flag_indices.append(self.TIMELY_SOURCE_FLAG_INDEX)
        if self.is_timely_target(state, is_matching_target, inclusion_delay):
            participation_flag_indices.append(self.TIMELY_TARGET_FLAG_INDEX)
        if (is_matching_head
                and inclusion_delay == self.MIN_ATTESTATION_INCLUSION_DELAY):
            participation_flag_indices.append(self.TIMELY_HEAD_FLAG_INDEX)
        return participation_flag_indices

    def is_timely_target(self, state, is_matching_target,
                         inclusion_delay) -> bool:
        # deneb removes the inclusion-delay bound for target
        return is_matching_target and inclusion_delay <= self.SLOTS_PER_EPOCH

    def get_flag_index_deltas(self, state, flag_index):
        n = len(state.validators)
        rewards = [uint64(0)] * n
        penalties = [uint64(0)] * n
        previous_epoch = self.get_previous_epoch(state)
        unslashed_participating_indices = \
            self.get_unslashed_participating_indices(
                state, flag_index, previous_epoch)
        weight = self.PARTICIPATION_FLAG_WEIGHTS[flag_index]
        unslashed_participating_balance = self.get_total_balance(
            state, unslashed_participating_indices)
        unslashed_participating_increments = \
            unslashed_participating_balance \
            // self.EFFECTIVE_BALANCE_INCREMENT
        active_increments = self.get_total_active_balance(state) \
            // self.EFFECTIVE_BALANCE_INCREMENT
        for index in self.get_eligible_validator_indices(state):
            base_reward = self.get_base_reward(state, index)
            if index in unslashed_participating_indices:
                if not self.is_in_inactivity_leak(state):
                    reward_numerator = (base_reward * weight
                                        * unslashed_participating_increments)
                    rewards[index] = uint64(
                        rewards[index] + reward_numerator
                        // (active_increments * self.WEIGHT_DENOMINATOR))
            elif flag_index != self.TIMELY_HEAD_FLAG_INDEX:
                penalties[index] = uint64(
                    penalties[index]
                    + base_reward * weight // self.WEIGHT_DENOMINATOR)
        return rewards, penalties

    def get_inactivity_penalty_deltas(self, state):
        n = len(state.validators)
        rewards = [uint64(0)] * n
        penalties = [uint64(0)] * n
        previous_epoch = self.get_previous_epoch(state)
        matching_target_indices = self.get_unslashed_participating_indices(
            state, self.TIMELY_TARGET_FLAG_INDEX, previous_epoch)
        for index in self.get_eligible_validator_indices(state):
            if index not in matching_target_indices:
                penalty_numerator = (
                    state.validators[index].effective_balance
                    * state.inactivity_scores[index])
                penalty_denominator = (
                    self.config.INACTIVITY_SCORE_BIAS
                    * self.inactivity_penalty_quotient())
                penalties[index] = uint64(
                    penalties[index]
                    + penalty_numerator // penalty_denominator)
        return rewards, penalties

    def inactivity_penalty_quotient(self) -> int:
        return self.INACTIVITY_PENALTY_QUOTIENT_ALTAIR

    def min_slashing_penalty_quotient(self) -> int:
        return self.MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR

    def proportional_slashing_multiplier(self) -> int:
        return self.PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR

    def slashing_proposer_reward(self, whistleblower_reward):
        return uint64(whistleblower_reward * self.PROPOSER_WEIGHT
                      // self.WEIGHT_DENOMINATOR)

    # ------------------------------------------------------------------
    # epoch processing (altair ordering)
    # ------------------------------------------------------------------
    def process_epoch(self, state) -> None:
        from . import epoch_fast
        if epoch_fast.fused_epoch(self, state):
            # the fused ONE-dispatch sweep handled justification through
            # the effective-balance update; only the cheap tail resets
            # remain (eth1_data_reset commutes past the sweep: it clears
            # vote bookkeeping no fused pass reads or writes)
            self.process_eth1_data_reset(state)
            self.process_slashings_reset(state)
            self.process_randao_mixes_reset(state)
            self.process_historical_roots_update(state)
            self.process_participation_flag_updates(state)
            self.process_sync_committee_updates(state)
            return
        self.process_justification_and_finalization(state)
        self.process_inactivity_updates(state)
        self.process_rewards_and_penalties(state)
        self.process_registry_updates(state)
        self.process_slashings(state)
        self.process_eth1_data_reset(state)
        self.process_effective_balance_updates(state)
        self.process_slashings_reset(state)
        self.process_randao_mixes_reset(state)
        self.process_historical_roots_update(state)
        self.process_participation_flag_updates(state)
        self.process_sync_committee_updates(state)

    def process_justification_and_finalization(self, state) -> None:
        if self.get_current_epoch(state) <= self.GENESIS_EPOCH + 1:
            return
        previous_indices = self.get_unslashed_participating_indices(
            state, self.TIMELY_TARGET_FLAG_INDEX,
            self.get_previous_epoch(state))
        current_indices = self.get_unslashed_participating_indices(
            state, self.TIMELY_TARGET_FLAG_INDEX,
            self.get_current_epoch(state))
        total_active_balance = self.get_total_active_balance(state)
        previous_target_balance = self.get_total_balance(
            state, previous_indices)
        current_target_balance = self.get_total_balance(
            state, current_indices)
        self.weigh_justification_and_finalization(
            state, total_active_balance, previous_target_balance,
            current_target_balance)

    def process_inactivity_updates(self, state) -> None:
        # no inactivity accounting in the genesis epoch
        if self.get_current_epoch(state) == self.GENESIS_EPOCH:
            return
        previous_target_indices = self.get_unslashed_participating_indices(
            state, self.TIMELY_TARGET_FLAG_INDEX,
            self.get_previous_epoch(state))
        for index in self.get_eligible_validator_indices(state):
            if index in previous_target_indices:
                state.inactivity_scores[index] = uint64(
                    state.inactivity_scores[index]
                    - min(1, int(state.inactivity_scores[index])))
            else:
                state.inactivity_scores[index] = uint64(
                    state.inactivity_scores[index]
                    + self.config.INACTIVITY_SCORE_BIAS)
            if not self.is_in_inactivity_leak(state):
                state.inactivity_scores[index] = uint64(
                    state.inactivity_scores[index]
                    - min(self.config.INACTIVITY_SCORE_RECOVERY_RATE,
                          int(state.inactivity_scores[index])))

    def process_rewards_and_penalties(self, state) -> None:
        if self.get_current_epoch(state) == self.GENESIS_EPOCH:
            return
        flag_deltas = [
            self.get_flag_index_deltas(state, flag_index)
            for flag_index in range(len(self.PARTICIPATION_FLAG_WEIGHTS))]
        deltas = flag_deltas + [self.get_inactivity_penalty_deltas(state)]
        for rewards, penalties in deltas:
            for index in range(len(state.validators)):
                self.increase_balance(state, index, rewards[index])
                self.decrease_balance(state, index, penalties[index])

    def process_participation_flag_updates(self, state) -> None:
        state.previous_epoch_participation = \
            state.current_epoch_participation
        state.current_epoch_participation = type(
            state.current_epoch_participation)(
                [0] * len(state.validators))

    def process_sync_committee_updates(self, state) -> None:
        next_epoch = uint64(self.get_current_epoch(state) + 1)
        if next_epoch % self.EPOCHS_PER_SYNC_COMMITTEE_PERIOD == 0:
            state.current_sync_committee = state.next_sync_committee
            state.next_sync_committee = self.get_next_sync_committee(state)

    # ------------------------------------------------------------------
    # block processing
    # ------------------------------------------------------------------
    def process_block(self, state, block) -> None:
        self.process_block_header(state, block)
        self.process_randao(state, block.body)
        self.process_eth1_data(state, block.body)
        self.process_operations(state, block.body)
        self.process_sync_aggregate(state, block.body.sync_aggregate)

    def process_attestation(self, state, attestation) -> None:
        data = attestation.data
        assert data.target.epoch in (self.get_previous_epoch(state),
                                     self.get_current_epoch(state))
        assert data.target.epoch == self.compute_epoch_at_slot(data.slot)
        assert data.slot + self.MIN_ATTESTATION_INCLUSION_DELAY <= state.slot
        self.check_attestation_inclusion_window(state, data)
        assert data.index < self.get_committee_count_per_slot(
            state, data.target.epoch)

        committee = self.get_beacon_committee(state, data.slot, data.index)
        assert len(attestation.aggregation_bits) == len(committee)

        # participation flags for this (data, delay)
        participation_flag_indices = \
            self.get_attestation_participation_flag_indices(
                state, data, uint64(state.slot - data.slot))

        assert self.is_valid_indexed_attestation(
            state, self.get_indexed_attestation(state, attestation))

        if data.target.epoch == self.get_current_epoch(state):
            epoch_participation = state.current_epoch_participation
        else:
            epoch_participation = state.previous_epoch_participation

        proposer_reward_numerator = 0
        for index in self.get_attesting_indices(state, attestation):
            for flag_index, weight in enumerate(
                    self.PARTICIPATION_FLAG_WEIGHTS):
                if (flag_index in participation_flag_indices
                        and not self.has_flag(epoch_participation[index],
                                              flag_index)):
                    epoch_participation[index] = self.add_flag(
                        epoch_participation[index], flag_index)
                    proposer_reward_numerator += int(
                        self.get_base_reward(state, index) * weight)

        proposer_reward_denominator = (
            (self.WEIGHT_DENOMINATOR - self.PROPOSER_WEIGHT)
            * self.WEIGHT_DENOMINATOR // self.PROPOSER_WEIGHT)
        proposer_reward = uint64(
            proposer_reward_numerator // proposer_reward_denominator)
        self.increase_balance(
            state, self.get_beacon_proposer_index(state), proposer_reward)

    def check_attestation_inclusion_window(self, state, data) -> None:
        # deneb removes the upper bound; altair keeps it
        assert state.slot <= data.slot + self.SLOTS_PER_EPOCH

    def add_validator_to_registry(self, state, pubkey,
                                  withdrawal_credentials, amount) -> None:
        super().add_validator_to_registry(
            state, pubkey, withdrawal_credentials, amount)
        state.previous_epoch_participation.append(0)
        state.current_epoch_participation.append(0)
        state.inactivity_scores.append(0)

    def process_sync_aggregate(self, state, sync_aggregate) -> None:
        # verify the (possibly empty) aggregate over the previous slot root
        committee_pubkeys = state.current_sync_committee.pubkeys
        participant_pubkeys = [
            pubkey for pubkey, bit in zip(
                committee_pubkeys, sync_aggregate.sync_committee_bits)
            if bit]
        previous_slot = uint64(max(int(state.slot), 1) - 1)
        domain = self.get_domain(state, self.DOMAIN_SYNC_COMMITTEE,
                                 self.compute_epoch_at_slot(previous_slot))
        signing_root = self.compute_signing_root(
            self.get_block_root_at_slot(state, previous_slot), domain)
        assert self.eth_fast_aggregate_verify(
            participant_pubkeys, signing_root,
            sync_aggregate.sync_committee_signature)

        # participant / proposer rewards
        total_active_increments = self.get_total_active_balance(state) \
            // self.EFFECTIVE_BALANCE_INCREMENT
        total_base_rewards = uint64(
            self.get_base_reward_per_increment(state)
            * total_active_increments)
        max_participant_rewards = uint64(
            total_base_rewards * self.SYNC_REWARD_WEIGHT
            // self.WEIGHT_DENOMINATOR // self.SLOTS_PER_EPOCH)
        participant_reward = uint64(
            max_participant_rewards // self.SYNC_COMMITTEE_SIZE)
        proposer_reward = uint64(
            participant_reward * self.PROPOSER_WEIGHT
            // (self.WEIGHT_DENOMINATOR - self.PROPOSER_WEIGHT))

        all_pubkeys = [v.pubkey for v in state.validators]
        committee_indices = [all_pubkeys.index(pubkey)
                             for pubkey in committee_pubkeys]
        for participant_index, participation_bit in zip(
                committee_indices, sync_aggregate.sync_committee_bits):
            if participation_bit:
                self.increase_balance(state, participant_index,
                                      participant_reward)
                self.increase_balance(
                    state, self.get_beacon_proposer_index(state),
                    proposer_reward)
            else:
                self.decrease_balance(state, participant_index,
                                      participant_reward)

    # ------------------------------------------------------------------
    # fork upgrade (altair/fork.md)
    # ------------------------------------------------------------------
    def genesis_fork_versions(self):
        return (Bytes4(self.config.GENESIS_FORK_VERSION),
                Bytes4(self.config.ALTAIR_FORK_VERSION))

    def translate_participation(self, post, pre_pending_attestations) -> None:
        for attestation in pre_pending_attestations:
            data = attestation.data
            inclusion_delay = attestation.inclusion_delay
            participation_flag_indices = \
                self.get_attestation_participation_flag_indices(
                    post, data, inclusion_delay)
            for index in self.get_attesting_indices(post, attestation):
                for flag_index in participation_flag_indices:
                    post.previous_epoch_participation[index] = self.add_flag(
                        post.previous_epoch_participation[index], flag_index)

    def upgrade_from(self, pre):
        """upgrade_to_altair (altair/fork.md:77)."""
        epoch = self.get_current_epoch(pre)
        post = self.BeaconState(
            genesis_time=pre.genesis_time,
            genesis_validators_root=pre.genesis_validators_root,
            slot=pre.slot,
            fork=self.Fork(
                previous_version=pre.fork.current_version,
                current_version=Bytes4(self.config.ALTAIR_FORK_VERSION),
                epoch=epoch),
            latest_block_header=pre.latest_block_header,
            block_roots=list(pre.block_roots),
            state_roots=list(pre.state_roots),
            historical_roots=list(pre.historical_roots),
            eth1_data=pre.eth1_data,
            eth1_data_votes=list(pre.eth1_data_votes),
            eth1_deposit_index=pre.eth1_deposit_index,
            validators=list(pre.validators),
            balances=list(pre.balances),
            randao_mixes=list(pre.randao_mixes),
            slashings=list(pre.slashings),
            previous_epoch_participation=[0] * len(pre.validators),
            current_epoch_participation=[0] * len(pre.validators),
            justification_bits=list(pre.justification_bits),
            previous_justified_checkpoint=pre.previous_justified_checkpoint,
            current_justified_checkpoint=pre.current_justified_checkpoint,
            finalized_checkpoint=pre.finalized_checkpoint,
            inactivity_scores=[0] * len(pre.validators),
        )
        self.translate_participation(post, pre.previous_epoch_attestations)
        post.current_sync_committee = self.get_next_sync_committee(post)
        post.next_sync_committee = self.get_next_sync_committee(post)
        return post

    # ------------------------------------------------------------------
    # validator duties (altair/validator.md)
    # ------------------------------------------------------------------
    def compute_sync_committee_period(self, epoch) -> int:
        return uint64(epoch // self.EPOCHS_PER_SYNC_COMMITTEE_PERIOD)

    def is_assigned_to_sync_committee(self, state, epoch,
                                      validator_index) -> bool:
        sync_committee_period = self.compute_sync_committee_period(epoch)
        current_epoch = self.get_current_epoch(state)
        current_period = self.compute_sync_committee_period(current_epoch)
        next_period = uint64(current_period + 1)
        if sync_committee_period == current_period:
            committee = state.current_sync_committee
        else:
            assert sync_committee_period == next_period
            committee = state.next_sync_committee
        pubkey = state.validators[validator_index].pubkey
        return pubkey in list(committee.pubkeys)

    def get_sync_committee_message(self, state, block_root, validator_index,
                                   privkey):
        epoch = self.get_current_epoch(state)
        domain = self.get_domain(state, self.DOMAIN_SYNC_COMMITTEE, epoch)
        signing_root = self.compute_signing_root(Bytes32(block_root), domain)
        return self.SyncCommitteeMessage(
            slot=state.slot, beacon_block_root=block_root,
            validator_index=validator_index,
            signature=bls.Sign(privkey, signing_root))

    def compute_subnets_for_sync_committee(self, state, validator_index):
        next_slot_epoch = self.compute_epoch_at_slot(
            uint64(state.slot + 1))
        if (self.compute_sync_committee_period(
                self.get_current_epoch(state))
                == self.compute_sync_committee_period(next_slot_epoch)):
            sync_committee = state.current_sync_committee
        else:
            sync_committee = state.next_sync_committee
        target_pubkey = state.validators[validator_index].pubkey
        sync_committee_indices = [
            index for index, pubkey in enumerate(sync_committee.pubkeys)
            if pubkey == target_pubkey]
        return set(
            uint64(index // (self.SYNC_COMMITTEE_SIZE
                             // self.SYNC_COMMITTEE_SUBNET_COUNT))
            for index in sync_committee_indices)

    def get_sync_committee_selection_proof(self, state, slot,
                                           subcommittee_index, privkey):
        domain = self.get_domain(
            state, self.DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
            self.compute_epoch_at_slot(slot))
        signing_data = self.SyncAggregatorSelectionData(
            slot=slot, subcommittee_index=subcommittee_index)
        return bls.Sign(privkey,
                        self.compute_signing_root(signing_data, domain))

    def is_sync_committee_aggregator(self, signature) -> bool:
        modulo = max(
            1, self.SYNC_COMMITTEE_SIZE
            // self.SYNC_COMMITTEE_SUBNET_COUNT
            // self.TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE)
        from .phase0 import bytes_to_uint64
        return bytes_to_uint64(
            self.hash(bytes(signature))[0:8]) % modulo == 0

    def get_sync_subcommittee_pubkeys(self, state, subcommittee_index):
        """Pubkeys of one sync subnet's subcommittee
        (altair/p2p-interface.md)."""
        next_slot_epoch = self.compute_epoch_at_slot(
            uint64(state.slot + 1))
        if self.compute_sync_committee_period(
                self.get_current_epoch(state)) \
                == self.compute_sync_committee_period(next_slot_epoch):
            sync_committee = state.current_sync_committee
        else:
            sync_committee = state.next_sync_committee
        size = (self.SYNC_COMMITTEE_SIZE
                // self.SYNC_COMMITTEE_SUBNET_COUNT)
        i = int(subcommittee_index) * size
        return list(sync_committee.pubkeys[i:i + size])

    def process_sync_committee_contributions(self, block,
                                             contributions) -> None:
        """Assemble the block's SyncAggregate out of per-subnet
        contributions (altair/validator.md)."""
        sync_aggregate = self.SyncAggregate()
        signatures = []
        sync_subcommittee_size = (self.SYNC_COMMITTEE_SIZE
                                  // self.SYNC_COMMITTEE_SUBNET_COUNT)
        for contribution in contributions:
            subcommittee_index = int(contribution.subcommittee_index)
            for index, participated in enumerate(
                    contribution.aggregation_bits):
                if participated:
                    participant_index = (sync_subcommittee_size
                                         * subcommittee_index + index)
                    sync_aggregate.sync_committee_bits[
                        participant_index] = True
            signatures.append(contribution.signature)
        sync_aggregate.sync_committee_signature = bls.Aggregate(
            [bytes(sig) for sig in signatures])
        block.body.sync_aggregate = sync_aggregate

    def get_contribution_and_proof(self, state, aggregator_index,
                                   contribution, privkey):
        selection_proof = self.get_sync_committee_selection_proof(
            state, contribution.slot, contribution.subcommittee_index,
            privkey)
        return self.ContributionAndProof(
            aggregator_index=aggregator_index,
            contribution=contribution,
            selection_proof=selection_proof)

    def get_contribution_and_proof_signature(self, state,
                                             contribution_and_proof,
                                             privkey):
        contribution = contribution_and_proof.contribution
        domain = self.get_domain(
            state, self.DOMAIN_CONTRIBUTION_AND_PROOF,
            self.compute_epoch_at_slot(contribution.slot))
        signing_root = self.compute_signing_root(
            contribution_and_proof, domain)
        return bls.Sign(privkey, signing_root)
