"""Vectorized (validator-axis) epoch processing.

The reference's epoch passes are per-validator Python loops over O(n)
validators with O(n) helpers inside (e.g. `get_base_reward` recomputing the
total active balance), which is quadratic at mainnet scale
(reference: specs/phase0/beacon-chain.md:1553-1589, altair:385-421).  This
engine re-designs each hot pass as numpy array sweeps over a
structure-of-arrays extraction of the validator registry: masks instead of
per-index `if`, scatter-adds instead of dict accumulation, one pass per
delta family.  Write-back touches only changed elements, so the SSZ views
stay the source of truth and results are bit-identical to the scalar spec
methods (differential tests: tests/test_epoch_fast.py).

The engine is enabled by default (ENABLED); `scalar_epoch()` restores the
reference-shaped scalar path for differential testing.  The heavy pure
reductions here are numpy on host — the device-bound work of an epoch
(hash_tree_root merkleization, BLS verification, shuffling) flows through
the JAX kernels in ops/.
"""
from __future__ import annotations

import contextlib
from math import isqrt

import numpy as np

ENABLED = True

# installed by parallel/mesh_engine.enable(): routes the per-flag
# reward/penalty passes through validator-axis shard_map collectives
MESH_ENGINE = None

_I64MAX = np.iinfo(np.int64).max
_ORDER_BITS = 24          # attestations per epoch < 2**24; delay keys above


@contextlib.contextmanager
def scalar_epoch():
    """Temporarily disable the vectorized engine (differential testing)."""
    global ENABLED
    prev, ENABLED = ENABLED, False
    try:
        yield
    finally:
        ENABLED = prev


# ---------------------------------------------------------------------------
# structure-of-arrays extraction
# ---------------------------------------------------------------------------

class StateArrays:
    """Validator-axis columns of the BeaconState (read-only snapshot)."""

    def __init__(self, state):
        vs = state.validators
        n = len(vs)
        self.n = n
        self.eff = np.fromiter(
            (int(v.effective_balance) for v in vs), np.int64, n)
        self.slashed = np.fromiter((bool(v.slashed) for v in vs), bool, n)
        self.activation_eligibility = np.fromiter(
            (int(v.activation_eligibility_epoch) for v in vs), np.uint64, n)
        self.activation = np.fromiter(
            (int(v.activation_epoch) for v in vs), np.uint64, n)
        self.exit = np.fromiter(
            (int(v.exit_epoch) for v in vs), np.uint64, n)
        self.withdrawable = np.fromiter(
            (int(v.withdrawable_epoch) for v in vs), np.uint64, n)
        self.balances = np.fromiter(
            (int(b) for b in state.balances), np.int64, n)

    def active(self, epoch) -> np.ndarray:
        e = np.uint64(int(epoch))
        return (self.activation <= e) & (e < self.exit)

    def eligible(self, previous_epoch) -> np.ndarray:
        """Reference get_eligible_validator_indices semantics."""
        prev = int(previous_epoch)
        return self.active(prev) | (
            self.slashed & (np.uint64(prev + 1) < self.withdrawable))

    def total_active_balance(self, epoch, increment) -> int:
        return max(int(increment), int(self.eff[self.active(epoch)].sum()))


def _write_balances(state, old: np.ndarray, new: np.ndarray) -> None:
    for i in np.nonzero(new != old)[0]:
        state.balances[int(i)] = int(new[i])


# ---------------------------------------------------------------------------
# phase0: attestation participation masks
# ---------------------------------------------------------------------------

def phase0_attestation_masks(spec, state, epoch, targets_only=False):
    """source/target/head attester masks for `epoch`'s pending attestations
    plus, per source attester, the minimal-inclusion-delay key and its
    proposer (reference beacon-chain.md:1497-1551 matching helpers).

    `targets_only` skips the head/inclusion-delay bookkeeping — the
    justification pass needs only the target mask."""
    n = len(state.validators)
    src = np.zeros(n, bool)
    tgt = np.zeros(n, bool)
    head = np.zeros(n, bool)
    best_key = np.full(n, _I64MAX, np.int64)
    best_prop = np.zeros(n, np.int64)
    atts = spec.get_matching_source_attestations(state, epoch)
    if not atts:
        return src, tgt, head, best_key, best_prop
    target_root = spec.get_block_root(state, epoch)
    for order, a in enumerate(atts):
        committee = spec.get_beacon_committee(
            state, a.data.slot, a.data.index)
        m = len(committee)
        comm = np.fromiter((int(c) for c in committee), np.int64, m)
        bits = np.fromiter(
            (bool(b) for b in a.aggregation_bits), bool, m)
        att = comm[bits]
        src[att] = True
        if a.data.target.root == target_root:
            tgt[att] = True
            if not targets_only and a.data.beacon_block_root == \
                    spec.get_block_root_at_slot(state, int(a.data.slot)):
                head[att] = True
        if targets_only:
            continue
        key = (int(a.inclusion_delay) << _ORDER_BITS) | order
        upd = key < best_key[att]
        best_key[att] = np.where(upd, key, best_key[att])
        best_prop[att] = np.where(upd, int(a.proposer_index), best_prop[att])
    return src, tgt, head, best_key, best_prop


def phase0_target_balances(spec, state, arr: StateArrays):
    """(total_active, prev_target, cur_target) attesting balances for
    justification (beacon-chain.md:1360-1386)."""
    incr = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    cur = int(spec.get_current_epoch(state))
    prev = int(spec.get_previous_epoch(state))
    total = arr.total_active_balance(cur, incr)
    out = []
    for epoch in (prev, cur):
        _, tgt, _, _, _ = phase0_attestation_masks(
            spec, state, epoch, targets_only=True)
        m = tgt & ~arr.slashed
        out.append(max(incr, int(arr.eff[m].sum())))
    return total, out[0], out[1]


def phase0_attestation_deltas(spec, state):
    """Vectorized get_attestation_deltas (beacon-chain.md:1553-1589):
    source/target/head components, inclusion-delay rewards with proposer
    scatter, inactivity-leak penalties."""
    arr = StateArrays(state)
    n = arr.n
    incr = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    cur = int(spec.get_current_epoch(state))
    prev = int(spec.get_previous_epoch(state))
    tb = arr.total_active_balance(cur, incr)
    base = (arr.eff * int(spec.BASE_REWARD_FACTOR) // isqrt(tb)
            // int(spec.BASE_REWARDS_PER_EPOCH))
    prop_reward = base // int(spec.PROPOSER_REWARD_QUOTIENT)
    eligible = arr.eligible(prev)
    leak = bool(spec.is_in_inactivity_leak(state))
    finality_delay = int(spec.get_finality_delay(state))

    src, tgt, head, best_key, best_prop = phase0_attestation_masks(
        spec, state, prev)

    rewards = np.zeros(n, np.int64)
    penalties = np.zeros(n, np.int64)

    # source/target/head components
    for mask in (src, tgt, head):
        unsl = mask & ~arr.slashed
        att_bal = max(incr, int(arr.eff[unsl].sum()))
        if leak:
            comp = base
        else:
            comp = base * (att_bal // incr) // (tb // incr)
        rewards += np.where(eligible & unsl, comp, 0)
        penalties += np.where(eligible & ~unsl, base, 0)

    # inclusion-delay rewards (no eligibility filter, matches scalar)
    unsl_src = np.nonzero(src & ~arr.slashed)[0]
    if unsl_src.size:
        delays = best_key[unsl_src] >> _ORDER_BITS
        max_att = base[unsl_src] - prop_reward[unsl_src]
        np.add.at(rewards, unsl_src, max_att // delays)
        np.add.at(rewards, best_prop[unsl_src], prop_reward[unsl_src])

    # inactivity leak penalties
    if leak:
        unsl_tgt = tgt & ~arr.slashed
        pen = int(spec.BASE_REWARDS_PER_EPOCH) * base - prop_reward
        penalties += np.where(eligible, pen, 0)
        extra = (arr.eff * finality_delay
                 // int(spec.INACTIVITY_PENALTY_QUOTIENT))
        penalties += np.where(eligible & ~unsl_tgt, extra, 0)

    return arr, rewards, penalties


# ---------------------------------------------------------------------------
# altair-family: flag-based deltas
# ---------------------------------------------------------------------------

def _participation(state, which: str, n: int) -> np.ndarray:
    col = (state.previous_epoch_participation if which == "previous"
           else state.current_epoch_participation)
    return np.fromiter((int(x) for x in col), np.int64, n)


def altair_unslashed_participating(spec, state, arr, flag_index, epoch):
    which = ("current"
             if int(epoch) == int(spec.get_current_epoch(state))
             else "previous")
    part = _participation(state, which, arr.n)
    return (arr.active(epoch) & (((part >> int(flag_index)) & 1) == 1)
            & ~arr.slashed)


def altair_target_balances(spec, state, arr: StateArrays):
    incr = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    cur = int(spec.get_current_epoch(state))
    prev = int(spec.get_previous_epoch(state))
    flag = int(spec.TIMELY_TARGET_FLAG_INDEX)
    total = arr.total_active_balance(cur, incr)
    prev_m = altair_unslashed_participating(spec, state, arr, flag, prev)
    cur_m = altair_unslashed_participating(spec, state, arr, flag, cur)
    return (total,
            max(incr, int(arr.eff[prev_m].sum())),
            max(incr, int(arr.eff[cur_m].sum())))


def altair_delta_sets(spec, state):
    """Vectorized flag deltas + inactivity deltas (altair
    beacon-chain.md:385-421), as an ordered list of (rewards, penalties) —
    the scalar path applies each set sequentially with zero-flooring, so
    the order is part of the semantics."""
    arr = StateArrays(state)
    n = arr.n
    incr = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    cur = int(spec.get_current_epoch(state))
    prev = int(spec.get_previous_epoch(state))
    tb = arr.total_active_balance(cur, incr)
    base_per_incr = (incr * int(spec.BASE_REWARD_FACTOR) // isqrt(tb))
    base = (arr.eff // incr) * base_per_incr
    eligible = arr.eligible(prev)
    leak = bool(spec.is_in_inactivity_leak(state))
    active_increments = tb // incr
    wd = int(spec.WEIGHT_DENOMINATOR)

    flag_specs = []
    for flag_index, weight in enumerate(spec.PARTICIPATION_FLAG_WEIGHTS):
        flag_specs.append((
            int(weight),
            altair_unslashed_participating(
                spec, state, arr, flag_index, prev),
            flag_index == int(spec.TIMELY_HEAD_FLAG_INDEX)))

    if MESH_ENGINE is not None:
        # the production mesh path: psum reductions over ICI, bit-exact
        # to the host lanes below; invariant arrays shard once
        sets = MESH_ENGINE.flag_set_batch(
            arr.eff // incr, arr.active(cur), eligible,
            [(w, wd, unsl, head) for w, unsl, head in flag_specs],
            base_per_incr, leak)
    else:
        sets = []
        for w, unsl, head_flag in flag_specs:
            part_incr = int(arr.eff[unsl].sum())
            part_incr = max(incr, part_incr) // incr
            rewards = np.zeros(n, np.int64)
            penalties = np.zeros(n, np.int64)
            if not leak:
                num = base * w * part_incr
                rewards = np.where(eligible & unsl,
                                   num // (active_increments * wd), 0)
            if not head_flag:
                penalties = np.where(eligible & ~unsl, base * w // wd, 0)
            sets.append((rewards, penalties))

    # inactivity penalties
    scores = np.fromiter(
        (int(s) for s in state.inactivity_scores), np.int64, n)
    tgt_unsl = altair_unslashed_participating(
        spec, state, arr, int(spec.TIMELY_TARGET_FLAG_INDEX), prev)
    denom = (int(spec.config.INACTIVITY_SCORE_BIAS)
             * int(spec.inactivity_penalty_quotient()))
    pen = arr.eff * scores // denom
    penalties = np.where(eligible & ~tgt_unsl, pen, 0)
    sets.append((np.zeros(n, np.int64), penalties))
    return arr, sets


def altair_inactivity_updates(spec, state) -> None:
    """Vectorized process_inactivity_updates (altair beacon-chain.md:602)."""
    arr = StateArrays(state)
    prev = int(spec.get_previous_epoch(state))
    eligible = arr.eligible(prev)
    tgt_unsl = altair_unslashed_participating(
        spec, state, arr, int(spec.TIMELY_TARGET_FLAG_INDEX), prev)
    scores = np.fromiter(
        (int(s) for s in state.inactivity_scores), np.int64, arr.n)
    new = scores.copy()
    bias = int(spec.config.INACTIVITY_SCORE_BIAS)
    new = np.where(eligible & tgt_unsl, new - np.minimum(1, new), new)
    new = np.where(eligible & ~tgt_unsl, new + bias, new)
    if not bool(spec.is_in_inactivity_leak(state)):
        rec = int(spec.config.INACTIVITY_SCORE_RECOVERY_RATE)
        new = np.where(eligible, new - np.minimum(rec, new), new)
    for i in np.nonzero(new != scores)[0]:
        state.inactivity_scores[int(i)] = int(new[i])


# ---------------------------------------------------------------------------
# balance application & remaining passes
# ---------------------------------------------------------------------------

def apply_delta_sets(state, arr: StateArrays, sets) -> None:
    """Apply (rewards, penalties) sets sequentially with the spec's
    zero-floor decrease semantics."""
    bal = arr.balances
    new = bal.copy()
    for rewards, penalties in sets:
        new = np.maximum(new + rewards - penalties, 0)
    _write_balances(state, bal, new)
    arr.balances = new


def slashings_pass(spec, state) -> bool:
    """Vectorized process_slashings; handles both the phase0/altair form
    (beacon-chain.md:1640) and electra's increment-factored penalty
    (electra beacon-chain.md:846).  Returns False if the spec overrides
    process_slashings with something unknown."""
    arr = StateArrays(state)
    incr = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    epoch = int(spec.get_current_epoch(state))
    tb = arr.total_active_balance(epoch, incr)
    adj = min(sum(int(x) for x in state.slashings)
              * int(spec.proportional_slashing_multiplier()), tb)
    mask = arr.slashed & (
        np.uint64(epoch + int(spec.EPOCHS_PER_SLASHINGS_VECTOR) // 2)
        == arr.withdrawable)
    electra = bool(spec.is_post("electra"))
    if adj == 0 or not mask.any():
        # nothing slashable this epoch: skip the sweep entirely (the
        # device dispatch would provably return all zeros)
        masked_pen = np.zeros(arr.n, np.int64)
    elif MESH_ENGINE is not None:
        # the compiled validator-axis sweep (single-chip or mesh —
        # same program, psums collapse at n_dev=1)
        masked_pen = MESH_ENGINE.slashings_batch(
            arr.eff // incr, mask, adj, tb, incr, electra)
    elif electra:
        per_incr = adj // (tb // incr)
        masked_pen = np.where(mask, (arr.eff // incr) * per_incr, 0)
    else:
        masked_pen = np.where(mask,
                              (arr.eff // incr) * adj // tb * incr, 0)
    new = np.maximum(arr.balances - masked_pen, 0)
    _write_balances(state, arr.balances, new)
    return True


def effective_balance_updates_pass(spec, state) -> None:
    """Vectorized process_effective_balance_updates
    (beacon-chain.md:1656; electra compounding max via credential
    prefix)."""
    arr = StateArrays(state)
    incr = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    h = incr // int(spec.HYSTERESIS_QUOTIENT)
    down = h * int(spec.HYSTERESIS_DOWNWARD_MULTIPLIER)
    up = h * int(spec.HYSTERESIS_UPWARD_MULTIPLIER)
    if spec.is_post("electra"):
        prefix = np.fromiter(
            (v.withdrawal_credentials[0] for v in state.validators),
            np.uint8, arr.n)
        comp = prefix == int.from_bytes(
            bytes(spec.COMPOUNDING_WITHDRAWAL_PREFIX), "big")
        max_eff = np.where(comp, int(spec.MAX_EFFECTIVE_BALANCE_ELECTRA),
                           int(spec.MIN_ACTIVATION_BALANCE))
    else:
        max_eff = np.full(arr.n, int(spec.MAX_EFFECTIVE_BALANCE), np.int64)
    cond = ((arr.balances + down < arr.eff)
            | (arr.eff + up < arr.balances))
    new_eff = np.minimum(arr.balances - arr.balances % incr, max_eff)
    for i in np.nonzero(cond & (new_eff != arr.eff))[0]:
        state.validators[int(i)].effective_balance = int(new_eff[i])


def registry_updates_pass(spec, state) -> None:
    """Vectorized pre-electra process_registry_updates
    (beacon-chain.md:1590): mask-based eligibility/ejection detection,
    lexsort-based activation queue; only the (rare) mutating indices run
    scalar spec calls so churn bookkeeping stays identical."""
    arr = StateArrays(state)
    cur = int(spec.get_current_epoch(state))
    far = np.uint64(int(spec.FAR_FUTURE_EPOCH))

    # eligibility for the activation queue
    elig_q = (arr.activation_eligibility == far) & (
        arr.eff == int(spec.MAX_EFFECTIVE_BALANCE))
    for i in np.nonzero(elig_q)[0]:
        state.validators[int(i)].activation_eligibility_epoch = cur + 1
        arr.activation_eligibility[i] = cur + 1

    # ejections (sequential churn semantics via scalar initiate)
    eject = arr.active(cur) & (
        arr.eff <= int(spec.config.EJECTION_BALANCE))
    for i in np.nonzero(eject)[0]:
        spec.initiate_validator_exit(state, int(i))

    # activation queue: finalized-eligibility, not yet activated
    finalized = int(state.finalized_checkpoint.epoch)
    ready = ((arr.activation_eligibility <= np.uint64(finalized))
             & (arr.activation == far))
    idx = np.nonzero(ready)[0]
    order = np.lexsort((idx, arr.activation_eligibility[idx]))
    churn = int(spec.get_validator_churn_limit(state))
    target_epoch = int(spec.compute_activation_exit_epoch(cur))
    for i in idx[order][:churn]:
        state.validators[int(i)].activation_epoch = target_epoch
