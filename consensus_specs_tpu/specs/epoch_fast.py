"""Fused epoch processing behind the `ops.epoch_sweep` dispatch seam.

The reference's epoch passes are per-validator Python loops over O(n)
validators with O(n) helpers inside (e.g. `get_base_reward` recomputing
the total active balance), which is quadratic at mainnet scale
(reference: specs/phase0/beacon-chain.md:1553-1589, altair:385-421).
This engine extracts a structure-of-arrays snapshot of the validator
registry ONCE per epoch (`StateArrays`), precomputes the
committee-dependent masks and global scalars on host, and hands every
hot pass — attestation / participation-flag delta sets, inactivity
scores, slashings, effective-balance hysteresis, registry-eligibility
masks — to ONE registered device dispatch::

    resilience.dispatch("ops.epoch_sweep", device_fn, numpy_fallback)

`ops/epoch_sweep.py` holds the fused jitted program (the only module
allowed to import it is this one — speclint `epoch-scalar-bypass`);
`numpy_sweep` here is the counted, byte-identical fallback AND the
differential-guard oracle.  Writeback is batched through
`ssz.incremental.bulk_set_basic` — one Python-level call per mutated
column (balances, inactivity scores), marking the dirty merkle cone in
one pass — so a mainnet everyone's-balance-changed epoch no longer pays
1M `__setitem__` round trips and the re-root stays the O(dirty) fused
device sweep.  The rare per-validator mutations (registry churn,
effective-balance hysteresis hits) stay scalar spec calls.

Escape hatches: `scalar_epoch()` restores the reference-shaped scalar
pass list (differential testing, the bench scalar leg);
`supervisor.force_scalar()` keeps the fused shape but pins the numpy
fallback (counted, reason `disabled`).  `set_guard(rate, seed)` arms
sampled lane-for-lane comparison of device output against the numpy
oracle — a mismatch quarantines the site and returns the oracle lanes.

Public surface (everything else is engine-internal — speclint
`epoch-scalar-bypass` flags outside access): ENABLED, SWEEP_SITE,
scalar_epoch, fused_epoch, set_guard.
"""
from __future__ import annotations

import contextlib
import random
from math import isqrt

import numpy as np

ENABLED = True

SWEEP_SITE = "ops.epoch_sweep"

_I64MAX = np.iinfo(np.int64).max
_ORDER_BITS = 24          # attestations per epoch < 2**24; delay keys above

_GUARD_RATE = 0.0
_GUARD_RNG = random.Random(0)


@contextlib.contextmanager
def scalar_epoch():
    """Temporarily disable the fused engine (differential testing)."""
    global ENABLED
    prev, ENABLED = ENABLED, False
    try:
        yield
    finally:
        ENABLED = prev


def set_guard(rate: float, seed: int = 0) -> None:
    """Differential-guard sampling probability for the fused sweep
    (production: low single-digit percent; the chaos tier runs 1.0).
    A sampled epoch recomputes every lane through `numpy_sweep` and
    compares; a mismatch quarantines `ops.epoch_sweep` and the oracle
    lanes are the ones written back."""
    global _GUARD_RATE, _GUARD_RNG
    if not 0.0 <= rate <= 1.0:
        raise ValueError(f"guard rate {rate} outside [0, 1]")
    _GUARD_RATE = rate
    _GUARD_RNG = random.Random(seed)


# ---------------------------------------------------------------------------
# structure-of-arrays extraction
# ---------------------------------------------------------------------------

class StateArrays:
    """Validator-axis columns of the BeaconState (read-only snapshot)."""

    def __init__(self, state):
        vs = state.validators
        n = len(vs)
        self.n = n
        self.eff = np.fromiter(
            (int(v.effective_balance) for v in vs), np.int64, n)
        self.slashed = np.fromiter((bool(v.slashed) for v in vs), bool, n)
        self.activation_eligibility = np.fromiter(
            (int(v.activation_eligibility_epoch) for v in vs), np.uint64, n)
        self.activation = np.fromiter(
            (int(v.activation_epoch) for v in vs), np.uint64, n)
        self.exit = np.fromiter(
            (int(v.exit_epoch) for v in vs), np.uint64, n)
        self.withdrawable = np.fromiter(
            (int(v.withdrawable_epoch) for v in vs), np.uint64, n)
        self.balances = np.fromiter(
            (int(b) for b in state.balances), np.int64, n)

    def active(self, epoch) -> np.ndarray:
        e = np.uint64(int(epoch))
        return (self.activation <= e) & (e < self.exit)

    def eligible(self, previous_epoch) -> np.ndarray:
        """Reference get_eligible_validator_indices semantics."""
        prev = int(previous_epoch)
        return self.active(prev) | (
            self.slashed & (np.uint64(prev + 1) < self.withdrawable))

    def total_active_balance(self, epoch, increment) -> int:
        return max(int(increment), int(self.eff[self.active(epoch)].sum()))


# ---------------------------------------------------------------------------
# phase0: attestation participation masks
# ---------------------------------------------------------------------------

def phase0_attestation_masks(spec, state, epoch, targets_only=False):
    """source/target/head attester masks for `epoch`'s pending attestations
    plus, per source attester, the minimal-inclusion-delay key and its
    proposer (reference beacon-chain.md:1497-1551 matching helpers).

    `targets_only` skips the head/inclusion-delay bookkeeping — the
    justification pass needs only the target mask."""
    n = len(state.validators)
    src = np.zeros(n, bool)
    tgt = np.zeros(n, bool)
    head = np.zeros(n, bool)
    best_key = np.full(n, _I64MAX, np.int64)
    best_prop = np.zeros(n, np.int64)
    atts = spec.get_matching_source_attestations(state, epoch)
    if not atts:
        return src, tgt, head, best_key, best_prop
    target_root = spec.get_block_root(state, epoch)
    for order, a in enumerate(atts):
        committee = spec.get_beacon_committee(
            state, a.data.slot, a.data.index)
        m = len(committee)
        comm = np.fromiter((int(c) for c in committee), np.int64, m)
        bits = np.fromiter(
            (bool(b) for b in a.aggregation_bits), bool, m)
        att = comm[bits]
        src[att] = True
        if a.data.target.root == target_root:
            tgt[att] = True
            if not targets_only and a.data.beacon_block_root == \
                    spec.get_block_root_at_slot(state, int(a.data.slot)):
                head[att] = True
        if targets_only:
            continue
        key = (int(a.inclusion_delay) << _ORDER_BITS) | order
        upd = key < best_key[att]
        best_key[att] = np.where(upd, key, best_key[att])
        best_prop[att] = np.where(upd, int(a.proposer_index), best_prop[att])
    return src, tgt, head, best_key, best_prop


def _participation(state, which: str, n: int) -> np.ndarray:
    col = (state.previous_epoch_participation if which == "previous"
           else state.current_epoch_participation)
    return np.fromiter((int(x) for x in col), np.int64, n)


# ---------------------------------------------------------------------------
# sweep inputs: everything the fused program needs, host-extracted once
# ---------------------------------------------------------------------------

class SweepInputs:
    """Immutable-by-convention bundle crossing the dispatch seam.

    `family` is "phase0" or "altair"; `cols` maps the family's column
    names (ops.epoch_sweep.{PHASE0,ALTAIR}_COLS) to length-n numpy
    arrays; `scalars` maps the family's scalar names to numpy 0-d
    values; `statics` is a sorted tuple of (name, value) pairs baked
    into the compiled program (the compile-cache key)."""

    __slots__ = ("family", "n", "cols", "scalars", "statics")

    def __init__(self, family, n, cols, scalars, statics):
        self.family = family
        self.n = n
        self.cols = cols
        self.scalars = scalars
        self.statics = statics


def _collect(spec, state, arr, part_prev, masks_prev, do_rewards, leak,
             tb, cur, prev):
    """Build SweepInputs from the post-justification state.  Every value
    here is read ONCE; the sweep (device or numpy) is a pure function of
    this bundle."""
    n = arr.n
    incr = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    altair_family = bool(spec.is_post("altair"))
    electra = bool(spec.is_post("electra"))
    finalized = int(state.finalized_checkpoint.epoch)
    adj = min(sum(int(x) for x in state.slashings)
              * int(spec.proportional_slashing_multiplier()), tb)
    slash_epoch = cur + int(spec.EPOCHS_PER_SLASHINGS_VECTOR) // 2
    if electra:
        prefix = np.fromiter(
            (v.withdrawal_credentials[0] for v in state.validators),
            np.uint8, n)
        comp = prefix == int.from_bytes(
            bytes(spec.COMPOUNDING_WITHDRAWAL_PREFIX), "big")
        max_eff = np.where(comp, int(spec.MAX_EFFECTIVE_BALANCE_ELECTRA),
                           int(spec.MIN_ACTIVATION_BALANCE)).astype(np.int64)
    else:
        max_eff = np.full(n, int(spec.MAX_EFFECTIVE_BALANCE), np.int64)
    cols = {
        "eff": arr.eff, "slashed": arr.slashed,
        "activation": arr.activation, "exit_epoch": arr.exit,
        "act_elig": arr.activation_eligibility,
        "withdrawable": arr.withdrawable,
        "balances": arr.balances, "max_eff": max_eff,
    }
    scalars = {
        "cur": np.uint64(cur), "prev": np.uint64(prev),
        "finalized": np.uint64(finalized),
        "slash_epoch": np.uint64(slash_epoch),
        "tb": np.int64(tb), "adj": np.int64(adj),
    }
    statics = {
        "do_rewards": bool(do_rewards), "leak": bool(leak), "incr": incr,
        "max_eb": int(spec.MAX_EFFECTIVE_BALANCE),
        "ejection": int(spec.config.EJECTION_BALANCE),
        "hyst_q": int(spec.HYSTERESIS_QUOTIENT),
        "hyst_down": int(spec.HYSTERESIS_DOWNWARD_MULTIPLIER),
        "hyst_up": int(spec.HYSTERESIS_UPWARD_MULTIPLIER),
    }
    if altair_family:
        family = "altair"
        cols["part_prev"] = part_prev
        cols["scores"] = np.fromiter(
            (int(s) for s in state.inactivity_scores), np.int64, n)
        scalars["base_per_incr"] = np.int64(
            incr * int(spec.BASE_REWARD_FACTOR) // isqrt(tb))
        scalars["bias"] = np.int64(int(spec.config.INACTIVITY_SCORE_BIAS))
        scalars["recovery"] = np.int64(
            int(spec.config.INACTIVITY_SCORE_RECOVERY_RATE))
        scalars["inact_denom"] = np.int64(
            int(spec.config.INACTIVITY_SCORE_BIAS)
            * int(spec.inactivity_penalty_quotient()))
        statics["electra"] = electra
        statics["wd"] = int(spec.WEIGHT_DENOMINATOR)
        statics["target_flag"] = int(spec.TIMELY_TARGET_FLAG_INDEX)
        statics["flags"] = tuple(
            (i, int(w), i == int(spec.TIMELY_HEAD_FLAG_INDEX))
            for i, w in enumerate(spec.PARTICIPATION_FLAG_WEIGHTS))
    else:
        family = "phase0"
        if masks_prev is None:
            src = np.zeros(n, bool)
            tgt = np.zeros(n, bool)
            head = np.zeros(n, bool)
            best_key = np.full(n, _I64MAX, np.int64)
            best_prop = np.zeros(n, np.int64)
        else:
            src, tgt, head, best_key, best_prop = masks_prev
        cols.update(src=src, tgt=tgt, head=head,
                    best_key=best_key, best_prop=best_prop)
        scalars["sqrt_tb"] = np.int64(isqrt(tb))
        scalars["finality_delay"] = np.int64(
            int(spec.get_finality_delay(state)) if do_rewards else 1)
        statics["brf"] = int(spec.BASE_REWARD_FACTOR)
        statics["brpe"] = int(spec.BASE_REWARDS_PER_EPOCH)
        statics["prop_q"] = int(spec.PROPOSER_REWARD_QUOTIENT)
        statics["inact_q"] = int(spec.INACTIVITY_PENALTY_QUOTIENT)
    return SweepInputs(family, n, cols, scalars,
                       tuple(sorted(statics.items())))


# ---------------------------------------------------------------------------
# the numpy twin: counted fallback AND differential-guard oracle
# ---------------------------------------------------------------------------

def numpy_sweep(inp: SweepInputs):
    """Exact lane math of the `ops.epoch_sweep` device program, in host
    numpy, from the same SweepInputs — byte-identical by construction
    (the fork-matrix differential tests pin device == numpy ==
    `scalar_epoch()` post-state roots).  All integer math is int64 with
    non-negative operands and non-zero divisors, so `//` agrees with
    the device's floor division exactly."""
    st = dict(inp.statics)
    c = inp.cols
    incr = st["incr"]
    n = inp.n
    cur = np.uint64(inp.scalars["cur"])
    prev = np.uint64(inp.scalars["prev"])
    finalized = np.uint64(inp.scalars["finalized"])
    slash_epoch = np.uint64(inp.scalars["slash_epoch"])
    tb = int(inp.scalars["tb"])
    adj = int(inp.scalars["adj"])
    eff = c["eff"]
    slashed = c["slashed"]
    activation = c["activation"]
    exit_epoch = c["exit_epoch"]
    far = np.uint64((1 << 64) - 1)

    active_prev = (activation <= prev) & (prev < exit_epoch)
    active_cur = (activation <= cur) & (cur < exit_epoch)
    eligible = active_prev | (
        slashed & (np.uint64(int(prev) + 1) < c["withdrawable"]))
    unsl = ~slashed
    bal = c["balances"]
    new_scores = None

    if inp.family == "phase0":
        if st["do_rewards"]:
            base = eff * st["brf"] // int(inp.scalars["sqrt_tb"]) \
                // st["brpe"]
            prop_reward = base // st["prop_q"]
            rewards = np.zeros(n, np.int64)
            penalties = np.zeros(n, np.int64)
            for mask in (c["src"], c["tgt"], c["head"]):
                m = mask & unsl
                if st["leak"]:
                    comp = base
                else:
                    att_bal = max(incr, int(eff[m].sum()))
                    comp = base * (att_bal // incr) // (tb // incr)
                rewards = rewards + np.where(eligible & m, comp, 0)
                penalties = penalties + np.where(eligible & ~m, base, 0)
            unsl_src = c["src"] & unsl
            delays = c["best_key"] >> _ORDER_BITS
            rewards = rewards + np.where(
                unsl_src, (base - prop_reward) // delays, 0)
            prop_gain = np.zeros(n, np.int64)
            np.add.at(prop_gain, c["best_prop"],
                      np.where(unsl_src, prop_reward, 0))
            rewards = rewards + prop_gain
            if st["leak"]:
                unsl_tgt = c["tgt"] & unsl
                penalties = penalties + np.where(
                    eligible, st["brpe"] * base - prop_reward, 0)
                penalties = penalties + np.where(
                    eligible & ~unsl_tgt,
                    eff * int(inp.scalars["finality_delay"])
                    // st["inact_q"], 0)
            bal = np.maximum(bal + rewards - penalties, 0)
    else:
        new_scores = c["scores"]
        if st["do_rewards"]:
            part_prev = c["part_prev"]
            tflag = st["target_flag"]
            tgt_unsl = (active_prev & (((part_prev >> tflag) & 1) == 1)
                        & unsl)
            bias = int(inp.scalars["bias"])
            new_scores = np.where(
                eligible & tgt_unsl,
                new_scores - np.minimum(1, new_scores), new_scores)
            new_scores = np.where(
                eligible & ~tgt_unsl, new_scores + bias, new_scores)
            if not st["leak"]:
                rec = int(inp.scalars["recovery"])
                new_scores = np.where(
                    eligible, new_scores - np.minimum(rec, new_scores),
                    new_scores)
            active_incr = tb // incr
            base = (eff // incr) * int(inp.scalars["base_per_incr"])
            for flag_idx, weight, is_head in st["flags"]:
                funsl = (active_prev
                         & (((part_prev >> flag_idx) & 1) == 1) & unsl)
                if st["leak"]:
                    r = 0
                else:
                    part_incr = max(incr, int(eff[funsl].sum())) // incr
                    r = np.where(
                        eligible & funsl,
                        base * weight * part_incr
                        // (active_incr * st["wd"]), 0)
                if is_head:
                    p = 0
                else:
                    p = np.where(eligible & ~funsl,
                                 base * weight // st["wd"], 0)
                bal = np.maximum(bal + r - p, 0)
            pen = eff * new_scores // int(inp.scalars["inact_denom"])
            bal = np.maximum(
                bal - np.where(eligible & ~tgt_unsl, pen, 0), 0)

    # slashings
    eff_incr = eff // incr
    if st.get("electra"):
        pen = eff_incr * (adj // (tb // incr))
    else:
        pen = eff_incr * adj // tb * incr
    slash_mask = slashed & (c["withdrawable"] == slash_epoch)
    bal = np.maximum(bal - np.where(slash_mask, pen, 0), 0)

    # effective-balance hysteresis
    h = incr // st["hyst_q"]
    cond = ((bal + h * st["hyst_down"] < eff)
            | (eff + h * st["hyst_up"] < bal))
    new_eff = np.where(
        cond, np.minimum(bal - bal % incr, c["max_eff"]), eff)

    # registry-update eligibility masks
    elig_q = (c["act_elig"] == far) & (eff == st["max_eb"])
    eject = active_cur & (eff <= st["ejection"])
    ready = (c["act_elig"] <= finalized) & (activation == far)

    if new_scores is None:
        return bal, new_eff, elig_q, eject, ready
    return bal, new_scores, new_eff, elig_q, eject, ready


# ---------------------------------------------------------------------------
# writeback + registry application (the rare scalar mutations)
# ---------------------------------------------------------------------------

def _bulk_write(view, old: np.ndarray, new: np.ndarray) -> int:
    """ONE Python-level writeback call for a whole mutated column: the
    changed-index vector + packed values go through
    `incremental.bulk_set_basic`, which marks the dirty merkle cone in
    one pass.  Returns the element count (epoch_writeback_elems)."""
    changed = np.nonzero(new != old)[0]
    if changed.size:
        from ..ssz import incremental
        incremental.bulk_set_basic(view, changed, new[changed])
    return int(changed.size)


def _apply_registry(spec, state, cur, arr, elig_q, eject, ready) -> None:
    """Pre-electra process_registry_updates from the sweep's masks
    (beacon-chain.md:1590): only the (rare) mutating indices run scalar
    spec calls so churn bookkeeping stays identical."""
    for i in np.nonzero(elig_q)[0].tolist():
        state.validators[i].activation_eligibility_epoch = cur + 1
    for i in np.nonzero(eject)[0].tolist():
        spec.initiate_validator_exit(state, i)
    # activation queue: the sweep's `ready` mask is computed from the
    # PRE-update eligibility epochs, which is exact — newly eligible
    # validators get epoch cur+1 > finalized and can never be ready in
    # the same epoch
    idx = np.nonzero(ready)[0]
    order = np.lexsort((idx, arr.activation_eligibility[idx]))
    churn = int(spec.get_validator_churn_limit(state))
    target_epoch = int(spec.compute_activation_exit_epoch(cur))
    for i in idx[order][:churn].tolist():
        state.validators[i].activation_epoch = target_epoch


def _fallback_reason() -> str:
    from ..resilience import supervisor
    sup = supervisor.active()
    if sup is None:
        return "unsupervised"
    if sup.forced_scalar:
        return "disabled"
    state = sup.breaker_state(SWEEP_SITE)
    if state == supervisor.QUARANTINED:
        return "quarantined"
    if state == supervisor.OPEN:
        return "breaker_open"
    return "dispatch_failed"


# ---------------------------------------------------------------------------
# the orchestrator: ONE dispatch per process_epoch
# ---------------------------------------------------------------------------

def fused_epoch(spec, state) -> bool:
    """Run the fused head of `process_epoch` — justification through the
    effective-balance update (electra: including the scalar registry +
    pending-deposit/consolidation queues at their reference positions) —
    with exactly ONE `ops.epoch_sweep` dispatch.  Returns True when it
    handled that prefix (the caller then runs only the tail resets);
    returns False — before mutating anything — when the engine is
    disabled, so the caller falls through to the reference-shaped scalar
    pass list."""
    if not ENABLED:
        return False
    n = len(state.validators)
    if n == 0:
        return False
    from ..sigpipe.metrics import METRICS

    altair_family = bool(spec.is_post("altair"))
    electra = bool(spec.is_post("electra"))
    cur = int(spec.get_current_epoch(state))
    prev = int(spec.get_previous_epoch(state))
    genesis = int(spec.GENESIS_EPOCH)
    do_rewards = cur != genesis
    incr = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    arr = StateArrays(state)
    tb = arr.total_active_balance(cur, incr)

    # -- host prefix: justification (checkpoint/bit mutations only) ----
    part_prev = None
    masks_prev = None
    if altair_family:
        part_prev = _participation(state, "previous", n)
    elif do_rewards:
        masks_prev = phase0_attestation_masks(spec, state, prev)
    if cur > genesis + 1:
        if altair_family:
            tflag = int(spec.TIMELY_TARGET_FLAG_INDEX)
            prev_m = (arr.active(prev)
                      & (((part_prev >> tflag) & 1) == 1) & ~arr.slashed)
            part_cur = _participation(state, "current", n)
            cur_m = (arr.active(cur)
                     & (((part_cur >> tflag) & 1) == 1) & ~arr.slashed)
        else:
            prev_m = masks_prev[1] & ~arr.slashed
            cur_m = phase0_attestation_masks(
                spec, state, cur, targets_only=True)[1] & ~arr.slashed
        spec.weigh_justification_and_finalization(
            state, tb,
            max(incr, int(arr.eff[prev_m].sum())),
            max(incr, int(arr.eff[cur_m].sum())))

    # leak/finality/finalized all read the POST-justification state
    leak = bool(spec.is_in_inactivity_leak(state)) if do_rewards else False
    inp = _collect(spec, state, arr, part_prev, masks_prev,
                   do_rewards, leak, tb, cur, prev)

    # -- the ONE dispatch ----------------------------------------------
    from ..resilience import supervisor

    used_fallback = False

    def _device():
        from ..ops import epoch_sweep
        return epoch_sweep.run_sweep(inp)

    def _numpy_fallback():
        nonlocal used_fallback
        used_fallback = True
        METRICS.inc_labeled("epoch_sweep_fallbacks", _fallback_reason())
        return numpy_sweep(inp)

    METRICS.inc("epoch_sweep_dispatches")
    out = supervisor.dispatch(SWEEP_SITE, _device, _numpy_fallback)

    # -- differential guard: sampled, device output only, pre-writeback
    if not used_fallback and _GUARD_RNG.random() < _GUARD_RATE:
        METRICS.inc("epoch_guard_samples")
        oracle = numpy_sweep(inp)
        if not all(np.array_equal(a, b) for a, b in zip(out, oracle)):
            METRICS.inc("epoch_guard_mismatches")
            from ..resilience.incidents import INCIDENTS
            INCIDENTS.record(SWEEP_SITE, "guard_mismatch",
                             detail="sweep lanes != numpy oracle")
            sup = supervisor.active()
            if sup is not None:
                sup.quarantine(SWEEP_SITE, "guard_mismatch")
            out = oracle

    if altair_family:
        new_bal, new_scores, new_eff, elig_q, eject, ready = out
    else:
        new_bal, new_eff, elig_q, eject, ready = out
        new_scores = None

    # -- batched writeback + the rare scalar mutations ------------------
    wb = 0
    if new_scores is not None:
        wb += _bulk_write(state.inactivity_scores,
                          inp.cols["scores"], new_scores)
    wb += _bulk_write(state.balances, arr.balances, new_bal)

    if not electra:
        _apply_registry(spec, state, cur, arr, elig_q, eject, ready)
        changed = np.nonzero(new_eff != arr.eff)[0]
        for i in changed.tolist():
            state.validators[i].effective_balance = int(new_eff[i])
        wb += int(changed.size)
    else:
        # electra's single-pass registry and its deposit/consolidation
        # queues stay scalar spec calls at their reference positions;
        # they read effective balances (untouched so far) and may move
        # balances or append validators — the sweep's hysteresis lanes
        # stay valid exactly for the untouched validators
        spec.process_registry_updates(state)
        spec.process_pending_deposits(state)
        spec.process_pending_consolidations(state)
        n2 = len(state.validators)
        bal_after = np.fromiter(
            (int(b) for b in state.balances), np.int64, n2)
        moved = np.ones(n2, bool)
        moved[:n] = bal_after[:n] != new_bal
        untouched = np.nonzero(~moved[:n] & (new_eff != arr.eff))[0]
        for i in untouched.tolist():
            state.validators[i].effective_balance = int(new_eff[i])
        wb += int(untouched.size)
        h = incr // int(spec.HYSTERESIS_QUOTIENT)
        down = h * int(spec.HYSTERESIS_DOWNWARD_MULTIPLIER)
        up = h * int(spec.HYSTERESIS_UPWARD_MULTIPLIER)
        comp_prefix = int.from_bytes(
            bytes(spec.COMPOUNDING_WITHDRAWAL_PREFIX), "big")
        for i in np.nonzero(moved)[0].tolist():
            v = state.validators[i]
            bal_i = int(bal_after[i])
            eff_i = int(v.effective_balance)
            if bal_i + down < eff_i or eff_i + up < bal_i:
                max_eb = (int(spec.MAX_EFFECTIVE_BALANCE_ELECTRA)
                          if v.withdrawal_credentials[0] == comp_prefix
                          else int(spec.MIN_ACTIVATION_BALANCE))
                v.effective_balance = min(bal_i - bal_i % incr, max_eb)

    METRICS.inc("epoch_writeback_elems", wb)
    return True
