"""Vectorized swap-or-not shuffle: the full permutation in one sweep.

The spec's per-index `compute_shuffled_index` (reference:
specs/phase0/beacon-chain.md:775-797) costs 90 rounds x 2 SHA-256 per
index.  Computing the WHOLE permutation at once collapses that to
90 x (ceil(n/256) + 1) hashes total — every index in a 256-position block
shares one `source` digest, and the swap decisions become numpy mask ops
over the index axis.  This is the committee fast path the reference gets
from its LRU layer (pysetup/spec_builders/phase0.py:59-62), re-designed as
a batched array kernel instead of memoized scalar calls.

Differentially tested against the scalar spec function
(tests/test_epoch_fast.py::test_shuffle_permutation_matches_scalar).
"""
from __future__ import annotations

import hashlib
import numpy as np


def shuffle_permutation(seed: bytes, n: int, rounds: int) -> np.ndarray:
    """perm with perm[i] == compute_shuffled_index(i, n, seed), vectorized.

    Returns an int64 array of length n.
    """
    if n <= 1:
        return np.arange(max(n, 0), dtype=np.int64)
    idx = np.arange(n, dtype=np.int64)
    n_blocks = (n + 255) // 256
    sha = hashlib.sha256
    for r in range(rounds):
        rb = bytes([r])
        pivot = int.from_bytes(sha(seed + rb).digest()[:8], "little") % n
        flip = (pivot - idx) % n
        pos = np.maximum(idx, flip)
        src = np.frombuffer(
            b"".join(sha(seed + rb + b.to_bytes(4, "little")).digest()
                     for b in range(n_blocks)),
            dtype=np.uint8).reshape(n_blocks, 32)
        byte_val = src[pos >> 8, (pos & 0xFF) >> 3]
        bit = (byte_val >> (pos & 0x07).astype(np.uint8)) & 1
        idx = np.where(bit == 1, flip, idx)
    return idx


def proposer_candidate_tables(seed: bytes, n: int,
                              max_rounds: int = 4096) -> np.ndarray:
    """random_byte[i] for the proposer rejection-sampling loop
    (beacon-chain.md:802-816): byte i%32 of hash(seed + uint64(i//32))."""
    sha = hashlib.sha256
    n_words = (max_rounds + 31) // 32
    return np.frombuffer(
        b"".join(sha(seed + w.to_bytes(8, "little")).digest()
                 for w in range(n_words)),
        dtype=np.uint8)
