"""Deneb spec: blobs (EIP-4844), KZG commitments, blob sidecars.

From-scratch implementation of /root/reference/specs/deneb/
{beacon-chain.md,polynomial-commitments.md,fork-choice.md,p2p-interface.md}
as a CapellaSpec subclass.  The KZG engine lives in crypto/kzg.py; the spec
surface re-exports it under the spec function names.
"""
from dataclasses import dataclass

from ..ssz import (
    uint64, uint256, Bitvector, Vector, List, Container, ByteList,
    ByteVector, Bytes4, Bytes20, Bytes32, Bytes48, Bytes96,
    hash_tree_root,
)
from ..ssz.proofs import (
    compute_merkle_proof, get_generalized_index,
    get_generalized_index_length, get_subtree_index,
)
from ..crypto.kzg import (
    get_kzg, bls_field_to_bytes, bytes_to_bls_field, hash_to_bls_field,
    compute_powers, bit_reversal_permutation, BYTES_PER_FIELD_ELEMENT,
)
from .capella import CapellaSpec


@dataclass
class NewPayloadRequest:
    execution_payload: object
    versioned_hashes: list
    parent_beacon_block_root: bytes


class DenebSpec(CapellaSpec):
    fork = "deneb"

    def _build_constants(self) -> None:
        super()._build_constants()
        self.VERSIONED_HASH_VERSION_KZG = b"\x01"
        self.BLS_MODULUS = \
            0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
        self.BYTES_PER_FIELD_ELEMENT = BYTES_PER_FIELD_ELEMENT
        self.BYTES_PER_BLOB = \
            BYTES_PER_FIELD_ELEMENT * self.FIELD_ELEMENTS_PER_BLOB
        self.VersionedHash = Bytes32
        self.BlobIndex = uint64
        self.KZGCommitment = Bytes48
        self.KZGProof = Bytes48
        self._kzg = get_kzg(self.FIELD_ELEMENTS_PER_BLOB)

    def _build_types(self) -> None:
        super()._build_types()
        p = self

        self.Blob = ByteVector[p.BYTES_PER_BLOB]

        class ExecutionPayload(Container):
            parent_hash: Bytes32
            fee_recipient: Bytes20
            state_root: Bytes32
            receipts_root: Bytes32
            logs_bloom: ByteVector[p.BYTES_PER_LOGS_BLOOM]
            prev_randao: Bytes32
            block_number: uint64
            gas_limit: uint64
            gas_used: uint64
            timestamp: uint64
            extra_data: ByteList[p.MAX_EXTRA_DATA_BYTES]
            base_fee_per_gas: uint256
            block_hash: Bytes32
            transactions: List[p.Transaction, p.MAX_TRANSACTIONS_PER_PAYLOAD]
            withdrawals: List[p.Withdrawal, p.MAX_WITHDRAWALS_PER_PAYLOAD]
            blob_gas_used: uint64
            excess_blob_gas: uint64

        class ExecutionPayloadHeader(Container):
            parent_hash: Bytes32
            fee_recipient: Bytes20
            state_root: Bytes32
            receipts_root: Bytes32
            logs_bloom: ByteVector[p.BYTES_PER_LOGS_BLOOM]
            prev_randao: Bytes32
            block_number: uint64
            gas_limit: uint64
            gas_used: uint64
            timestamp: uint64
            extra_data: ByteList[p.MAX_EXTRA_DATA_BYTES]
            base_fee_per_gas: uint256
            block_hash: Bytes32
            transactions_root: Bytes32
            withdrawals_root: Bytes32
            blob_gas_used: uint64
            excess_blob_gas: uint64

        class BeaconBlockBody(Container):
            randao_reveal: Bytes96
            eth1_data: p.Eth1Data
            graffiti: Bytes32
            proposer_slashings: List[p.ProposerSlashing, p.MAX_PROPOSER_SLASHINGS]
            attester_slashings: List[p.AttesterSlashing, p.MAX_ATTESTER_SLASHINGS]
            attestations: List[p.Attestation, p.MAX_ATTESTATIONS]
            deposits: List[p.Deposit, p.MAX_DEPOSITS]
            voluntary_exits: List[p.SignedVoluntaryExit, p.MAX_VOLUNTARY_EXITS]
            sync_aggregate: p.SyncAggregate
            execution_payload: ExecutionPayload
            bls_to_execution_changes: List[p.SignedBLSToExecutionChange, p.MAX_BLS_TO_EXECUTION_CHANGES]
            blob_kzg_commitments: List[Bytes48, p.MAX_BLOB_COMMITMENTS_PER_BLOCK]

        class BeaconBlock(Container):
            slot: uint64
            proposer_index: uint64
            parent_root: Bytes32
            state_root: Bytes32
            body: BeaconBlockBody

        class SignedBeaconBlock(Container):
            message: BeaconBlock
            signature: Bytes96

        class BeaconState(Container):
            genesis_time: uint64
            genesis_validators_root: Bytes32
            slot: uint64
            fork: p.Fork
            latest_block_header: p.BeaconBlockHeader
            block_roots: Vector[Bytes32, p.SLOTS_PER_HISTORICAL_ROOT]
            state_roots: Vector[Bytes32, p.SLOTS_PER_HISTORICAL_ROOT]
            historical_roots: List[Bytes32, p.HISTORICAL_ROOTS_LIMIT]
            eth1_data: p.Eth1Data
            eth1_data_votes: List[p.Eth1Data, p.EPOCHS_PER_ETH1_VOTING_PERIOD * p.SLOTS_PER_EPOCH]
            eth1_deposit_index: uint64
            validators: List[p.Validator, p.VALIDATOR_REGISTRY_LIMIT]
            balances: List[uint64, p.VALIDATOR_REGISTRY_LIMIT]
            randao_mixes: Vector[Bytes32, p.EPOCHS_PER_HISTORICAL_VECTOR]
            slashings: Vector[uint64, p.EPOCHS_PER_SLASHINGS_VECTOR]
            previous_epoch_participation: List[p.ParticipationFlags, p.VALIDATOR_REGISTRY_LIMIT]
            current_epoch_participation: List[p.ParticipationFlags, p.VALIDATOR_REGISTRY_LIMIT]
            justification_bits: Bitvector[p.JUSTIFICATION_BITS_LENGTH]
            previous_justified_checkpoint: p.Checkpoint
            current_justified_checkpoint: p.Checkpoint
            finalized_checkpoint: p.Checkpoint
            inactivity_scores: List[uint64, p.VALIDATOR_REGISTRY_LIMIT]
            current_sync_committee: p.SyncCommittee
            next_sync_committee: p.SyncCommittee
            latest_execution_payload_header: ExecutionPayloadHeader
            next_withdrawal_index: uint64
            next_withdrawal_validator_index: uint64
            historical_summaries: List[p.HistoricalSummary, p.HISTORICAL_ROOTS_LIMIT]

        class BlobSidecar(Container):
            index: uint64
            blob: p.Blob
            kzg_commitment: Bytes48
            kzg_proof: Bytes48
            signed_block_header: p.SignedBeaconBlockHeader
            kzg_commitment_inclusion_proof: Vector[Bytes32, p.KZG_COMMITMENT_INCLUSION_PROOF_DEPTH]

        class BlobIdentifier(Container):
            block_root: Bytes32
            index: uint64

        for name, cls in list(locals().items()):
            if isinstance(cls, type) and issubclass(cls, Container):
                setattr(self, name, cls)

    # ------------------------------------------------------------------
    # KZG spec surface (polynomial-commitments.md)
    # ------------------------------------------------------------------
    blob_to_kzg_commitment = property(
        lambda self: self._kzg.blob_to_kzg_commitment)
    compute_kzg_proof = property(lambda self: self._kzg.compute_kzg_proof)
    compute_blob_kzg_proof = property(
        lambda self: self._kzg.compute_blob_kzg_proof)
    verify_kzg_proof = property(lambda self: self._kzg.verify_kzg_proof)
    verify_kzg_proof_batch = property(
        lambda self: self._kzg.verify_kzg_proof_batch)
    verify_blob_kzg_proof = property(
        lambda self: self._kzg.verify_blob_kzg_proof)
    verify_blob_kzg_proof_batch = property(
        lambda self: self._kzg.verify_blob_kzg_proof_batch)
    blob_to_polynomial = property(lambda self: self._kzg.blob_to_polynomial)
    compute_challenge = property(lambda self: self._kzg.compute_challenge)
    g1_lincomb = property(lambda self: self._kzg.g1_lincomb)
    evaluate_polynomial_in_evaluation_form = property(
        lambda self: self._kzg.evaluate_polynomial_in_evaluation_form)
    # _impl tier + input validation (polynomial-commitments.md:364-521)
    compute_kzg_proof_impl = property(
        lambda self: self._kzg.compute_kzg_proof_impl)
    verify_kzg_proof_impl = property(
        lambda self: self._kzg.verify_kzg_proof_impl)
    validate_kzg_g1 = property(lambda self: self._kzg.validate_kzg_g1)

    def compute_roots_of_unity(self, order=None):
        """Roots of unity in NATURAL order (polynomial-commitments.md
        :155) — callers bit-reverse as needed, like the markdown does."""
        from ..crypto.kzg import (
            BLS_MODULUS, PRIMITIVE_ROOT_OF_UNITY, compute_powers)
        order = (int(order) if order is not None
                 else int(self.FIELD_ELEMENTS_PER_BLOB))
        assert (BLS_MODULUS - 1) % order == 0
        root = pow(PRIMITIVE_ROOT_OF_UNITY, (BLS_MODULUS - 1) // order,
                   BLS_MODULUS)
        return compute_powers(root, order)

    bytes_to_bls_field = staticmethod(bytes_to_bls_field)
    bls_field_to_bytes = staticmethod(bls_field_to_bytes)
    hash_to_bls_field = staticmethod(hash_to_bls_field)
    compute_powers = staticmethod(compute_powers)
    bit_reversal_permutation = staticmethod(bit_reversal_permutation)

    # ------------------------------------------------------------------
    # blob helpers (beacon-chain.md)
    # ------------------------------------------------------------------
    def kzg_commitment_to_versioned_hash(self, kzg_commitment) -> bytes:
        return Bytes32(self.VERSIONED_HASH_VERSION_KZG
                       + bytes(self.hash(bytes(kzg_commitment)))[1:])

    def max_blobs_per_block(self) -> int:
        return self.config.MAX_BLOBS_PER_BLOCK

    # ------------------------------------------------------------------
    # block processing deltas
    # ------------------------------------------------------------------
    def process_execution_payload(self, state, body,
                                  execution_engine) -> None:
        payload = body.execution_payload
        assert payload.parent_hash == \
            state.latest_execution_payload_header.block_hash
        assert payload.prev_randao == self.get_randao_mix(
            state, self.get_current_epoch(state))
        assert payload.timestamp == self.compute_timestamp_at_slot(
            state, state.slot)
        # [New in Deneb] blob cap
        assert len(body.blob_kzg_commitments) <= self.max_blobs_per_block()
        versioned_hashes = [
            self.kzg_commitment_to_versioned_hash(commitment)
            for commitment in body.blob_kzg_commitments]
        assert execution_engine.verify_and_notify_new_payload(
            NewPayloadRequest(
                execution_payload=payload,
                versioned_hashes=versioned_hashes,
                parent_beacon_block_root=state.latest_block_header.parent_root))
        state.latest_execution_payload_header = \
            self.build_execution_payload_header(payload)

    def build_execution_payload_header(self, payload):
        header = super().build_execution_payload_header(payload)
        header.blob_gas_used = payload.blob_gas_used
        header.excess_blob_gas = payload.excess_blob_gas
        return header

    def voluntary_exit_domain(self, state, voluntary_exit):
        # [Modified in Deneb:EIP7044] pinned to the capella fork version
        return self.compute_domain(
            self.DOMAIN_VOLUNTARY_EXIT,
            Bytes4(self.config.CAPELLA_FORK_VERSION),
            state.genesis_validators_root)

    def is_timely_target(self, state, is_matching_target,
                         inclusion_delay) -> bool:
        # [Modified in Deneb:EIP7045] no inclusion-delay bound for target
        return is_matching_target

    def check_attestation_inclusion_window(self, state, data) -> None:
        # [Modified in Deneb:EIP7045] no upper inclusion bound
        pass

    # ------------------------------------------------------------------
    # fork choice: blob data availability (deneb/fork-choice.md)
    # ------------------------------------------------------------------
    def retrieve_blobs_and_proofs(self, beacon_block_root):
        """Network-retrieval stub; tests monkeypatch this
        (the reference's pysetup/spec_builders/deneb.py:41-44 pattern)."""
        return "TEST", "TEST"

    def is_data_available(self, beacon_block_root, blob_kzg_commitments) -> bool:
        blobs, proofs = self.retrieve_blobs_and_proofs(beacon_block_root)
        if isinstance(blobs, str) and blobs == "TEST":
            return True  # stubbed retrieval: assume available
        return self.verify_blob_kzg_proof_batch(
            blobs, [bytes(c) for c in blob_kzg_commitments], proofs)

    def check_block_data_availability(self, store, signed_block) -> None:
        assert self.is_data_available(
            hash_tree_root(signed_block.message),
            signed_block.message.body.blob_kzg_commitments)

    # ------------------------------------------------------------------
    # blob sidecars (p2p-interface.md + validator.md)
    # ------------------------------------------------------------------
    def get_blob_sidecars(self, signed_block, blobs, blob_kzg_proofs):
        block = signed_block.message
        block_header = self.BeaconBlockHeader(
            slot=block.slot,
            proposer_index=block.proposer_index,
            parent_root=block.parent_root,
            state_root=block.state_root,
            body_root=hash_tree_root(block.body))
        signed_block_header = self.SignedBeaconBlockHeader(
            message=block_header, signature=signed_block.signature)
        sidecars = []
        for index, blob in enumerate(blobs):
            gindex = get_generalized_index(
                self.BeaconBlockBody, "blob_kzg_commitments", index)
            proof = compute_merkle_proof(block.body, gindex)
            sidecars.append(self.BlobSidecar(
                index=index,
                blob=blob,
                kzg_commitment=block.body.blob_kzg_commitments[index],
                kzg_proof=blob_kzg_proofs[index],
                signed_block_header=signed_block_header,
                kzg_commitment_inclusion_proof=proof))
        return sidecars

    def verify_blob_sidecar_inclusion_proof(self, blob_sidecar) -> bool:
        gindex = get_generalized_index(
            self.BeaconBlockBody, "blob_kzg_commitments",
            int(blob_sidecar.index))
        return self.is_valid_merkle_branch(
            leaf=hash_tree_root(blob_sidecar.kzg_commitment),
            branch=blob_sidecar.kzg_commitment_inclusion_proof,
            depth=self.KZG_COMMITMENT_INCLUSION_PROOF_DEPTH,
            index=get_subtree_index(gindex),
            root=blob_sidecar.signed_block_header.message.body_root)

    # ------------------------------------------------------------------
    # fork upgrade (deneb/fork.md)
    # ------------------------------------------------------------------
    def genesis_fork_versions(self):
        return (Bytes4(self.config.CAPELLA_FORK_VERSION),
                Bytes4(self.config.DENEB_FORK_VERSION))

    def upgrade_from(self, pre):
        epoch = self.get_current_epoch(pre)
        pre_header = pre.latest_execution_payload_header
        post_header = self.ExecutionPayloadHeader(
            parent_hash=pre_header.parent_hash,
            fee_recipient=pre_header.fee_recipient,
            state_root=pre_header.state_root,
            receipts_root=pre_header.receipts_root,
            logs_bloom=pre_header.logs_bloom,
            prev_randao=pre_header.prev_randao,
            block_number=pre_header.block_number,
            gas_limit=pre_header.gas_limit,
            gas_used=pre_header.gas_used,
            timestamp=pre_header.timestamp,
            extra_data=pre_header.extra_data,
            base_fee_per_gas=pre_header.base_fee_per_gas,
            block_hash=pre_header.block_hash,
            transactions_root=pre_header.transactions_root,
            withdrawals_root=pre_header.withdrawals_root,
            blob_gas_used=0,
            excess_blob_gas=0)
        post = self.BeaconState(
            genesis_time=pre.genesis_time,
            genesis_validators_root=pre.genesis_validators_root,
            slot=pre.slot,
            fork=self.Fork(
                previous_version=pre.fork.current_version,
                current_version=Bytes4(self.config.DENEB_FORK_VERSION),
                epoch=epoch),
            latest_block_header=pre.latest_block_header,
            block_roots=list(pre.block_roots),
            state_roots=list(pre.state_roots),
            historical_roots=list(pre.historical_roots),
            eth1_data=pre.eth1_data,
            eth1_data_votes=list(pre.eth1_data_votes),
            eth1_deposit_index=pre.eth1_deposit_index,
            validators=list(pre.validators),
            balances=list(pre.balances),
            randao_mixes=list(pre.randao_mixes),
            slashings=list(pre.slashings),
            previous_epoch_participation=list(
                pre.previous_epoch_participation),
            current_epoch_participation=list(
                pre.current_epoch_participation),
            justification_bits=list(pre.justification_bits),
            previous_justified_checkpoint=pre.previous_justified_checkpoint,
            current_justified_checkpoint=pre.current_justified_checkpoint,
            finalized_checkpoint=pre.finalized_checkpoint,
            inactivity_scores=list(pre.inactivity_scores),
            current_sync_committee=pre.current_sync_committee,
            next_sync_committee=pre.next_sync_committee,
            latest_execution_payload_header=post_header,
            next_withdrawal_index=pre.next_withdrawal_index,
            next_withdrawal_validator_index=pre.next_withdrawal_validator_index,
            historical_summaries=list(pre.historical_summaries))
        return post
