"""Fulu spec: PeerDAS — cells/columns, custody groups, cell KZG proofs,
erasure recovery, peer sampling.

From-scratch implementation of /root/reference/specs/fulu/
{das-core.md,polynomial-commitments-sampling.md,fork.md,fork-choice.md,
p2p-interface.md,peer-sampling.md,beacon-chain.md} as an ElectraSpec
subclass.  The cell-proof engine lives in crypto/kzg_sampling.py.
"""
from ..ssz import (
    uint64, Vector, List, Container, ByteVector, Bytes4, Bytes32, Bytes48,
    hash_tree_root,
)
from ..ssz.proofs import (
    compute_merkle_proof, get_generalized_index, get_subtree_index,
)
from ..crypto.kzg_sampling import get_kzg_sampling
from ..utils.hash import hash as sha256_hash
from .electra import ElectraSpec
from .phase0 import bytes_to_uint64


class FuluSpec(ElectraSpec):
    fork = "fulu"

    # ------------------------------------------------------------------
    # constants & derived presets (das-core.md:42-74, sampling.md:84-96)
    # ------------------------------------------------------------------
    def _build_constants(self) -> None:
        super()._build_constants()
        self.UINT256_MAX = 2**256 - 1
        self.FIELD_ELEMENTS_PER_EXT_BLOB = 2 * self.FIELD_ELEMENTS_PER_BLOB
        self.BYTES_PER_CELL = \
            self.FIELD_ELEMENTS_PER_CELL * self.BYTES_PER_FIELD_ELEMENT
        self.CELLS_PER_EXT_BLOB = \
            self.FIELD_ELEMENTS_PER_EXT_BLOB // self.FIELD_ELEMENTS_PER_CELL
        self.RowIndex = uint64
        self.ColumnIndex = uint64
        self.CustodyIndex = uint64
        self.CellIndex = uint64
        self._kzg_sampling = get_kzg_sampling(
            self.FIELD_ELEMENTS_PER_BLOB, self.FIELD_ELEMENTS_PER_CELL)

    def _build_types(self) -> None:
        super()._build_types()
        p = self

        self.Cell = ByteVector[p.BYTES_PER_CELL]

        class DataColumnSidecar(Container):
            index: uint64
            column: List[p.Cell, p.MAX_BLOB_COMMITMENTS_PER_BLOCK]
            kzg_commitments: List[Bytes48, p.MAX_BLOB_COMMITMENTS_PER_BLOCK]
            kzg_proofs: List[Bytes48, p.MAX_BLOB_COMMITMENTS_PER_BLOCK]
            signed_block_header: p.SignedBeaconBlockHeader
            kzg_commitments_inclusion_proof: Vector[Bytes32, p.KZG_COMMITMENTS_INCLUSION_PROOF_DEPTH]

        class MatrixEntry(Container):
            cell: p.Cell
            kzg_proof: Bytes48
            column_index: uint64
            row_index: uint64

        class DataColumnIdentifier(Container):
            block_root: Bytes32
            index: uint64

        for name, cls in list(locals().items()):
            if isinstance(cls, type) and issubclass(cls, Container):
                setattr(self, name, cls)

    # ------------------------------------------------------------------
    # KZG sampling surface (polynomial-commitments-sampling.md public
    # methods + helpers)
    # ------------------------------------------------------------------
    compute_cells_and_kzg_proofs = property(
        lambda self: self._kzg_sampling.compute_cells_and_kzg_proofs)
    verify_cell_kzg_proof_batch = property(
        lambda self: self._kzg_sampling.verify_cell_kzg_proof_batch)
    recover_cells_and_kzg_proofs = property(
        lambda self: self._kzg_sampling.recover_cells_and_kzg_proofs)
    cell_to_coset_evals = property(
        lambda self: self._kzg_sampling.cell_to_coset_evals)
    coset_evals_to_cell = property(
        lambda self: self._kzg_sampling.coset_evals_to_cell)
    coset_for_cell = property(
        lambda self: self._kzg_sampling.coset_for_cell)
    coset_shift_for_cell = property(
        lambda self: self._kzg_sampling.coset_shift_for_cell)

    # ------------------------------------------------------------------
    # custody (das-core.md:102-137)
    # ------------------------------------------------------------------
    def get_custody_groups(self, node_id: int, custody_group_count: int):
        assert custody_group_count <= self.config.NUMBER_OF_CUSTODY_GROUPS
        current_id = int(node_id)
        custody_groups: list = []
        while len(custody_groups) < custody_group_count:
            digest = sha256_hash(current_id.to_bytes(32, "little"))
            custody_group = uint64(
                bytes_to_uint64(digest[0:8])
                % self.config.NUMBER_OF_CUSTODY_GROUPS)
            if custody_group not in custody_groups:
                custody_groups.append(custody_group)
            if current_id == self.UINT256_MAX:
                current_id = 0
            else:
                current_id += 1
        assert len(custody_groups) == len(set(custody_groups))
        return sorted(custody_groups)

    def compute_columns_for_custody_group(self, custody_group: int):
        assert custody_group < self.config.NUMBER_OF_CUSTODY_GROUPS
        columns_per_group = self.config.NUMBER_OF_COLUMNS \
            // self.config.NUMBER_OF_CUSTODY_GROUPS
        return sorted([
            uint64(self.config.NUMBER_OF_CUSTODY_GROUPS * i + custody_group)
            for i in range(columns_per_group)])

    # ------------------------------------------------------------------
    # matrix (das-core.md:139-186)
    # ------------------------------------------------------------------
    def compute_matrix(self, blobs):
        matrix = []
        for blob_index, blob in enumerate(blobs):
            cells, proofs = self.compute_cells_and_kzg_proofs(bytes(blob))
            for cell_index, (cell, proof) in enumerate(zip(cells, proofs)):
                matrix.append(self.MatrixEntry(
                    cell=cell,
                    kzg_proof=proof,
                    row_index=blob_index,
                    column_index=cell_index))
        return matrix

    def recover_matrix(self, partial_matrix, blob_count: int):
        matrix = []
        for blob_index in range(blob_count):
            cell_indices = [int(e.column_index) for e in partial_matrix
                            if e.row_index == blob_index]
            cells = [bytes(e.cell) for e in partial_matrix
                     if e.row_index == blob_index]
            recovered_cells, recovered_proofs = \
                self.recover_cells_and_kzg_proofs(cell_indices, cells)
            for cell_index, (cell, proof) in enumerate(
                    zip(recovered_cells, recovered_proofs)):
                matrix.append(self.MatrixEntry(
                    cell=cell,
                    kzg_proof=proof,
                    row_index=blob_index,
                    column_index=cell_index))
        return matrix

    # ------------------------------------------------------------------
    # sidecars (das-core.md:187-221, p2p-interface.md:81-141)
    # ------------------------------------------------------------------
    def compute_signed_block_header(self, signed_block):
        block = signed_block.message
        block_header = self.BeaconBlockHeader(
            slot=block.slot,
            proposer_index=block.proposer_index,
            parent_root=block.parent_root,
            state_root=block.state_root,
            body_root=hash_tree_root(block.body))
        return self.SignedBeaconBlockHeader(
            message=block_header, signature=signed_block.signature)

    def get_data_column_sidecars(self, signed_block, cells_and_kzg_proofs):
        blob_kzg_commitments = \
            signed_block.message.body.blob_kzg_commitments
        assert len(cells_and_kzg_proofs) == len(blob_kzg_commitments)
        signed_block_header = self.compute_signed_block_header(signed_block)
        kzg_commitments_inclusion_proof = compute_merkle_proof(
            signed_block.message.body,
            get_generalized_index(self.BeaconBlockBody,
                                  "blob_kzg_commitments"))
        sidecars = []
        for column_index in range(self.config.NUMBER_OF_COLUMNS):
            column_cells, column_proofs = [], []
            for cells, proofs in cells_and_kzg_proofs:
                column_cells.append(cells[column_index])
                column_proofs.append(proofs[column_index])
            sidecars.append(self.DataColumnSidecar(
                index=column_index,
                column=column_cells,
                kzg_commitments=list(blob_kzg_commitments),
                kzg_proofs=column_proofs,
                signed_block_header=signed_block_header,
                kzg_commitments_inclusion_proof=(
                    kzg_commitments_inclusion_proof)))
        return sidecars

    def verify_data_column_sidecar(self, sidecar) -> bool:
        """p2p-interface.md:81"""
        if sidecar.index >= self.config.NUMBER_OF_COLUMNS:
            return False
        if len(sidecar.kzg_commitments) == 0:
            return False
        if (len(sidecar.column) != len(sidecar.kzg_commitments)
                or len(sidecar.column) != len(sidecar.kzg_proofs)):
            return False
        return True

    def verify_data_column_sidecar_kzg_proofs(self, sidecar) -> bool:
        """p2p-interface.md:103"""
        cell_indices = [int(sidecar.index)] * len(sidecar.column)
        return self.verify_cell_kzg_proof_batch(
            commitments_bytes=[bytes(c) for c in sidecar.kzg_commitments],
            cell_indices=cell_indices,
            cells=[bytes(c) for c in sidecar.column],
            proofs_bytes=[bytes(p) for p in sidecar.kzg_proofs])

    def verify_data_column_sidecar_inclusion_proof(self, sidecar) -> bool:
        """p2p-interface.md:122"""
        gindex = get_subtree_index(get_generalized_index(
            self.BeaconBlockBody, "blob_kzg_commitments"))
        return self.is_valid_merkle_branch(
            leaf=hash_tree_root(sidecar.kzg_commitments),
            branch=sidecar.kzg_commitments_inclusion_proof,
            depth=self.KZG_COMMITMENTS_INCLUSION_PROOF_DEPTH,
            index=gindex,
            root=sidecar.signed_block_header.message.body_root)

    def compute_subnet_for_data_column_sidecar(self, column_index: int):
        return uint64(int(column_index)
                      % self.config.DATA_COLUMN_SIDECAR_SUBNET_COUNT)

    # ------------------------------------------------------------------
    # peer sampling (peer-sampling.md:33)
    # ------------------------------------------------------------------
    def get_extended_sample_count(self, allowed_failures: int) -> int:
        assert 0 <= allowed_failures <= self.config.NUMBER_OF_COLUMNS // 2

        def math_comb(n: int, k: int) -> int:
            if not 0 <= k <= n:
                return 0
            r = 1
            for i in range(min(k, n - k)):
                r = r * (n - i) // (i + 1)
            return r

        def hypergeom_cdf(k, M, n, N) -> float:
            k, M, n, N = int(k), int(M), int(n), int(N)
            return sum(math_comb(n, i) * math_comb(M - n, N - i)
                       / math_comb(M, N) for i in range(k + 1))

        number_of_columns = self.config.NUMBER_OF_COLUMNS
        samples_per_slot = self.config.SAMPLES_PER_SLOT
        worst_case_missing = number_of_columns // 2 + 1
        false_positive_threshold = hypergeom_cdf(
            0, number_of_columns, worst_case_missing, samples_per_slot)
        for sample_count in range(samples_per_slot,
                                  number_of_columns + 1):
            if hypergeom_cdf(allowed_failures, number_of_columns,
                             worst_case_missing,
                             sample_count) <= false_positive_threshold:
                break
        return uint64(sample_count)

    # ------------------------------------------------------------------
    # beacon-chain delta (beacon-chain.md:37) + fork choice
    # ------------------------------------------------------------------
    def max_blobs_per_block(self) -> int:
        # [Modified in Fulu:EIP7594]
        return self.config.MAX_BLOBS_PER_BLOCK_FULU

    def retrieve_column_sidecars(self, beacon_block_root):
        """Network-retrieval stub; tests monkeypatch
        (fulu/fork-choice.md:26 is_data_available)."""
        return "TEST"

    def is_data_available(self, beacon_block_root,
                          blob_kzg_commitments=None) -> bool:
        column_sidecars = self.retrieve_column_sidecars(beacon_block_root)
        if isinstance(column_sidecars, str) and column_sidecars == "TEST":
            return True
        return all(
            self.verify_data_column_sidecar(sidecar)
            and self.verify_data_column_sidecar_kzg_proofs(sidecar)
            for sidecar in column_sidecars)

    # ------------------------------------------------------------------
    # fork helpers (fork.md:41; compute_fork_version is the generic
    # ladder on Phase0Spec)
    # ------------------------------------------------------------------
    def genesis_fork_versions(self):
        return (Bytes4(self.config.ELECTRA_FORK_VERSION),
                Bytes4(self.config.FULU_FORK_VERSION))

    def upgrade_from(self, pre):
        """upgrade_to_fulu (fulu/fork.md:75): same state shape as electra,
        only the fork version advances."""
        epoch = self.get_current_epoch(pre)
        post = self.BeaconState(
            genesis_time=pre.genesis_time,
            genesis_validators_root=pre.genesis_validators_root,
            slot=pre.slot,
            fork=self.Fork(
                previous_version=pre.fork.current_version,
                current_version=Bytes4(self.config.FULU_FORK_VERSION),
                epoch=epoch),
            latest_block_header=pre.latest_block_header,
            block_roots=list(pre.block_roots),
            state_roots=list(pre.state_roots),
            historical_roots=list(pre.historical_roots),
            eth1_data=pre.eth1_data,
            eth1_data_votes=list(pre.eth1_data_votes),
            eth1_deposit_index=pre.eth1_deposit_index,
            validators=list(pre.validators),
            balances=list(pre.balances),
            randao_mixes=list(pre.randao_mixes),
            slashings=list(pre.slashings),
            previous_epoch_participation=list(
                pre.previous_epoch_participation),
            current_epoch_participation=list(
                pre.current_epoch_participation),
            justification_bits=list(pre.justification_bits),
            previous_justified_checkpoint=pre.previous_justified_checkpoint,
            current_justified_checkpoint=pre.current_justified_checkpoint,
            finalized_checkpoint=pre.finalized_checkpoint,
            inactivity_scores=list(pre.inactivity_scores),
            current_sync_committee=pre.current_sync_committee,
            next_sync_committee=pre.next_sync_committee,
            latest_execution_payload_header=(
                pre.latest_execution_payload_header),
            next_withdrawal_index=pre.next_withdrawal_index,
            next_withdrawal_validator_index=(
                pre.next_withdrawal_validator_index),
            historical_summaries=list(pre.historical_summaries),
            deposit_requests_start_index=pre.deposit_requests_start_index,
            deposit_balance_to_consume=pre.deposit_balance_to_consume,
            exit_balance_to_consume=pre.exit_balance_to_consume,
            earliest_exit_epoch=pre.earliest_exit_epoch,
            consolidation_balance_to_consume=(
                pre.consolidation_balance_to_consume),
            earliest_consolidation_epoch=pre.earliest_consolidation_epoch,
            pending_deposits=list(pre.pending_deposits),
            pending_partial_withdrawals=list(
                pre.pending_partial_withdrawals),
            pending_consolidations=list(pre.pending_consolidations))
        return post
