"""Phase0 LMD-GHOST fork choice.

From-scratch implementation of /root/reference/specs/phase0/fork-choice.md:
Store, get_head, on_tick/on_block/on_attestation/on_attester_slashing,
proposer boost, unrealized-checkpoint pull-up, and the proposer-reorg
helpers.  Mixed into Phase0Spec (methods use the spec's own accessors).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Set

from ..ssz import Bytes32, hash_tree_root, uint64
from ..txn import transactional


@dataclass
class LatestMessage:
    epoch: int
    root: bytes


@dataclass
class Store:
    time: int
    genesis_time: int
    justified_checkpoint: object
    finalized_checkpoint: object
    unrealized_justified_checkpoint: object
    unrealized_finalized_checkpoint: object
    proposer_boost_root: bytes
    equivocating_indices: Set[int] = field(default_factory=set)
    blocks: Dict[bytes, object] = field(default_factory=dict)
    block_states: Dict[bytes, object] = field(default_factory=dict)
    block_timeliness: Dict[bytes, bool] = field(default_factory=dict)
    checkpoint_states: Dict[object, object] = field(default_factory=dict)
    latest_messages: Dict[int, LatestMessage] = field(default_factory=dict)
    unrealized_justifications: Dict[bytes, object] = field(default_factory=dict)


class Phase0ForkChoice:
    INTERVALS_PER_SLOT = 3

    Store = Store
    LatestMessage = LatestMessage

    # ------------------------------------------------------------------
    # store construction & time
    # ------------------------------------------------------------------
    def get_forkchoice_store(self, anchor_state, anchor_block) -> Store:
        assert anchor_block.state_root == hash_tree_root(anchor_state)
        anchor_root = hash_tree_root(anchor_block)
        anchor_epoch = self.get_current_epoch(anchor_state)
        justified_checkpoint = self.Checkpoint(epoch=anchor_epoch,
                                               root=anchor_root)
        finalized_checkpoint = self.Checkpoint(epoch=anchor_epoch,
                                               root=anchor_root)
        return Store(
            time=int(anchor_state.genesis_time
                     + self.config.SECONDS_PER_SLOT * anchor_state.slot),
            genesis_time=int(anchor_state.genesis_time),
            justified_checkpoint=justified_checkpoint,
            finalized_checkpoint=finalized_checkpoint,
            unrealized_justified_checkpoint=justified_checkpoint,
            unrealized_finalized_checkpoint=finalized_checkpoint,
            proposer_boost_root=Bytes32(),
            blocks={anchor_root: anchor_block.copy()},
            block_states={anchor_root: anchor_state.copy()},
            checkpoint_states={justified_checkpoint: anchor_state.copy()},
            unrealized_justifications={anchor_root: justified_checkpoint},
        )

    def get_slots_since_genesis(self, store: Store) -> int:
        return (store.time - store.genesis_time) \
            // self.config.SECONDS_PER_SLOT

    def get_current_slot(self, store: Store) -> int:
        return uint64(self.GENESIS_SLOT + self.get_slots_since_genesis(store))

    def get_current_store_epoch(self, store: Store) -> int:
        return self.compute_epoch_at_slot(self.get_current_slot(store))

    def compute_slots_since_epoch_start(self, slot) -> int:
        return int(slot - self.compute_start_slot_at_epoch(
            self.compute_epoch_at_slot(slot)))

    # ------------------------------------------------------------------
    # ancestry & weights
    # ------------------------------------------------------------------
    def get_ancestor(self, store: Store, root, slot):
        block = store.blocks[root]
        if block.slot > slot:
            return self.get_ancestor(store, block.parent_root, slot)
        return root

    def get_checkpoint_block(self, store: Store, root, epoch):
        epoch_first_slot = self.compute_start_slot_at_epoch(epoch)
        return self.get_ancestor(store, root, epoch_first_slot)

    def calculate_committee_fraction(self, state, committee_percent) -> int:
        committee_weight = self.get_total_active_balance(state) \
            // self.SLOTS_PER_EPOCH
        return uint64((committee_weight * committee_percent) // 100)

    def get_proposer_score(self, store: Store) -> int:
        justified_checkpoint_state = \
            store.checkpoint_states[store.justified_checkpoint]
        committee_weight = \
            self.get_total_active_balance(justified_checkpoint_state) \
            // self.SLOTS_PER_EPOCH
        return uint64((committee_weight
                       * self.config.PROPOSER_SCORE_BOOST) // 100)

    def get_weight(self, store: Store, root) -> int:
        state = store.checkpoint_states[store.justified_checkpoint]
        unslashed_and_active_indices = [
            i for i in self.get_active_validator_indices(
                state, self.get_current_epoch(state))
            if not state.validators[i].slashed]
        attestation_score = uint64(sum(
            int(state.validators[i].effective_balance)
            for i in unslashed_and_active_indices
            if (int(i) in store.latest_messages
                and int(i) not in store.equivocating_indices
                and self.get_ancestor(
                    store, store.latest_messages[int(i)].root,
                    store.blocks[root].slot) == root)))
        if store.proposer_boost_root == Bytes32():
            return attestation_score
        proposer_score = uint64(0)
        if self.get_ancestor(store, store.proposer_boost_root,
                             store.blocks[root].slot) == root:
            proposer_score = self.get_proposer_score(store)
        return uint64(attestation_score + proposer_score)

    # ------------------------------------------------------------------
    # head selection
    # ------------------------------------------------------------------
    def get_voting_source(self, store: Store, block_root):
        block = store.blocks[block_root]
        current_epoch = self.get_current_store_epoch(store)
        block_epoch = self.compute_epoch_at_slot(block.slot)
        if current_epoch > block_epoch:
            # block from a prior epoch: the unrealized justification counts
            return store.unrealized_justifications[block_root]
        head_state = store.block_states[block_root]
        return head_state.current_justified_checkpoint

    def filter_block_tree(self, store: Store, block_root, blocks) -> bool:
        block = store.blocks[block_root]
        children = [root for root in store.blocks
                    if store.blocks[root].parent_root == block_root]
        if any(children):
            results = [self.filter_block_tree(store, child, blocks)
                       for child in children]
            if any(results):
                blocks[block_root] = block
                return True
            return False

        # leaf: viable-for-head criteria
        current_epoch = self.get_current_store_epoch(store)
        voting_source = self.get_voting_source(store, block_root)
        correct_justified = (
            store.justified_checkpoint.epoch == self.GENESIS_EPOCH
            or voting_source.epoch == store.justified_checkpoint.epoch
            or voting_source.epoch + 2 >= current_epoch)
        finalized_checkpoint_block = self.get_checkpoint_block(
            store, block_root, store.finalized_checkpoint.epoch)
        correct_finalized = (
            store.finalized_checkpoint.epoch == self.GENESIS_EPOCH
            or store.finalized_checkpoint.root == finalized_checkpoint_block)
        if correct_justified and correct_finalized:
            blocks[block_root] = block
            return True
        return False

    def get_filtered_block_tree(self, store: Store) -> dict:
        base = store.justified_checkpoint.root
        blocks: dict = {}
        self.filter_block_tree(store, base, blocks)
        return blocks

    def get_head(self, store: Store):
        blocks = self.get_filtered_block_tree(store)
        head = store.justified_checkpoint.root
        while True:
            children = [root for root in blocks
                        if blocks[root].parent_root == head]
            if len(children) == 0:
                return head
            # lexicographic root order breaks ties
            head = max(children,
                       key=lambda root: (self.get_weight(store, root),
                                         bytes(root)))

    # ------------------------------------------------------------------
    # checkpoint bookkeeping
    # ------------------------------------------------------------------
    def update_checkpoints(self, store: Store, justified_checkpoint,
                           finalized_checkpoint) -> None:
        if justified_checkpoint.epoch > store.justified_checkpoint.epoch:
            store.justified_checkpoint = justified_checkpoint
        if finalized_checkpoint.epoch > store.finalized_checkpoint.epoch:
            store.finalized_checkpoint = finalized_checkpoint

    def update_unrealized_checkpoints(
            self, store: Store, unrealized_justified_checkpoint,
            unrealized_finalized_checkpoint) -> None:
        if (unrealized_justified_checkpoint.epoch
                > store.unrealized_justified_checkpoint.epoch):
            store.unrealized_justified_checkpoint = \
                unrealized_justified_checkpoint
        if (unrealized_finalized_checkpoint.epoch
                > store.unrealized_finalized_checkpoint.epoch):
            store.unrealized_finalized_checkpoint = \
                unrealized_finalized_checkpoint

    def compute_pulled_up_tip(self, store: Store, block_root) -> None:
        self._apply_pulled_up_tip(store, block_root,
                                  store.blocks[block_root],
                                  store.block_states[block_root])

    def _apply_pulled_up_tip(self, store: Store, block_root, block,
                             state) -> None:
        """The body of compute_pulled_up_tip with the new block and its
        state passed as locals: on_block calls this BEFORE inserting
        into store.blocks/block_states, so the insertion can be the
        handler's last mutation (the torn-store defense)."""
        pulled = state.copy()
        self.process_justification_and_finalization(pulled)
        store.unrealized_justifications[block_root] = \
            pulled.current_justified_checkpoint
        self.update_unrealized_checkpoints(
            store, pulled.current_justified_checkpoint,
            pulled.finalized_checkpoint)
        # blocks from prior epochs apply realized checkpoints immediately
        block_epoch = self.compute_epoch_at_slot(block.slot)
        current_epoch = self.get_current_store_epoch(store)
        if block_epoch < current_epoch:
            self.update_checkpoints(store,
                                    pulled.current_justified_checkpoint,
                                    pulled.finalized_checkpoint)

    # ------------------------------------------------------------------
    # handlers
    # ------------------------------------------------------------------
    def on_tick_per_slot(self, store: Store, time: int) -> None:
        previous_slot = self.get_current_slot(store)
        store.time = int(time)
        current_slot = self.get_current_slot(store)
        if current_slot > previous_slot:
            store.proposer_boost_root = Bytes32()
        if (current_slot > previous_slot
                and self.compute_slots_since_epoch_start(current_slot) == 0):
            self.update_checkpoints(store,
                                    store.unrealized_justified_checkpoint,
                                    store.unrealized_finalized_checkpoint)

    @transactional
    def on_tick(self, store: Store, time: int) -> None:
        # tick through every intervening slot boundary
        tick_slot = (int(time) - store.genesis_time) \
            // self.config.SECONDS_PER_SLOT
        while self.get_current_slot(store) < tick_slot:
            previous_time = store.genesis_time \
                + (self.get_current_slot(store) + 1) \
                * self.config.SECONDS_PER_SLOT
            self.on_tick_per_slot(store, previous_time)
        self.on_tick_per_slot(store, time)

    @transactional
    def on_block(self, store: Store, signed_block) -> None:
        block = signed_block.message
        # parent known
        assert block.parent_root in store.block_states
        # not from the future
        assert self.get_current_slot(store) >= block.slot
        # descends from (and is after) the finalized checkpoint
        finalized_slot = self.compute_start_slot_at_epoch(
            store.finalized_checkpoint.epoch)
        assert block.slot > finalized_slot
        assert self.get_checkpoint_block(
            store, block.parent_root, store.finalized_checkpoint.epoch) \
            == store.finalized_checkpoint.root

        self.check_block_data_availability(store, signed_block)

        pre_state = store.block_states[block.parent_root]
        state = pre_state.copy()
        self.state_transition(state, signed_block, True)

        # [New in Bellatrix] merge-transition validation hook — no-op
        # before the merge fork (bellatrix/fork-choice.md on_block)
        self.validate_merge_transition_block(pre_state, block)

        block_root = hash_tree_root(block)

        # timeliness & proposer boost (computed before any mutation)
        time_into_slot = (store.time - store.genesis_time) \
            % self.config.SECONDS_PER_SLOT
        is_before_attesting_interval = time_into_slot < (
            self.config.SECONDS_PER_SLOT // self.INTERVALS_PER_SLOT)
        is_timely = (self.get_current_slot(store) == block.slot
                     and is_before_attesting_interval)
        is_first_block = store.proposer_boost_root == Bytes32()

        # Mutation phase.  blocks/block_states insertion goes LAST: the
        # final mutations are the ones that make the block visible to
        # the rest of fork choice, so a crash between any two mutations
        # can never leave a half-applied block that get_head or the
        # gossip pipeline would build on (every earlier write is keyed
        # by a root nothing else resolves yet, or is a monotone
        # checkpoint update that is valid on its own).  Defense in depth
        # under the scalar path; the txn overlay makes the whole phase
        # atomic when enabled.
        store.block_timeliness[block_root] = is_timely
        if is_timely and is_first_block:
            store.proposer_boost_root = block_root
        self.update_checkpoints(store, state.current_justified_checkpoint,
                                state.finalized_checkpoint)
        self._apply_pulled_up_tip(store, block_root, block, state)
        store.blocks[block_root] = block
        store.block_states[block_root] = state

    def check_block_data_availability(self, store, signed_block) -> None:
        """Phase0: nothing to check (deneb overrides for blob DA)."""

    def validate_merge_transition_block(self, pre_state, block) -> None:
        """Phase0/altair: nothing to validate (bellatrix overrides with
        the TTD terminal-pow-block check, bellatrix/fork-choice.md)."""

    def validate_target_epoch_against_current_time(self, store,
                                                   attestation) -> None:
        target = attestation.data.target
        current_epoch = self.get_current_store_epoch(store)
        previous_epoch = (current_epoch - 1
                          if current_epoch > self.GENESIS_EPOCH
                          else self.GENESIS_EPOCH)
        assert target.epoch in (current_epoch, previous_epoch)

    def validate_on_attestation(self, store, attestation,
                                is_from_block: bool) -> None:
        target = attestation.data.target
        if not is_from_block:
            self.validate_target_epoch_against_current_time(store, attestation)
        assert target.epoch == self.compute_epoch_at_slot(
            attestation.data.slot)
        assert target.root in store.blocks
        assert attestation.data.beacon_block_root in store.blocks
        assert store.blocks[attestation.data.beacon_block_root].slot \
            <= attestation.data.slot
        # LMD vote must be consistent with the FFG target
        assert target.root == self.get_checkpoint_block(
            store, attestation.data.beacon_block_root, target.epoch)
        # only apply after the attestation's slot has passed
        assert self.get_current_slot(store) >= attestation.data.slot + 1

    def compute_target_checkpoint_state(self, store, target):
        """The checkpoint state for `target`, computed on a private copy
        — the pure half of store_target_checkpoint_state.  The gossip
        collector (gossip/collect.py) calls this directly so its
        predicted signing roots can never drift from the handler's."""
        base_state = store.block_states[target.root].copy()
        if base_state.slot < self.compute_start_slot_at_epoch(
                target.epoch):
            self.process_slots(base_state,
                               self.compute_start_slot_at_epoch(
                                   target.epoch))
        return base_state

    def store_target_checkpoint_state(self, store, target) -> None:
        if target not in store.checkpoint_states:
            store.checkpoint_states[target] = \
                self.compute_target_checkpoint_state(store, target)

    def update_latest_messages(self, store, attesting_indices,
                               attestation) -> None:
        target = attestation.data.target
        beacon_block_root = attestation.data.beacon_block_root
        non_equivocating = [i for i in attesting_indices
                            if int(i) not in store.equivocating_indices]
        for i in non_equivocating:
            i = int(i)
            if (i not in store.latest_messages
                    or target.epoch > store.latest_messages[i].epoch):
                store.latest_messages[i] = LatestMessage(
                    epoch=int(target.epoch), root=beacon_block_root)

    def apply_attestation(self, store, attestation) -> None:
        """The store-update half of on_attestation (post-validation):
        cache the target checkpoint state, verify the indexed
        attestation, record the latest messages."""
        self.store_target_checkpoint_state(store, attestation.data.target)
        target_state = store.checkpoint_states[attestation.data.target]
        indexed_attestation = self.get_indexed_attestation(
            target_state, attestation)
        assert self.is_valid_indexed_attestation(
            target_state, indexed_attestation)
        self.update_latest_messages(
            store, indexed_attestation.attesting_indices, attestation)

    @transactional
    def on_attestation(self, store, attestation,
                       is_from_block: bool = False) -> None:
        self.validate_on_attestation(store, attestation, is_from_block)
        self.apply_attestation(store, attestation)

    # ------------------------------------------------------------------
    # gossip-path handlers (p2p-interface.md validation, executable
    # subset).  These are what the admission pipeline (gossip/) fronts;
    # every signature check flows through the bls_verify /
    # bls_fast_aggregate_verify seams so a micro-batch verdict can stand
    # in for the scalar call with byte-identical accept/reject behavior.
    # ------------------------------------------------------------------
    def aggregate_committee_index(self, aggregate) -> int:
        """Committee index of an aggregate: data.index pre-electra, the
        single set bit of committee_bits after EIP-7549."""
        bits = getattr(aggregate, "committee_bits", None)
        if bits is not None:
            indices = self.get_committee_indices(bits)
            assert len(indices) == 1
            return indices[0]
        return aggregate.data.index

    def gossip_selection_proof_check(self, state, aggregate_and_proof):
        """(pubkeys, signing_root, signature) of an aggregator's
        selection proof — THE single derivation, consumed by both
        validate_aggregate_and_proof and the gossip collector so the
        two can never drift."""
        aggregate = aggregate_and_proof.aggregate
        pubkey = state.validators[
            int(aggregate_and_proof.aggregator_index)].pubkey
        domain = self.get_domain(
            state, self.DOMAIN_SELECTION_PROOF,
            self.compute_epoch_at_slot(aggregate.data.slot))
        root = self.compute_signing_root(uint64(aggregate.data.slot),
                                         domain)
        return (pubkey,), root, aggregate_and_proof.selection_proof

    def gossip_aggregate_and_proof_check(self, state, signed):
        """(pubkeys, signing_root, signature) of the outer
        SignedAggregateAndProof envelope — shared with the collector."""
        aggregate_and_proof = signed.message
        pubkey = state.validators[
            int(aggregate_and_proof.aggregator_index)].pubkey
        domain = self.get_domain(
            state, self.DOMAIN_AGGREGATE_AND_PROOF,
            self.compute_epoch_at_slot(
                aggregate_and_proof.aggregate.data.slot))
        root = self.compute_signing_root(aggregate_and_proof, domain)
        return (pubkey,), root, signed.signature

    def validate_aggregate_and_proof(self, store, signed) -> None:
        """beacon_aggregate_and_proof gossip validation: the inner
        aggregate passes on_attestation validation, the aggregator is a
        selected member of the committee, and both the selection proof
        and the outer signature verify."""
        aggregate_and_proof = signed.message
        aggregate = aggregate_and_proof.aggregate
        aggregator_index = int(aggregate_and_proof.aggregator_index)
        self.validate_on_attestation(store, aggregate, is_from_block=False)
        self.store_target_checkpoint_state(store, aggregate.data.target)
        state = store.checkpoint_states[aggregate.data.target]
        index = self.aggregate_committee_index(aggregate)
        committee = self.get_beacon_committee(
            state, aggregate.data.slot, index)
        assert aggregator_index in [int(i) for i in committee]
        assert self.is_aggregator(state, aggregate.data.slot, index,
                                  aggregate_and_proof.selection_proof)
        pubkeys, root, signature = self.gossip_selection_proof_check(
            state, aggregate_and_proof)
        assert self.bls_verify(pubkeys[0], root, signature)
        pubkeys, root, signature = self.gossip_aggregate_and_proof_check(
            state, signed)
        assert self.bls_verify(pubkeys[0], root, signature)

    @transactional
    def on_aggregate_and_proof(self, store, signed) -> None:
        """Gossip aggregate admission: validate the envelope, then apply
        the inner aggregate.  validate_aggregate_and_proof already ran
        the full on_attestation validation, so only the store-update
        half remains — no double validation on the hot path."""
        self.validate_aggregate_and_proof(store, signed)
        self.apply_attestation(store, signed.message.aggregate)

    def validate_sync_committee_message(self, store, message) -> None:
        """sync_committee_{subnet} gossip validation (altair+): the
        referenced block is known, the validator is in the sync
        committee FOR THE MESSAGE'S SLOT (the referenced block may be
        from the previous period, whose state still knows the message
        period's committee as next_sync_committee), and the signature
        over the block root verifies."""
        assert self.is_post("altair")
        assert message.beacon_block_root in store.block_states
        state = store.block_states[message.beacon_block_root]
        validator = state.validators[message.validator_index]
        state_period = self.compute_sync_committee_period(
            self.get_current_epoch(state))
        message_period = self.compute_sync_committee_period(
            self.compute_epoch_at_slot(message.slot))
        assert message_period in (state_period, state_period + 1)
        committee = (state.current_sync_committee
                     if message_period == state_period
                     else state.next_sync_committee)
        assert validator.pubkey in list(committee.pubkeys)
        pubkeys, root, signature = self.gossip_sync_message_check(
            state, message)
        assert self.bls_verify(pubkeys[0], root, signature)

    def gossip_sync_message_check(self, state, message):
        """(pubkeys, signing_root, signature) of a sync-committee
        message — shared by validate_sync_committee_message and the
        gossip collector."""
        pubkey = state.validators[message.validator_index].pubkey
        domain = self.get_domain(state, self.DOMAIN_SYNC_COMMITTEE,
                                 self.compute_epoch_at_slot(message.slot))
        root = self.compute_signing_root(
            Bytes32(message.beacon_block_root), domain)
        return (pubkey,), root, message.signature

    @transactional
    def on_sync_committee_message(self, store, message) -> None:
        """Gossip sync-message admission: pure validation — accepted
        messages feed the local aggregator, not the fork-choice store,
        so the handler leaves `store` untouched."""
        self.validate_sync_committee_message(store, message)

    @transactional
    def on_attester_slashing(self, store, attester_slashing) -> None:
        attestation_1 = attester_slashing.attestation_1
        attestation_2 = attester_slashing.attestation_2
        assert self.is_slashable_attestation_data(
            attestation_1.data, attestation_2.data)
        state = store.block_states[store.justified_checkpoint.root]
        assert self.is_valid_indexed_attestation(state, attestation_1)
        assert self.is_valid_indexed_attestation(state, attestation_2)
        indices = set(int(i) for i in attestation_1.attesting_indices) \
            & set(int(i) for i in attestation_2.attesting_indices)
        store.equivocating_indices.update(indices)

    # ------------------------------------------------------------------
    # proposer-reorg helpers (fork-choice.md "Helpers")
    # ------------------------------------------------------------------
    def is_head_late(self, store, head_root) -> bool:
        return not store.block_timeliness[head_root]

    def is_shuffling_stable(self, slot) -> bool:
        return self.compute_slots_since_epoch_start(slot) != 0

    def is_ffg_competitive(self, store, head_root, parent_root) -> bool:
        return (store.unrealized_justifications[head_root]
                == store.unrealized_justifications[parent_root])

    def is_finalization_ok(self, store, slot) -> bool:
        epochs_since_finalization = self.compute_epoch_at_slot(slot) \
            - store.finalized_checkpoint.epoch
        return epochs_since_finalization \
            <= self.config.REORG_MAX_EPOCHS_SINCE_FINALIZATION

    def is_proposing_on_time(self, store) -> bool:
        time_into_slot = (store.time - store.genesis_time) \
            % self.config.SECONDS_PER_SLOT
        proposer_reorg_cutoff = self.config.SECONDS_PER_SLOT \
            // self.INTERVALS_PER_SLOT // 2
        return time_into_slot <= proposer_reorg_cutoff

    def is_head_weak(self, store, head_root) -> bool:
        justified_state = store.checkpoint_states[store.justified_checkpoint]
        reorg_threshold = self.calculate_committee_fraction(
            justified_state, self.config.REORG_HEAD_WEIGHT_THRESHOLD)
        return self.get_weight(store, head_root) < reorg_threshold

    def is_parent_strong(self, store, parent_root) -> bool:
        justified_state = store.checkpoint_states[store.justified_checkpoint]
        parent_threshold = self.calculate_committee_fraction(
            justified_state, self.config.REORG_PARENT_WEIGHT_THRESHOLD)
        return self.get_weight(store, parent_root) > parent_threshold

    def get_proposer_head(self, store, head_root, slot):
        head_block = store.blocks[head_root]
        parent_root = head_block.parent_root
        parent_block = store.blocks[parent_root]

        head_late = self.is_head_late(store, head_root)
        shuffling_stable = self.is_shuffling_stable(slot)
        ffg_competitive = self.is_ffg_competitive(store, head_root,
                                                  parent_root)
        finalization_ok = self.is_finalization_ok(store, slot)
        proposing_on_time = self.is_proposing_on_time(store)

        # single-slot reorgs only
        parent_slot_ok = parent_block.slot + 1 == head_block.slot
        current_time_ok = head_block.slot + 1 == slot
        single_slot_reorg = parent_slot_ok and current_time_ok

        # boost must have worn off
        assert store.proposer_boost_root != head_root
        head_weak = self.is_head_weak(store, head_root)
        parent_strong = self.is_parent_strong(store, parent_root)

        if all([head_late, shuffling_stable, ffg_competitive, finalization_ok,
                proposing_on_time, single_slot_reorg, head_weak,
                parent_strong]):
            return parent_root
        return head_root

    # safe-block helper (fork_choice/safe-block.md)
    def get_safe_beacon_block_root(self, store):
        return store.justified_checkpoint.root

    def get_safe_execution_block_hash(self, store):
        """Phase0 has no execution payloads; bellatrix overrides."""
        return Bytes32()
