"""Structured incident log for the accelerator dispatch supervisor.

Every noteworthy event at a dispatch seam — an injected fault, a device
error, a watchdog timeout, a retry, a breaker trip / half-open probe /
restore, a differential-guard mismatch, a quarantine — lands here as one
dict with a monotonic sequence number.  The log is the audit trail the
chaos tier asserts on: an injected fault that does NOT show up here is a
silent failure of the harness itself.

Bounded (FIFO over `max_entries`) and thread-safe: the supervisor's
watchdog runs dispatches on worker threads, and production operators tail
this from a metrics thread.  `snapshot()` returns plain JSON-able dicts.

`INCIDENTS` is a *router*: each record consults the node-context stack
(utils/nodectx.py) and lands in the active node's own `IncidentLog`
when the scenario harness installed one — tagged with that node's
`node_id` — or in the process-global default otherwise.  Per-node logs
may also inject a clock (the driver passes its ManualClock) so the `t`
field is simulation time and a seeded scenario's incident stream
replays bit-identically.
"""
from __future__ import annotations

import json
import time
from collections import deque

from ..utils import nodectx
from ..utils.locks import named_rlock


class IncidentLog:
    def __init__(self, max_entries: int = 4096,
                 node_id: str | None = None, clock=None):
        self._lock = named_rlock("resilience.incidents")
        self._entries: deque = deque(maxlen=max_entries)
        self._seq = 0
        self.node_id = node_id
        self._clock = clock          # None -> wall clock

    def record(self, site: str, event: str, **detail) -> dict:
        """Append one incident; returns the record (already sequenced)."""
        with self._lock:
            self._seq += 1
            t = (round(time.time(), 3) if self._clock is None
                 else round(self._clock.now(), 6))
            entry = {"seq": self._seq, "t": t,
                     "site": site, "event": event}
            if self.node_id is not None:
                entry["node_id"] = self.node_id
            entry.update(detail)
            self._entries.append(entry)
            return entry

    def snapshot(self) -> list:
        with self._lock:
            return [dict(e) for e in self._entries]

    def count(self, event: str | None = None,
              site: str | None = None) -> int:
        with self._lock:
            return sum(1 for e in self._entries
                       if (event is None or e["event"] == event)
                       and (site is None or e["site"] == site))

    def events(self, event: str) -> list:
        with self._lock:
            return [dict(e) for e in self._entries if e["event"] == event]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._seq = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def to_json(self) -> str:
        return json.dumps(self.snapshot())


INCIDENTS = nodectx.Router(IncidentLog(), "incidents")
