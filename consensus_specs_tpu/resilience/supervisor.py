"""Graceful-degradation supervisor for accelerator dispatches.

`dispatch(site, device_fn, fallback_fn)` is the single seam every
accelerator entry point routes through (utils/bls.py batch APIs and
pairing check, sigpipe's hash-to-G2 sweep, ssz/merkle device hashing,
kzg's device MSM).  With no supervisor enabled it is a two-attribute
read plus the call — behavior byte-identical to the unwrapped code,
including exception propagation.

With a supervisor enabled, each site gets a circuit breaker:

    CLOSED ──failures ≥ threshold──▶ OPEN ──probe_after fallbacks──▶
    HALF_OPEN ──probe ok──▶ CLOSED   (probe fails ─▶ OPEN again)

* Transient faults are absorbed in place: up to `max_retries` in-call
  retries with exponential backoff, never visible to the caller.
* Persistent faults trip the breaker; every dispatch at that site then
  takes the native fallback — same values, same exceptions at the same
  operation boundary, because the fallback IS the scalar-oracle code
  path — until a half-open probe answers correctly again.
* A watchdog deadline (optional) runs the dispatch on a daemon worker
  thread and abandons it on expiry: an XLA dispatch cannot be cancelled,
  but the block-processing thread must not hang with it.  The abandoned
  thread parks on the dead dispatch and is never joined — the same
  discipline production clients use for a wedged device runtime.
* `quarantine()` (the differential guard's verdict-corruption response)
  is an OPEN state that never half-opens: silent corruption means the
  device cannot be trusted to self-report recovery, so only an explicit
  operator `reset()` re-arms the accelerator path.

Degradation is observable, not silent: every retry/trip/probe/restore
lands in the incident log, and every fallback increments the
reason-labeled `scalar_fallbacks` counter (`dispatch_failed` for a
failed call below the trip threshold, `breaker_open` once tripped,
`guard_mismatch` / the quarantine reason, `disabled` for the forced
kill switch) — the reason always agrees with the breaker-state map.
"""
from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field

from ..sigpipe.metrics import METRICS
from ..utils import nodectx
from ..utils.clock import MONOTONIC
from ..utils.locks import named_lock, named_rlock
from . import faults
from .incidents import INCIDENTS

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"
QUARANTINED = "quarantined"


class DispatchTimeout(RuntimeError):
    """Watchdog deadline expired before the dispatch answered."""


@dataclass
class SupervisorConfig:
    max_retries: int = 2          # in-call retries before a failure counts
    backoff_base_s: float = 0.0   # first retry delay; doubles per retry
    breaker_threshold: int = 3    # consecutive failed calls until trip
    probe_after: int = 4          # fallback calls in OPEN before a probe
    cooldown_s: float = 0.0       # min wall-clock in OPEN before a probe
    deadline_s: float | None = None   # watchdog; None = no watchdog
    # decision clock (utils/clock.py): breaker cooldown reads and retry
    # backoff sleeps go through it so chaos schedules replay
    # deterministically under a ManualClock.  The watchdog deadline
    # stays on real thread waits — it times an actual worker thread,
    # which no virtual clock can advance.
    clock: object = field(default_factory=lambda: MONOTONIC)


class _Breaker:
    __slots__ = ("state", "consecutive_failures", "fallbacks_since_trip",
                 "tripped_at", "trips", "restores", "quarantine_reason")

    def __init__(self):
        self.state = CLOSED
        self.consecutive_failures = 0
        self.fallbacks_since_trip = 0
        self.tripped_at = 0.0
        self.trips = 0
        self.restores = 0
        self.quarantine_reason = None


class _SiteWorker:
    """One long-lived daemon worker per dispatch site for watchdog'd
    calls: the healthy path pays a queue hand-off, not a thread spawn.
    On deadline expiry the worker is abandoned (it parks on the hung
    dispatch, finishes it whenever the runtime lets go, then exits) and
    the site gets a fresh worker on the next call."""

    def __init__(self, site: str):
        self._jobs: queue.Queue = queue.Queue()
        self.abandoned = False
        self._thread = threading.Thread(
            target=self._loop, name=f"dispatch-{site}", daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            fn, box, done = self._jobs.get()
            if fn is not None:
                try:
                    box.append((True, fn()))
                except BaseException as e:   # shipped across the boundary
                    box.append((False, e))
                done.set()
            if self.abandoned:
                return

    def call(self, fn, deadline: float):
        """Run fn on the worker; returns (ok, value-or-exception), or
        None if the deadline expired (worker now abandoned)."""
        box: list = []
        done = threading.Event()
        self._jobs.put((fn, box, done))
        if not done.wait(deadline):
            self.abandoned = True
            # wake the worker if the job actually finished just now, so
            # a non-hung abandoned worker exits instead of parking on an
            # empty queue forever
            self._jobs.put((None, None, None))
            return None
        return box[0]


class Supervisor:
    def __init__(self, config: SupervisorConfig | None = None, **overrides):
        self.config = config or SupervisorConfig(**overrides)
        self._clock = self.config.clock
        self._breakers: dict = {}
        self._workers: dict = {}
        self._worker_locks: dict = {}
        self._lock = named_rlock("resilience.supervisor")
        self._forced_scalar = False

    @property
    def forced_scalar(self) -> bool:
        """True while the force_scalar() kill switch is held on."""
        return self._forced_scalar

    # -- administrative controls --------------------------------------
    def force_scalar(self, on: bool = True) -> None:
        """Administratively disable the accelerator path (every dispatch
        takes the fallback, reason `disabled`) — the bench degraded tier
        and operator kill switches use this."""
        self._forced_scalar = bool(on)

    def quarantine(self, site: str, reason: str = "guard_mismatch") -> None:
        """Permanently open `site` (no half-open probes) until reset().
        `reason` labels both the incident and every subsequent fallback
        the quarantine forces."""
        with self._lock:
            br = self._breaker(site)
            if br.state != QUARANTINED:
                br.state = QUARANTINED
                br.quarantine_reason = reason
                br.tripped_at = self._clock.now()
                br.trips += 1
                METRICS.inc("breaker_trips")
                METRICS.inc("quarantines")
                INCIDENTS.record(site, "quarantine", reason=reason)

    def reset(self, site: str | None = None) -> None:
        """Re-arm one site's breaker, or all of them."""
        with self._lock:
            sites = [site] if site is not None else list(self._breakers)
            for s in sites:
                br = self._breakers.get(s)
                if br is not None and br.state != CLOSED:
                    INCIDENTS.record(s, "reset", previous=br.state)
                self._breakers.pop(s, None)

    def breaker_state(self, site: str) -> str:
        with self._lock:
            br = self._breakers.get(site)
            return br.state if br is not None else CLOSED

    def breaker_states(self) -> dict:
        with self._lock:
            return {site: br.state for site, br in self._breakers.items()}

    # -- the seam ------------------------------------------------------
    def run(self, site: str, device_fn, fallback_fn):
        if self._forced_scalar:
            return self._fallback(site, fallback_fn, "disabled")
        with self._lock:
            br = self._breaker(site)
            state = br.state
            if state == OPEN:
                br.fallbacks_since_trip += 1
                if (br.fallbacks_since_trip >= self.config.probe_after
                        and (self._clock.now() - br.tripped_at
                             >= self.config.cooldown_s)):
                    br.state = state = HALF_OPEN
                    INCIDENTS.record(site, "probe")
                    METRICS.inc("breaker_probes")
        if state == QUARANTINED:
            return self._fallback(site, fallback_fn,
                                  br.quarantine_reason or "guard_mismatch")
        if state == OPEN:
            return self._fallback(site, fallback_fn, "breaker_open")
        # CLOSED or HALF_OPEN: attempt the device path, with in-call
        # retries for transient faults
        attempt = 0
        while True:
            try:
                result = self._call(site, device_fn)
            except Exception as e:
                attempt += 1
                kind = ("timeout" if isinstance(e, DispatchTimeout)
                        else "dispatch_error")
                INCIDENTS.record(site, kind, attempt=attempt,
                                 error=f"{type(e).__name__}: {e}")
                if state != HALF_OPEN and attempt <= self.config.max_retries:
                    METRICS.inc("dispatch_retries")
                    backoff = self.config.backoff_base_s * (
                        2 ** (attempt - 1))
                    if backoff > 0:
                        self._clock.sleep(backoff)
                    continue
                self._on_failure(site, br, state)
                # label by what the breaker actually did: below the trip
                # threshold this call failed but the site is still live
                reason = ("breaker_open"
                          if br.state in (OPEN, QUARANTINED)
                          else "dispatch_failed")
                return self._fallback(site, fallback_fn, reason)
            else:
                self._on_success(site, br, state, recovered=attempt > 0)
                return result

    # -- internals -----------------------------------------------------
    def _breaker(self, site: str) -> _Breaker:
        br = self._breakers.get(site)
        if br is None:
            br = self._breakers[site] = _Breaker()
        return br

    def _call(self, site: str, fn):
        deadline = self.config.deadline_s
        if deadline is None:
            return fn()
        # serialize watchdog'd calls per site: a job is only handed to
        # the worker when it is idle, so the deadline clocks the
        # dispatch itself — a caller queued behind a slow-but-healthy
        # dispatch waits on the site lock (uncounted), never inherits
        # the previous job's elapsed time as its own timeout
        with self._lock:
            site_lock = self._worker_locks.get(site)
            if site_lock is None:
                site_lock = self._worker_locks[site] = named_lock(
                    "resilience.site_worker")
        with site_lock:
            with self._lock:
                worker = self._workers.get(site)
                if worker is None or worker.abandoned:
                    worker = self._workers[site] = _SiteWorker(site)
            # carry the async flush engine's in-flight ticket across
            # the thread hop: the abandoned-flush cache-write
            # suppression (pipeline_async.writes_allowed) is
            # thread-local and must follow the dispatch onto this
            # site's worker
            from ..sigpipe.pipeline_async import bind_current_ticket
            outcome = worker.call(bind_current_ticket(fn), deadline)
        if outcome is None:
            # abandoned: the worker parks on the hung dispatch; the next
            # call gets a fresh one
            METRICS.inc("watchdog_timeouts")
            raise DispatchTimeout(
                f"dispatch at {site} exceeded {deadline}s watchdog")
        ok, value = outcome
        if not ok:
            raise value
        return value

    def _on_failure(self, site: str, br: _Breaker, state: str) -> None:
        with self._lock:
            br.consecutive_failures += 1
            if state == HALF_OPEN:
                # failed probe: back to OPEN, wait a full window again
                br.state = OPEN
                br.fallbacks_since_trip = 0
                br.tripped_at = self._clock.now()
                INCIDENTS.record(site, "probe_failed")
                METRICS.inc("breaker_probe_failures")
            elif (br.state == CLOSED and br.consecutive_failures
                    >= self.config.breaker_threshold):
                br.state = OPEN
                br.fallbacks_since_trip = 0
                br.tripped_at = self._clock.now()
                br.trips += 1
                INCIDENTS.record(
                    site, "trip", failures=br.consecutive_failures)
                METRICS.inc("breaker_trips")

    def _on_success(self, site: str, br: _Breaker, state: str,
                    recovered: bool) -> None:
        with self._lock:
            br.consecutive_failures = 0
            if state == HALF_OPEN:
                br.state = CLOSED
                br.restores += 1
                INCIDENTS.record(site, "restore")
                METRICS.inc("breaker_restores")
            elif recovered:
                INCIDENTS.record(site, "retry_recovered")

    def _fallback(self, site: str, fallback_fn, reason: str):
        METRICS.inc_labeled("scalar_fallbacks", reason)
        return fallback_fn()


# The active supervisor is a per-node-context ROUTER (the
# INCIDENTS/METRICS discipline): a SimNode that owns a `supervisor`
# Slot gets its own breaker table — a trip, quarantine, or
# force_scalar on node 3 leaves nodes 0-2 on the device path — while
# callers with no node context installed land on the process-global
# default cell exactly as before.
_ACTIVE = nodectx.StateRouter("supervisor")


def enable(config: SupervisorConfig | None = None, **overrides) -> Supervisor:
    """Install a supervisor at every dispatch seam (for the active node
    context's slot when one is installed, else process-global);
    returns it."""
    sup = Supervisor(config, **overrides)
    _ACTIVE.set(sup)
    return sup


def disable() -> None:
    _ACTIVE.set(None)


def enabled() -> bool:
    return _ACTIVE.get() is not None


def active() -> Supervisor | None:
    return _ACTIVE.get()


def dispatch(site: str, device_fn, fallback_fn):
    """THE accelerator dispatch seam.

    `device_fn` runs the accelerated path (whatever backend is selected);
    `fallback_fn` is the native-scalar oracle path with byte-identical
    semantics.  Fault injection (faults.py) wraps `device_fn` only — the
    fallback is the trusted path, which is exactly what makes
    trip-to-scalar a *recovery* and not a different failure mode.
    """
    plan = faults.active_plan()
    fn = plan.wrap(site, device_fn) if plan is not None else device_fn
    sup = _ACTIVE.get()
    if sup is None:
        return fn()
    return sup.run(site, fn, fallback_fn)
