"""Differential guard: sampled cross-checks of fused verdicts against the
per-set native oracle.

Raised errors and hangs are loud; a bit-flipped pairing verdict is not.
The fused scheduler turns ~n signature checks into one boolean product, so
a single silent corruption can flip a whole block's validity with no
exception anywhere.  The only defense is re-deriving a sample of verdicts
on a path that shares no hardware with the fused dispatch: the pure-Python
scalar oracle (crypto/bls12_381), called directly — not through the
backend shim, not through the caches, not through any seam faults can
reach.

On a mismatch the backend is assumed compromised: the guard quarantines
every dispatch site the fused path uses (no half-open probes — a device
that lies cannot be trusted to self-report recovery), recomputes EVERY
verdict in the batch through the oracle, and hands those back.  The block
decision is therefore always made on trusted verdicts; the sample rate
only tunes detection latency, never correctness of what was checked.

`sample_rate=1.0` is the chaos-tier setting (every fused verdict checked);
production would run low single-digit percent.
"""
from __future__ import annotations

import random

from ..sigpipe.metrics import METRICS
from ..utils import nodectx
from ..utils.locks import named_rlock
from .incidents import INCIDENTS
from .sites import fused_sites

# every site the fused pipeline's verdicts flow through; quarantined as a
# unit on mismatch (the guard cannot attribute corruption to one kernel).
# Derived from the canonical registry so the quarantine unit can never
# drift from the sites that actually exist (speclint pins the rest).
FUSED_SITES = fused_sites()


def oracle_verdict(s) -> bool:
    """Scalar-oracle verdict for one SignatureSet: native FastAggregate
    semantics (False on empty pubkeys / undecodable points), bypassing
    the backend shim and every dispatch seam."""
    from ..crypto import bls12_381 as native
    if len(s.pubkeys) == 0:
        return False
    try:
        return native.FastAggregateVerify(
            [bytes(pk) for pk in s.pubkeys], bytes(s.signing_root),
            bytes(s.signature))
    except ValueError:
        return False


class DifferentialGuard:
    def __init__(self, sample_rate: float = 0.05, seed: int = 0):
        if not 0.0 <= sample_rate <= 1.0:
            raise ValueError(f"sample_rate {sample_rate} not in [0, 1]")
        self.sample_rate = sample_rate
        self._rng = random.Random(seed)
        self._lock = named_rlock("resilience.guard")

    def check(self, sets, indices, verdicts, reason_for=None):
        """Cross-check a sample of `verdicts` (for sets[i], i in indices)
        against the oracle.  Returns None if the batch is trustworthy;
        otherwise the mismatch's reason label — the backend was
        quarantined under it and the CALLER MUST recompute all verdicts
        via the oracle.  `reason_for(i)` maps the MISMATCHING set index
        to the label of the path that produced its verdict
        (`fold_mismatch` for a folded fused leg, `guard_mismatch`
        otherwise), so incident streams attribute a trip to the path
        that actually corrupted — not merely to whatever mode the flush
        ran in."""
        if self.sample_rate <= 0.0 or not indices:
            return None
        with self._lock:
            sampled = [i for i in indices
                       if self._rng.random() < self.sample_rate]
        if not sampled:
            return None
        METRICS.inc("guard_samples", len(sampled))
        for i in sampled:
            expect = oracle_verdict(sets[i])
            if bool(verdicts[i]) != expect:
                reason = (reason_for(i) if reason_for is not None
                          else "guard_mismatch")
                METRICS.inc("guard_mismatches")
                INCIDENTS.record(
                    "sigpipe.fused", "guard_mismatch",
                    set_kind=sets[i].kind, got=bool(verdicts[i]),
                    expected=expect, reason=reason)
                self._quarantine_backend(reason)
                return reason
        return None

    @staticmethod
    def _quarantine_backend(reason: str = "guard_mismatch") -> None:
        from . import supervisor
        sup = supervisor.active()
        if sup is None:
            return
        for site in FUSED_SITES:
            sup.quarantine(site, reason=reason)


# Per-node-context ROUTER like the supervisor and the fault plan: a
# SimNode owning a `guard` Slot samples (and quarantines) with its own
# seeded guard — `_quarantine_backend` consults `supervisor.active()`,
# itself routed, so a mismatch on one node quarantines only that
# node's breaker table.  No node context installed -> the
# process-global default cell, byte-identical to the old singleton.
_ACTIVE = nodectx.StateRouter("guard")


def enable(sample_rate: float = 0.05, seed: int = 0) -> DifferentialGuard:
    g = DifferentialGuard(sample_rate, seed)
    _ACTIVE.set(g)
    return g


def disable() -> None:
    _ACTIVE.set(None)


def active() -> DifferentialGuard | None:
    return _ACTIVE.get()
