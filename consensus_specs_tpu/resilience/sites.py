"""Canonical registry of every resilience seam in the repo.

Seven PRs grew the safety story on cross-cutting conventions — every
accelerator entry point behind ``dispatch(site, device_fn,
fallback_fn)``, every transactional barrier behind ``faults.fire`` —
but the site NAMES lived as scattered string literals, and the chaos
tier, the differential guard, and the fault injector each hand-
maintained their own drift-prone tuples of them.  This module is the
single source of truth those consumers now derive from:

* ``tests/test_chaos.py`` builds ``SITES`` / ``GOSSIP_SITES`` /
  ``KILL_SITES`` from :func:`chaos_replay_sites`,
  :func:`chaos_gossip_sites`, :func:`kill_sites`.
* ``resilience/guard.py`` builds ``FUSED_SITES`` (the quarantine unit)
  from :func:`fused_sites`.
* ``resilience/faults.py`` builds ``_DIGEST_GUARDED_SITES`` (which
  results bytes-corruption may target) from
  :func:`digest_guarded_sites`.
* ``speclint`` (``consensus_specs_tpu/analysis/``) machine-checks every
  ``dispatch(...)`` / ``fire(...)`` / ``FaultSpec(...)`` site argument,
  the docs/resilience.md site table, and the chaos reachability policy
  against this registry — an unregistered site name fails CI.

Registering a new seam means adding ONE :class:`Site` entry here (and a
row in docs/resilience.md); speclint then enforces that the call site,
the chaos tier, and the docs all agree.  See docs/analysis.md.

This module deliberately imports nothing from the package (stdlib
only), mirroring utils/nodectx.py: the cycle-sensitive wrapper modules
(utils/bls.py, ssz/merkle.py, ssz/incremental.py) keep their lazy-
import discipline and use validated string literals instead, while
everything that CAN import it at module scope (txn/, guard, faults,
tests) derives.  speclint loads it standalone by file path, so linting
never imports jax or the heavy packages.
"""
from __future__ import annotations

from dataclasses import dataclass

# site kinds
DISPATCH = "dispatch"   # a resilience.dispatch(site, device_fn, fallback_fn)
BARRIER = "barrier"     # a faults.fire(site) crash point (no value to corrupt)

# chaos tiers — where the chaos tier reaches the site from
REPLAY = "replay"   # native-backend sanity replay (test_chaos SITES)
GOSSIP = "gossip"   # gossip admission tier extra (GOSSIP_SITES adds these)
KILL = "kill"       # transactional crash points (KILL_SITES)
UNIT = "unit"       # unreachable from a CPU chaos replay; unit-tier covered
                    # (entries must say where in `note`)

_KINDS = (DISPATCH, BARRIER)
_TIERS = (REPLAY, GOSSIP, KILL, UNIT)
_CORRUPT = ("verdict", "digest", "none")


@dataclass(frozen=True)
class Site:
    """One registered seam.

    name     — the canonical dotted site string passed to dispatch/fire.
    module   — the wrapper module that owns the seam (the only module,
               besides registered kernel-layer ones, allowed to import
               device kernels directly — speclint's bypass pass).
    kind     — DISPATCH or BARRIER.
    chaos    — which chaos tier exercises it (REPLAY/GOSSIP/KILL/UNIT).
    corrupt  — what the fault injector's "corrupt" kind may flip:
               "verdict" (bool/bool-list), "digest" (one bit of a bytes
               root — only sites a differential oracle guards), "none"
               (barriers: a crash point has no value).
    fused    — verdicts flow through the fused signature pipeline; the
               differential guard quarantines all fused sites as a unit.
    sharded  — the device path may run mesh-partitioned over >1 chip
               (parallel/shard_verify.py): the `shard_dead` fault kind
               models a dead mesh member at exactly these seams, and
               the chaos tier's shard matrix derives from this flag.
    doc      — the document whose site table must list the name.
    note     — required for UNIT tier: where coverage lives instead.
    """

    name: str
    module: str
    kind: str = DISPATCH
    chaos: str = UNIT
    corrupt: str = "verdict"
    fused: bool = False
    sharded: bool = False
    doc: str = "docs/resilience.md"
    note: str = ""


# Declaration order is contractual: the chaos tuples derive from it, so
# seeded randomized fault schedules draw sites in this order.
REGISTRY: tuple[Site, ...] = (
    # -- replay tier: every native-backend sanity replay crosses these
    Site("bls.pairing_check", "consensus_specs_tpu.utils.bls",
         kind=DISPATCH, chaos=REPLAY, fused=True),
    Site("bls.verify_batch", "consensus_specs_tpu.utils.bls",
         kind=DISPATCH, chaos=REPLAY, fused=True),
    Site("bls.fast_aggregate_verify_batch", "consensus_specs_tpu.utils.bls",
         kind=DISPATCH, chaos=REPLAY, fused=True),
    Site("ops.g1_aggregate", "consensus_specs_tpu.sigpipe.cache",
         kind=DISPATCH, chaos=REPLAY, sharded=True),
    Site("ops.msm", "consensus_specs_tpu.sigpipe.scheduler",
         kind=DISPATCH, chaos=REPLAY, sharded=True),
    Site("ssz.merkle_sweep", "consensus_specs_tpu.ssz.incremental",
         kind=DISPATCH, chaos=REPLAY, corrupt="digest"),
    # -- gossip tier extra: the admission pipeline's batch window
    Site("gossip.batch_verify", "consensus_specs_tpu.gossip.batcher",
         kind=DISPATCH, chaos=GOSSIP),
    # -- transactional crash points (KILL_SITES order is contractual)
    Site("txn.mutate", "consensus_specs_tpu.txn.overlay",
         kind=BARRIER, chaos=KILL, corrupt="none"),
    Site("txn.commit", "consensus_specs_tpu.txn",
         kind=DISPATCH, chaos=KILL, corrupt="none"),
    Site("txn.commit.apply", "consensus_specs_tpu.txn.overlay",
         kind=BARRIER, chaos=KILL, corrupt="none"),
    Site("txn.journal", "consensus_specs_tpu.txn.journal",
         kind=BARRIER, chaos=KILL, corrupt="none"),
    # -- unit tier: tpu-backend-only seams a CPU chaos replay never
    #    crosses; each names its covering unit suite
    Site("bls.verify", "consensus_specs_tpu.utils.bls",
         kind=DISPATCH, chaos=UNIT,
         note="tpu-backend scalar seam; tests/test_resilience.py + "
              "tests/test_bls_tpu.py"),
    Site("bls.aggregate_verify", "consensus_specs_tpu.utils.bls",
         kind=DISPATCH, chaos=UNIT,
         note="tpu-backend scalar seam; tests/test_resilience.py + "
              "tests/test_bls_tpu.py"),
    Site("bls.fast_aggregate_verify", "consensus_specs_tpu.utils.bls",
         kind=DISPATCH, chaos=UNIT,
         note="tpu-backend scalar seam; tests/test_resilience.py + "
              "tests/test_bls_tpu.py"),
    # fused (the guard quarantines it with its sibling batch seams) but
    # NOT replay-tier: no node-runtime path calls AggregateVerifyBatch
    # today — the scheduler's per-set mode rides FastAggregateVerifyBatch
    # — so a chaos FaultSpec here would never fire and the tuple entry
    # would claim coverage it does not deliver
    Site("bls.aggregate_verify_batch", "consensus_specs_tpu.utils.bls",
         kind=DISPATCH, chaos=UNIT, fused=True,
         note="batch API surface with no runtime caller yet; "
              "tests/test_bls_tpu.py + tests/test_sigpipe.py"),
    # sharded since the async-flush PR: the padded message axis of the
    # cofactor sweep partitions over the verify mesh via shard_jobs —
    # the last unsharded per-flush device call
    Site("sigpipe.hash_to_g2_batch", "consensus_specs_tpu.sigpipe.scheduler",
         kind=DISPATCH, chaos=UNIT, fused=True, sharded=True,
         note="tpu-backend cofactor sweep; tests/test_resilience.py + "
              "tests/test_shard_verify.py (kernel tier)"),
    # the mesh-sharded fused pairing product: engages only when the
    # verify mesh has >1 device AND the tpu backend is active, which a
    # native-backend CPU chaos replay never is — the sharded sweeps at
    # ops.g1_aggregate / ops.msm (replay tier, sharded=True) carry the
    # shard_dead chaos matrix instead
    Site("ops.pairing_product", "consensus_specs_tpu.parallel.shard_verify",
         kind=DISPATCH, chaos=UNIT, fused=True, sharded=True,
         note="mesh-sharded pairing product (tpu backend + >1-device "
              "mesh only); tests/test_shard_verify.py (kernel tier) + "
              "tests/test_resilience.py shard_dead unit suite"),
    Site("ops.msm.g1", "consensus_specs_tpu.utils.bls",
         kind=DISPATCH, chaos=UNIT,
         note="threshold-gated device MSM; tests/test_msm_pippenger.py"),
    Site("ops.msm.g2", "consensus_specs_tpu.utils.bls",
         kind=DISPATCH, chaos=UNIT,
         note="threshold-gated device MSM; tests/test_msm_pippenger.py"),
    Site("ops.msm.kzg", "consensus_specs_tpu.crypto.kzg",
         kind=DISPATCH, chaos=UNIT,
         note="threshold-gated device MSM; tests/test_kzg.py"),
    Site("ops.sha256.hash_level", "consensus_specs_tpu.ssz.merkle",
         kind=DISPATCH, chaos=UNIT,
         note="install-gated bulk hasher; tests/test_sha256_jax.py + "
              "tests/test_merkle_sweep_jax.py"),
    Site("ops.sha256.subtree", "consensus_specs_tpu.ssz.merkle",
         kind=DISPATCH, chaos=UNIT,
         note="install-gated subtree hasher; tests/test_sha256_jax.py"),
)

# speclint: disable=global-mutable-state -- name index over the frozen
# REGISTRY tuple, built once at import and read-only afterwards
SITES: dict[str, Site] = {s.name: s for s in REGISTRY}

if len(SITES) != len(REGISTRY):
    raise RuntimeError("duplicate site name in resilience.sites.REGISTRY")
for _s in REGISTRY:
    if _s.kind not in _KINDS:
        raise RuntimeError(f"{_s.name}: bad kind {_s.kind!r}")
    if _s.chaos not in _TIERS:
        raise RuntimeError(f"{_s.name}: bad chaos tier {_s.chaos!r}")
    if _s.corrupt not in _CORRUPT:
        raise RuntimeError(f"{_s.name}: bad corrupt class {_s.corrupt!r}")
    if _s.chaos == UNIT and not _s.note:
        raise RuntimeError(
            f"{_s.name}: UNIT-tier sites must say where coverage lives")


def site(name: str) -> Site:
    """Look up one registered site; KeyError on unregistered names."""
    return SITES[name]


def is_registered(name: str) -> bool:
    return name in SITES


def names() -> tuple[str, ...]:
    return tuple(s.name for s in REGISTRY)


def chaos_replay_sites() -> tuple[str, ...]:
    """test_chaos.py SITES: seams a native-backend sanity replay crosses."""
    return tuple(s.name for s in REGISTRY if s.chaos == REPLAY)


def chaos_gossip_sites() -> tuple[str, ...]:
    """test_chaos.py GOSSIP_SITES: the replay tier plus the admission
    pipeline's own seams."""
    return chaos_replay_sites() + tuple(
        s.name for s in REGISTRY if s.chaos == GOSSIP)


def kill_sites() -> tuple[str, ...]:
    """test_chaos.py KILL_SITES: every transactional crash-point family."""
    return tuple(s.name for s in REGISTRY if s.chaos == KILL)


def fused_sites() -> tuple[str, ...]:
    """guard.py FUSED_SITES: quarantined as a unit on a guard mismatch."""
    return tuple(s.name for s in REGISTRY if s.fused)


def digest_guarded_sites() -> frozenset[str]:
    """faults.py _DIGEST_GUARDED_SITES: bytes-root results the corrupt
    fault kind may bit-flip (a differential oracle guards them)."""
    return frozenset(s.name for s in REGISTRY if s.corrupt == "digest")


def sharded_sites() -> tuple[str, ...]:
    """Seams whose device path may run mesh-partitioned
    (parallel/shard_verify.py): the shard_dead fault kind models a dead
    mesh member here, and test_chaos.py's shard matrix derives from
    this tuple (intersected with the replay tier — the sharded pairing
    product itself is tpu-backend-only and unit-covered)."""
    return tuple(s.name for s in REGISTRY if s.sharded)


def wrapper_modules() -> frozenset[str]:
    """Modules that own a seam — allowed to import device kernels."""
    return frozenset(s.module for s in REGISTRY)


# ---------------------------------------------------------------------------
# declared host-sync join barriers (speclint async-host-sync pass)
# ---------------------------------------------------------------------------
# The async flush engine's contract is that device dispatches stay
# un-forced until a DECLARED join barrier: a host-sync primitive
# (`jax.device_get`, `.block_until_ready()`, `np.asarray` on a device
# value) anywhere else in sigpipe/ssz/parallel silently re-serializes
# the pipeline.  Each entry is (module, function) naming a function
# whose body IS a declared barrier — the verdict joins and result
# downloads the pipeline design blesses.  speclint's hostsync pass
# (analysis/hostsync.py) flags any sync primitive outside this table;
# adding a new barrier means adding a row HERE (and saying why in the
# function's docstring), not sprinkling a disable.
HOST_SYNC_BARRIERS: tuple = (
    # the sharded pairing product's verdict join: pack + upload + ONE
    # np.asarray of the final Fp12-is-one verdict per flush
    ("consensus_specs_tpu.parallel.shard_verify",
     "_device_pairing_product"),
    # mesh-engine result downloads: each is the single forced read at
    # the end of one fused epoch-processing dispatch
    ("consensus_specs_tpu.parallel.mesh_engine", "subtree_root"),
    ("consensus_specs_tpu.parallel.mesh_engine", "flag_set_batch"),
    ("consensus_specs_tpu.parallel.mesh_engine", "slashings_batch"),
    ("consensus_specs_tpu.parallel.mesh_engine", "g1_msm"),
)
