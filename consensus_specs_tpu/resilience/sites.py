"""Canonical registry of every resilience seam in the repo.

Seven PRs grew the safety story on cross-cutting conventions — every
accelerator entry point behind ``dispatch(site, device_fn,
fallback_fn)``, every transactional barrier behind ``faults.fire`` —
but the site NAMES lived as scattered string literals, and the chaos
tier, the differential guard, and the fault injector each hand-
maintained their own drift-prone tuples of them.  This module is the
single source of truth those consumers now derive from:

* ``tests/test_chaos.py`` builds ``SITES`` / ``GOSSIP_SITES`` /
  ``KILL_SITES`` from :func:`chaos_replay_sites`,
  :func:`chaos_gossip_sites`, :func:`kill_sites`.
* ``resilience/guard.py`` builds ``FUSED_SITES`` (the quarantine unit)
  from :func:`fused_sites`.
* ``resilience/faults.py`` builds ``_DIGEST_GUARDED_SITES`` (which
  results bytes-corruption may target) from
  :func:`digest_guarded_sites`.
* ``speclint`` (``consensus_specs_tpu/analysis/``) machine-checks every
  ``dispatch(...)`` / ``fire(...)`` / ``FaultSpec(...)`` site argument,
  the docs/resilience.md site table, and the chaos reachability policy
  against this registry — an unregistered site name fails CI.

Registering a new seam means adding ONE :class:`Site` entry here (and a
row in docs/resilience.md); speclint then enforces that the call site,
the chaos tier, and the docs all agree.  See docs/analysis.md.

This module deliberately imports nothing from the package (stdlib
only), mirroring utils/nodectx.py: the cycle-sensitive wrapper modules
(utils/bls.py, ssz/merkle.py, ssz/incremental.py) keep their lazy-
import discipline and use validated string literals instead, while
everything that CAN import it at module scope (txn/, guard, faults,
tests) derives.  speclint loads it standalone by file path, so linting
never imports jax or the heavy packages.
"""
from __future__ import annotations

from dataclasses import dataclass

# site kinds
DISPATCH = "dispatch"   # a resilience.dispatch(site, device_fn, fallback_fn)
BARRIER = "barrier"     # a faults.fire(site) crash point (no value to corrupt)

# chaos tiers — where the chaos tier reaches the site from
REPLAY = "replay"   # native-backend sanity replay (test_chaos SITES)
GOSSIP = "gossip"   # gossip admission tier extra (GOSSIP_SITES adds these)
KILL = "kill"       # transactional crash points (KILL_SITES)
UNIT = "unit"       # unreachable from a CPU chaos replay; unit-tier covered
                    # (entries must say where in `note`)

_KINDS = (DISPATCH, BARRIER)
_TIERS = (REPLAY, GOSSIP, KILL, UNIT)
_CORRUPT = ("verdict", "digest", "lanes", "none")


@dataclass(frozen=True)
class Site:
    """One registered seam.

    name     — the canonical dotted site string passed to dispatch/fire.
    module   — the wrapper module that owns the seam (the only module,
               besides registered kernel-layer ones, allowed to import
               device kernels directly — speclint's bypass pass).
    kind     — DISPATCH or BARRIER.
    chaos    — which chaos tier exercises it (REPLAY/GOSSIP/KILL/UNIT).
    corrupt  — what the fault injector's "corrupt" kind may flip:
               "verdict" (bool/bool-list), "digest" (one bit of a bytes
               root — only sites a differential oracle guards), "lanes"
               (one element of one numpy lane array in a tuple result —
               again only oracle-guarded sites), "none" (barriers: a
               crash point has no value).
    fused    — verdicts flow through the fused signature pipeline; the
               differential guard quarantines all fused sites as a unit.
    sharded  — the device path may run mesh-partitioned over >1 chip
               (parallel/shard_verify.py): the `shard_dead` fault kind
               models a dead mesh member at exactly these seams, and
               the chaos tier's shard matrix derives from this flag.
    doc      — the document whose site table must list the name.
    note     — required for UNIT tier: where coverage lives instead.
    """

    name: str
    module: str
    kind: str = DISPATCH
    chaos: str = UNIT
    corrupt: str = "verdict"
    fused: bool = False
    sharded: bool = False
    doc: str = "docs/resilience.md"
    note: str = ""


# Declaration order is contractual: the chaos tuples derive from it, so
# seeded randomized fault schedules draw sites in this order.
REGISTRY: tuple[Site, ...] = (
    # -- replay tier: every native-backend sanity replay crosses these
    Site("bls.pairing_check", "consensus_specs_tpu.utils.bls",
         kind=DISPATCH, chaos=REPLAY, fused=True),
    Site("bls.verify_batch", "consensus_specs_tpu.utils.bls",
         kind=DISPATCH, chaos=REPLAY, fused=True),
    Site("bls.fast_aggregate_verify_batch", "consensus_specs_tpu.utils.bls",
         kind=DISPATCH, chaos=REPLAY, fused=True),
    Site("ops.g1_aggregate", "consensus_specs_tpu.sigpipe.cache",
         kind=DISPATCH, chaos=REPLAY, sharded=True),
    Site("ops.msm", "consensus_specs_tpu.sigpipe.scheduler",
         kind=DISPATCH, chaos=REPLAY, sharded=True),
    # the folded signature-leg seam (sigpipe/fold.py): the G2 MSM that
    # collapses every e(-c_i*g1, sig_i) leg into ONE e(-g1, S) pair —
    # and, on the tpu backend's one-launch path, the whole fused flush
    # program per shard.  REPLAY tier: folding is on by default, so
    # every native-backend fused replay crosses it (FOLD_VERIFY=0 is
    # the escape hatch back to the 2N-leg flush)
    Site("ops.pairing_fold", "consensus_specs_tpu.sigpipe.fold",
         kind=DISPATCH, chaos=REPLAY, fused=True, sharded=True),
    Site("ssz.merkle_sweep", "consensus_specs_tpu.ssz.incremental",
         kind=DISPATCH, chaos=REPLAY, corrupt="digest"),
    # the fused epoch sweep (ops/epoch_sweep.py behind specs/
    # epoch_fast.fused_epoch): ONE dispatch per process_epoch carrying
    # every hot per-validator pass; numpy twin as the counted
    # byte-identical fallback, sampled lane guard quarantines on
    # mismatch.  REPLAY tier — any replay crossing an epoch boundary
    # dispatches here (the block-level replay workload does not, so the
    # shard matrix and fault kinds run in the dedicated epoch-boundary
    # chaos matrix; see tests/test_chaos.py).  sharded: the validator
    # axis partitions over the verify mesh via shard_jobs.
    Site("ops.epoch_sweep", "consensus_specs_tpu.specs.epoch_fast",
         kind=DISPATCH, chaos=REPLAY, corrupt="lanes", sharded=True),
    # -- gossip tier extra: the admission pipeline's batch window
    Site("gossip.batch_verify", "consensus_specs_tpu.gossip.batcher",
         kind=DISPATCH, chaos=GOSSIP),
    # -- transactional crash points (KILL_SITES order is contractual)
    Site("txn.mutate", "consensus_specs_tpu.txn.overlay",
         kind=BARRIER, chaos=KILL, corrupt="none"),
    Site("txn.commit", "consensus_specs_tpu.txn",
         kind=DISPATCH, chaos=KILL, corrupt="none"),
    Site("txn.commit.apply", "consensus_specs_tpu.txn.overlay",
         kind=BARRIER, chaos=KILL, corrupt="none"),
    Site("txn.journal", "consensus_specs_tpu.txn.journal",
         kind=BARRIER, chaos=KILL, corrupt="none"),
    # the durable journal's mid-fsync crash window: record bytes are
    # written (page cache) but not yet durable when this fires — the
    # chaos crash-anywhere tier drives it through a DurableJournal, and
    # scripts/kill_drill.py SIGKILLs a real subprocess at it
    Site("txn.journal.fsync", "consensus_specs_tpu.txn.durable",
         kind=BARRIER, chaos=KILL, corrupt="none"),
    # -- unit tier: tpu-backend-only seams a CPU chaos replay never
    #    crosses; each names its covering unit suite
    Site("bls.verify", "consensus_specs_tpu.utils.bls",
         kind=DISPATCH, chaos=UNIT,
         note="tpu-backend scalar seam; tests/test_resilience.py + "
              "tests/test_bls_tpu.py"),
    Site("bls.aggregate_verify", "consensus_specs_tpu.utils.bls",
         kind=DISPATCH, chaos=UNIT,
         note="tpu-backend scalar seam; tests/test_resilience.py + "
              "tests/test_bls_tpu.py"),
    Site("bls.fast_aggregate_verify", "consensus_specs_tpu.utils.bls",
         kind=DISPATCH, chaos=UNIT,
         note="tpu-backend scalar seam; tests/test_resilience.py + "
              "tests/test_bls_tpu.py"),
    # fused (the guard quarantines it with its sibling batch seams) but
    # NOT replay-tier: no node-runtime path calls AggregateVerifyBatch
    # today — the scheduler's per-set mode rides FastAggregateVerifyBatch
    # — so a chaos FaultSpec here would never fire and the tuple entry
    # would claim coverage it does not deliver
    Site("bls.aggregate_verify_batch", "consensus_specs_tpu.utils.bls",
         kind=DISPATCH, chaos=UNIT, fused=True,
         note="batch API surface with no runtime caller yet; "
              "tests/test_bls_tpu.py + tests/test_sigpipe.py"),
    # sharded since the async-flush PR: the padded message axis of the
    # cofactor sweep partitions over the verify mesh via shard_jobs —
    # the last unsharded per-flush device call
    Site("sigpipe.hash_to_g2_batch", "consensus_specs_tpu.sigpipe.scheduler",
         kind=DISPATCH, chaos=UNIT, fused=True, sharded=True,
         note="tpu-backend cofactor sweep; tests/test_resilience.py + "
              "tests/test_shard_verify.py (kernel tier)"),
    # the mesh-sharded fused pairing product: engages only when the
    # verify mesh has >1 device AND the tpu backend is active, which a
    # native-backend CPU chaos replay never is — the sharded sweeps at
    # ops.g1_aggregate / ops.msm (replay tier, sharded=True) carry the
    # shard_dead chaos matrix instead
    Site("ops.pairing_product", "consensus_specs_tpu.parallel.shard_verify",
         kind=DISPATCH, chaos=UNIT, fused=True, sharded=True,
         note="mesh-sharded pairing product (tpu backend + >1-device "
              "mesh only); tests/test_shard_verify.py (kernel tier) + "
              "tests/test_resilience.py shard_dead unit suite"),
    Site("ops.msm.g1", "consensus_specs_tpu.utils.bls",
         kind=DISPATCH, chaos=UNIT,
         note="threshold-gated device MSM; tests/test_msm_pippenger.py"),
    Site("ops.msm.g2", "consensus_specs_tpu.utils.bls",
         kind=DISPATCH, chaos=UNIT,
         note="threshold-gated device MSM; tests/test_msm_pippenger.py"),
    Site("ops.msm.kzg", "consensus_specs_tpu.crypto.kzg",
         kind=DISPATCH, chaos=UNIT,
         note="threshold-gated device MSM; tests/test_kzg.py"),
    Site("ops.sha256.hash_level", "consensus_specs_tpu.ssz.merkle",
         kind=DISPATCH, chaos=UNIT,
         note="install-gated bulk hasher; tests/test_sha256_jax.py + "
              "tests/test_merkle_sweep_jax.py"),
    Site("ops.sha256.subtree", "consensus_specs_tpu.ssz.merkle",
         kind=DISPATCH, chaos=UNIT,
         note="install-gated subtree hasher; tests/test_sha256_jax.py"),
    # -- vector-factory barrier kill points: the generation service's
    #    durable progress journal and content-addressed artifact store
    #    (factory/).  UNIT tier — the chaos replay tier drives txn
    #    stores, not generation shards; coverage is the process-boundary
    #    SIGKILL drill (scripts/factory_drill.py, `make factory-drill`)
    #    plus the in-process crash suite.  Family order here is the
    #    drill's matrix order.
    Site("factory.journal", "consensus_specs_tpu.factory.journal",
         kind=BARRIER, chaos=UNIT, corrupt="none",
         note="mid-journal-record-write kill point; "
              "scripts/factory_drill.py + tests/test_factory.py"),
    Site("factory.journal.fsync", "consensus_specs_tpu.factory.journal",
         kind=BARRIER, chaos=UNIT, corrupt="none",
         note="the factory journal's written-but-not-yet-durable "
              "window; scripts/factory_drill.py + tests/test_factory.py"),
    Site("factory.publish", "consensus_specs_tpu.factory.artifacts",
         kind=BARRIER, chaos=UNIT, corrupt="none",
         note="between an artifact's staged tmp write and its atomic "
              "rename into the content-addressed store; "
              "scripts/factory_drill.py + tests/test_factory.py"),
    Site("factory.manifest", "consensus_specs_tpu.factory.artifacts",
         kind=BARRIER, chaos=UNIT, corrupt="none",
         note="before the manifest's atomic replace; "
              "scripts/factory_drill.py + tests/test_factory.py"),
    # -- front-door barrier kill points: the long-lived node process's
    #    serving path (node/).  UNIT tier — coverage is the
    #    process-boundary SIGKILL drill through the real socket
    #    (scripts/node_drill.py, `make node-drill`) plus the
    #    in-process codec/drain tests.
    Site("node.ingest", "consensus_specs_tpu.node.service",
         kind=BARRIER, chaos=UNIT, corrupt="none",
         note="before each socket message's pipeline submit; "
              "scripts/node_drill.py + tests/test_node.py"),
    Site("node.drain", "consensus_specs_tpu.node.service",
         kind=BARRIER, chaos=UNIT, corrupt="none",
         note="inside graceful drain, after accepts stop and before "
              "the flush/fsync; scripts/node_drill.py + "
              "tests/test_node.py"),
    # -- mesh: real peer-to-peer socket traffic (mesh/).  UNIT tier —
    #    coverage is the multi-process drill over the scenario
    #    library's partition/kill timelines (scripts/mesh_drill.py,
    #    `make mesh-drill`) plus the link-layer unit suite.
    # speclint: disable=site-unused -- the link worker consults
    # plan.decide(site) directly: a corrupt spec must damage the
    # in-flight FRAME bytes (there is no verdict or return value at
    # this seam), which the dispatch/fire grammar cannot express
    Site("mesh.link", "consensus_specs_tpu.mesh.link",
         kind=DISPATCH, chaos=UNIT, corrupt="none",
         note="per-send link fault consult: raise = frame + connection "
              "lost, timeout = wire stall, corrupt = one on-wire bit "
              "flip the RECEIVER's CRC sheds (the link applies the "
              "damage itself — no verdict to flip, so corrupt='none'); "
              "scripts/mesh_drill.py + tests/test_mesh.py"),
    Site("mesh.send", "consensus_specs_tpu.mesh.link",
         kind=BARRIER, chaos=UNIT, corrupt="none",
         note="before each link sendall — the drill's kill/shed point "
              "on the outbound hop; scripts/mesh_drill.py + "
              "tests/test_mesh.py"),
    Site("mesh.recv", "consensus_specs_tpu.mesh.service",
         kind=BARRIER, chaos=UNIT, corrupt="none",
         note="before a peer-forwarded message's admission — the "
              "drill's kill/shed point on the inbound hop; "
              "scripts/mesh_drill.py + tests/test_mesh.py"),
    Site("mesh.join", "consensus_specs_tpu.mesh.service",
         kind=BARRIER, chaos=UNIT, corrupt="none",
         note="before a JOIN frame mutates the peer table — the "
              "churn drill's kill/shed point on dynamic admission; "
              "scripts/mesh_drill.py + tests/test_mesh.py"),
    Site("mesh.leave", "consensus_specs_tpu.mesh.service",
         kind=BARRIER, chaos=UNIT, corrupt="none",
         note="before a LEAVE frame drains a member's link out — the "
              "churn drill's kill/shed point on graceful departure; "
              "scripts/mesh_drill.py + tests/test_mesh.py"),
)

# speclint: disable=global-mutable-state -- name index over the frozen
# REGISTRY tuple, built once at import and read-only afterwards
SITES: dict[str, Site] = {s.name: s for s in REGISTRY}

if len(SITES) != len(REGISTRY):
    raise RuntimeError("duplicate site name in resilience.sites.REGISTRY")
for _s in REGISTRY:
    if _s.kind not in _KINDS:
        raise RuntimeError(f"{_s.name}: bad kind {_s.kind!r}")
    if _s.chaos not in _TIERS:
        raise RuntimeError(f"{_s.name}: bad chaos tier {_s.chaos!r}")
    if _s.corrupt not in _CORRUPT:
        raise RuntimeError(f"{_s.name}: bad corrupt class {_s.corrupt!r}")
    if _s.chaos == UNIT and not _s.note:
        raise RuntimeError(
            f"{_s.name}: UNIT-tier sites must say where coverage lives")


def site(name: str) -> Site:
    """Look up one registered site; KeyError on unregistered names."""
    return SITES[name]


def is_registered(name: str) -> bool:
    return name in SITES


def names() -> tuple[str, ...]:
    return tuple(s.name for s in REGISTRY)


def chaos_replay_sites() -> tuple[str, ...]:
    """test_chaos.py SITES: seams a native-backend sanity replay crosses."""
    return tuple(s.name for s in REGISTRY if s.chaos == REPLAY)


def chaos_gossip_sites() -> tuple[str, ...]:
    """test_chaos.py GOSSIP_SITES: the replay tier plus the admission
    pipeline's own seams."""
    return chaos_replay_sites() + tuple(
        s.name for s in REGISTRY if s.chaos == GOSSIP)


def kill_sites() -> tuple[str, ...]:
    """test_chaos.py KILL_SITES: every transactional crash-point family."""
    return tuple(s.name for s in REGISTRY if s.chaos == KILL)


def fused_sites() -> tuple[str, ...]:
    """guard.py FUSED_SITES: quarantined as a unit on a guard mismatch."""
    return tuple(s.name for s in REGISTRY if s.fused)


def digest_guarded_sites() -> frozenset[str]:
    """faults.py _DIGEST_GUARDED_SITES: bytes-root results the corrupt
    fault kind may bit-flip (a differential oracle guards them)."""
    return frozenset(s.name for s in REGISTRY if s.corrupt == "digest")


def lanes_guarded_sites() -> frozenset[str]:
    """faults.py _LANES_GUARDED_SITES: tuple-of-numpy-lane results the
    corrupt fault kind may damage by one element (a differential oracle
    guards them)."""
    return frozenset(s.name for s in REGISTRY if s.corrupt == "lanes")


def sharded_sites() -> tuple[str, ...]:
    """Seams whose device path may run mesh-partitioned
    (parallel/shard_verify.py): the shard_dead fault kind models a dead
    mesh member here, and test_chaos.py's shard matrix derives from
    this tuple (intersected with the replay tier — the sharded pairing
    product itself is tpu-backend-only and unit-covered)."""
    return tuple(s.name for s in REGISTRY if s.sharded)


def wrapper_modules() -> frozenset[str]:
    """Modules that own a seam — allowed to import device kernels."""
    return frozenset(s.module for s in REGISTRY)


# ---------------------------------------------------------------------------
# declared host-sync join barriers (speclint async-host-sync pass)
# ---------------------------------------------------------------------------
# The async flush engine's contract is that device dispatches stay
# un-forced until a DECLARED join barrier: a host-sync primitive
# (`jax.device_get`, `.block_until_ready()`, `np.asarray` on a device
# value) anywhere else in sigpipe/ssz/parallel silently re-serializes
# the pipeline.  Each entry is (module, function) naming a function
# whose body IS a declared barrier — the verdict joins and result
# downloads the pipeline design blesses.  speclint's hostsync pass
# (analysis/hostsync.py) flags any sync primitive outside this table;
# adding a new barrier means adding a row HERE (and saying why in the
# function's docstring), not sprinkling a disable.
HOST_SYNC_BARRIERS: tuple = (
    # the sharded pairing product's verdict join: pack + upload + ONE
    # np.asarray of the final Fp12-is-one verdict per flush
    ("consensus_specs_tpu.parallel.shard_verify",
     "_device_pairing_product"),
    # the one-launch folded flush's verdict join: one compiled program
    # per shard, then ONE np.asarray of the final Fp12-is-one verdict
    ("consensus_specs_tpu.parallel.shard_verify", "pairing_fold"),
    # mesh-engine result downloads: each is the single forced read at
    # the end of one fused device dispatch (the per-pass epoch rows —
    # flag_set_batch / slashings_batch — retired into ops.epoch_sweep)
    ("consensus_specs_tpu.parallel.mesh_engine", "subtree_root"),
    ("consensus_specs_tpu.parallel.mesh_engine", "g1_msm"),
    # the fused epoch sweep's single download: ONE jax.device_get of
    # every output lane per process_epoch
    ("consensus_specs_tpu.ops.epoch_sweep", "run_sweep"),
)


# ---------------------------------------------------------------------------
# the concurrency registry (speclint lock-discipline / lock-order /
# thread-escape passes + the SPECLINT_TSAN runtime lock tracer)
# ---------------------------------------------------------------------------
# PR 11 made the hot path genuinely multi-threaded; the overlap
# contracts (single-drainer delivery, ticket-joined verdicts,
# abandoned-flush write suppression) were until now enforced only by
# tests that happen to race.  This registry applies the same
# declare-once discipline as the seam table above to threads and locks:
#
# * every named lock is declared HERE (name -> owning module/class,
#   attribute, kind, the attribute set it guards) and constructed in
#   code via ``utils/locks.py`` ``named_lock``/``named_rlock``/
#   ``named_condition`` with its registry name — speclint's
#   lock-discipline pass fails on a bare ``threading.Lock()`` in the
#   concurrency-scoped packages, and with ``SPECLINT_TSAN=1`` the
#   named constructors return traced wrappers so the runtime sanitizer
#   can compare observed acquisition orders against the static graph.
# * every thread ROLE (who may run which entry point) is declared so
#   the thread-escape pass can check that state mutated from a worker
#   is lock-guarded or reaches the worker through a registered handoff.
# * every legal cross-thread HANDOFF object is declared; anything else
#   crossing a thread boundary is a finding.

@dataclass(frozen=True)
class LockSpec:
    """One named lock.

    name    — canonical dotted name (what named_lock(...) is called with
              and what the tracer reports).
    module  — the owning module; the lock-discipline pass checks guarded
              attributes only inside it (cross-module access to guarded
              state is a bug by construction: the attrs are private).
    attr    — the attribute / module global the lock object binds to.
    cls     — owning class ("" = module-level global); disambiguates
              modules holding several ``_lock`` attributes.
    kind    — "lock" | "rlock" | "condition".  A static self-edge on a
              plain "lock" is a self-deadlock finding; on an rlock or
              condition it is legal reentrancy.
    guards  — attribute / global names that may be read or written only
              under this lock (lexically or via the under-lock call
              closure).  Guarding is a claim the pass ENFORCES — list
              only what really holds, and record the deliberate
              exceptions with reasoned disables at the access site.
    note    — why the guard set is shaped the way it is (e.g. which
              state is serialized by a role discipline instead).
    """

    name: str
    module: str
    attr: str
    cls: str = ""
    kind: str = "rlock"
    guards: tuple = ()
    note: str = ""


@dataclass(frozen=True)
class ThreadRole:
    """One thread role: who may run which entry point.

    func is the role's entry point ("Class.method" or a module-level
    function); "" marks the implicit role of the default thread.  The
    thread-escape pass analyzes mutations reachable from the entry
    point inside its own module — cross-module work a worker performs
    is covered by the lock-discipline pass and the runtime tracer.
    """

    name: str
    module: str = ""
    func: str = ""
    note: str = ""


@dataclass(frozen=True)
class Handoff:
    """One sanctioned cross-thread handoff object: state may legally
    cross a thread boundary only as (or through) one of these."""

    name: str
    module: str
    attr: str
    note: str = ""


@dataclass(frozen=True)
class Concurrency:
    locks: tuple
    roles: tuple
    handoffs: tuple

    def lock_names(self) -> tuple:
        return tuple(spec.name for spec in self.locks)


_PA = "consensus_specs_tpu.sigpipe.pipeline_async"
_GP = "consensus_specs_tpu.gossip.pipeline"
_NS = "consensus_specs_tpu.node.service"
_NI = "consensus_specs_tpu.node.ingest"
_ML = "consensus_specs_tpu.mesh.link"
_MS = "consensus_specs_tpu.mesh.service"

CONCURRENCY = Concurrency(
    locks=(
        # -- sigpipe: the async flush engine ---------------------------
        LockSpec("sigpipe.engine", _PA, "_ENGINE_LOCK", kind="lock",
                 guards=("_FLUSH_WORKER", "_LEG_WORKER"),
                 note="worker singletons: creation and respawn checks"),
        LockSpec("sigpipe.ticket", _PA, "_lock", cls="FlushTicket",
                 kind="lock", guards=("_state", "_value", "_error"),
                 note="ticket outcome; _done Event is the join handoff, "
                      "_overlapped is written pre-publication only"),
        LockSpec("sigpipe.worker_cv", _PA, "_cv", cls="_Worker",
                 kind="condition", guards=("_pending",),
                 note="queued+running job count; drain() waits on it"),
        LockSpec("sigpipe.pubkey_cache",
                 "consensus_specs_tpu.sigpipe.cache", "_lock",
                 cls="PubkeyCache", guards=("_cache",)),
        LockSpec("sigpipe.aggregate_cache",
                 "consensus_specs_tpu.sigpipe.cache", "_lock",
                 cls="AggregatePubkeyCache",
                 guards=("_cache", "_track_stack")),
        LockSpec("sigpipe.metrics",
                 "consensus_specs_tpu.sigpipe.metrics", "_lock",
                 cls="Metrics",
                 guards=("counters", "labeled", "observations",
                         "histograms", "timers")),
        # -- gossip: ingress vs the single drainer ---------------------
        LockSpec("gossip.ingress", _GP, "_ingress_lock",
                 cls="AdmissionPipeline",
                 guards=("_seq", "seen", "results", "queues", "quotas",
                         "batcher", "_finalized_order"),
                 note="admission state; order: drainer may take "
                      "ingress, never the reverse"),
        LockSpec("gossip.drainer", _GP, "_drainer_lock",
                 cls="AdmissionPipeline", kind="lock",
                 guards=("delivered_log", "guard"),
                 note="single-drainer discipline: whoever holds it owns "
                      "flushing, handler delivery, and the equivocation "
                      "guard; the store itself is serialized by it"),
        # -- txn -------------------------------------------------------
        LockSpec("txn.active", "consensus_specs_tpu.txn", "_lock",
                 guards=("_ACTIVE",),
                 note="manager installs; hot-path reads of the single "
                      "reference are atomic under the GIL and carry "
                      "reasoned disables in place"),
        LockSpec("txn.journal", "consensus_specs_tpu.txn.journal",
                 "_lock", cls="Journal",
                 guards=("_entries", "_snapshots", "_seq")),
        LockSpec("txn.durable.io", "consensus_specs_tpu.txn.durable",
                 "_io", cls="DurableJournal",
                 guards=("_seg_fh", "_seg_index", "_seg_written",
                         "_seg_max_seq", "_closed_segments",
                         "_raw_entries", "_raw_snaps", "_scanned_snaps",
                         "_dirty"),
                 note="segment file handle + rotation/compaction "
                      "bookkeeping and the raw records loaded by "
                      "open_dir; ordered after txn.journal (the entry "
                      "book) — durable methods append in memory first, "
                      "then persist under this lock"),
        # -- resilience ------------------------------------------------
        LockSpec("resilience.supervisor",
                 "consensus_specs_tpu.resilience.supervisor", "_lock",
                 cls="Supervisor",
                 guards=("_breakers", "_workers", "_worker_locks")),
        LockSpec("resilience.site_worker",
                 "consensus_specs_tpu.resilience.supervisor",
                 "site_lock", cls="Supervisor", kind="lock",
                 note="per-site watchdog serialization: a job is handed "
                      "to the site worker only while holding it"),
        LockSpec("resilience.incidents",
                 "consensus_specs_tpu.resilience.incidents", "_lock",
                 cls="IncidentLog", guards=("_entries", "_seq")),
        LockSpec("resilience.faults",
                 "consensus_specs_tpu.resilience.faults", "_lock",
                 cls="FaultPlan", guards=("_rng",),
                 note="seeded decision stream: every draw must be "
                      "serialized or replay determinism dies; specs/"
                      "_by_site are frozen after __init__"),
        LockSpec("resilience.guard",
                 "consensus_specs_tpu.resilience.guard", "_lock",
                 cls="DifferentialGuard", guards=("_rng",)),
        # -- ops (outside the static pass scope; registered for the
        # runtime tracer and the dead-entry check) ---------------------
        LockSpec("ops.sha256.pool", "consensus_specs_tpu.ops.sha256",
                 "_POOL_LOCK", kind="lock",
                 guards=(),
                 note="device-resident merkle literal pool "
                      "(_LIT_POOL/_LIT_INDEX/_LIT_USED): an abandoned "
                      "watchdog sweep may still be inserting while the "
                      "block thread starts the next sweep; the jitted "
                      "program runs on an immutable snapshot outside "
                      "the lock.  ops is outside the lock-discipline "
                      "pass scope, so the guard set is enforced by "
                      "review + the TSAN tracer, not listed here"),
        # -- node: the front-door process ------------------------------
        LockSpec("node.ingest", _NS, "_cond", cls="NodeService",
                 kind="condition",
                 guards=("_queue", "_shed_overload", "_shed_draining"),
                 note="the bounded ingest queue (conn readers push, "
                      "the pump pops) + overload counters; submits and "
                      "verdict work happen OUTSIDE it on the pump"),
        LockSpec("node.state", _NS, "_state_lock", cls="NodeService",
                 kind="lock",
                 guards=("_inflight", "_latencies", "_degraded"),
                 note="pump-side verdict bookkeeping, read by health() "
                      "from conn threads; never nested with node.ingest"),
        LockSpec("node.conn", _NI, "_send_lock", cls="_Connection",
                 kind="lock", guards=(),
                 note="per-connection response writes (pump, conn "
                      "reader, and evictions all answer on the same "
                      "socket); sendall is the only guarded effect"),
        LockSpec("node.server", _NI, "_lock", cls="IngestServer",
                 kind="lock",
                 guards=("_conns", "_next_id", "_accepting"),
                 note="live-connection table shared by the accept loop "
                      "and each conn reader's teardown"),
        # -- mesh: peer links + anti-entropy ---------------------------
        LockSpec("mesh.link", _ML, "_cond", cls="PeerLink",
                 kind="condition",
                 guards=("_queue", "_blocked", "_quarantined",
                         "_closing", "_sent", "_shed", "_dropped",
                         "_connects"),
                 note="one per-peer outbound queue + link state "
                      "machine (blocked/quarantined) shared by "
                      "offerers, control frames, and the mesh-link "
                      "worker; the socket itself is worker-local"),
        LockSpec("mesh.replay", _MS, "_replay_lock",
                 cls="MeshNodeService", kind="lock",
                 guards=("_replay",),
                 note="the anti-entropy replay log: the pump appends "
                      "on accept (transport seam), conn threads serve "
                      "SUMMARY/PULL from it inline; never nested with "
                      "mesh.link — offers happen after release"),
        LockSpec("mesh.links", _MS, "_links_lock",
                 cls="MeshNodeService", kind="lock",
                 guards=("links",),
                 note="the runtime peer table: JOIN/LEAVE frames "
                      "mutate it on conn threads while the pump "
                      "(flood, sync) and health snapshot it; links "
                      "start/close OUTSIDE the lock (they join worker "
                      "threads), and it never nests under mesh.link "
                      "or mesh.replay"),
        # -- utils -----------------------------------------------------
        LockSpec("nodectx.stack", "consensus_specs_tpu.utils.nodectx",
                 "_lock", guards=("_stack",)),
        LockSpec("nodectx.slot", "consensus_specs_tpu.utils.nodectx",
                 "_lock", cls="StateRouter", guards=("_global",),
                 note="a StateRouter's process-global default cell "
                      "(supervisor/plan/guard singletons); per-context "
                      "Slot values are serialized by the scenario "
                      "driver's single-scheduler discipline, like the "
                      "context stack itself"),
    ),
    roles=(
        ThreadRole("block",
                   note="the default thread: block processing, flush "
                        "submit, merkle plan/commit, scenario stepping"),
        ThreadRole("engine-worker", _PA, "_Worker._loop",
                   note="runs a whole flush's batch-verify behind its "
                        "FlushTicket (thread 'sigpipe-flush-engine')"),
        ThreadRole("leg-worker", _PA, "_Worker._loop",
                   note="runs the hash-to-G2 leg of an in-flight flush "
                        "(thread 'sigpipe-flush-leg')"),
        ThreadRole("gossip-drainer", _GP, "AdmissionPipeline._poll",
                   note="whichever thread wins _drainer_lock; stages "
                        "window N+1 and delivers window N in order"),
        ThreadRole("watchdog-worker",
                   "consensus_specs_tpu.resilience.supervisor",
                   "_SiteWorker._loop",
                   note="per-site daemon running watchdog'd dispatches; "
                        "abandoned on deadline expiry"),
        ThreadRole("node-listener", _NI, "IngestServer._accept_loop",
                   note="the front door's accept loop; spawns one "
                        "node-conn reader per connection"),
        ThreadRole("node-conn", _NI, "IngestServer._conn_loop",
                   note="per-connection deframer/decoder; pushes work "
                        "items onto the bounded ingest queue, never "
                        "touches the pipeline or store"),
        ThreadRole("node-pump", _NS, "NodeService._pump_loop",
                   note="the ONLY thread that drives the node's "
                        "pipeline/store: pops the ingest queue, submits "
                        "under scope(), harvests verdicts (on a mesh "
                        "node: also runs the anti-entropy sync via the "
                        "_pump_extra hook)"),
        ThreadRole("mesh-link", _ML, "PeerLink._run",
                   note="one per peer (thread 'mesh-link-<peer>'): "
                        "pops the outbound queue, reconnects with "
                        "backoff, sends under the mesh.link/mesh.send "
                        "fault boundary; never touches the pipeline"),
    ),
    handoffs=(
        Handoff("flush.ticket", _PA, "FlushTicket",
                note="THE join handle: result()/Leg.get() are the only "
                     "ways a flush outcome crosses back"),
        Handoff("flush.ticket_tls", _PA, "_TL",
                note="thread-local slot carrying a worker's own "
                     "in-flight ticket (writes_allowed)"),
        Handoff("engine.jobs", _PA, "_jobs",
                note="FIFO staging queue into the engine/leg workers; "
                     "FIFO is the determinism contract"),
        Handoff("watchdog.jobs",
                "consensus_specs_tpu.resilience.supervisor", "_jobs",
                note="site-worker job queue; the result box + done "
                     "Event travel inside each job"),
        Handoff("watchdog.done",
                "consensus_specs_tpu.resilience.supervisor", "done",
                note="the supervisor Event a watchdog'd caller waits "
                     "on; expiry abandons the worker"),
        Handoff("node.ingest_queue", _NS, "_queue",
                note="decoded socket frames cross from conn readers to "
                     "the pump as queue items; FIFO is the front "
                     "door's ordering contract, shed-oldest its "
                     "overload contract"),
        Handoff("node.respond", _NI, "respond",
                note="each work item carries its connection's respond "
                     "callable back to the pump; writes serialize "
                     "under node.conn"),
        Handoff("mesh.outbound", _ML, "_queue",
                note="framed bytes cross from the pump (transport "
                     "seam) to each mesh-link worker; bounded, "
                     "shed-oldest under backpressure"),
    ),
)

_LOCK_KINDS = ("lock", "rlock", "condition")

if len(set(CONCURRENCY.lock_names())) != len(CONCURRENCY.locks):
    raise RuntimeError("duplicate lock name in sites.CONCURRENCY")
for _l in CONCURRENCY.locks:
    if _l.kind not in _LOCK_KINDS:
        raise RuntimeError(f"{_l.name}: bad lock kind {_l.kind!r}")
    if not isinstance(_l.guards, tuple):
        raise RuntimeError(f"{_l.name}: guards must be a tuple")
if len({r.name for r in CONCURRENCY.roles}) != len(CONCURRENCY.roles):
    raise RuntimeError("duplicate role name in sites.CONCURRENCY")
if len({h.name for h in CONCURRENCY.handoffs}) != len(CONCURRENCY.handoffs):
    raise RuntimeError("duplicate handoff name in sites.CONCURRENCY")


def lock_spec(name: str) -> LockSpec:
    """Look up one registered lock; KeyError on unregistered names."""
    for spec in CONCURRENCY.locks:
        if spec.name == name:
            return spec
    raise KeyError(name)


def lock_names() -> tuple:
    return CONCURRENCY.lock_names()
