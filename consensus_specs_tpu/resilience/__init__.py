"""Fault-injection harness + graceful-degradation supervisor for
accelerator dispatches.

Production consensus clients treat batch verification as an optimization
that must *degrade*, never *decide*: any doubt about the accelerated path
falls back to the scalar oracle.  This package builds that guarantee for
every device dispatch seam in the repo, plus the fault injection needed
to prove it holds:

* faults.py      — seeded deterministic injection of raised device
                   errors, watchdog-visible hangs, and silent verdict
                   corruption (transient or persistent) at named seams.
* supervisor.py  — per-site circuit breaker: bounded retry w/ backoff,
                   trip-to-native-scalar on persistent faults, half-open
                   probes that restore the accelerator path; optional
                   watchdog deadline.  `dispatch()` is the seam.
* guard.py       — differential cross-check of sampled fused verdicts
                   against the pure-Python oracle; quarantines the
                   backend on mismatch (the only defense against silent
                   corruption).
* incidents.py   — bounded, thread-safe structured incident log; the
                   audit trail the chaos tier asserts on.

Typical production wiring:

    from consensus_specs_tpu import resilience, sigpipe
    resilience.enable(max_retries=2, breaker_threshold=3,
                      deadline_s=30.0, guard_sample_rate=0.05)
    sigpipe.enable()
    spec.state_transition(state, signed_block)

Chaos wiring (tests/test_chaos.py, `make chaos`):

    plan = resilience.FaultPlan(
        [resilience.FaultSpec("bls.pairing_check", "corrupt",
                              persistent=True)], seed=7)
    with resilience.inject(plan):
        spec.state_transition(state, signed_block)   # still byte-identical
"""
from .faults import DeviceFault, FaultPlan, FaultSpec, ShardDead, inject
from .incidents import INCIDENTS, IncidentLog
from .supervisor import (
    CLOSED, HALF_OPEN, OPEN, QUARANTINED, DispatchTimeout, Supervisor,
    SupervisorConfig, active, dispatch, enabled,
)
from . import faults, guard, incidents, sites, supervisor
from ..sigpipe.metrics import METRICS


def enable(config: SupervisorConfig | None = None,
           guard_sample_rate: float | None = None,
           guard_seed: int = 0, **overrides) -> Supervisor:
    """Enable the supervisor and, if `guard_sample_rate` is given, the
    differential guard, in one call.  The call describes the WHOLE
    desired resilience state: omitting `guard_sample_rate` disables any
    previously enabled guard (symmetric with disable())."""
    sup = supervisor.enable(config, **overrides)
    if guard_sample_rate is not None:
        guard.enable(guard_sample_rate, guard_seed)
    else:
        guard.disable()
    return sup


def disable() -> None:
    supervisor.disable()
    guard.disable()


def force_scalar(on: bool = True) -> None:
    """Administratively route every dispatch to the scalar fallback
    (reason `disabled`) — the bench `degraded` tier and operator kill
    switches.  Requires an enabled supervisor."""
    sup = supervisor.active()
    if sup is None:
        raise RuntimeError("resilience.enable() first")
    sup.force_scalar(on)


def report() -> dict:
    """One JSON-able dict: metrics + breaker states + incident log."""
    sup = supervisor.active()
    return {
        "metrics": METRICS.snapshot(),
        "breakers": sup.breaker_states() if sup is not None else {},
        "incidents": INCIDENTS.snapshot(),
    }


__all__ = [
    "DeviceFault", "DispatchTimeout", "FaultPlan", "FaultSpec",
    "IncidentLog", "INCIDENTS", "Supervisor", "SupervisorConfig",
    "CLOSED", "OPEN", "HALF_OPEN", "QUARANTINED",
    "active", "dispatch", "disable", "enable", "enabled", "force_scalar",
    "inject", "report", "faults", "guard", "incidents", "sites",
    "supervisor",
]
