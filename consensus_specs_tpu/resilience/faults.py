"""Deterministic fault injection at the accelerator dispatch seams.

A `FaultPlan` holds `FaultSpec`s — one per targeted dispatch site — and a
seeded RNG; `inject(plan)` installs it so every `resilience.dispatch()`
call consults the plan before running the device function.  Four fault
kinds model the ways a real accelerator dispatch goes wrong:

* ``raise``   — the dispatch dies with a `DeviceFault` (XLA runtime error,
                relay disconnect, OOM): loud, immediate.
* ``timeout`` — the dispatch hangs: the injected function sleeps past the
                supervisor's watchdog deadline before answering.  Without
                a supervisor it is merely slow — exactly like a real hang.
* ``corrupt`` — the dispatch *answers wrong*: a verdict bool (or one
                element of a verdict list) is silently flipped.  No
                exception, no signal — only the differential guard can
                catch this one.
* ``shard_dead`` — one seeded device of the verify mesh dies under a
                SHARDED dispatch (registry `sharded=True` sites): the
                runtime surfaces a dead mesh member as a failed launch,
                so the seam sees a raised `ShardDead` (a `DeviceFault`)
                and the incident log records which shard died.  Same
                breaker → scalar-fallback → half-open contract as
                ``raise`` — "one shard of the mesh died" is just
                another fault.

Transient vs persistent: a transient spec fires on a seeded coin-flip per
call (bounded by `max_fires`); a persistent spec fires on every call once
triggered — the model of a wedged device that will not heal until the
breaker quarantines it.

Every fired fault is recorded in the incident log (event ``injected``)
and counted in METRICS *by the injector itself*, so the chaos tier can
assert "every injected fault is visible" without trusting the component
under test to have noticed.

Determinism: decisions come from `random.Random(seed)` in call order, so
a single-threaded replay with the same plan injects the same faults at
the same dispatches.
"""
from __future__ import annotations

import random
import time
from contextlib import contextmanager
from dataclasses import dataclass, field

from ..sigpipe.metrics import METRICS
from ..utils import nodectx
from ..utils.locks import named_rlock
from . import sites
from .incidents import INCIDENTS

KINDS = ("raise", "timeout", "corrupt", "shard_dead")


class DeviceFault(RuntimeError):
    """Injected stand-in for a raised device/runtime error."""


class ShardDead(DeviceFault):
    """One device of the verify mesh died mid-dispatch — it raised, or
    returned garbage the collective's checksum rejected.  Either way
    the XLA runtime surfaces a dead mesh member as a FAILED launch, so
    at the dispatch seam "one shard died" is just another raised
    fault: same retry → breaker-trip → scalar-fallback → half-open
    contract (parallel/shard_verify.py owns the sharded entry points;
    its `poison_shard` hook models the returns-garbage flavor with
    real data in the kernel tier)."""

    def __init__(self, site: str, shard: int, fire: int):
        super().__init__(
            f"injected dead mesh shard {shard} at {site} (fire {fire})")
        self.shard = shard


def _mesh_width() -> int:
    """Shards a seeded shard_dead fault can kill: the live verify-mesh
    width, 1 when the mesh (or jax itself) is unavailable — the fault
    still fires, modeling the last chip of a 1-wide mesh."""
    try:
        from ..parallel.shard_verify import mesh_devices
        return max(mesh_devices(), 1)
    except Exception:
        return 1


@dataclass
class FaultSpec:
    site: str                    # dispatch site name (exact match)
    kind: str                    # "raise" | "timeout" | "corrupt"
    rate: float = 1.0            # per-call fire probability (seeded)
    persistent: bool = False     # once fired, fire on every later call
    max_fires: int | None = None  # cap for transient specs (None: no cap)
    sleep_s: float = 0.05        # hang duration for kind="timeout"
    fires: int = field(default=0, compare=False)
    _triggered: bool = field(default=False, compare=False)

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")


def _is_bool(v) -> bool:
    return isinstance(v, bool) or type(v).__name__ == "bool_"  # np.bool_


# sites whose dispatch result is a root digest guarded by a
# differential oracle check — ONLY these get bytes corruption (a bytes
# result at an unguarded site, e.g. ops.sha256.hash_level, has no
# quarantine path, so corrupting it would just break the byte-identical
# invariant instead of modeling a catchable silent fault).  Derived from
# the canonical site registry (corrupt="digest" entries).
_DIGEST_GUARDED_SITES = sites.digest_guarded_sites()

# sites whose dispatch result is a tuple of numpy lane arrays guarded by
# a differential oracle (corrupt="lanes" entries): corruption damages
# one element of one array — the silent-lane fault only the sampled
# guard comparison can catch.
_LANES_GUARDED_SITES = sites.lanes_guarded_sites()


def _flip_verdict(result, rng: random.Random, site: str | None = None):
    """Corrupt a verdict-shaped result: flip a bool, one element of a
    list of bools, at digest-guarded sites one bit of a bytes root, or
    at lanes-guarded sites one element of one numpy lane array (the
    silent corruption only the differential guard can catch).  Other
    payloads pass through unchanged (a corrupted point batch surfaces
    as a False product, which the `raise` path already covers)."""
    if _is_bool(result):
        return not bool(result)
    if isinstance(result, list) and result and all(
            _is_bool(v) for v in result):
        out = [bool(v) for v in result]
        j = rng.randrange(len(out))
        out[j] = not out[j]
        return out
    if (site in _DIGEST_GUARDED_SITES
            and isinstance(result, (bytes, bytearray)) and result):
        out = bytearray(result)
        j = rng.randrange(len(out))
        out[j] ^= 1 << rng.randrange(8)
        return bytes(out)
    if (site in _LANES_GUARDED_SITES and isinstance(result, tuple)
            and result and all(hasattr(a, "dtype") for a in result)):
        lanes = [a.copy() for a in result]
        k = rng.randrange(len(lanes))
        arr = lanes[k]
        if arr.size:
            j = rng.randrange(arr.size)
            flat = arr.reshape(-1)
            if flat.dtype.kind == "b":
                flat[j] = not bool(flat[j])
            else:
                flat[j] = flat[j] ^ 1
        return tuple(lanes)
    return result


class FaultPlan:
    """Seeded schedule of faults over named dispatch sites."""

    def __init__(self, specs, seed: int = 0):
        self.specs = list(specs)
        self.seed = seed
        self._rng = random.Random(seed)
        self._lock = named_rlock("resilience.faults")
        by_site: dict = {}
        for s in self.specs:
            by_site.setdefault(s.site, []).append(s)
        self._by_site = by_site

    def _should_fire(self, spec: FaultSpec) -> bool:
        if spec.persistent and spec._triggered:
            return True
        if spec.max_fires is not None and spec.fires >= spec.max_fires:
            return False
        if self._rng.random() >= spec.rate:
            return False
        spec._triggered = True
        return True

    def decide(self, site: str) -> FaultSpec | None:
        """The spec firing at this call to `site`, if any (first match
        wins; records the injection)."""
        with self._lock:
            for spec in self._by_site.get(site, ()):
                if self._should_fire(spec):
                    spec.fires += 1
                    METRICS.inc("faults_injected")
                    METRICS.inc_labeled("faults_injected_by_kind",
                                        spec.kind)
                    INCIDENTS.record(site, "injected", kind=spec.kind,
                                     persistent=spec.persistent,
                                     fire=spec.fires)
                    return spec
            return None

    def wrap(self, site: str, fn):
        """Device function -> possibly-faulting device function.  The
        decision is made per CALL (at invocation time), so retries of the
        same dispatch re-roll the schedule — a transient fault heals, a
        persistent one keeps firing."""
        if site not in self._by_site:
            return fn

        def faulty():
            spec = self.decide(site)
            if spec is None:
                return fn()
            if spec.kind == "raise":
                raise DeviceFault(f"injected fault at {site} "
                                  f"(fire {spec.fires})")
            if spec.kind == "shard_dead":
                # a seeded mesh member dies; the launch fails loud
                # (ShardDead is a DeviceFault: the breaker contract is
                # identical, the incident records WHICH shard).  The
                # shard draw rides the plan lock like every other draw:
                # concurrent dispatches racing the seeded stream would
                # otherwise de-determinize the schedule
                with self._lock:
                    shard = self._rng.randrange(_mesh_width())
                INCIDENTS.record(site, "shard_dead", shard=shard,
                                 fire=spec.fires)
                raise ShardDead(site, shard, spec.fires)
            if spec.kind == "timeout":
                time.sleep(spec.sleep_s)
                return fn()
            # corrupt: silently flip the verdict.  The dispatch itself
            # runs OUTSIDE the plan lock (holding it across a device
            # call would serialize every site behind one flush); only
            # the flip's draws are serialized
            result = fn()
            with self._lock:
                return _flip_verdict(result, self._rng, site)
        return faulty

    def total_fires(self) -> int:
        with self._lock:
            return sum(s.fires for s in self.specs)


# The active plan is a per-node-context ROUTER: a SimNode that owns a
# `fault_plan` Slot has its own seeded schedule (possibly empty — a
# Slot holding None is "no faults for THIS node", never a fall-through
# to a globally injected plan), so the scenario generator can kill one
# node's device while the rest of the fleet stays healthy.  Callers
# with no node context land on the process-global default cell.
_ACTIVE = nodectx.StateRouter("fault_plan")


def active_plan() -> FaultPlan | None:
    return _ACTIVE.get()


def fire(site: str) -> None:
    """Consult the active plan at a *barrier* site — a named point in a
    control path that produces no value to corrupt (a store mutation, a
    commit boundary, a journal write; the txn/ subsystem's kill points).
    A ``raise`` spec dies here with a `DeviceFault` (the simulated
    crash), a ``timeout`` spec stalls, and a ``corrupt`` spec is a no-op
    beyond being recorded — there is no verdict at a barrier to flip.
    With no plan installed this is one routed read."""
    plan = _ACTIVE.get()
    if plan is None:
        return
    spec = plan.decide(site)
    if spec is None:
        return
    if spec.kind == "raise":
        raise DeviceFault(f"injected crash at {site} (fire {spec.fires})")
    if spec.kind == "timeout":
        time.sleep(spec.sleep_s)


@contextmanager
def inject(plan: FaultPlan):
    """Install `plan` at every dispatch seam for the duration — into
    the active node context's plan slot when one is installed (and
    still installed at exit: enter and exit must see the same
    context), else process-global."""
    previous = _ACTIVE.get()
    _ACTIVE.set(plan)
    try:
        yield plan
    finally:
        _ACTIVE.set(previous)
