"""SSZ type system: views + type descriptors.

Capability parity with the reference's SSZ layer (remerkleable re-exported via
/root/reference/tests/core/pyspec/eth2spec/utils/ssz/ssz_typing.py and the
rules in /root/reference/ssz/simple-serialize.md), built from scratch with a
different design: values are thin mutable views over Python data, and
merkleization is a flat chunk sweep (ssz/merkle.py) that can be dispatched to
the batched JAX SHA-256 kernel.  No object-graph persistent trees.

Supported types: boolean, uint8/16/32/64/128/256, Bitvector[N], Bitlist[N],
ByteVector[N], ByteList[N], Vector[T, N], List[T, N], Container, Union[...].
"""
from __future__ import annotations

from .merkle import (
    merkleize_chunks, mix_in_length, mix_in_selector, ZERO_CHUNK,
)

BYTES_PER_CHUNK = 32

# Incremental-merkleization seam (ssz/incremental.py).  While that mode
# is enabled it installs `_inc_root_hook` (view -> cached/swept root, or
# None to fall through to the legacy full computation) and `_inc_mut`
# (the mutation-hook table that keeps dirty-chunk tracking current).
# Both are None when disabled: the only overhead on the legacy path is
# one global check per call.
_inc_root_hook = None
_inc_mut = None


def _htr(view) -> bytes:
    """Composite hash_tree_root entry: incremental when tracked, legacy
    full chunk rebuild (`_htr_full`) otherwise."""
    hook = _inc_root_hook
    if hook is not None:
        root = hook(view)
        if root is not None:
            return root
    return view._htr_full()


class SSZType:
    """Base for all SSZ views.  Class-level descriptors double as types."""

    @classmethod
    def is_fixed_size(cls) -> bool:
        raise NotImplementedError

    @classmethod
    def type_byte_length(cls) -> int:
        """Fixed serialized length (only valid if is_fixed_size())."""
        raise NotImplementedError

    @classmethod
    def default(cls):
        raise NotImplementedError

    @classmethod
    def coerce(cls, value):
        """Coerce a python value (or another view) into a view of this type."""
        if isinstance(value, cls):
            return value
        return cls(value)

    @classmethod
    def coerce_assign(cls, value):
        """Coerce for STORAGE inside another view.  Composite (mutable)
        views are copied so the stored value never aliases a caller-held
        view — the reference's remerkleable views are persistent, so
        assignment there is by value; sharing our mutable views would let
        a later mutation of one object silently rewrite another (e.g.
        storing state.current_justified_checkpoint into an
        AttestationData must snapshot it)."""
        v = cls.coerce(value)
        if v is value and isinstance(v, _MUTABLE_VIEW_BASES):
            return _structural_copy(v)
        return v

    @classmethod
    def decode_bytes(cls, data: bytes):
        return cls.deserialize(data)

    def encode_bytes(self) -> bytes:
        return self.serialize()

    def serialize(self) -> bytes:
        raise NotImplementedError

    @classmethod
    def deserialize(cls, data: bytes):
        raise NotImplementedError

    def hash_tree_root(self) -> bytes:
        raise NotImplementedError

    def copy(self):
        return self.__class__.deserialize(self.serialize())

    def __eq__(self, other):
        if isinstance(other, SSZType):
            return self.serialize() == other.serialize() and \
                type(self).ssz_compatible(type(other))
        return NotImplemented

    def __hash__(self):
        return hash((self.__class__.__name__, self.serialize()))

    @classmethod
    def ssz_compatible(cls, other) -> bool:
        return cls is other or cls.__name__ == other.__name__


# ---------------------------------------------------------------------------
# basic types
# ---------------------------------------------------------------------------

class uint(int, SSZType):
    BYTE_LEN = 0

    def __new__(cls, value=0):
        value = int(value)
        if not 0 <= value < (1 << (8 * cls.BYTE_LEN)):
            raise ValueError(
                f"{cls.__name__} out of range: {value}")
        return super().__new__(cls, value)

    @classmethod
    def is_fixed_size(cls):
        return True

    @classmethod
    def type_byte_length(cls):
        return cls.BYTE_LEN

    @classmethod
    def default(cls):
        return cls(0)

    def serialize(self) -> bytes:
        return int(self).to_bytes(self.BYTE_LEN, "little")

    @classmethod
    def deserialize(cls, data: bytes):
        if len(data) != cls.BYTE_LEN:
            raise ValueError(f"{cls.__name__}: bad length {len(data)}")
        return cls(int.from_bytes(data, "little"))

    def hash_tree_root(self) -> bytes:
        return int(self).to_bytes(self.BYTE_LEN, "little").ljust(32, b"\x00")

    def copy(self):
        return self

    # checked arithmetic: stays in-type, raises on over/underflow — this is
    # how invalid state transitions surface as exceptions, matching the
    # reference semantics (remerkleable uints; see SURVEY.md §7 hard part 2).
    def _wrap(self, value):
        return type(self)(value)

    def __add__(self, o): return self._wrap(int(self) + int(o))
    def __radd__(self, o): return self._wrap(int(o) + int(self))
    def __sub__(self, o): return self._wrap(int(self) - int(o))
    def __rsub__(self, o): return self._wrap(int(o) - int(self))
    def __mul__(self, o): return self._wrap(int(self) * int(o))
    def __rmul__(self, o): return self._wrap(int(o) * int(self))
    def __floordiv__(self, o): return self._wrap(int(self) // int(o))

    def __truediv__(self, o):
        raise TypeError("use // for integer division on SSZ uints")

    def __mod__(self, o): return self._wrap(int(self) % int(o))
    def __pow__(self, o, m=None): return self._wrap(pow(int(self), int(o), m))
    def __and__(self, o): return self._wrap(int(self) & int(o))
    def __or__(self, o): return self._wrap(int(self) | int(o))
    def __xor__(self, o): return self._wrap(int(self) ^ int(o))
    def __lshift__(self, o): return self._wrap(int(self) << int(o))
    def __rshift__(self, o): return self._wrap(int(self) >> int(o))

    def __eq__(self, other):
        return int(self) == other if isinstance(other, int) else NotImplemented

    def __hash__(self):
        return int.__hash__(self)

    def __repr__(self):
        return f"{type(self).__name__}({int(self)})"


class uint8(uint):
    BYTE_LEN = 1


class uint16(uint):
    BYTE_LEN = 2


class uint32(uint):
    BYTE_LEN = 4


class uint64(uint):
    BYTE_LEN = 8


class uint128(uint):
    BYTE_LEN = 16


class uint256(uint):
    BYTE_LEN = 32


class boolean(int, SSZType):
    def __new__(cls, value=0):
        value = int(value)
        if value not in (0, 1):
            raise ValueError("boolean must be 0 or 1")
        return super().__new__(cls, value)

    @classmethod
    def is_fixed_size(cls):
        return True

    @classmethod
    def type_byte_length(cls):
        return 1

    @classmethod
    def default(cls):
        return cls(0)

    def serialize(self):
        return bytes([int(self)])

    @classmethod
    def deserialize(cls, data):
        if data == b"\x00":
            return cls(0)
        if data == b"\x01":
            return cls(1)
        raise ValueError("invalid boolean encoding")

    def hash_tree_root(self):
        return bytes([int(self)]).ljust(32, b"\x00")

    def copy(self):
        return self

    def __repr__(self):
        return f"boolean({int(self)})"


def is_basic_type(t) -> bool:
    return isinstance(t, type) and issubclass(t, (uint, boolean))


# ---------------------------------------------------------------------------
# parameterized-type machinery:  Vector[uint64, 8] etc.
# ---------------------------------------------------------------------------

class ParamMeta(type):
    _cache: dict = {}

    def __getitem__(cls, params):
        if not isinstance(params, tuple):
            params = (params,)
        key = (cls, params)
        cached = ParamMeta._cache.get(key)
        if cached is None:
            cached = cls._parametrize(params)
            ParamMeta._cache[key] = cached
        return cached


# ---------------------------------------------------------------------------
# byte types
# ---------------------------------------------------------------------------

class ByteVector(bytes, SSZType, metaclass=ParamMeta):
    LENGTH = 0

    @classmethod
    def _parametrize(cls, params):
        (n,) = params
        return type(f"ByteVector[{n}]", (ByteVector,), {"LENGTH": int(n)})

    def __new__(cls, value=None):
        if cls.LENGTH == 0 and cls is ByteVector:
            raise TypeError("use ByteVector[N]")
        if value is None:
            value = b"\x00" * cls.LENGTH
        if isinstance(value, str):
            value = bytes.fromhex(value.removeprefix("0x"))
        value = bytes(value)
        if len(value) != cls.LENGTH:
            raise ValueError(f"{cls.__name__}: need {cls.LENGTH} bytes, got {len(value)}")
        return super().__new__(cls, value)

    @classmethod
    def is_fixed_size(cls):
        return True

    @classmethod
    def type_byte_length(cls):
        return cls.LENGTH

    @classmethod
    def default(cls):
        return cls(b"\x00" * cls.LENGTH)

    def serialize(self):
        return bytes(self)

    @classmethod
    def deserialize(cls, data):
        return cls(data)

    def hash_tree_root(self):
        chunks = _bytes_to_chunks(bytes(self))
        return merkleize_chunks(chunks)

    def copy(self):
        return self

    @classmethod
    def ssz_compatible(cls, other):
        return issubclass(other, ByteVector) and other.LENGTH == cls.LENGTH

    def __repr__(self):
        return f"{type(self).__name__}(0x{bytes(self).hex()})"


class ByteList(bytes, SSZType, metaclass=ParamMeta):
    LIMIT = 0

    @classmethod
    def _parametrize(cls, params):
        (n,) = params
        return type(f"ByteList[{n}]", (ByteList,), {"LIMIT": int(n)})

    def __new__(cls, value=b""):
        if isinstance(value, str):
            value = bytes.fromhex(value.removeprefix("0x"))
        value = bytes(value)
        if len(value) > cls.LIMIT:
            raise ValueError(f"{cls.__name__}: {len(value)} bytes exceeds limit")
        return super().__new__(cls, value)

    @classmethod
    def is_fixed_size(cls):
        return False

    @classmethod
    def default(cls):
        return cls(b"")

    def serialize(self):
        return bytes(self)

    @classmethod
    def deserialize(cls, data):
        return cls(data)

    def hash_tree_root(self):
        chunks = _bytes_to_chunks(bytes(self))
        limit = (self.LIMIT + 31) // 32
        return mix_in_length(merkleize_chunks(chunks, limit=limit), len(self))

    def copy(self):
        return self

    @classmethod
    def ssz_compatible(cls, other):
        return issubclass(other, ByteList) and other.LIMIT == cls.LIMIT

    def __repr__(self):
        return f"{type(self).__name__}(0x{bytes(self).hex()})"


def _bytes_to_chunks(data: bytes) -> list[bytes]:
    if len(data) == 0:
        return []
    padded_len = (len(data) + 31) // 32 * 32
    data = data.ljust(padded_len, b"\x00")
    return [data[i:i + 32] for i in range(0, len(data), 32)]


# ---------------------------------------------------------------------------
# bit types
# ---------------------------------------------------------------------------

class Bits(SSZType):
    """Shared machinery for Bitvector/Bitlist; stores a python list of bools."""

    def __init__(self, bits=()):
        if isinstance(bits, (bytes, bytearray)):
            raise TypeError("construct bit types from an iterable of bools")
        self._bits = [bool(b) for b in bits]

    def __len__(self):
        return len(self._bits)

    def __iter__(self):
        return iter(self._bits)

    def __getitem__(self, i):
        return self._bits[i]

    def __setitem__(self, i, v):
        self._bits[i] = bool(v)
        if _inc_mut is not None:
            _inc_mut.on_bits_set(self, i)

    def copy(self):
        return _structural_copy(self)

    def __eq__(self, other):
        if isinstance(other, (list, tuple)):
            return (len(self._bits) == len(other)
                    and all(bool(a) == bool(b)
                            for a, b in zip(self._bits, other)))
        return SSZType.__eq__(self, other)

    def __hash__(self):
        return SSZType.__hash__(self)

    def _pack_bits(self) -> bytes:
        out = bytearray((len(self._bits) + 7) // 8)
        for i, b in enumerate(self._bits):
            if b:
                out[i // 8] |= 1 << (i % 8)
        return bytes(out)

    def __repr__(self):
        return f"{type(self).__name__}({self._bits})"


class Bitvector(Bits, metaclass=ParamMeta):
    LENGTH = 0

    @classmethod
    def _parametrize(cls, params):
        (n,) = params
        if n <= 0:
            raise TypeError("Bitvector length must be > 0")
        return type(f"Bitvector[{n}]", (Bitvector,), {"LENGTH": int(n)})

    def __init__(self, bits=None):
        if bits is None:
            bits = [False] * self.LENGTH
        super().__init__(bits)
        if len(self._bits) != self.LENGTH:
            raise ValueError(f"{type(self).__name__}: need {self.LENGTH} bits")

    @classmethod
    def is_fixed_size(cls):
        return True

    @classmethod
    def type_byte_length(cls):
        return (cls.LENGTH + 7) // 8

    @classmethod
    def default(cls):
        return cls()

    def serialize(self):
        return self._pack_bits()

    @classmethod
    def deserialize(cls, data):
        if len(data) != (cls.LENGTH + 7) // 8:
            raise ValueError("bad bitvector length")
        # check zero padding in the last byte
        if cls.LENGTH % 8 != 0 and data[-1] >> (cls.LENGTH % 8):
            raise ValueError("non-zero padding bits")
        bits = [bool((data[i // 8] >> (i % 8)) & 1) for i in range(cls.LENGTH)]
        return cls(bits)

    def hash_tree_root(self):
        return _htr(self)

    def _htr_full(self):
        chunks = _bytes_to_chunks(self._pack_bits())
        limit = (self.LENGTH + 255) // 256
        return merkleize_chunks(chunks, limit=limit)

    @classmethod
    def ssz_compatible(cls, other):
        return issubclass(other, Bitvector) and other.LENGTH == cls.LENGTH


class Bitlist(Bits, metaclass=ParamMeta):
    LIMIT = 0

    @classmethod
    def _parametrize(cls, params):
        (n,) = params
        return type(f"Bitlist[{n}]", (Bitlist,), {"LIMIT": int(n)})

    def __init__(self, bits=()):
        super().__init__(bits)
        if len(self._bits) > self.LIMIT:
            raise ValueError(f"{type(self).__name__}: exceeds limit {self.LIMIT}")

    @classmethod
    def is_fixed_size(cls):
        return False

    @classmethod
    def default(cls):
        return cls()

    def append(self, v):
        if len(self._bits) >= self.LIMIT:
            raise ValueError("bitlist full")
        self._bits.append(bool(v))
        if _inc_mut is not None:
            _inc_mut.on_bits_append(self)

    def serialize(self):
        # delimiter bit marks the length
        out = bytearray(self._pack_bits())
        n = len(self._bits)
        if n % 8 == 0:
            out.append(1)
        else:
            out[-1] |= 1 << (n % 8)
        return bytes(out)

    @classmethod
    def deserialize(cls, data):
        if len(data) == 0:
            raise ValueError("empty bitlist encoding")
        last = data[-1]
        if last == 0:
            raise ValueError("missing delimiter bit")
        delim = last.bit_length() - 1
        n = (len(data) - 1) * 8 + delim
        if n > cls.LIMIT:
            raise ValueError("bitlist exceeds limit")
        bits = [bool((data[i // 8] >> (i % 8)) & 1) for i in range(n)]
        return cls(bits)

    def hash_tree_root(self):
        return _htr(self)

    def _htr_full(self):
        chunks = _bytes_to_chunks(self._pack_bits())
        limit = (self.LIMIT + 255) // 256
        return mix_in_length(merkleize_chunks(chunks, limit=limit), len(self._bits))

    @classmethod
    def ssz_compatible(cls, other):
        return issubclass(other, Bitlist) and other.LIMIT == cls.LIMIT


# ---------------------------------------------------------------------------
# composite sequences
# ---------------------------------------------------------------------------

def _pack_basics(values, elem_type) -> list[bytes]:
    data = b"".join(elem_type.coerce(v).serialize() for v in values)
    return _bytes_to_chunks(data)


class _Sequence(SSZType):
    ELEM_TYPE: type = None

    def __init__(self, elems=()):
        t = self.ELEM_TYPE
        self._elems = [t.coerce_assign(e) for e in elems]

    @classmethod
    def _from_elems(cls, elems: list):
        """Internal no-coerce constructor for deserialize paths (elements
        are freshly built and correctly typed — re-coercing would copy
        every composite element a second time)."""
        obj = cls.__new__(cls)
        obj._elems = elems
        return obj

    def __len__(self):
        return len(self._elems)

    def __iter__(self):
        return iter(self._elems)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return self._elems[i]
        return self._elems[i]

    def __setitem__(self, i, v):
        # slice assignment is unsupported either way: coerce_assign
        # raises on a non-element value before the store happens
        coerced = self.ELEM_TYPE.coerce_assign(v)
        if _inc_mut is None:
            self._elems[i] = coerced
        else:
            old = self._elems[i]
            self._elems[i] = coerced
            _inc_mut.on_seq_set(self, i, old, coerced)

    def index(self, v):
        return self._elems.index(self.ELEM_TYPE.coerce(v))

    def count(self, v):
        try:
            return self._elems.count(self.ELEM_TYPE.coerce(v))
        except (ValueError, TypeError):
            return 0  # un-coercible values occur 0 times (list.count parity)

    def __contains__(self, v):
        try:
            return self.ELEM_TYPE.coerce(v) in self._elems
        except (ValueError, TypeError):
            return False

    def _serialize_elems(self):
        t = self.ELEM_TYPE
        if t.is_fixed_size():
            return b"".join(e.serialize() for e in self._elems)
        parts = [e.serialize() for e in self._elems]
        offset = 4 * len(parts)
        head = b""
        for p in parts:
            head += offset.to_bytes(4, "little")
            offset += len(p)
        return head + b"".join(parts)

    @classmethod
    def _deserialize_elems(cls, data: bytes) -> list:
        t = cls.ELEM_TYPE
        if t.is_fixed_size():
            n = t.type_byte_length()
            if len(data) % n != 0:
                raise ValueError("bad sequence encoding")
            return [t.deserialize(data[i:i + n]) for i in range(0, len(data), n)]
        if len(data) == 0:
            return []
        first_off = int.from_bytes(data[0:4], "little")
        if first_off == 0 or first_off % 4 != 0 or first_off > len(data):
            raise ValueError("bad first offset")
        count = first_off // 4
        offsets = [int.from_bytes(data[4 * i:4 * i + 4], "little")
                   for i in range(count)] + [len(data)]
        elems = []
        for i in range(count):
            if offsets[i + 1] < offsets[i]:
                raise ValueError("offsets not monotonic")
            elems.append(t.deserialize(data[offsets[i]:offsets[i + 1]]))
        return elems

    def _elem_chunks(self) -> list[bytes]:
        if is_basic_type(self.ELEM_TYPE):
            return _pack_basics(self._elems, self.ELEM_TYPE)
        return [e.hash_tree_root() for e in self._elems]

    def copy(self):
        return _structural_copy(self)

    def __eq__(self, other):
        # spec code compares views against plain python sequences
        # (e.g. `indices == sorted(set(indices))`) — remerkleable supports
        # this, so we must too
        if isinstance(other, (list, tuple)):
            return (len(self._elems) == len(other)
                    and all(a == b for a, b in zip(self._elems, other)))
        return SSZType.__eq__(self, other)

    def __hash__(self):
        return SSZType.__hash__(self)

    def __repr__(self):
        return f"{type(self).__name__}({self._elems!r})"


class Vector(_Sequence, metaclass=ParamMeta):
    LENGTH = 0

    @classmethod
    def _parametrize(cls, params):
        t, n = params
        if int(n) <= 0:
            raise TypeError("Vector length must be > 0")
        return type(f"Vector[{t.__name__},{n}]", (Vector,),
                    {"ELEM_TYPE": t, "LENGTH": int(n)})

    def __init__(self, elems=None):
        if elems is None:
            elems = [self.ELEM_TYPE.default() for _ in range(self.LENGTH)]
        super().__init__(elems)
        if len(self._elems) != self.LENGTH:
            raise ValueError(
                f"{type(self).__name__}: need {self.LENGTH} elements, "
                f"got {len(self._elems)}")

    @classmethod
    def is_fixed_size(cls):
        return cls.ELEM_TYPE.is_fixed_size()

    @classmethod
    def type_byte_length(cls):
        return cls.ELEM_TYPE.type_byte_length() * cls.LENGTH

    @classmethod
    def default(cls):
        return cls()

    def serialize(self):
        return self._serialize_elems()

    @classmethod
    def deserialize(cls, data):
        elems = cls._deserialize_elems(data)
        if len(elems) != cls.LENGTH:
            raise ValueError(
                f"{cls.__name__}: need {cls.LENGTH} elements, "
                f"got {len(elems)}")
        return cls._from_elems(elems)

    def hash_tree_root(self):
        return _htr(self)

    def _htr_full(self):
        if is_basic_type(self.ELEM_TYPE):
            return merkleize_chunks(self._elem_chunks())
        return merkleize_chunks(self._elem_chunks(), limit=self.LENGTH)

    @classmethod
    def ssz_compatible(cls, other):
        return (issubclass(other, Vector) and other.LENGTH == cls.LENGTH
                and cls.ELEM_TYPE.ssz_compatible(other.ELEM_TYPE))


class List(_Sequence, metaclass=ParamMeta):
    LIMIT = 0

    @classmethod
    def _parametrize(cls, params):
        t, n = params
        return type(f"List[{t.__name__},{n}]", (List,),
                    {"ELEM_TYPE": t, "LIMIT": int(n)})

    def __init__(self, elems=()):
        super().__init__(elems)
        if len(self._elems) > self.LIMIT:
            raise ValueError(f"{type(self).__name__}: exceeds limit {self.LIMIT}")

    @classmethod
    def is_fixed_size(cls):
        return False

    @classmethod
    def default(cls):
        return cls()

    def append(self, v):
        if len(self._elems) >= self.LIMIT:
            raise ValueError("list full")
        self._elems.append(self.ELEM_TYPE.coerce_assign(v))
        if _inc_mut is not None:
            _inc_mut.on_seq_append(self)

    def pop(self, i=-1):
        if _inc_mut is None:
            return self._elems.pop(i)
        old_len = len(self._elems)
        v = self._elems.pop(i)
        _inc_mut.on_seq_pop(self, v, i if i >= 0 else i + old_len, old_len)
        return v

    def serialize(self):
        return self._serialize_elems()

    @classmethod
    def deserialize(cls, data):
        elems = cls._deserialize_elems(data)
        if len(elems) > cls.LIMIT:
            raise ValueError(
                f"{cls.__name__}: exceeds limit {cls.LIMIT}")
        return cls._from_elems(elems)

    def hash_tree_root(self):
        return _htr(self)

    def _htr_full(self):
        if is_basic_type(self.ELEM_TYPE):
            elem_len = self.ELEM_TYPE.type_byte_length()
            limit = (self.LIMIT * elem_len + 31) // 32
        else:
            limit = self.LIMIT
        root = merkleize_chunks(self._elem_chunks(), limit=limit)
        return mix_in_length(root, len(self._elems))

    @classmethod
    def ssz_compatible(cls, other):
        return (issubclass(other, List) and other.LIMIT == cls.LIMIT
                and cls.ELEM_TYPE.ssz_compatible(other.ELEM_TYPE))


# ---------------------------------------------------------------------------
# Container
# ---------------------------------------------------------------------------

class Container(SSZType):
    """SSZ container; fields declared via class annotations, in order.

    class Checkpoint(Container):
        epoch: uint64
        root: Bytes32
    """
    _field_names: tuple = ()
    _field_types: tuple = ()

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        # collect fields across the MRO (base-class fields first, subclass
        # fields appended; re-annotating an inherited name overrides in place)
        fields: dict = {}
        for klass in reversed(cls.__mro__):
            anns = klass.__dict__.get("__annotations__", {})
            for k, v in anns.items():
                if not k.startswith("_"):
                    if isinstance(v, str):
                        raise TypeError(
                            f"{cls.__name__}.{k}: field annotation is a "
                            "string — remove `from __future__ import "
                            "annotations` from the defining module")
                    fields[k] = v
        if fields:
            cls._field_names = tuple(fields)
            cls._field_types = tuple(fields.values())

    @classmethod
    def fields(cls) -> dict:
        return dict(zip(cls._field_names, cls._field_types))

    @classmethod
    def coerce(cls, value):
        if isinstance(value, cls):
            return value
        if isinstance(value, Container):
            # same-shaped container from another spec instance (each fork x
            # preset builds its own classes): rebuild structurally
            if not cls.ssz_compatible(type(value)):
                raise TypeError(
                    f"cannot coerce {type(value).__name__} to "
                    f"{cls.__name__}: incompatible SSZ structure")
            return cls.deserialize(value.serialize())
        if isinstance(value, dict):
            return cls(**value)
        raise TypeError(
            f"cannot coerce {type(value).__name__} to {cls.__name__}")

    def __init__(self, **kwargs):
        values = {}
        for name, t in zip(self._field_names, self._field_types):
            if name in kwargs:
                values[name] = t.coerce_assign(kwargs.pop(name))
            else:
                values[name] = t.default()
        if kwargs:
            raise TypeError(f"unknown fields {list(kwargs)} for {type(self).__name__}")
        object.__setattr__(self, "_values", values)

    def __getattr__(self, name):
        # only called when normal lookup fails
        values = self.__dict__.get("_values")
        if values is not None and name in values:
            return values[name]
        raise AttributeError(name)

    def __setattr__(self, name, value):
        if name in self._field_names:
            idx = self._field_names.index(name)
            coerced = self._field_types[idx].coerce_assign(value)
            if _inc_mut is None:
                self._values[name] = coerced
            else:
                old = self._values[name]
                self._values[name] = coerced
                _inc_mut.on_container_set(self, idx, old, coerced)
        else:
            object.__setattr__(self, name, value)

    @classmethod
    def is_fixed_size(cls):
        return all(t.is_fixed_size() for t in cls._field_types)

    @classmethod
    def type_byte_length(cls):
        return sum(t.type_byte_length() for t in cls._field_types)

    @classmethod
    def default(cls):
        return cls()

    def serialize(self) -> bytes:
        fixed_parts = []
        variable_parts = []
        for name, t in zip(self._field_names, self._field_types):
            v = self._values[name]
            if t.is_fixed_size():
                fixed_parts.append(v.serialize())
                variable_parts.append(b"")
            else:
                fixed_parts.append(None)  # placeholder for 4-byte offset
                variable_parts.append(v.serialize())
        fixed_len = sum(4 if p is None else len(p) for p in fixed_parts)
        offset = fixed_len
        out = b""
        for p, vp in zip(fixed_parts, variable_parts):
            if p is None:
                out += offset.to_bytes(4, "little")
                offset += len(vp)
            else:
                out += p
        return out + b"".join(variable_parts)

    @classmethod
    def deserialize(cls, data: bytes):
        values = {}
        # first pass: fixed fields + collect offsets
        pos = 0
        offsets = []
        var_fields = []
        for name, t in zip(cls._field_names, cls._field_types):
            if t.is_fixed_size():
                n = t.type_byte_length()
                if pos + n > len(data):
                    raise ValueError("container encoding too short")
                values[name] = t.deserialize(data[pos:pos + n])
                pos += n
            else:
                if pos + 4 > len(data):
                    raise ValueError("container encoding too short")
                offsets.append(int.from_bytes(data[pos:pos + 4], "little"))
                var_fields.append((name, t))
                pos += 4
        if var_fields:
            if offsets[0] != pos:
                raise ValueError("bad first offset in container")
            bounds = offsets + [len(data)]
            for (name, t), start, end in zip(var_fields, bounds, bounds[1:]):
                if end < start or end > len(data):
                    raise ValueError("bad offsets in container")
                values[name] = t.deserialize(data[start:end])
        elif pos != len(data):
            raise ValueError("trailing bytes in container encoding")
        obj = cls.__new__(cls)
        object.__setattr__(obj, "_values", values)
        return obj

    def copy(self):
        return _structural_copy(self)

    def hash_tree_root(self) -> bytes:
        if not self._field_names:
            return merkleize_chunks([ZERO_CHUNK])
        return _htr(self)

    def _htr_full(self) -> bytes:
        chunks = [self._values[n].hash_tree_root() for n in self._field_names]
        return merkleize_chunks(chunks)

    @classmethod
    def ssz_compatible(cls, other):
        return (issubclass(other, Container)
                and cls._field_names == other._field_names
                and all(a.ssz_compatible(b) for a, b in
                        zip(cls._field_types, other._field_types)))

    def __repr__(self):
        inner = ", ".join(f"{n}={self._values[n]!r}" for n in self._field_names)
        return f"{type(self).__name__}({inner})"


# ---------------------------------------------------------------------------
# Union
# ---------------------------------------------------------------------------

class Union(SSZType, metaclass=ParamMeta):
    OPTIONS: tuple = ()

    @classmethod
    def _parametrize(cls, params):
        names = ",".join("None" if t is None else t.__name__ for t in params)
        if params[0] is None and len(params) == 1:
            raise TypeError("Union[None] is invalid")
        if any(t is None for t in params[1:]):
            raise TypeError("only the first union option may be None")
        return type(f"Union[{names}]", (Union,), {"OPTIONS": tuple(params)})

    def __init__(self, selector: int, value=None):
        if not 0 <= selector < len(self.OPTIONS):
            raise ValueError("bad union selector")
        t = self.OPTIONS[selector]
        if t is None:
            if value is not None:
                raise ValueError("None option takes no value")
        else:
            value = t.coerce(value if value is not None else t.default())
        self.selector = selector
        self.value = value

    def __setattr__(self, name, value):
        old = self.__dict__.get("value") if name == "value" else None
        object.__setattr__(self, name, value)
        if _inc_mut is not None and name in ("selector", "value"):
            _inc_mut.on_union_set(self, old)

    @classmethod
    def is_fixed_size(cls):
        return False

    @classmethod
    def default(cls):
        t = cls.OPTIONS[0]
        return cls(0, None if t is None else t.default())

    def serialize(self):
        body = b"" if self.value is None else self.value.serialize()
        return bytes([self.selector]) + body

    @classmethod
    def deserialize(cls, data):
        if len(data) == 0:
            raise ValueError("empty union encoding")
        sel = data[0]
        if sel >= len(cls.OPTIONS):
            raise ValueError("bad union selector")
        t = cls.OPTIONS[sel]
        if t is None:
            if len(data) != 1:
                raise ValueError("None union option with body")
            return cls(sel, None)
        return cls(sel, t.deserialize(data[1:]))

    def copy(self):
        return _structural_copy(self)

    def hash_tree_root(self):
        return _htr(self)

    def _htr_full(self):
        root = ZERO_CHUNK if self.value is None else self.value.hash_tree_root()
        return mix_in_selector(root, self.selector)

    @classmethod
    def ssz_compatible(cls, other):
        return issubclass(other, Union) and cls.OPTIONS == other.OPTIONS

    def __repr__(self):
        return f"{type(self).__name__}(selector={self.selector}, value={self.value!r})"


# common aliases used throughout the specs
Bytes1 = ByteVector[1]
Bytes4 = ByteVector[4]
Bytes8 = ByteVector[8]
Bytes20 = ByteVector[20]
Bytes31 = ByteVector[31]
Bytes32 = ByteVector[32]
Bytes48 = ByteVector[48]
Bytes96 = ByteVector[96]
bit = boolean
byte = uint8
null = None

# mutable composite views: stored-by-copy on assignment (see
# SSZType.coerce_assign).  uintN / boolean / ByteVector / ByteList are
# immutable Python objects and safe to share.
_MUTABLE_VIEW_BASES = (_Sequence, Container, Bits, Union)


def _structural_copy(v):
    """Deep copy of a composite view WITHOUT the serialize round-trip of
    SSZType.copy(): rebuild the object graph, sharing immutable leaves
    (uints/bytes) and recursing only through mutable views.  This is the
    hot path of coerce_assign — every composite assignment/append pays
    it.

    When the source carries an incremental-merkleization cache, the copy
    shares it copy-on-write (ssz/incremental.on_copy): the level arrays
    are shared until either side's next sweep needs to write, so a
    transactional state copy costs no re-hashing."""
    if isinstance(v, _Sequence):
        t = v.ELEM_TYPE
        if is_basic_type(t) or not issubclass(t, _MUTABLE_VIEW_BASES):
            obj = type(v)._from_elems(list(v._elems))
        else:
            obj = type(v)._from_elems(
                [_structural_copy(e) for e in v._elems])
    elif isinstance(v, Container):
        values = {}
        for name in v._field_names:
            f = v._values[name]
            values[name] = (_structural_copy(f)
                            if isinstance(f, _MUTABLE_VIEW_BASES) else f)
        obj = type(v).__new__(type(v))
        object.__setattr__(obj, "_values", values)
    elif isinstance(v, Bits):
        obj = type(v).__new__(type(v))
        obj._bits = list(v._bits)
    elif isinstance(v, Union):
        val = v.value
        if isinstance(val, _MUTABLE_VIEW_BASES):
            val = _structural_copy(val)
        obj = type(v).__new__(type(v))
        obj.selector = v.selector
        obj.value = val
    else:
        raise TypeError(f"not a composite view: {type(v).__name__}")
    if _inc_mut is not None:
        _inc_mut.on_copy(v, obj)
    return obj
