"""SSZ engine: type system, serialization, merkleization.

The spec-facing surface matches the reference's
eth2spec.utils.ssz.{ssz_typing,ssz_impl} capability
(/root/reference/tests/core/pyspec/eth2spec/utils/ssz/), implemented from
scratch (see types.py / merkle.py / impl.py).
"""
from .types import (  # noqa: F401
    SSZType, uint, uint8, uint16, uint32, uint64, uint128, uint256,
    boolean, bit, byte, Bitvector, Bitlist, ByteVector, ByteList,
    Vector, List, Container, Union,
    Bytes1, Bytes4, Bytes8, Bytes20, Bytes31, Bytes32, Bytes48, Bytes96,
)
from .impl import (  # noqa: F401
    serialize, hash_tree_root, uint_to_bytes, copy,
    use_python_backend, use_tpu_backend, current_backend,
)
from .merkle import (  # noqa: F401
    merkleize_chunks, mix_in_length, get_merkle_proof, is_valid_merkle_branch,
    ZERO_HASHES,
)
from . import incremental  # noqa: F401  (dirty-subtree hash_tree_root)
