"""Generalized indices and single-leaf merkle proofs over SSZ views.

Capability counterpart of /root/reference/ssz/merkle-proofs.md:58-249 and
remerkleable's proof machinery: compute a generalized index from a type +
path, and produce the sibling branch for any generalized index of a view.
Used by blob-sidecar inclusion proofs (deneb) and the light-client sync
protocol (altair).
"""
from __future__ import annotations

from .merkle import (ZERO_HASHES, chunk_depth, hash_pair, merkleize_chunks,
                     next_power_of_two)
from .types import (
    Bits, Bitlist, ByteList, ByteVector, Container, List, SSZType, Union,
    Vector, _Sequence, is_basic_type,
)


def concat_generalized_indices(*indices: int) -> int:
    out = 1
    for index in indices:
        anchor = 1 << (index.bit_length() - 1)  # power-of-two floor
        out = out * anchor + (index - anchor)
    return out


def get_generalized_index_length(index: int) -> int:
    return index.bit_length() - 1


def get_subtree_index(generalized_index: int) -> int:
    return generalized_index % (
        1 << get_generalized_index_length(generalized_index))


def generalized_index_sibling(index: int) -> int:
    return index ^ 1

def generalized_index_parent(index: int) -> int:
    return index // 2

def generalized_index_child(index: int, right_side: bool) -> int:
    return index * 2 + int(right_side)


def _chunk_count(typ) -> int:
    """Number of bottom-layer chunks of the type's merkleization."""
    if is_basic_type(typ):
        return 1
    if issubclass(typ, ByteVector):
        return (typ.LENGTH + 31) // 32
    if issubclass(typ, ByteList):
        return (typ.LIMIT + 31) // 32
    if issubclass(typ, Bitlist):
        return (typ.LIMIT + 255) // 256
    if issubclass(typ, Bits):  # Bitvector
        return (typ.LENGTH + 255) // 256
    if issubclass(typ, Vector):
        if is_basic_type(typ.ELEM_TYPE):
            return (typ.LENGTH * typ.ELEM_TYPE.type_byte_length() + 31) // 32
        return typ.LENGTH
    if issubclass(typ, List):
        if is_basic_type(typ.ELEM_TYPE):
            return (typ.LIMIT * typ.ELEM_TYPE.type_byte_length() + 31) // 32
        return typ.LIMIT
    if issubclass(typ, Container):
        return len(typ._field_names)
    raise TypeError(f"no chunk count for {typ}")


def _has_length_mixin(typ) -> bool:
    return issubclass(typ, (List, ByteList, Bitlist))


def get_generalized_index(typ, *path) -> int:
    """Generalized index of the node at `path` starting from `typ`'s root.

    Path elements: field names (containers), integer indices (vectors /
    lists; descends into the data subtree under the length mix-in), or the
    special "__len__" for a list's length node.
    """
    gindex = 1
    for step_num, step in enumerate(path):
        is_last = step_num == len(path) - 1
        if _has_length_mixin(typ):
            if step == "__len__":
                if not is_last:
                    raise TypeError("cannot descend below a length mix-in")
                return concat_generalized_indices(gindex, 3)
            gindex = concat_generalized_indices(gindex, 2)
        elif step == "__len__":
            raise TypeError(f"{typ} has no length mix-in")
        chunk_count = _chunk_count(typ)
        depth = chunk_depth(chunk_count)
        if issubclass(typ, Container):
            if step not in typ._field_names:
                raise KeyError(f"{typ.__name__} has no field {step!r}")
            pos = typ._field_names.index(step)
            gindex = concat_generalized_indices(gindex, (1 << depth) + pos)
            typ = typ._field_types[pos]
        elif issubclass(typ, (Vector, List)):
            elem = typ.ELEM_TYPE
            if is_basic_type(elem):
                per_chunk = 32 // elem.type_byte_length()
                chunk = int(step) // per_chunk
                if chunk >= chunk_count:
                    raise IndexError("element index out of type bounds")
                if not is_last:
                    raise TypeError(
                        "cannot descend into a basic element")
                return concat_generalized_indices(
                    gindex, (1 << depth) + chunk)
            if int(step) >= chunk_count:
                raise IndexError("element index out of type bounds")
            gindex = concat_generalized_indices(
                gindex, (1 << depth) + int(step))
            typ = elem
        elif issubclass(typ, (ByteVector, ByteList, Bits)):
            # bytes pack 32 per chunk; bit sequences pack 256 per chunk
            per_chunk = 256 if issubclass(typ, Bits) else 32
            chunk = int(step) // per_chunk
            if chunk >= chunk_count:
                raise IndexError("index out of type bounds")
            if not is_last:
                raise TypeError("cannot descend below a leaf chunk")
            return concat_generalized_indices(
                gindex, (1 << depth) + chunk)
        else:
            raise TypeError(f"cannot descend into {typ}")
    return gindex


# ---------------------------------------------------------------------------
# node resolution over a live view
# ---------------------------------------------------------------------------

def _chunk_subtree_node(chunks: list[bytes], depth: int, gindex: int) -> bytes:
    """Root of the node `gindex` within a zero-padded chunk subtree of the
    given depth (gindex local: 1 = subtree root)."""
    path_len = get_generalized_index_length(gindex)
    if path_len > depth:
        raise ValueError("gindex below chunk level")
    # position of the node's subtree among 2**path_len slices
    pos = get_subtree_index(gindex)
    sub_depth = depth - path_len
    size = 1 << sub_depth
    start = pos * size
    sub = chunks[start:start + size]
    if not sub:
        return ZERO_HASHES[sub_depth]
    # merkleize the slice at fixed depth via the pluggable level hasher
    return merkleize_chunks(sub, limit=size)


def _node_of(view, gindex: int) -> bytes:
    """Root of the subtree at `gindex` of `view`'s merkle tree."""
    if gindex == 1:
        return bytes(view.hash_tree_root())
    typ = type(view)

    if _has_length_mixin(typ):
        # root = hash(data_root, length): gindex 2 -> data, 3 -> length
        if gindex == 3:
            if isinstance(view, Bits):
                return len(view._bits).to_bytes(32, "little")
            return len(view).to_bytes(32, "little")
        path_len = get_generalized_index_length(gindex)
        first_bit = (gindex >> (path_len - 1)) & 1
        if first_bit:
            raise ValueError("cannot descend below a length mix-in")
        return _data_node(view, _strip_top(gindex, 1))
    return _data_node(view, gindex)


def _strip_top(gindex: int, levels: int) -> int:
    """Drop the top `levels` path bits of a generalized index."""
    length = get_generalized_index_length(gindex)
    if length < levels:
        raise ValueError("gindex too short")
    rest_len = length - levels
    return (1 << rest_len) | (gindex & ((1 << rest_len) - 1))


def _data_node(view, gindex: int) -> bytes:
    """Node within the data subtree (no length mix-in at this level)."""
    typ = type(view)
    if gindex == 1:
        if _has_length_mixin(typ):
            # data root of a list-like view
            chunks = _data_chunks(view)
            return _chunk_subtree_node(chunks, chunk_depth(_chunk_count(typ)), 1)
        return bytes(view.hash_tree_root())

    depth = chunk_depth(_chunk_count(typ))
    path_len = get_generalized_index_length(gindex)

    if path_len <= depth:
        chunks = _data_chunks(view)
        return _chunk_subtree_node(chunks, depth, gindex)

    # crosses below chunk level: descend into a composite child
    top = _top_bits(gindex, depth)
    rest = _strip_top(gindex, depth)
    child = _child_view(view, top)
    if child is None:
        # padding position: the chunk is a zero chunk; there is no tree
        # below it to descend into
        if rest == 1:
            return ZERO_HASHES[0]
        raise ValueError("gindex descends below a zero-padding chunk")
    return _node_of(child, rest)


def _top_bits(gindex: int, levels: int) -> int:
    """First `levels` path bits of the gindex as a chunk position."""
    length = get_generalized_index_length(gindex)
    return (gindex >> (length - levels)) - (1 << levels)


def _data_chunks(view) -> list[bytes]:
    """Bottom-layer chunks of the view's (data) merkleization."""
    typ = type(view)
    if isinstance(view, Container):
        return [bytes(view._values[n].hash_tree_root())
                for n in typ._field_names]
    if isinstance(view, (ByteVector, ByteList)):
        from .types import _bytes_to_chunks
        return _bytes_to_chunks(bytes(view))
    if isinstance(view, Bits):
        from .types import _bytes_to_chunks
        return _bytes_to_chunks(view._pack_bits())
    if isinstance(view, _Sequence):
        return view._elem_chunks()
    raise TypeError(f"no chunks for {typ}")


def _child_view(view, position: int):
    """Composite child at chunk `position`, or None if out of range."""
    if isinstance(view, Container):
        if position >= len(type(view)._field_names):
            return None
        return view._values[type(view)._field_names[position]]
    if isinstance(view, _Sequence) and not is_basic_type(view.ELEM_TYPE):
        if position >= len(view._elems):
            return None
        return view._elems[position]
    return None


def compute_merkle_proof(view, generalized_index: int) -> list[bytes]:
    """Sibling branch for `generalized_index`, ordered leaf-sibling first —
    directly consumable by is_valid_merkle_branch(leaf, branch, depth,
    get_subtree_index(gindex), root)."""
    branch = []
    g = generalized_index
    while g > 1:
        branch.append(_node_of(view, generalized_index_sibling(g)))
        g = generalized_index_parent(g)
    return branch
