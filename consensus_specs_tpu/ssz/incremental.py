"""Incremental device merkleization: dirty-subtree tracked hash_tree_root.

The legacy merkleization (types.py `_htr_full` + merkle.merkleize_chunks)
recomputes every chunk of a view on every `hash_tree_root` call — O(state)
hashing per `process_slot` even though a block touches a tiny fraction of
the BeaconState.  This module gives composite SSZ views a cached chunk
tree with a dirty-gindex tracker so a re-root after k leaf mutations
hashes only the touched root-to-leaf paths — O(k · log state) chunks —
and batches ALL dirty nodes of the diff into ONE layer-parallel sweep:
levels are grouped bottom-up by dependency height and each level is one
call into the batched SHA-256 kernel (ops/sha256.hash_level_ragged via
merkle's installed bulk hasher; hashlib below the bulk threshold).

Cache layout (per tracked composite view, stored at ``view._mcache``):

* ``levels[d]`` — the populated nodes of the view's data subtree at
  height d (``levels[0]`` = leaf chunks, ``levels[depth][0]`` = data
  root).  Zero-padding stays virtual: a missing right sibling at height
  d reads ``ZERO_HASHES[d]``, exactly like merkleize_chunks.
* ``dirty`` — set of leaf-chunk indices whose content changed since the
  last successful sweep.  Mutation hooks in types.py mark the touched
  chunk and propagate up through ``_mc_parent`` links (child position in
  the parent's chunk layer), so the state root's whole dirty cone is
  known without walking the object graph.
* ``root`` — the view's full root (after length/selector mix-ins);
  ``None`` while dirty.
* copy-on-write: ``copy()`` / ``coerce_assign`` share the level arrays
  between the copies (``shared`` flag); the first sweep that needs to
  write into a shared cache clones the arrays first, so transactional
  state copies (txn/ overlay discipline) can never corrupt each other's
  caches — a rolled-back copy just drops its private dirty set.

The sweep is a real resilience seam: ``dispatch("ssz.merkle_sweep",
device_fn, fallback_fn)`` where the fallback is the legacy full Python
re-root (byte-identical by construction, caches unwritten, dirty sets
preserved) — a tripped breaker degrades to O(state) hashing, never to a
wrong root.  Only the pure hash rounds cross the dispatch seam: the
planner runs before it and the commit after it, both on the calling
thread, so a sweep abandoned by the watchdog deadline keeps hashing
into private buffers but can never write a cache (or clear a dirty
mark) concurrently with the resumed block-processing thread.  A differential guard re-checks sampled incremental roots
against the full-rebuild oracle and quarantines the cache (epoch bump +
site quarantine) on mismatch, exactly like the BLS guard.

Observability (sigpipe metrics registry): ``merkle_sweep_dispatches``,
``merkle_sweep_levels``, ``merkle_chunks_hashed``, ``merkle_dirty_nodes``
(+ power-of-two ``merkle_dirty_occupancy`` histogram),
``merkle_full_rebuilds``, ``merkle_cached_roots``,
``merkle_guard_samples`` / ``merkle_guard_mismatches``.
"""
from __future__ import annotations

import random
import threading

from . import merkle as _merkle
from . import types as _types
from .merkle import ZERO_CHUNK, ZERO_HASHES, chunk_depth
from .types import (
    Bitlist, Bits, Bitvector, Container, List, Union, Vector,
    _MUTABLE_VIEW_BASES, _Sequence, is_basic_type,
)

SWEEP_SITE = "ssz.merkle_sweep"

_ON = False
_EPOCH = 0          # bumped on enable/disable/quarantine: stale caches die
_GUARD_RATE = 0.0
_GUARD_RNG = random.Random(0)
_TL = threading.local()   # .oracle: full-rebuild recursion depth

# resolved at enable() time (lazy: ssz must stay importable before the
# heavier sigpipe/resilience packages)
_METRICS = None
_INCIDENTS = None
_dispatch = None


def enabled() -> bool:
    return _ON


def enable(guard_sample_rate: float = 0.0, guard_seed: int = 0) -> None:
    """Turn incremental merkleization on.  Only *tracked* views (see
    `track`) get caches; everything else keeps the legacy path.  A fresh
    cache epoch starts, so caches from a previous enable (whose
    mutations may have gone unhooked while disabled) are discarded.

    `guard_sample_rate` is the differential-guard probability per sweep
    of re-checking the incremental root against the full-rebuild oracle
    (production would run low single-digit percent; the chaos tier runs
    1.0)."""
    global _ON, _EPOCH, _GUARD_RATE, _GUARD_RNG
    global _METRICS, _INCIDENTS, _dispatch
    if not 0.0 <= guard_sample_rate <= 1.0:
        raise ValueError(f"guard_sample_rate {guard_sample_rate} not in [0, 1]")
    from ..sigpipe.metrics import METRICS
    from ..resilience.incidents import INCIDENTS
    from ..resilience.supervisor import dispatch
    _METRICS, _INCIDENTS, _dispatch = METRICS, INCIDENTS, dispatch
    _EPOCH += 1
    _GUARD_RATE = guard_sample_rate
    _GUARD_RNG = random.Random(guard_seed)
    _ON = True
    _types._inc_root_hook = _root_hook
    _types._inc_mut = _Hooks


def disable() -> None:
    global _ON, _EPOCH
    _ON = False
    _EPOCH += 1
    _types._inc_root_hook = None
    _types._inc_mut = None


def track(view):
    """Mark `view` (a mutable composite, typically a BeaconState) for
    incremental merkleization: its first hash_tree_root builds the chunk
    tree, later ones sweep only the dirty cone.  No-op when the mode is
    disabled or the view is already tracked.  Returns the view."""
    if _ON and isinstance(view, _MUTABLE_VIEW_BASES):
        c = view.__dict__.get("_mcache")
        if c is None or c.epoch != _EPOCH:
            view.__dict__["_mcache"] = _MCache()
    return view


def is_tracked(view) -> bool:
    c = view.__dict__.get("_mcache") if isinstance(
        view, _MUTABLE_VIEW_BASES) else None
    return c is not None and c.epoch == _EPOCH


def oracle_root(view) -> bytes:
    """Full-rebuild root: recompute every chunk, bypassing every cache
    (the differential-guard oracle and the sweep-site fallback)."""
    _TL.oracle = getattr(_TL, "oracle", 0) + 1
    try:
        return bytes(view.hash_tree_root())
    finally:
        _TL.oracle -= 1


def quarantine_caches(reason: str = "guard_mismatch") -> None:
    """Invalidate EVERY merkle cache (epoch bump) and quarantine the
    sweep dispatch site — the cache cannot be trusted after a root
    mismatch, and a device that corrupted one sweep cannot self-report
    recovery."""
    global _EPOCH
    _EPOCH += 1
    from ..resilience import supervisor
    sup = supervisor.active()
    if sup is not None:
        sup.quarantine(SWEEP_SITE, reason=reason)


def bulk_set_basic(view, indices, values) -> int:
    """Batched element assignment on a basic-element sequence view: ONE
    Python-level writeback call replaces len(indices) `__setitem__`
    round trips (the fused epoch engine's balances / inactivity-scores
    columns — a mainnet everyone's-balance-changed epoch is one call,
    not 1M).  Semantically identical to the per-element path: values are
    coerced through `ELEM_TYPE.coerce_assign`, and when the view is
    tracked every touched leaf chunk is marked dirty (the whole cone in
    one pass), so the next re-root stays the O(dirty) fused sweep.

    `indices` / `values` are parallel sequences (numpy arrays welcome);
    indices must be in-range and non-negative.  Returns the element
    count written."""
    t = view.ELEM_TYPE
    if not isinstance(view, _Sequence) or not is_basic_type(t):
        raise TypeError(
            f"bulk_set_basic needs a basic-element sequence view, "
            f"got {type(view).__name__}")
    idx = [int(i) for i in
           (indices.tolist() if hasattr(indices, "tolist") else indices)]
    vals = (values.tolist() if hasattr(values, "tolist")
            else list(values))
    if len(idx) != len(vals):
        raise ValueError(
            f"{len(idx)} indices vs {len(vals)} values")
    if not idx:
        return 0
    elems = view._elems
    n = len(elems)
    if min(idx) < 0 or max(idx) >= n:
        raise IndexError(f"bulk index outside [0, {n})")
    coerce = t.coerce_assign
    for i, v in zip(idx, vals):
        elems[i] = coerce(v)
    if _types._inc_mut is not None and _cache_of(view) is not None:
        esz = t.type_byte_length()
        for ci in {(i * esz) // 32 for i in idx}:
            _mark(view, ci)
    return len(idx)


def type_tree_height(typ) -> int:
    """Static height of the padded merkle tree of `typ` =
    ceil(log2(total padded chunk capacity)): the upper bound on sweep
    level-calls for any diff of a view of this type."""
    if is_basic_type(typ):
        return 0
    if issubclass(typ, (_types.ByteVector,)):
        return chunk_depth((typ.LENGTH + 31) // 32)
    if issubclass(typ, (_types.ByteList,)):
        return chunk_depth((typ.LIMIT + 31) // 32) + 1
    if issubclass(typ, Bitvector):
        return chunk_depth((typ.LENGTH + 255) // 256)
    if issubclass(typ, Bitlist):
        return chunk_depth((typ.LIMIT + 255) // 256) + 1
    if issubclass(typ, Vector):
        if is_basic_type(typ.ELEM_TYPE):
            return chunk_depth(
                (typ.LENGTH * typ.ELEM_TYPE.type_byte_length() + 31) // 32)
        return chunk_depth(typ.LENGTH) + type_tree_height(typ.ELEM_TYPE)
    if issubclass(typ, List):
        if is_basic_type(typ.ELEM_TYPE):
            return chunk_depth(
                (typ.LIMIT * typ.ELEM_TYPE.type_byte_length() + 31) // 32) + 1
        return chunk_depth(typ.LIMIT) + 1 + type_tree_height(typ.ELEM_TYPE)
    if issubclass(typ, Container):
        kids = max((type_tree_height(t) for t in typ._field_types), default=0)
        return chunk_depth(max(1, len(typ._field_names))) + kids
    if issubclass(typ, Union):
        kids = max((type_tree_height(t) for t in typ.OPTIONS
                    if t is not None), default=0)
        return 1 + kids
    raise TypeError(f"no tree height for {typ}")


# ---------------------------------------------------------------------------
# cache object
# ---------------------------------------------------------------------------

class _MCache:
    __slots__ = ("levels", "root", "dirty", "built", "shared",
                 "leaf_count", "epoch")

    def __init__(self):
        self.levels = None      # list[list[bytes|None]] once built
        self.root = None        # full root incl. mix-ins, None while dirty
        self.dirty = set()      # dirty leaf-chunk indices
        self.built = False
        self.shared = False     # levels arrays shared with a copy (CoW)
        self.leaf_count = 0     # chunk count at last successful sweep
        self.epoch = _EPOCH

    def cow_copy(self) -> "_MCache":
        n = _MCache.__new__(_MCache)
        n.levels = self.levels
        n.root = self.root
        n.dirty = set(self.dirty)
        n.built = self.built
        n.leaf_count = self.leaf_count
        n.epoch = self.epoch
        n.shared = True
        if self.levels is not None:
            self.shared = True
        return n

    def unshare(self) -> None:
        if self.shared:
            if self.levels is not None:
                self.levels = [list(lv) for lv in self.levels]
            self.shared = False


def _cache_of(view) -> _MCache | None:
    c = view.__dict__.get("_mcache")
    if c is not None and c.epoch == _EPOCH:
        return c
    return None


# ---------------------------------------------------------------------------
# mutation hooks (installed into types.py while enabled)
# ---------------------------------------------------------------------------

def _mark(view, chunk_idx: int) -> None:
    """Mark leaf `chunk_idx` of `view` dirty and propagate up the parent
    links.  Early exit when the chunk is already dirty AND the root is
    already invalidated: by induction every ancestor is then dirty too."""
    while True:
        cache = view.__dict__.get("_mcache")
        if cache is None or cache.epoch != _EPOCH:
            return
        if cache.root is None and chunk_idx in cache.dirty:
            return
        cache.dirty.add(chunk_idx)
        cache.root = None
        parent = view.__dict__.get("_mc_parent")
        if parent is None:
            return
        view, chunk_idx = parent


def _invalidate_root(view) -> None:
    """Invalidate `view`'s root (no specific leaf chunk — e.g. a pop to
    empty, where only the length mix-in changes) and propagate."""
    cache = _cache_of(view)
    if cache is None:
        return
    cache.root = None
    parent = view.__dict__.get("_mc_parent")
    if parent is not None:
        _mark(parent[0], parent[1])


def _attach(parent, idx: int, child) -> None:
    if isinstance(child, _MUTABLE_VIEW_BASES):
        child.__dict__["_mc_parent"] = (parent, idx)


def _detach(parent, child) -> None:
    if isinstance(child, _MUTABLE_VIEW_BASES):
        link = child.__dict__.get("_mc_parent")
        if link is not None and link[0] is parent:
            child.__dict__["_mc_parent"] = None


class _Hooks:
    """Mutation hooks types.py calls while incremental mode is on.  Every
    hook is a no-op for untracked views (one dict lookup)."""

    @staticmethod
    def on_container_set(view, idx, old, new):
        if _cache_of(view) is None:
            return
        if old is not new:
            _detach(view, old)
        _attach(view, idx, new)
        _mark(view, idx)

    @staticmethod
    def on_seq_set(view, i, old, new):
        if _cache_of(view) is None:
            return
        n = len(view._elems)
        if i < 0:
            i += n
        t = view.ELEM_TYPE
        if is_basic_type(t):
            ci = (i * t.type_byte_length()) // 32
        else:
            ci = i
            if old is not new:
                _detach(view, old)
            _attach(view, i, new)
        _mark(view, ci)

    @staticmethod
    def on_seq_append(view):
        if _cache_of(view) is None:
            return
        n = len(view._elems)
        t = view.ELEM_TYPE
        if is_basic_type(t):
            ci = ((n - 1) * t.type_byte_length()) // 32
        else:
            ci = n - 1
            _attach(view, n - 1, view._elems[n - 1])
        _mark(view, ci)

    @staticmethod
    def on_seq_pop(view, popped, i, old_len):
        cache = _cache_of(view)
        if cache is None:
            return
        _detach(view, popped)
        new_len = old_len - 1
        t = view.ELEM_TYPE
        if is_basic_type(t):
            esz = t.type_byte_length()
            n_chunks = (new_len * esz + 31) // 32
            first = (i * esz) // 32
        else:
            n_chunks = new_len
            first = i
            # a middle pop shifts every later element down one slot:
            # their parent links carry positions, so re-index them
            for j in range(i, new_len):
                _attach(view, j, view._elems[j])
        if n_chunks == 0:
            _invalidate_root(view)
            return
        for ci in range(min(first, n_chunks - 1), n_chunks):
            _mark(view, ci)

    @staticmethod
    def on_bits_set(view, i):
        if _cache_of(view) is None:
            return
        if i < 0:
            i += len(view._bits)
        _mark(view, i // 256)

    @staticmethod
    def on_bits_append(view):
        if _cache_of(view) is None:
            return
        _mark(view, (len(view._bits) - 1) // 256)

    @staticmethod
    def on_union_set(view, old_value):
        if _cache_of(view) is None:
            return
        value = view.__dict__.get("value")
        if old_value is not value:
            _detach(view, old_value)
        if value is not None:
            _attach(view, 0, value)
        _mark(view, 0)

    @staticmethod
    def on_copy(src, dst):
        """Called by _structural_copy after `dst`'s object graph is
        built: share the cache copy-on-write and point dst's composite
        children at dst (their copies carry their own shared caches
        from their own on_copy calls)."""
        cache = _cache_of(src)
        if cache is None:
            return
        dst.__dict__["_mcache"] = cache.cow_copy()
        if isinstance(dst, Container):
            for j, name in enumerate(type(dst)._field_names):
                _attach(dst, j, dst._values[name])
        elif isinstance(dst, _Sequence):
            if not is_basic_type(dst.ELEM_TYPE):
                for j, child in enumerate(dst._elems):
                    _attach(dst, j, child)
        elif isinstance(dst, Union):
            if dst.value is not None:
                _attach(dst, 0, dst.value)


# ---------------------------------------------------------------------------
# shape helpers
# ---------------------------------------------------------------------------

def _view_shape(view):
    """(n_chunks, depth, mix) for the view's data subtree; mix is None,
    ("len", n) or ("sel", s)."""
    if isinstance(view, Container):
        n = len(type(view)._field_names)
        return n, chunk_depth(n), None
    if isinstance(view, Bitlist):
        bits = len(view._bits)
        return ((bits + 255) // 256,
                chunk_depth((view.LIMIT + 255) // 256), ("len", bits))
    if isinstance(view, Bitvector):
        n = (view.LENGTH + 255) // 256
        return n, chunk_depth(n), None
    if isinstance(view, Union):
        return 1, 0, ("sel", view.selector)
    t = view.ELEM_TYPE
    count = len(view._elems)
    if isinstance(view, Vector):
        if is_basic_type(t):
            n = (count * t.type_byte_length() + 31) // 32
            return n, chunk_depth(n), None
        return count, chunk_depth(view.LENGTH), None
    # List
    if is_basic_type(t):
        n = (count * t.type_byte_length() + 31) // 32
        cap = (view.LIMIT * t.type_byte_length() + 31) // 32
        return n, chunk_depth(cap), ("len", count)
    return count, chunk_depth(view.LIMIT), ("len", count)


def _packed_chunk(view, ci: int) -> bytes:
    if isinstance(view, Bits):
        bits = view._bits[ci * 256:(ci + 1) * 256]
        out = bytearray(32)
        for j, b in enumerate(bits):
            if b:
                out[j // 8] |= 1 << (j % 8)
        return bytes(out)
    t = view.ELEM_TYPE
    per = 32 // t.type_byte_length()
    data = b"".join(e.serialize()
                    for e in view._elems[ci * per:(ci + 1) * per])
    return data.ljust(32, b"\x00")


def _lvl_len(n: int, d: int) -> int:
    return (n + (1 << d) - 1) >> d


# ---------------------------------------------------------------------------
# sweep planner + executor
# ---------------------------------------------------------------------------

class _Sweep:
    """Global hash-job DAG, grouped bottom-up by dependency height.

    A job is one 2-to-1 hash; its inputs are literal 32-byte chunks or
    outputs of lower rounds.  Round r collects every job whose inputs
    are all available after round r-1, so the executor issues exactly
    one (ragged) batched level-call per round — across ALL dirty
    subtrees of the view graph at once.  A job ref is (round, index);
    a literal ref is the bytes themselves (round 0)."""

    __slots__ = ("rounds", "writebacks", "finals", "dirty_leaves")

    def __init__(self):
        self.rounds = []       # rounds[r] = [(left_ref, right_ref), ...]
        self.writebacks = []   # (cache, level, idx, ref)
        self.finals = []       # (cache, leaf_count, root_ref)
        self.dirty_leaves = 0

    def job(self, left, right):
        r = 0
        if type(left) is tuple:
            r = left[0]
        if type(right) is tuple and right[0] > r:
            r = right[0]
        while len(self.rounds) <= r:
            self.rounds.append([])
        self.rounds[r].append((left, right))
        return (r + 1, len(self.rounds[r]) - 1)

    def resolve(self, outs, ref):
        if type(ref) is tuple:
            return outs[ref[0] - 1][ref[1]]
        return ref


def _plan_view(sw: _Sweep, view):
    """Plan the re-root of `view`: append this view's hash jobs to the
    sweep and return a ref for its full root (a literal when the cached
    root is still valid).  Builds missing caches (all leaves dirty) and
    installs parent links on composite children as it descends."""
    cache = view.__dict__.get("_mcache")
    if cache is None or cache.epoch != _EPOCH:
        cache = _MCache()
        view.__dict__["_mcache"] = cache
    if cache.built and not cache.dirty and cache.root is not None:
        return cache.root

    n, depth, mix = _view_shape(view)
    cache.unshare()
    if not cache.built or cache.levels is None:
        cache.levels = [[None] * _lvl_len(n, d) for d in range(depth + 1)]
        dirty = set(range(n))
    else:
        levels = cache.levels
        for d in range(depth + 1):
            want = _lvl_len(n, d)
            have = len(levels[d])
            if want < have:
                del levels[d][want:]
            elif want > have:
                levels[d].extend([None] * (want - have))
        dirty = {i for i in cache.dirty if i < n}
        if cache.leaf_count != n and n > 0:
            # the last node at every level is the only one whose
            # (virtual-zero) sibling set can change with the count
            dirty.add(n - 1)
    sw.dirty_leaves += len(dirty)

    cur = {}
    for i in dirty:
        ref = _leaf_ref(sw, view, i)
        sw.writebacks.append((cache, 0, i, ref))
        cur[i] = ref
    for d in range(depth):
        if not cur:
            break
        cur_level = cache.levels[d]
        cur_len = len(cur_level)
        nxt = {}
        for p in sorted({i >> 1 for i in cur}):
            li, ri = 2 * p, 2 * p + 1
            left = cur[li] if li in cur else cur_level[li]
            if ri in cur:
                right = cur[ri]
            elif ri < cur_len:
                right = cur_level[ri]
            else:
                right = ZERO_HASHES[d]
            ref = sw.job(left, right)
            sw.writebacks.append((cache, d + 1, p, ref))
            nxt[p] = ref
        cur = nxt

    if n == 0:
        data_ref = ZERO_HASHES[depth]
    elif 0 in cur:
        data_ref = cur[0]
    else:
        data_ref = cache.levels[depth][0]

    if mix is None:
        root_ref = data_ref
    else:  # ("len", n) and ("sel", s) mix in the same way
        root_ref = sw.job(data_ref, int(mix[1]).to_bytes(32, "little"))
    sw.finals.append((cache, n, root_ref))
    return root_ref


def _leaf_ref(sw: _Sweep, view, i: int):
    """Ref for the content of leaf chunk `i` of `view`: a host-packed
    literal for basic/bit chunks, the (possibly planned) child root for
    composite positions, a host-computed root for immutable children."""
    if isinstance(view, Container):
        child = view._values[type(view)._field_names[i]]
    elif isinstance(view, Union):
        child = view.value
        if child is None:
            return ZERO_CHUNK
    elif isinstance(view, Bits):
        return _packed_chunk(view, i)
    else:  # Vector / List
        if is_basic_type(view.ELEM_TYPE):
            return _packed_chunk(view, i)
        child = view._elems[i]
    if isinstance(child, _MUTABLE_VIEW_BASES):
        _attach(view, i, child)
        return _plan_view(sw, child)
    return bytes(child.hash_tree_root())


def _level_hash(data: bytes) -> tuple:
    """One ragged level: route through the installed bulk device hasher
    (ops/sha256.hash_level_ragged) above the bulk threshold, hashlib
    below it — the same split every legacy hash_tree_root uses.
    Returns (hashed bytes, device round-trip count: 1 for a bulk call,
    0 for hashlib)."""
    bulk = _merkle._bulk_hash_level
    if bulk is not None and len(data) // 64 >= _merkle._bulk_threshold:
        return bulk(data), 1
    return _merkle._hash_level_python(data), 0


def _hash_rounds(sw: _Sweep) -> list:
    """Run the sweep's hash rounds level-by-level and return the
    per-round outputs (the PER-LEVEL path: each bulk level pays its own
    host<->device round-trip — counted in `merkle_device_round_trips`).
    Pure: every input is a literal chunk copied in by the planner or a
    lower round's output, so this is safe to run on the supervisor's
    watchdog worker — an abandoned (timed-out) run touches no cache."""
    outs = []
    trips = 0
    for jobs in sw.rounds:
        buf = bytearray()
        for left, right in jobs:
            buf += left if type(left) is bytes else outs[left[0] - 1][left[1]]
            buf += right if type(right) is bytes else outs[right[0] - 1][right[1]]
        hashed, t = _level_hash(bytes(buf))
        trips += t
        outs.append([hashed[k * 32:(k + 1) * 32] for k in range(len(jobs))])
    if trips:
        _METRICS.inc("merkle_device_round_trips", trips)
    return outs


def _hash_rounds_fused(sw: _Sweep) -> list:
    """Run ALL the sweep's rounds as ONE compiled device program
    (ops/sha256.fused_rounds): literal inputs are deduped and uploaded
    once, every round's dirty-index gather and batched hash stays in
    device memory, and the per-round outputs come back in a single
    download — one host<->device round-trip per re-root instead of one
    per tree level.  Between consecutive sweeps the device literal pool
    keeps the clean-sibling level buffers (and the previous sweep's
    outputs) resident, so a re-root uploads only the DIRTY literals —
    pool hits land in `merkle_sibling_uploads_skipped`, the sibling
    counter next to `merkle_device_round_trips`.  Pure, like
    `_hash_rounds`: inputs are copied into the job plan, nothing
    touches a cache (the pool is content-addressed device residency,
    never consulted for roots)."""
    from ..ops import sha256 as _sha
    lits: list = []
    lit_pos: dict = {}
    for jobs in sw.rounds:
        for ref in (r for job in jobs for r in job):
            if type(ref) is bytes and ref not in lit_pos:
                lit_pos[ref] = len(lits)
                lits.append(ref)
    n_lits = len(lits)
    cum = [0]
    for jobs in sw.rounds:
        cum.append(cum[-1] + len(jobs))

    def idx(ref):
        if type(ref) is bytes:
            return lit_pos[ref]
        return n_lits + cum[ref[0] - 1] + ref[1]

    rounds = [([idx(left) for left, _r in jobs],
               [idx(right) for _l, right in jobs]) for jobs in sw.rounds]
    stats: dict = {}
    out_bytes = _sha.fused_rounds(b"".join(lits), rounds, stats=stats)
    _METRICS.inc("merkle_device_round_trips")
    if stats.get("skipped"):
        _METRICS.inc("merkle_sibling_uploads_skipped", stats["skipped"])
    _METRICS.inc("merkle_sibling_uploads", stats.get("uploaded", 0))
    return [[ob[k * 32:(k + 1) * 32] for k in range(len(jobs))]
            for ob, jobs in zip(out_bytes, sw.rounds)]


def _run_rounds(sw: _Sweep) -> list:
    """Pick the sweep execution engine: the fused device-resident
    program when bulk device hashing is installed and the sweep is big
    enough to be worth a dispatch (MERKLE_FUSED=0 forces the per-level
    path), else the per-level split."""
    import os
    total = sum(len(jobs) for jobs in sw.rounds)
    if (_merkle._bulk_hash_level is not None
            and total >= _merkle._bulk_threshold
            and os.environ.get("MERKLE_FUSED", "") not in ("0", "off")):
        return _hash_rounds_fused(sw)
    return _hash_rounds(sw)


def _commit(sw: _Sweep, outs: list) -> None:
    """Write the sweep's results into the caches and clear the dirty
    cones.  MUST run on the caller's (block-processing) thread, after
    the dispatch came back on the device path: a commit running on an
    abandoned watchdog worker would race later mutations and could
    clear a dirty mark the block thread set in the meantime."""
    for cache, level, idx, ref in sw.writebacks:
        cache.levels[level][idx] = sw.resolve(outs, ref)
    for cache, leaf_count, root_ref_i in sw.finals:
        cache.root = sw.resolve(outs, root_ref_i)
        cache.leaf_count = leaf_count
        cache.built = True
        cache.dirty.clear()


# ---------------------------------------------------------------------------
# the hash_tree_root hook
# ---------------------------------------------------------------------------

def _root_hook(view):
    """types.py calls this from every composite hash_tree_root while the
    mode is on.  Returns None to fall through to the legacy path
    (untracked view, or full-rebuild oracle mode)."""
    if getattr(_TL, "oracle", 0):
        return None
    cache = _cache_of(view)
    if cache is None:
        return None
    if cache.built and not cache.dirty and cache.root is not None:
        _METRICS.inc("merkle_cached_roots")
        return cache.root
    return _recompute(view, cache)


def _recompute(view, cache: _MCache) -> bytes:
    if not cache.built:
        # first root of a tracked view: the sweep IS the cache build
        # (every leaf dirty) — not a degradation, counted separately
        _METRICS.inc("merkle_cache_builds")

    # plan on THIS thread: the planner builds/resizes cache level arrays
    # (commit-safe without a sweep: sizes are re-derived and unwritten
    # nodes stay dirty), so only pure hashing crosses the dispatch seam
    sw = _Sweep()
    root_ref = _plan_view(sw, view)
    outs_box = [None]

    def device():
        outs = _run_rounds(sw)
        outs_box[0] = outs
        return sw.resolve(outs, root_ref)

    used_fallback = False

    def fallback():
        # legacy full python re-root: byte-identical, caches unwritten
        # (dirty sets survive, so a recovered breaker resumes sweeping)
        nonlocal used_fallback
        used_fallback = True
        _METRICS.inc("merkle_full_rebuilds")
        return oracle_root(view)

    _METRICS.inc("merkle_sweep_dispatches")
    root = _dispatch(SWEEP_SITE, device, fallback)
    if not used_fallback:
        # device path: commit on this thread (never on the watchdog
        # worker — an abandoned run must not touch the caches), from
        # the pre-corruption outputs so an injected corrupt fault skews
        # only the returned root, which the guard below can catch
        _METRICS.inc("merkle_chunks_hashed",
                     sum(len(jobs) for jobs in sw.rounds))
        _METRICS.inc("merkle_sweep_levels", len(sw.rounds))
        _METRICS.inc("merkle_dirty_nodes", sw.dirty_leaves)
        _METRICS.observe_hist("merkle_dirty_occupancy", sw.dirty_leaves)
        _commit(sw, outs_box[0])

    # guard only sweep-produced roots: a fallback root IS the oracle
    # root, so re-deriving it would compare two identical full rebuilds
    if (not used_fallback
            and _GUARD_RATE > 0.0 and _GUARD_RNG.random() < _GUARD_RATE):
        _METRICS.inc("merkle_guard_samples")
        expect = oracle_root(view)
        if bytes(root) != expect:
            _METRICS.inc("merkle_guard_mismatches")
            _INCIDENTS.record(SWEEP_SITE, "guard_mismatch",
                              got=bytes(root).hex(), expected=expect.hex())
            quarantine_caches()
            return expect
    return root
