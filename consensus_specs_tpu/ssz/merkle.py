"""Binary SHA-256 merkleization over 32-byte chunks.

Capability parity with the reference's merkleization rules
(/root/reference/ssz/simple-serialize.md:229-257 "Merkleization" and
/root/reference/tests/core/pyspec/eth2spec/utils/merkle_minimal.py), re-built
as a flat chunk-array sweep so the same level-by-level loop can be dispatched
either to hashlib (oracle) or to the batched JAX SHA-256 kernel (TPU backend,
see consensus_specs_tpu.ops.sha256).
"""
from __future__ import annotations

import hashlib
from typing import Sequence

ZERO_CHUNK = b"\x00" * 32
MAX_DEPTH = 64


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def hash_pair(left: bytes, right: bytes) -> bytes:
    return hashlib.sha256(left + right).digest()


def _build_zero_hashes() -> list[bytes]:
    zh = [ZERO_CHUNK]
    for _ in range(MAX_DEPTH):
        zh.append(hash_pair(zh[-1], zh[-1]))
    return zh


#: ZERO_HASHES[i] = root of a fully-zero subtree of depth i
ZERO_HASHES: list[bytes] = _build_zero_hashes()


def next_power_of_two(n: int) -> int:
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


def chunk_depth(chunk_count: int) -> int:
    """Depth of the padded tree for `chunk_count` leaves."""
    return max(0, (next_power_of_two(chunk_count) - 1).bit_length())


# Pluggable level-hasher.  `hash_level(data)` takes a bytes object that is a
# concatenation of 2N chunks and returns the N parent chunks concatenated.
# The TPU backend replaces this with a batched JAX SHA-256 compression sweep.
def _hash_level_python(data: bytes) -> bytes:
    out = bytearray()
    h = hashlib.sha256
    for i in range(0, len(data), 64):
        out += h(data[i:i + 64]).digest()
    return bytes(out)


_hash_level = _hash_level_python

# Bulk hasher: used instead of _hash_level for levels of >= _bulk_threshold
# chunks, where the device batch amortizes the host<->device transfer.  Small
# levels (the vast majority of container nodes) stay on hashlib.
_bulk_hash_level = None
_bulk_threshold = 2048


def set_level_hasher(fn) -> None:
    """Install a replacement level hasher (e.g. the JAX batched kernel)."""
    global _hash_level
    _hash_level = fn if fn is not None else _hash_level_python


def set_bulk_level_hasher(fn, threshold: int = 2048) -> None:
    """Install a large-level hasher: `fn` receives the concatenation of 2N
    chunks (N >= threshold) and returns the N parents.  Pass None to
    uninstall.  This is how the TPU SHA-256 kernel plugs into every
    hash_tree_root without penalizing small containers."""
    global _bulk_hash_level, _bulk_threshold
    _bulk_hash_level = fn
    _bulk_threshold = threshold


def use_tpu_hashing(threshold: int = 2048, pallas: bool = False) -> None:
    """Route big merkle levels through the batched JAX SHA-256 kernel
    (pallas=True selects the fused Pallas kernel — TPU backends only)."""
    if pallas:
        from ..ops.sha256_pallas import hash_level_pallas
        set_bulk_level_hasher(hash_level_pallas, threshold)
    else:
        # hash_level_ragged: same kernel, ragged-batch contract — the
        # incremental sweep's per-round levels are arbitrary-size
        from ..ops.sha256 import hash_level_ragged
        set_bulk_level_hasher(hash_level_ragged, threshold)


def use_host_hashing() -> None:
    set_bulk_level_hasher(None)


# Whole-subtree hasher: collapses the level loop for large populated
# subtrees into one call (the mesh engine shards the subtree across
# devices and all-gathers the per-device roots — parallel/mesh_engine).
# `fn(level_bytes, depth)` gets a power-of-two chunk concatenation and
# returns the 32-byte subtree root.
_subtree_hasher = None
_subtree_threshold = 1 << 14


def set_subtree_hasher(fn, threshold: int = 1 << 14) -> None:
    global _subtree_hasher, _subtree_threshold
    _subtree_hasher = fn
    _subtree_threshold = threshold

# NOTE: the native C++ tier's sha256_2to1_batch is NOT wired here on
# purpose — measured 0.92x vs hashlib on a SHA-NI host (OpenSSL's
# assembly beats portable C++ per hash; the saved Python loop overhead
# doesn't cover the gap).  The plug points above stand ready if a
# vectorized native hasher lands.


def _dispatch(site, device_fn, fallback_fn):
    """Resilience seam for the installed device hashers (lazy import —
    hash_tree_root must stay importable before the heavier packages)."""
    from ..resilience.supervisor import dispatch
    return dispatch(site, device_fn, fallback_fn)


def _host_subtree_root(level: bytes, sub_depth: int) -> bytes:
    """hashlib fallback for a whole populated subtree: the plain level
    loop the subtree hasher replaces."""
    for _ in range(sub_depth):
        level = _hash_level_python(level)
    assert len(level) == 32
    return level


def merkleize_chunks(chunks: Sequence[bytes], limit: int | None = None) -> bytes:
    """Merkle root of `chunks`, virtually padded with zero chunks.

    `limit` is the maximum number of leaves the tree is sized for (list
    merkleization); None means pad to the next power of two of len(chunks)
    (vector merkleization).  Only the populated subtree is hashed; zero
    subtrees come from the precomputed ZERO_HASHES table.
    """
    count = len(chunks)
    if limit is not None:
        if count > limit:
            raise ValueError(f"chunk count {count} exceeds limit {limit}")
        depth = chunk_depth(limit)
    else:
        depth = chunk_depth(count)

    if count == 0:
        return ZERO_HASHES[depth]

    level = b"".join(chunks)

    padded = next_power_of_two(count)
    if (_subtree_hasher is not None and count >= _subtree_threshold
            and (padded - count) * 8 <= count):
        # hash the whole populated subtree in one sharded call, then
        # climb the virtually-padded top with zero-tree siblings.  Only
        # near-full trees (< 12.5% zero padding) take this path — a
        # barely-past-a-power-of-two count would nearly double the hash
        # work vs the level loop's ZERO_HASHES shortcuts
        sub_depth = chunk_depth(count)
        if padded != count:
            level += bytes(32) * (padded - count)
        root = _dispatch(
            "ops.sha256.subtree",
            lambda: _subtree_hasher(level, sub_depth),
            lambda: _host_subtree_root(level, sub_depth))
        for d in range(sub_depth, depth):
            root = hash_pair(root, ZERO_HASHES[d])
        return root

    for d in range(depth):
        n = len(level) // 32
        if n % 2 == 1:
            level += ZERO_HASHES[d]
            n += 1
        if _bulk_hash_level is not None and n // 2 >= _bulk_threshold:
            data = level
            level = _dispatch("ops.sha256.hash_level",
                              lambda: _bulk_hash_level(data),
                              lambda: _hash_level_python(data))
        else:
            level = _hash_level(level)
    assert len(level) == 32
    return level


def mix_in_length(root: bytes, length: int) -> bytes:
    return hash_pair(root, length.to_bytes(32, "little"))


def mix_in_selector(root: bytes, selector: int) -> bytes:
    return hash_pair(root, selector.to_bytes(32, "little"))


def get_merkle_proof(chunks: Sequence[bytes], index: int,
                     limit: int | None = None) -> list[bytes]:
    """Merkle branch for leaf `index` in the (virtually padded) tree.

    Same capability as the reference's merkle_minimal.get_merkle_proof
    (/root/reference/tests/core/pyspec/eth2spec/utils/merkle_minimal.py).
    """
    count = len(chunks)
    depth = chunk_depth(limit if limit is not None else count)
    proof = []
    level_chunks = list(chunks)
    idx = index
    for d in range(depth):
        sib = idx ^ 1
        if sib < len(level_chunks):
            proof.append(level_chunks[sib])
        else:
            proof.append(ZERO_HASHES[d])
        # build next level
        nxt = []
        for i in range(0, len(level_chunks), 2):
            left = level_chunks[i]
            right = level_chunks[i + 1] if i + 1 < len(level_chunks) else ZERO_HASHES[d]
            nxt.append(hash_pair(left, right))
        level_chunks = nxt
        idx >>= 1
    return proof


def is_valid_merkle_branch(leaf: bytes, branch: Sequence[bytes], depth: int,
                           index: int, root: bytes) -> bool:
    """Verify a merkle branch (spec: phase0 beacon-chain.md is_valid_merkle_branch)."""
    value = leaf
    for i in range(depth):
        if (index >> i) & 1:
            value = hash_pair(branch[i], value)
        else:
            value = hash_pair(value, branch[i])
    return value == root
