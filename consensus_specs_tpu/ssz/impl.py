"""Free-function SSZ API, mirroring the reference's ssz_impl surface
(/root/reference/tests/core/pyspec/eth2spec/utils/ssz/ssz_impl.py:8-37):
serialize / hash_tree_root / uint_to_bytes / copy — plus a pluggable
merkle backend switch so hash_tree_root can run on the JAX SHA-256 kernel.
"""
from __future__ import annotations

from .types import SSZType, uint
from . import merkle

_ssz_backend = "python"


def use_python_backend() -> None:
    global _ssz_backend
    merkle.set_level_hasher(None)
    _ssz_backend = "python"


def use_tpu_backend() -> None:
    """Route merkle level hashing through the batched JAX SHA-256 kernel."""
    global _ssz_backend
    from ..ops.sha256 import hash_level_jax
    merkle.set_level_hasher(hash_level_jax)
    _ssz_backend = "tpu"


def current_backend() -> str:
    return _ssz_backend


def serialize(obj: SSZType) -> bytes:
    return obj.serialize()


def hash_tree_root(obj) -> bytes:
    # composite views route through ssz/incremental.py's dirty-subtree
    # cache when that mode is enabled and the view is tracked; the
    # legacy full chunk rebuild otherwise (byte-identical either way)
    from .types import Bytes32
    return Bytes32(obj.hash_tree_root())


def uint_to_bytes(n: uint) -> bytes:
    return serialize(n)


def copy(obj: SSZType):
    return obj.copy()
