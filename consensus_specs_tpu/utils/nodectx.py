"""Per-node execution context: the de-globalization seam.

The repo grew up single-node: one process-global incident log
(`resilience.INCIDENTS`) and one process-global metrics registry
(`sigpipe.METRICS`), imported by value everywhere.  The scenario
harness (scenario/) runs N gossip pipelines + transactional stores in
ONE process, and fleet-level assertions ("every adversarial event is
attributed to a node") need per-node books.  Rather than threading a
registry parameter through every call site, the two globals became
*routers*: each consults the context stack below and delegates to the
active node's registry, falling back to the process-global default when
no context is installed — single-node callers and every existing test
are byte-for-byte untouched.

    ctx = NodeContext("node3", metrics=Metrics(node_id="node3"),
                      incidents=IncidentLog(node_id="node3"))
    with nodectx.use(ctx):
        pipe.submit(...)        # every metric/incident lands in ctx's
                                # registries, tagged node_id=node3

The stack is deliberately PROCESS-global, not thread-local: the
scenario driver steps one node at a time on one thread, but a dispatch
inside that step may hop to the supervisor's watchdog worker — a
thread-local (or contextvar) stack would silently re-route those
records to the default registry, losing exactly the incidents the
chaos tier asserts on.  Concurrent multi-context use is therefore not
supported (and not needed: production wiring never installs a context;
the simulation's determinism contract is single-scheduler anyway).

This module sits at the bottom of the dependency graph on purpose: it
imports nothing from the package except the equally-bottom
``utils/locks.py`` primitive layer (stdlib-only at module scope), so
both resilience/ and sigpipe/ can consult it without cycles.
"""
from __future__ import annotations

from contextlib import contextmanager

from .locks import named_rlock


class NodeContext:
    """One simulated node's observability namespace.

    `metrics` / `incidents` are duck-typed (a `sigpipe.metrics.Metrics`
    and a `resilience.incidents.IncidentLog` in practice); either may be
    None to keep that stream on the process-global default.
    """

    __slots__ = ("node_id", "metrics", "incidents")

    def __init__(self, node_id: str, metrics=None, incidents=None):
        self.node_id = str(node_id)
        self.metrics = metrics
        self.incidents = incidents

    def __repr__(self) -> str:
        return f"NodeContext({self.node_id!r})"


_lock = named_rlock("nodectx.stack")
_stack: list = []


class Router:
    """The module-global delegation seam shared by `resilience.INCIDENTS`
    and `sigpipe.METRICS` (and any future per-node registry — the
    ROADMAP names the supervisor's breaker table next): every attribute
    access consults the context stack and lands on the active context's
    `attr` registry when one is installed, else on the process-global
    default.  `from ... import NAME` binds the router object by value
    everywhere, so the routing must live *inside* it, not in the module
    name."""

    def __init__(self, default, attr: str):
        self._default = default
        self._attr = attr

    @property
    def default(self):
        """The process-global registry, bypassing any installed context
        (the scenario driver reads this for fleet-wide series)."""
        return self._default

    def _target(self):
        ctx = current()
        if ctx is not None:
            registry = getattr(ctx, self._attr, None)
            if registry is not None:
                return registry
        return self._default

    def __getattr__(self, name):
        return getattr(self._target(), name)

    def __len__(self) -> int:            # len() bypasses __getattr__
        return len(self._target())


def current() -> NodeContext | None:
    """The innermost installed context, or None (process-global mode)."""
    with _lock:
        return _stack[-1] if _stack else None


@contextmanager
def use(ctx: NodeContext):
    """Install `ctx` for a lexical region.  Reentrant: the scenario
    driver wraps both the node step and the pipeline's own methods, so
    the same context may be pushed twice — inner pushes just shadow."""
    with _lock:
        _stack.append(ctx)
    try:
        yield ctx
    finally:
        with _lock:
            _stack.pop()
