"""Per-node execution context: the de-globalization seam.

The repo grew up single-node: one process-global incident log
(`resilience.INCIDENTS`) and one process-global metrics registry
(`sigpipe.METRICS`), imported by value everywhere.  The scenario
harness (scenario/) runs N gossip pipelines + transactional stores in
ONE process, and fleet-level assertions ("every adversarial event is
attributed to a node") need per-node books.  Rather than threading a
registry parameter through every call site, the two globals became
*routers*: each consults the context stack below and delegates to the
active node's registry, falling back to the process-global default when
no context is installed — single-node callers and every existing test
are byte-for-byte untouched.

    ctx = NodeContext("node3", metrics=Metrics(node_id="node3"),
                      incidents=IncidentLog(node_id="node3"))
    with nodectx.use(ctx):
        pipe.submit(...)        # every metric/incident lands in ctx's
                                # registries, tagged node_id=node3

The stack is deliberately PROCESS-global, not thread-local: the
scenario driver steps one node at a time on one thread, but a dispatch
inside that step may hop to the supervisor's watchdog worker — a
thread-local (or contextvar) stack would silently re-route those
records to the default registry, losing exactly the incidents the
chaos tier asserts on.  Concurrent multi-context use is therefore not
supported (and not needed: production wiring never installs a context;
the simulation's determinism contract is single-scheduler anyway).

This module sits at the bottom of the dependency graph on purpose: it
imports nothing from the package except the equally-bottom
``utils/locks.py`` primitive layer (stdlib-only at module scope), so
both resilience/ and sigpipe/ can consult it without cycles.
"""
from __future__ import annotations

from contextlib import contextmanager

from .locks import named_rlock


class Slot:
    """A mutable per-context cell for a value that may legitimately be
    None — an uninstalled `FaultPlan`, a disabled `Supervisor` or
    `DifferentialGuard`.  A `NodeContext` attribute that is a Slot (even
    one holding None) CLAIMS that stream: the StateRouter stops at the
    slot instead of falling through to the process-global default,
    which is exactly what keeps a globally injected fault plan from
    leaking into a SimNode that owns its own (empty) plan slot."""

    __slots__ = ("value",)

    def __init__(self, value=None):
        self.value = value

    def __repr__(self) -> str:
        return f"Slot({self.value!r})"


class NodeContext:
    """One simulated node's observability + resilience namespace.

    `metrics` / `incidents` are duck-typed (a `sigpipe.metrics.Metrics`
    and a `resilience.incidents.IncidentLog` in practice); either may be
    None to keep that stream on the process-global default.

    `supervisor` / `fault_plan` / `guard` are the resilience slots
    (each a :class:`Slot` or None): a node that owns them gets its own
    circuit-breaker table, injected fault schedule, and differential
    guard — a breaker trip or degraded window on this node leaves the
    rest of the fleet on the device path.  Leaving a slot at None keeps
    that singleton on the process-global default, exactly like
    metrics/incidents.

    `resident` marks a context that :func:`pin` installed as the
    PROCESS-OWNING base of the stack (a real node process serving one
    node for its whole lifetime).  A resident context is the opposite
    of a scenario SimNode's transient push: every thread in the process
    — conn readers, link workers, the async flush engine's workers —
    resolves to it by default, so cross-thread records attribute
    correctly without each thread pushing/popping, and the async
    engine's forced-inline rule does not apply (pipeline_async
    `overlap_live`).
    """

    __slots__ = ("node_id", "metrics", "incidents",
                 "supervisor", "fault_plan", "guard", "resident")

    def __init__(self, node_id: str, metrics=None, incidents=None,
                 supervisor=None, fault_plan=None, guard=None):
        self.node_id = str(node_id)
        self.metrics = metrics
        self.incidents = incidents
        self.supervisor = supervisor
        self.fault_plan = fault_plan
        self.guard = guard
        self.resident = False

    def __repr__(self) -> str:
        return f"NodeContext({self.node_id!r})"


_lock = named_rlock("nodectx.stack")
_stack: list = []


class Router:
    """The module-global delegation seam shared by `resilience.INCIDENTS`
    and `sigpipe.METRICS`: every attribute access consults the context
    stack and lands on the active context's `attr` registry when one is
    installed, else on the process-global default.  `from ... import
    NAME` binds the router object by value everywhere, so the routing
    must live *inside* it, not in the module name.  (Singletons that may
    be None — the supervisor/plan/guard — ride :class:`StateRouter`
    below instead.)"""

    def __init__(self, default, attr: str):
        self._default = default
        self._attr = attr

    @property
    def default(self):
        """The process-global registry, bypassing any installed context
        (the scenario driver reads this for fleet-wide series)."""
        return self._default

    def _target(self):
        ctx = current()
        if ctx is not None:
            registry = getattr(ctx, self._attr, None)
            if registry is not None:
                return registry
        return self._default

    def __getattr__(self, name):
        return getattr(self._target(), name)

    def __len__(self) -> int:            # len() bypasses __getattr__
        return len(self._target())


class StateRouter:
    """Router over an *optional singleton* — the resilience layer's
    `supervisor._ACTIVE` / `faults._ACTIVE` / `guard._ACTIVE` — where
    the routed value may legitimately be None (disabled / no plan
    installed), so the attribute-delegation `Router` above cannot
    carry it.  `get()`/`set()` land on the active context's
    :class:`Slot` when one is installed (a Slot holding None is an
    explicit "this node has no supervisor/plan/guard", NOT a
    fall-through), else on the process-global default cell — the same
    `.default` bypass contract as INCIDENTS/METRICS, so callers with
    no node context installed are byte-for-byte untouched."""

    def __init__(self, attr: str):
        self._attr = attr
        self._lock = named_rlock("nodectx.slot")
        self._global = None

    def _slot(self) -> Slot | None:
        ctx = current()
        if ctx is not None:
            return getattr(ctx, self._attr, None)
        return None

    def get(self):
        slot = self._slot()
        if slot is not None:
            return slot.value
        with self._lock:
            return self._global

    def set(self, value) -> None:
        slot = self._slot()
        if slot is not None:
            slot.value = value
            return
        with self._lock:
            self._global = value

    @property
    def default(self):
        """The process-global value, bypassing any installed context."""
        with self._lock:
            return self._global

    def set_default(self, value) -> None:
        """Write the process-global cell, bypassing any installed
        context (the scenario driver's restore path)."""
        with self._lock:
            self._global = value


def current() -> NodeContext | None:
    """The innermost installed context, or None (process-global mode)."""
    with _lock:
        return _stack[-1] if _stack else None


def pin(ctx: NodeContext) -> NodeContext:
    """Install `ctx` as the process-RESIDENT base context: it sits at
    the BOTTOM of the stack (transient `use()` pushes still shadow it)
    and stays installed until :func:`unpin`.  This is the real node
    process's wiring — one process, one node, every thread's records
    attributed to it — and what lifts the async flush engine's
    forced-inline rule (`pipeline_async.overlap_live`): with a single
    resident context there is no per-node push/pop to interleave.
    Reentrant-safe: pinning an already-pinned context is a no-op."""
    ctx.resident = True
    with _lock:
        if ctx not in _stack:
            _stack.insert(0, ctx)
    return ctx


def unpin(ctx: NodeContext) -> None:
    """Remove a pinned context (service shutdown / test teardown)."""
    ctx.resident = False
    with _lock:
        while ctx in _stack:
            _stack.remove(ctx)


@contextmanager
def use(ctx: NodeContext):
    """Install `ctx` for a lexical region.  Reentrant: the scenario
    driver wraps both the node step and the pipeline's own methods, so
    the same context may be pushed twice — inner pushes just shadow."""
    with _lock:
        _stack.append(ctx)
    try:
        yield ctx
    finally:
        with _lock:
            _stack.pop()
