"""Trusted-setup generation tooling (dev/test setups).

Counterpart of the reference's utils/kzg.py + scripts/gen_kzg_trusted_setups
(SURVEY.md §2.2): powers-of-secret monomial setups and the group FFT that
converts them to Lagrange form.  The conventional dev secret is 1337
(reference Makefile:263-270).
"""
from __future__ import annotations

from ..crypto.fields import R
from ..crypto import curve as cv

PRIMITIVE_ROOT_OF_UNITY = 7


def root_of_unity(order: int) -> int:
    assert (R - 1) % order == 0
    root = pow(PRIMITIVE_ROOT_OF_UNITY, (R - 1) // order, R)
    assert pow(root, order, R) == 1 and pow(root, order // 2, R) != 1
    return root


def group_fft(values: list, root: int) -> list:
    """Radix-2 FFT over group elements (scalars in the exponent)."""
    n = len(values)
    if n == 1:
        return list(values)
    even = group_fft(values[::2], root * root % R)
    odd = group_fft(values[1::2], root * root % R)
    out = [None] * n
    w = 1
    for i in range(n // 2):
        t = odd[i] * w
        out[i] = even[i] + t
        out[i + n // 2] = even[i] - t
        w = w * root % R
    return out


def monomial_to_lagrange(points: list) -> list:
    """[tau^i]G -> [L_i(tau)]G via inverse group FFT."""
    n = len(points)
    inv_root = pow(root_of_unity(n), R - 2, R)
    inv_n = pow(n, R - 2, R)
    return [p * inv_n for p in group_fft(points, inv_root)]


def generate_setup(width: int, secret: int = 1337) -> dict:
    """A complete dev trusted setup in the on-disk JSON shape."""
    g1 = cv.g1_generator()
    g2 = cv.g2_generator()
    g1_monomial = [g1 * pow(secret, i, R) for i in range(width)]
    g2_monomial = [g2 * pow(secret, i, R) for i in range(min(width, 65))]
    g1_lagrange = monomial_to_lagrange(g1_monomial)
    return {
        "g1_monomial": ["0x" + cv.g1_to_bytes(p).hex() for p in g1_monomial],
        "g1_lagrange": ["0x" + cv.g1_to_bytes(p).hex() for p in g1_lagrange],
        "g2_monomial": ["0x" + cv.g2_to_bytes(p).hex() for p in g2_monomial],
    }
