"""Named locks + the SPECLINT_TSAN runtime lock-order sanitizer.

Every lock in the concurrency-scoped packages is constructed through
:func:`named_lock` / :func:`named_rlock` / :func:`named_condition` with
its canonical name from ``resilience/sites.py CONCURRENCY`` — speclint's
lock-discipline pass fails on a bare ``threading.Lock()`` there, the
same way the seam pass fails on an unregistered dispatch site.  With
tracing off (the default) the constructors return the plain
``threading`` primitives: zero wrapping, zero overhead.

With ``SPECLINT_TSAN=1`` (the async/chaos suites — ``make chaos``,
``make pipeline-chaos``) they return traced wrappers that record, per
thread, which registered locks were held at every acquisition.  The
:class:`LockTracer` then fails the run when

* an observed acquisition order **contradicts the static graph** the
  lock-order speclint pass derived from the source (the static model
  says B-before-A somewhere, this thread just did A-then-B), or
* both orders of the same lock pair are **observed at runtime** (a
  real potential deadlock, whether or not the static pass saw either
  side), or
* an **unregistered lock name** participates (a named lock whose name
  the CONCURRENCY registry does not know).

This is the same keep-the-registry-honest wiring the differential
guard provides for the kernels: the static model is only trustworthy
while reality is checked against it.  Violations are recorded, not
raised — raising inside an arbitrary ``acquire()`` on a worker thread
would corrupt the very suites doing the observing — and asserted
empty by a session-teardown gate in tests/conftest.py.

Module-level imports are stdlib-only (``threading``/``os``), so
``utils/nodectx.py`` and the other bottom-of-the-graph modules can use
the constructors without import cycles; the registry and the static
graph load lazily, first time tracing actually needs them.
"""
from __future__ import annotations

import os
import threading


def tracing() -> bool:
    """Whether named locks are constructed traced: the SPECLINT_TSAN
    env var, or a `force_tracing` override (tests)."""
    if _FORCED is not None:
        return _FORCED
    return os.environ.get("SPECLINT_TSAN", "") not in ("", "0")


_FORCED: bool | None = None


def force_tracing(on: bool | None) -> None:
    """Override the environment for the current process (None = back to
    the env).  Only affects locks constructed AFTER the call."""
    global _FORCED
    _FORCED = on


class LockTracer:
    """Records per-thread lock-acquisition sequences and checks them
    against the static lock-order graph.

    `static_edges` is a set of (before, after) registered-name pairs —
    the sanctioned orders the speclint lock-order pass derived; its
    transitive closure is the order relation observed acquisitions must
    not contradict.  `registered` is the set of legal lock names.
    """

    def __init__(self, static_edges=None, registered=None):
        self._mu = threading.Lock()     # guards everything below
        self._held = threading.local()  # per-thread [(name, count), ...]
        self.observed: dict = {}        # (a, b) -> first-seen detail
        self.violations: list = []
        if static_edges is None or registered is None:
            derived_edges, derived_names = _repo_static_model()
            static_edges = derived_edges if static_edges is None \
                else static_edges
            registered = derived_names if registered is None \
                else registered
        self.registered = frozenset(registered)
        self._reach = _closure(static_edges)

    # -- bookkeeping ---------------------------------------------------
    def _stack(self) -> list:
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = self._held.stack = []
        return stack

    def _violate(self, kind: str, **detail) -> None:
        detail["kind"] = kind
        detail["thread"] = threading.current_thread().name
        self.violations.append(detail)

    def register_creation(self, name: str) -> None:
        if name not in self.registered:
            with self._mu:
                self._violate(
                    "unregistered-lock", lock=name,
                    hint="declare it in resilience/sites.py CONCURRENCY")

    def note_acquired(self, name: str) -> None:
        """Called with the lock just taken by this thread."""
        stack = self._stack()
        for held_name, count in stack:
            if held_name == name:       # reentrant re-acquire: no edge
                stack[stack.index((held_name, count))] = (name, count + 1)
                return
        held = [h for h, _ in stack]
        with self._mu:
            for h in held:
                edge = (h, name)
                if edge not in self.observed:
                    if name in self._reach.get(h, frozenset()) \
                            and h in self._reach.get(name, frozenset()):
                        pass    # statically cyclic pair: already a
                        #         lock-order finding, don't double-report
                    elif h in self._reach.get(name, frozenset()):
                        self._violate(
                            "order-contradiction", held=h, acquired=name,
                            static_order=f"{name} -> {h}")
                    elif (name, h) in self.observed:
                        self._violate(
                            "observed-reversal", held=h, acquired=name,
                            first_seen=self.observed[(name, h)])
                    self.observed[edge] = {
                        "thread": threading.current_thread().name,
                        "held": tuple(held)}
        stack.append((name, 1))

    def note_released(self, name: str) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] == name:
                held_name, count = stack[i]
                if count > 1:
                    stack[i] = (held_name, count - 1)
                else:
                    del stack[i]
                return

    def assert_clean(self) -> None:
        if self.violations:
            lines = "\n".join(f"  {v}" for v in self.violations)
            raise AssertionError(
                f"SPECLINT_TSAN: {len(self.violations)} lock-order "
                f"violation(s):\n{lines}")


def _closure(edges) -> dict:
    reach: dict = {}
    for a, b in edges:
        if a != b:
            reach.setdefault(a, set()).add(b)
    changed = True
    while changed:
        changed = False
        for a, outs in reach.items():
            add = set()
            for b in outs:
                add |= reach.get(b, set())
            add -= outs
            if add:
                outs |= add
                changed = True
    return {a: frozenset(outs) for a, outs in reach.items()}


def _repo_static_model():
    """(static edges, registered names) derived from this checkout:
    the lock-order pass's graph plus the CONCURRENCY registry.  Falls
    back to an empty graph when the analysis cannot run (installed
    without sources) — the tracer then still checks unregistered
    participation and observed reversals.

    CRITICAL: this runs while `_TRACER_MU` is held, from whatever
    module happened to construct the process's first traced lock — so
    it must never import a package module that constructs named locks
    (resilience/, sigpipe/, ...): the nested construction would
    re-enter `_tracer()` and self-deadlock the sanitizer.  The
    registry is therefore loaded STANDALONE by file path (the
    analysis/registry.py discipline), and analysis/ itself is
    stdlib-only."""
    from pathlib import Path
    root = Path(__file__).resolve().parents[2]
    try:
        from ..analysis import concurrency as _conc
        edges = _conc.static_lock_edges(root)
    except Exception:
        edges = frozenset()
    try:
        from ..analysis.registry import load_registry
        names = load_registry(root).lock_names()
    except Exception:
        names = ()
    return edges, names


class TracedLock:
    """A named, traced Lock/RLock: every acquire/release updates the
    tracer's per-thread held stack."""

    def __init__(self, name: str, kind: str = "lock", tracer=None):
        self.name = name
        self.kind = kind
        self._lock = threading.RLock() if kind == "rlock" \
            else threading.Lock()
        self._tracer = tracer if tracer is not None else _tracer()
        self._tracer.register_creation(name)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._tracer.note_acquired(self.name)
        return got

    def release(self) -> None:
        self._tracer.note_released(self.name)
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked() if hasattr(self._lock, "locked") \
            else False


class TracedCondition:
    """A named, traced Condition over an RLock.  `wait`/`wait_for`
    release the lock for the wait's duration and re-acquire after — the
    tracer's held stack mirrors that, so edges taken on re-acquire
    reflect what is really held across the wakeup."""

    def __init__(self, name: str, tracer=None):
        self.name = name
        self.kind = "condition"
        self._cond = threading.Condition()
        self._tracer = tracer if tracer is not None else _tracer()
        self._tracer.register_creation(name)

    def acquire(self, *args):
        got = self._cond.acquire(*args)
        if got:
            self._tracer.note_acquired(self.name)
        return got

    def release(self) -> None:
        self._tracer.note_released(self.name)
        self._cond.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def wait(self, timeout: float | None = None):
        self._tracer.note_released(self.name)
        try:
            return self._cond.wait(timeout)
        finally:
            self._tracer.note_acquired(self.name)

    def wait_for(self, predicate, timeout: float | None = None):
        self._tracer.note_released(self.name)
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            self._tracer.note_acquired(self.name)

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()


_TRACER: LockTracer | None = None
# RLock as defense in depth: tracer construction runs user-visible code
# (the static-model derivation above) under this mutex; a plain Lock
# would turn any accidental reentry into a silent process hang
_TRACER_MU = threading.RLock()


def _tracer() -> LockTracer:
    global _TRACER
    with _TRACER_MU:
        if _TRACER is None:
            _TRACER = LockTracer()
        return _TRACER


def tracer() -> LockTracer | None:
    """The process tracer, if any traced lock was ever constructed."""
    return _TRACER


def named_lock(name: str):
    """A mutex registered under `name` in sites.CONCURRENCY: a plain
    `threading.Lock` normally, a TracedLock under SPECLINT_TSAN=1."""
    if tracing():
        return TracedLock(name, "lock")
    return threading.Lock()


def named_rlock(name: str):
    if tracing():
        return TracedLock(name, "rlock")
    return threading.RLock()


def named_condition(name: str):
    if tracing():
        return TracedCondition(name)
    return threading.Condition()
