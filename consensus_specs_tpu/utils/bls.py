"""Pluggable BLS backend shim — the primary plug point of the framework.

Capability parity with the reference's eth2spec.utils.bls
(/root/reference/tests/core/pyspec/eth2spec/utils/bls.py:74-397): a
module-global backend switched at runtime, a `bls_active` flag that lets the
test harness stub signature checks, and the spec-facing API
(Sign/Verify/Aggregate/FastAggregateVerify/AggregateVerify/AggregatePKs/
SkToPk/KeyValidate) plus low-level curve ops used by KZG and Whisk.

Backends:
  * "native" — our from-scratch pure-Python BLS12-381 (crypto/bls12_381.py),
    the correctness oracle.
  * "tpu"    — JAX/Pallas batched verification kernels (ops/), falling back
    to native for single ops until each kernel lands.
"""
from __future__ import annotations

import functools

# global switches (reference: bls.py:74-124)
bls_active = True
_backend_name = "native"

STUB_SIGNATURE = b"\x11" * 96
STUB_PUBKEY = b"\x22" * 48
STUB_COORDINATES = (0, 0)


def use_backend(name: str) -> None:
    global _backend_name
    if name not in ("native", "tpu", "fastest"):
        raise ValueError(f"unknown bls backend {name!r}")
    if name == "fastest":
        name = "tpu"
    _backend_name = name


def use_native() -> None:
    use_backend("native")


def use_tpu() -> None:
    use_backend("tpu")


def current_backend() -> str:
    return _backend_name


def only_with_bls(alt_return=None):
    """Decorator: skip the wrapped function when bls is disabled
    (reference: bls.py:127-138)."""
    def decorator(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not bls_active:
                return alt_return
            return fn(*args, **kwargs)
        return wrapper
    return decorator


def _native():
    from ..crypto import bls12_381 as n
    return n


def _tpu():
    from ..ops import bls_tpu as t
    return t


def _dispatch(site, device_fn, fallback_fn):
    """Route one accelerator dispatch through the resilience seam
    (fault injection + circuit-breaker supervision when enabled; a plain
    call otherwise).  Lazy import: resilience pulls in sigpipe.metrics,
    and importing it at module scope would cycle through sigpipe ->
    scheduler -> this module."""
    from ..resilience.supervisor import dispatch
    return dispatch(site, device_fn, fallback_fn)


# --- signature API (reference: bls.py:141-221) -----------------------------

# Native scalar paths, shared between the default backend branch and the
# supervised fallback of every device dispatch (byte-identical semantics:
# backend import errors surface, DecodeError/ValueError reads as invalid).

class _Memo:
    """Bounded FIFO memo over PURE primitives (sign and the scalar
    verify oracles are functions of their byte inputs, nothing else).
    The test tier rebuilds identical blocks from the cached genesis
    state file after file, re-deriving byte-identical signatures and
    verdicts hundreds of times at ~100 ms a pairing; the memo sits
    BELOW the dispatch seam, so fault injection, supervision, and the
    differential guard still fire on every call."""

    _MISS = object()

    def __init__(self, cap: int = 1 << 14):
        self._store: dict = {}
        self._cap = cap

    def get(self, key, compute):
        hit = self._store.get(key, self._MISS)
        if hit is not self._MISS:
            return hit
        value = compute()
        if len(self._store) >= self._cap:
            self._store.pop(next(iter(self._store)))
        self._store[key] = value
        return value


_SIGN_MEMO = _Memo()
_VERIFY_MEMO = _Memo()


def _native_verify(PK, message, signature):
    key = ("v", bytes(PK), bytes(message), bytes(signature))

    def compute():
        try:
            return _native().Verify(key[1], key[2], key[3])
        except ValueError:
            return False
    return _VERIFY_MEMO.get(key, compute)


def _native_aggregate_verify(pubkeys, messages, signature):
    key = ("av", tuple(bytes(pk) for pk in pubkeys),
           tuple(bytes(m) for m in messages), bytes(signature))

    def compute():
        try:
            return _native().AggregateVerify(
                list(key[1]), list(key[2]), key[3])
        except ValueError:
            return False
    return _VERIFY_MEMO.get(key, compute)


def _native_fast_aggregate_verify(pubkeys, message, signature):
    key = ("fav", tuple(bytes(pk) for pk in pubkeys), bytes(message),
           bytes(signature))

    def compute():
        try:
            return _native().FastAggregateVerify(
                list(key[1]), key[2], key[3])
        except ValueError:
            return False
    return _VERIFY_MEMO.get(key, compute)


@only_with_bls(alt_return=True)
def Verify(PK, message, signature):
    if _backend_name == "tpu":
        return _dispatch(
            "bls.verify",
            lambda: _tpu().Verify(bytes(PK), bytes(message),
                                  bytes(signature)),
            lambda: _native_verify(PK, message, signature))
    return _native_verify(PK, message, signature)


@only_with_bls(alt_return=True)
def AggregateVerify(pubkeys, messages, signature):
    if _backend_name == "tpu":
        return _dispatch(
            "bls.aggregate_verify",
            lambda: _tpu().AggregateVerify(
                [bytes(pk) for pk in pubkeys],
                [bytes(m) for m in messages], bytes(signature)),
            lambda: _native_aggregate_verify(pubkeys, messages, signature))
    return _native_aggregate_verify(pubkeys, messages, signature)


@only_with_bls(alt_return=True)
def FastAggregateVerify(pubkeys, message, signature):
    if _backend_name == "tpu":
        return _dispatch(
            "bls.fast_aggregate_verify",
            lambda: _tpu().FastAggregateVerify(
                [bytes(pk) for pk in pubkeys], bytes(message),
                bytes(signature)),
            lambda: _native_fast_aggregate_verify(pubkeys, message,
                                                  signature))
    return _native_fast_aggregate_verify(pubkeys, message, signature)


# --- batched verification (TPU-native extension; one device dispatch for a
# block's worth of signature checks) ----------------------------------------

def _pk_bytes(pk):
    """Batch APIs accept compressed bytes or decompressed curve Points
    (the pubkey-cache shape); normalize for the byte-level native suite."""
    if hasattr(pk, "is_infinity"):
        return _native().G1_to_bytes48(pk)
    return bytes(pk)


def _sig_bytes(sig):
    if hasattr(sig, "is_infinity"):
        return _native().G2_to_bytes96(sig)
    return bytes(sig)


def _stub_or_dispatch(site, n_jobs, tpu_fn, native_fn):
    """Shared batch-API contract: with bls disabled every job reads as
    valid (the scalar APIs' stub-True semantics — one helper so the three
    batch entry points can't drift), the tpu backend runs all pairings as
    one batched kernel dispatch, and native falls back per-job.

    The batch boundary is a resilience dispatch seam on EVERY backend
    (it is where a whole block's verdicts ride one call), with the
    per-job native loop as the supervised fallback — so a fault-injection
    chaos run and a wedged device both degrade to the scalar oracle
    instead of deciding block validity."""
    if not bls_active:
        return [True] * n_jobs
    device_fn = tpu_fn if _backend_name == "tpu" else native_fn
    return _dispatch(site, device_fn, native_fn)


def FastAggregateVerifyBatch(pubkey_lists, messages, signatures):
    """Verdict list for many FastAggregateVerify jobs."""
    return _stub_or_dispatch(
        "bls.fast_aggregate_verify_batch",
        len(pubkey_lists),
        lambda: _tpu().fast_aggregate_verify_batch(
            pubkey_lists, messages, signatures),
        lambda: [_native_fast_aggregate_verify(
                     [_pk_bytes(pk) for pk in pks], m, _sig_bytes(s))
                 for pks, m, s in zip(pubkey_lists, messages, signatures)])


def VerifyBatch(pubkeys, messages, signatures):
    """Verdict list for many independent Verify jobs."""
    return _stub_or_dispatch(
        "bls.verify_batch",
        len(pubkeys),
        lambda: _tpu().verify_batch(pubkeys, messages, signatures),
        lambda: [_native_verify(_pk_bytes(pk), m, _sig_bytes(s))
                 for pk, m, s in zip(pubkeys, messages, signatures)])


def AggregateVerifyBatch(pubkey_lists, message_lists, signatures):
    """Verdict list for many AggregateVerify jobs (distinct message per
    pubkey within each job)."""
    return _stub_or_dispatch(
        "bls.aggregate_verify_batch",
        len(pubkey_lists),
        lambda: _tpu().aggregate_verify_batch(
            pubkey_lists, message_lists, signatures),
        lambda: [_native_aggregate_verify(
                     [_pk_bytes(pk) for pk in pks], ms, _sig_bytes(s))
                 for pks, ms, s in zip(pubkey_lists, message_lists,
                                       signatures)])


@only_with_bls(alt_return=STUB_SIGNATURE)
def Aggregate(signatures):
    return _native().Aggregate([bytes(s) for s in signatures])


@only_with_bls(alt_return=STUB_SIGNATURE)
def Sign(SK, message):
    key = (int(SK), bytes(message))
    return _SIGN_MEMO.get(key, lambda: _native().Sign(key[0], key[1]))


@only_with_bls(alt_return=STUB_PUBKEY)
def AggregatePKs(pubkeys):
    return _native().AggregatePKs([bytes(pk) for pk in pubkeys])


@only_with_bls(alt_return=STUB_PUBKEY)
def SkToPk(SK):
    return _native().SkToPk(int(SK))


def KeyValidate(pubkey) -> bool:
    return _native().KeyValidate(bytes(pubkey))


# --- low-level curve API for KZG/Whisk (reference: bls.py:224-392) ---------

def add(lhs, rhs):
    return _native().add(lhs, rhs)


def multiply(point, scalar):
    return _native().multiply(point, scalar)


def neg(point):
    return _native().neg(point)


# minimum batch size for device MSM dispatch: below this the per-call
# transfer + kernel launch (and a first-time XLA compile per shape) dwarfs
# the host Pippenger cost
MULTI_EXP_DEVICE_THRESHOLD = 128


def multi_exp(points, integers):
    """Multi-scalar multiplication over G1 or G2 points (the reference's
    arkworks multiexp slot, bls.py:224-296).  The tpu backend routes big
    G1/G2 batches through the device MSM kernel, supervised with the host
    Pippenger oracle as fallback."""
    if (_backend_name == "tpu"
            and len(points) >= MULTI_EXP_DEVICE_THRESHOLD):
        from ..crypto import curve as cv
        from ..ops import msm as device_msm
        first = points[0]
        if isinstance(first, cv.Point):
            if isinstance(first.x, cv.Fq1):
                return _dispatch(
                    "ops.msm.g1",
                    lambda: device_msm.g1_multi_exp(points, integers),
                    lambda: _native().multi_exp(points, integers))
            return _dispatch(
                "ops.msm.g2",
                lambda: device_msm.g2_multi_exp(points, integers),
                lambda: _native().multi_exp(points, integers))
    return _native().multi_exp(points, integers)


def pairing_check(values) -> bool:
    """Combined pairing-product check — the fused scheduler's single
    device dispatch rides this seam, so a hung or lying pairing kernel
    degrades to the host oracle instead of deciding block validity."""
    if _backend_name == "tpu":
        device_fn = lambda: _tpu().pairing_check_points(values)  # noqa: E731
    else:
        device_fn = lambda: _native().pairing_check(values)      # noqa: E731
    return _dispatch("bls.pairing_check", device_fn,
                     lambda: _native().pairing_check(values))


def G1_to_bytes48(point) -> bytes:
    return _native().G1_to_bytes48(point)


def bytes48_to_G1(b):
    return _native().bytes48_to_G1(bytes(b))


def G2_to_bytes96(point) -> bytes:
    return _native().G2_to_bytes96(point)


def bytes96_to_G2(b):
    return _native().bytes96_to_G2(bytes(b))


def Z1():
    return _native().Z1()


def Z2():
    return _native().Z2()


def G1():
    return _native().G1()


def G2():
    return _native().G2()
