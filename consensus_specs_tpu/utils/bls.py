"""Pluggable BLS backend shim — the primary plug point of the framework.

Capability parity with the reference's eth2spec.utils.bls
(/root/reference/tests/core/pyspec/eth2spec/utils/bls.py:74-397): a
module-global backend switched at runtime, a `bls_active` flag that lets the
test harness stub signature checks, and the spec-facing API
(Sign/Verify/Aggregate/FastAggregateVerify/AggregateVerify/AggregatePKs/
SkToPk/KeyValidate) plus low-level curve ops used by KZG and Whisk.

Backends:
  * "native" — our from-scratch pure-Python BLS12-381 (crypto/bls12_381.py),
    the correctness oracle.
  * "tpu"    — JAX/Pallas batched verification kernels (ops/), falling back
    to native for single ops until each kernel lands.
"""
from __future__ import annotations

import functools

# global switches (reference: bls.py:74-124)
bls_active = True
_backend_name = "native"

STUB_SIGNATURE = b"\x11" * 96
STUB_PUBKEY = b"\x22" * 48
STUB_COORDINATES = (0, 0)


def use_backend(name: str) -> None:
    global _backend_name
    if name not in ("native", "tpu", "fastest"):
        raise ValueError(f"unknown bls backend {name!r}")
    if name == "fastest":
        name = "tpu"
    _backend_name = name


def use_native() -> None:
    use_backend("native")


def use_tpu() -> None:
    use_backend("tpu")


def current_backend() -> str:
    return _backend_name


def only_with_bls(alt_return=None):
    """Decorator: skip the wrapped function when bls is disabled
    (reference: bls.py:127-138)."""
    def decorator(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not bls_active:
                return alt_return
            return fn(*args, **kwargs)
        return wrapper
    return decorator


def _native():
    from ..crypto import bls12_381 as n
    return n


def _tpu():
    from ..ops import bls_tpu as t
    return t


# --- signature API (reference: bls.py:141-221) -----------------------------

@only_with_bls(alt_return=True)
def Verify(PK, message, signature):
    if _backend_name == "tpu":
        return _tpu().Verify(bytes(PK), bytes(message), bytes(signature))
    n = _native()  # backend import errors must surface, not read as "invalid"
    try:
        return n.Verify(bytes(PK), bytes(message), bytes(signature))
    except ValueError:
        return False


@only_with_bls(alt_return=True)
def AggregateVerify(pubkeys, messages, signature):
    if _backend_name == "tpu":
        return _tpu().AggregateVerify(
            [bytes(pk) for pk in pubkeys],
            [bytes(m) for m in messages], bytes(signature))
    n = _native()
    try:
        return n.AggregateVerify(
            [bytes(pk) for pk in pubkeys],
            [bytes(m) for m in messages], bytes(signature))
    except ValueError:
        return False


@only_with_bls(alt_return=True)
def FastAggregateVerify(pubkeys, message, signature):
    if _backend_name == "tpu":
        return _tpu().FastAggregateVerify(
            [bytes(pk) for pk in pubkeys], bytes(message), bytes(signature))
    n = _native()
    try:
        return n.FastAggregateVerify(
            [bytes(pk) for pk in pubkeys], bytes(message), bytes(signature))
    except ValueError:
        return False


# --- batched verification (TPU-native extension; one device dispatch for a
# block's worth of signature checks) ----------------------------------------

def _pk_bytes(pk):
    """Batch APIs accept compressed bytes or decompressed curve Points
    (the pubkey-cache shape); normalize for the byte-level native suite."""
    if hasattr(pk, "is_infinity"):
        return _native().G1_to_bytes48(pk)
    return bytes(pk)


def _sig_bytes(sig):
    if hasattr(sig, "is_infinity"):
        return _native().G2_to_bytes96(sig)
    return bytes(sig)


def _stub_or_dispatch(n_jobs, tpu_fn, native_fn):
    """Shared batch-API contract: with bls disabled every job reads as
    valid (the scalar APIs' stub-True semantics — one helper so the three
    batch entry points can't drift), the tpu backend runs all pairings as
    one batched kernel dispatch, and native falls back per-job."""
    if not bls_active:
        return [True] * n_jobs
    if _backend_name == "tpu":
        return tpu_fn()
    return native_fn()


def FastAggregateVerifyBatch(pubkey_lists, messages, signatures):
    """Verdict list for many FastAggregateVerify jobs."""
    return _stub_or_dispatch(
        len(pubkey_lists),
        lambda: _tpu().fast_aggregate_verify_batch(
            pubkey_lists, messages, signatures),
        lambda: [FastAggregateVerify([_pk_bytes(pk) for pk in pks], m,
                                     _sig_bytes(s))
                 for pks, m, s in zip(pubkey_lists, messages, signatures)])


def VerifyBatch(pubkeys, messages, signatures):
    """Verdict list for many independent Verify jobs."""
    return _stub_or_dispatch(
        len(pubkeys),
        lambda: _tpu().verify_batch(pubkeys, messages, signatures),
        lambda: [Verify(_pk_bytes(pk), m, _sig_bytes(s))
                 for pk, m, s in zip(pubkeys, messages, signatures)])


def AggregateVerifyBatch(pubkey_lists, message_lists, signatures):
    """Verdict list for many AggregateVerify jobs (distinct message per
    pubkey within each job)."""
    return _stub_or_dispatch(
        len(pubkey_lists),
        lambda: _tpu().aggregate_verify_batch(
            pubkey_lists, message_lists, signatures),
        lambda: [AggregateVerify([_pk_bytes(pk) for pk in pks], ms,
                                 _sig_bytes(s))
                 for pks, ms, s in zip(pubkey_lists, message_lists,
                                       signatures)])


@only_with_bls(alt_return=STUB_SIGNATURE)
def Aggregate(signatures):
    return _native().Aggregate([bytes(s) for s in signatures])


@only_with_bls(alt_return=STUB_SIGNATURE)
def Sign(SK, message):
    return _native().Sign(int(SK), bytes(message))


@only_with_bls(alt_return=STUB_PUBKEY)
def AggregatePKs(pubkeys):
    return _native().AggregatePKs([bytes(pk) for pk in pubkeys])


@only_with_bls(alt_return=STUB_PUBKEY)
def SkToPk(SK):
    return _native().SkToPk(int(SK))


def KeyValidate(pubkey) -> bool:
    return _native().KeyValidate(bytes(pubkey))


# --- low-level curve API for KZG/Whisk (reference: bls.py:224-392) ---------

def add(lhs, rhs):
    return _native().add(lhs, rhs)


def multiply(point, scalar):
    return _native().multiply(point, scalar)


def neg(point):
    return _native().neg(point)


# minimum batch size for device MSM dispatch: below this the per-call
# transfer + kernel launch (and a first-time XLA compile per shape) dwarfs
# the host Pippenger cost
MULTI_EXP_DEVICE_THRESHOLD = 128


def multi_exp(points, integers):
    """Multi-scalar multiplication over G1 or G2 points (the reference's
    arkworks multiexp slot, bls.py:224-296).  The tpu backend routes big
    G1/G2 batches through the device MSM kernel."""
    if (_backend_name == "tpu"
            and len(points) >= MULTI_EXP_DEVICE_THRESHOLD):
        from ..crypto import curve as cv
        from ..ops import msm as device_msm
        first = points[0]
        if isinstance(first, cv.Point):
            if isinstance(first.x, cv.Fq1):
                return device_msm.g1_multi_exp(points, integers)
            return device_msm.g2_multi_exp(points, integers)
    return _native().multi_exp(points, integers)


def pairing_check(values) -> bool:
    if _backend_name == "tpu":
        return _tpu().pairing_check_points(values)
    return _native().pairing_check(values)


def G1_to_bytes48(point) -> bytes:
    return _native().G1_to_bytes48(point)


def bytes48_to_G1(b):
    return _native().bytes48_to_G1(bytes(b))


def G2_to_bytes96(point) -> bytes:
    return _native().G2_to_bytes96(point)


def bytes96_to_G2(b):
    return _native().bytes96_to_G2(bytes(b))


def Z1():
    return _native().Z1()


def Z2():
    return _native().Z2()


def G1():
    return _native().G1()


def G2():
    return _native().G2()
