"""Injectable clocks for time-driven decision paths.

Every component whose *decisions* depend on time — the gossip
micro-batcher's flush deadline, the per-peer token buckets, the
resilience supervisor's breaker cooldown and retry backoff — takes a
clock object instead of calling `time.time()`/`time.monotonic()`
directly.  Production wiring uses `MONOTONIC` (the module singleton);
tests and the fault injector use `ManualClock` so a seeded schedule
replays *identically*: the same submits at the same manual timestamps
produce the same flushes, the same quota verdicts, and the same breaker
transitions, run after run.

The contract is two methods:

* ``now() -> float``   — monotonic seconds (origin arbitrary).
* ``sleep(seconds)``   — block (or, for ManualClock, advance) that long.

Timer *measurement* (metrics timers, bench timings) stays on
`time.perf_counter` — observability may be wall-clock; decisions must
not be.
"""
from __future__ import annotations

import time


class SystemClock:
    """Real monotonic time; `sleep` really sleeps."""

    def now(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            time.sleep(seconds)


class ManualClock:
    """Deterministic clock: time moves only when told to.

    `sleep` advances instead of blocking, so code written against the
    clock contract (backoff loops, deadline waits) runs instantly and
    reproducibly under test.
    """

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def now(self) -> float:
        return self._now

    def advance(self, seconds: float) -> float:
        assert seconds >= 0, "time cannot run backwards"
        self._now += float(seconds)
        return self._now

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            self.advance(seconds)


MONOTONIC = SystemClock()
