"""The consensus hash function (SHA-256), matching the reference surface
(/root/reference/tests/core/pyspec/eth2spec/utils/hash_function.py:8-9).
"""
import hashlib

from ..ssz.types import Bytes32


def hash(data: bytes) -> Bytes32:  # noqa: A001 - spec name
    return Bytes32(hashlib.sha256(data).digest())
