"""One simulated node: gossip pipeline + transactional store + its own
observability namespace.

A `SimNode` owns everything PR 1-6 built, instantiated per node:

* an `AdmissionPipeline` over its own fork-choice store, with its own
  injected clock, quotas, dedup cache and equivocation guard;
* a `txn.TxnManager` around its own write-ahead `Journal` — every
  handler the pipeline delivers commits atomically and is replayable;
* a `NodeContext` carrying a `Metrics(node_id=...)` registry and an
  `IncidentLog(node_id=..., clock=sim)` — every metric and incident
  from this node's steps lands in ITS books, which is what fleet-wide
  attribution asserts against;
* its OWN resilience namespace (supervisor / fault-plan / guard
  Slots): a breaker trip, injected fault schedule, or quarantine on
  this node is invisible to every other node — the per-node fault
  isolation the soak runner and the randomized generator's per-node
  schedules drive.

Durable vs volatile state is the crash model's contract:

    durable   — the WAL journal (disk in a real node) and the
                equivocation guard (the slashing-protection DB real
                validators persist separately from the store);
    volatile  — the store (recovered via `txn.recover()`), the
                pipeline (queues, dedup cache, quotas, batch window:
                in-flight messages die with the process and come back
                through the driver's sync replay).

Two crash severities exercise it:

    crash()   — power-cut fiction: the in-memory journal OBJECT
                survives (pre-durable behavior, still the default);
    kill()    — SIGKILL: requires `durable_dir` (a real
                `txn.DurableJournal`); the journal object dies too and
                `recover()` must reopen the on-disk segment directory,
                repair any torn tail, and replay from the snapshot
                anchor — the in-process twin of the subprocess drill
                in scripts/kill_drill.py.

Handler execution always runs inside `scope()` — node context +
`txn.use(manager)` — so a store mutation can neither escape the
transaction nor mis-attribute its incidents.
"""
from __future__ import annotations

from contextlib import contextmanager

from .. import txn
from ..gossip import AdmissionPipeline, GossipConfig
from ..gossip.dedup import EquivocationGuard
from ..resilience.incidents import IncidentLog
from ..resilience.supervisor import Supervisor, SupervisorConfig
from ..sigpipe.metrics import Metrics
from ..test_infra.fork_choice import get_genesis_forkchoice_store
from ..utils import nodectx


class SimNode:
    def __init__(self, node_id: int, spec, anchor_state, clock,
                 config: GossipConfig | None = None, transport=None,
                 snapshot_interval: int = 256,
                 durable_dir: str | None = None,
                 supervisor_config: SupervisorConfig | None = None,
                 journal_kwargs: dict | None = None):
        self.node_id = int(node_id)
        self.name = f"node{node_id}"
        self.spec = spec
        self.clock = clock
        self.anchor_state = anchor_state
        self.config = config or GossipConfig(
            # convergence scenarios want backpressure, not starvation:
            # quotas generous by default (the bench scenario overrides)
            bucket_capacity=1 << 14, refill_rate=1 << 12,
            queue_depth=1 << 12)
        # the node's OWN resilience namespace: its own breaker table
        # (supervisor Slot), its own fault-plan Slot (empty = no
        # faults for THIS node, never a fall-through to a globally
        # injected plan), and a guard Slot — a degraded window,
        # shard_dead, or breaker trip here leaves every other node on
        # the device path.  Like metrics/incidents, the slots survive
        # crash()/kill(): they are the driver's per-node books, not
        # in-process node state.
        self.ctx = nodectx.NodeContext(
            self.name, metrics=Metrics(node_id=self.name),
            incidents=IncidentLog(max_entries=1 << 14,
                                  node_id=self.name, clock=clock),
            supervisor=nodectx.Slot(Supervisor(
                supervisor_config or SupervisorConfig(clock=clock))),
            fault_plan=nodectx.Slot(None),
            guard=nodectx.Slot(None))
        # durable state
        self.durable_dir = durable_dir
        self.snapshot_interval = snapshot_interval
        # extra DurableJournal knobs (segment_bytes, fsync_policy): the
        # soak runner shrinks segments so rotation + compaction really
        # fire inside a round
        self.journal_kwargs = dict(journal_kwargs or {})
        if durable_dir is not None:
            self.journal = txn.DurableJournal(durable_dir,
                                              **self.journal_kwargs)
        else:
            self.journal = txn.Journal()
        self.manager = txn.TxnManager(self.journal,
                                      snapshot_interval=snapshot_interval)
        self.guard = EquivocationGuard()
        # volatile state
        self.transport = transport
        self.store = None
        self.pipe = None
        self.up = False
        # driver-side bookkeeping (observability, not node state)
        self.accepted: set = set()           # digests applied to store
        self.seq_digest: dict = {}           # live pipeline seq -> digest
        self.retry: list = []                # [(due_s, topic, payload, peer)]
        self.crashes = 0
        self.boot()

    # -- lifecycle -----------------------------------------------------
    def boot(self) -> None:
        assert not self.up
        if self.store is None:
            self.store = get_genesis_forkchoice_store(self.spec,
                                                      self.anchor_state)
        self.pipe = AdmissionPipeline(
            self.spec, self.store, self.config, self.clock,
            guard=self.guard, transport=self.transport, ctx=self.ctx)
        self.seq_digest = {}
        self.up = True

    def crash(self) -> None:
        """Power cut: volatile state gone; journal + guard survive."""
        assert self.up
        self.up = False
        self.crashes += 1
        self.store = None
        self.pipe = None
        self.seq_digest = {}
        self.retry = []

    def kill(self) -> None:
        """SIGKILL: volatile state AND the in-memory journal object are
        gone — only the on-disk segments (and the guard, modeled as a
        separate durable DB) survive.  `recover()` reopens the
        directory."""
        assert self.durable_dir is not None, \
            "kill() needs a durable journal (SimNode durable_dir)"
        self.crash()
        self.journal.close()
        self.journal = None
        self.manager = None

    def recover(self, now_time: int) -> None:
        """Rebuild the store from the journal (`txn.recover` verifies
        the snapshot root and replays the committed tail — the
        `recovered` incident lands in THIS node's log), tick forward to
        the present, and restart the pipeline around the durable
        guard.  After a `kill()` the journal is first reopened from its
        segment directory (torn-tail repair incidents land in this
        node's log too)."""
        assert not self.up and self.store is None
        if self.journal is None:            # killed: reopen from disk
            with nodectx.use(self.ctx):
                self.journal = txn.open_dir(self.durable_dir,
                                            **self.journal_kwargs)
            self.manager = txn.TxnManager(
                self.journal, snapshot_interval=self.snapshot_interval)
        with self.scope():
            self.store = txn.recover(self.spec, self.journal)
        self.boot()
        self.tick(now_time)

    @contextmanager
    def scope(self):
        with nodectx.use(self.ctx):
            with txn.use(self.manager):
                yield

    # -- the per-node resilience surface -------------------------------
    @property
    def supervisor(self) -> Supervisor:
        return self.ctx.supervisor.value

    def breaker_states(self) -> dict:
        return self.supervisor.breaker_states()

    def install_fault_plan(self, plan) -> None:
        """Arm `plan` for THIS node only (the driver's per-node
        degraded windows); None disarms."""
        self.ctx.fault_plan.value = plan

    # -- the driver-facing surface -------------------------------------
    def tick(self, time: int) -> None:
        if not self.up:
            return
        if int(self.store.time) >= int(time):
            return
        with self.scope():
            self.spec.on_tick(self.store, int(time))

    def submit(self, topic: str, payload, digest: bytes,
               peer: str) -> None:
        if not self.up:
            return
        with self.scope():
            seq = self.pipe.submit(topic, payload, peer=peer)
        self.seq_digest[seq] = (digest, topic, payload, peer)

    def poll(self) -> None:
        if not self.up:
            return
        with self.scope():
            self.pipe.poll()
        self._harvest()

    def drain(self) -> None:
        if not self.up:
            return
        with self.scope():
            self.pipe.drain()
        self._harvest()

    def _harvest(self) -> None:
        """Fold finalized pipeline verdicts into the accepted-digest
        set and the retry queue (a REJECTED message is usually a
        transient ordering artifact — a block before its parent, an
        attestation before its target — redelivered a little later,
        exactly like mesh redelivery)."""
        done = []
        for seq, (digest, topic, payload, peer) in \
                self.seq_digest.items():
            result = self.pipe.results.get(seq)
            if result is None or not result.final:
                continue
            done.append(seq)
            if result.status == "accepted":
                self.accepted.add(digest)
            elif result.status == "rejected":
                self.retry.append((self.clock.now() + 1.0, topic,
                                   payload, peer, digest))
        for seq in done:
            del self.seq_digest[seq]

    def pump_retries(self, now: float, max_attempts: int = 64) -> int:
        """Redeliver due rejected messages; bounded by list turnover
        (each redelivery re-enters _harvest if it fails again).  Due
        items past `max_attempts` stay queued for the next pump."""
        if not self.up or not self.retry:
            return 0
        due = [r for r in self.retry if r[0] <= now]
        self.retry = [r for r in self.retry if r[0] > now] \
            + due[max_attempts:]
        for _t, topic, payload, peer, digest in due[:max_attempts]:
            self.submit(topic, payload, digest, peer)
        return len(due[:max_attempts])

    # -- reporting -----------------------------------------------------
    def head_root(self) -> bytes:
        head = self.spec.get_head(self.store)
        return bytes(getattr(head, "root", head))

    def store_root(self) -> bytes:
        return txn.store_root(self.store)

    def leak_check(self) -> None:
        """No deadlock, no unbounded queue/peer/history state: called
        after the final drain."""
        assert self.up, f"{self.name} ended the scenario down"
        assert self.pipe.pending_count() == 0, \
            f"{self.name} still has queued messages"
        statuses = [r.status for r in self.pipe.results.values()]
        assert "queued" not in statuses, f"{self.name} stuck message"
        assert "deferred" not in statuses, \
            f"{self.name} starved a deferred message"
        cfg = self.config
        assert len(self.pipe.seen) <= cfg.seen_cache_size
        assert len(self.pipe.results) <= cfg.history_bound + \
            len(self.seq_digest) + 1
