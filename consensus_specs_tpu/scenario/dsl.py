"""Declarative scenario specs: the battlefield DSL.

A `Scenario` is pure data — validator population (implied by the
preset), node count, topology (link delay/jitter/drop + partitions),
traffic mix (solo attestations, aggregates, sync messages, blocks, an
ingress multiplier for mesh redundancy), and a timeline of injected
events on the driver's ManualClock.  `(scenario, seed)` fully
determines a run: the driver derives every random decision (jitter,
drops, adversarial validator picks) from one seeded RNG, so two runs
replay bit-identically — the determinism pin the test tier asserts.

Time is measured in SLOTS (floats allowed): `at_slot=3.5` is halfway
through slot 3.  Events are constructed with the helpers below, e.g.:

    Scenario(
        name="battlefield3", nodes=3, slots=8,
        events=(
            partition(2.0, ((0, 1), (2,))),
            equivocation_storm(3.2, origin=0, validators=2),
            crash(4.1, node=1),
            heal(5.0),
            recover(6.1, node=1),
        ))

DETERMINISM DISCIPLINE (what makes byte-identical convergence a
theorem rather than luck — docs/scenario.md derives each point):

* every message carrying a given validator's sole vote originates at
  ONE node (its home, or the adversarial event's origin — event
  validators are picked from the origin's population), and the network
  delivers each origin's stream in publish order to every recipient
  (per-origin FIFO with stall/flush loss semantics, net.py) — so
  first-vote-wins guard decisions agree fleet-wide;
* validators burned by an adversarial event are muted from canonical
  solo traffic (their conflicting votes come from the event itself;
  they still ride committee aggregates) — a quarantine decision can
  therefore never race an honest vote across origins;
* blocks are published at the attesting-interval boundary, so
  `block_timeliness` is uniformly False at every node and the oracle
  (proposer-boost scenarios exist, they just assert head convergence
  instead of full store identity);
* partitions heal within the attestation staleness window (target
  epoch current-or-previous at flush time) — `validate()` rejects a
  scenario that cannot converge by construction.
"""
from __future__ import annotations

from dataclasses import dataclass, field

__all__ = [
    "LinkSpec", "Topology", "TrafficSpec", "Event", "Scenario",
    "partition", "heal", "equivocation_storm", "surround_attack",
    "long_range_fork", "crash", "kill", "recover", "degraded",
    "ADVERSARIAL_KINDS", "DEGRADED_FAULTS", "LIBRARY", "named",
    "randomized",
]

ADVERSARIAL_KINDS = frozenset({
    "partition", "equivocation_storm", "surround_attack",
    "long_range_fork", "crash", "kill", "degraded",
})


@dataclass(frozen=True)
class LinkSpec:
    delay_s: float = 0.25       # base one-way delay
    jitter_s: float = 0.25      # seeded uniform extra, per (msg, dest)
    drop_rate: float = 0.0      # seeded per-(msg, dest) stall odds


# graph shapes the process-mesh backend can wire (scenario/processes.py
# builds the peer sets); the in-process driver models direct delivery
# and treats every kind as full_mesh.  Partitions stay EVENTS.
TOPOLOGY_KINDS = frozenset({"full_mesh", "ring", "bridge", "star"})


@dataclass(frozen=True)
class Topology:
    kind: str = "full_mesh"     # one of TOPOLOGY_KINDS
    link: LinkSpec = field(default_factory=LinkSpec)


@dataclass(frozen=True)
class TrafficSpec:
    attestation_fraction: float = 1.0   # of each committee, solo votes
    aggregates: bool = True             # one aggregate per committee
    sync_messages: int = 2              # sync-committee msgs per slot
    ingress_multiplier: int = 1         # mesh redundancy: duplicate
    #                                     copies per delivery (dedup
    #                                     sheds them; >1 models the
    #                                     10x-100x gossip fan-in)


@dataclass(frozen=True)
class Event:
    at_slot: float
    kind: str
    params: tuple = ()          # sorted (key, value) pairs

    def get(self, key, default=None):
        for k, v in self.params:
            if k == key:
                return v
        return default


def _event(at_slot: float, kind: str, **params) -> Event:
    return Event(float(at_slot), kind, tuple(sorted(params.items())))


def partition(at_slot: float, groups) -> Event:
    """Cut the mesh into `groups` (a tuple of node-id tuples; every
    node must appear exactly once).  Cross-group streams stall until
    the next heal."""
    return _event(at_slot, "partition",
                  groups=tuple(tuple(int(n) for n in g) for g in groups))


def heal(at_slot: float) -> Event:
    """Restore the full mesh and flush every partition-stalled stream;
    healed nodes run an anti-entropy catch-up (recorded as a
    `scenario.sync` incident in their own logs)."""
    return _event(at_slot, "heal")


def equivocation_storm(at_slot: float, origin: int,
                       validators: int = 2) -> Event:
    """`validators` origin-hosted validators each publish a double vote:
    their real head attestation immediately followed by a conflicting
    same-target vote for its parent."""
    return _event(at_slot, "equivocation_storm", origin=int(origin),
                  validators=int(validators))


def surround_attack(at_slot: float, origin: int) -> Event:
    """One origin-hosted validator publishes a verified epoch-1 vote,
    then a crafted older-target vote whose source claims epoch 1 — the
    recorded vote surrounds it (the second arm of
    is_slashable_attestation_data).  Needs at_slot in epoch >= 1."""
    return _event(at_slot, "surround_attack", origin=int(origin))


def long_range_fork(at_slot: float, origin: int, fork_slot: int,
                    length: int = 2) -> Event:
    """Publish a `length`-block fork built on the canonical block at
    `fork_slot` — each fork block is a second proposal for a slot that
    already has one, so the guard quarantines every fork-slot proposer
    post-acceptance (blocks are exempt from pre-delivery shed)."""
    return _event(at_slot, "long_range_fork", origin=int(origin),
                  fork_slot=int(fork_slot), length=int(length))


def crash(at_slot: float, node: int) -> Event:
    """Power-cut `node`: store, pipeline, queues and dedup state are
    lost; the WAL journal and the slashing-protection guard survive
    (they are the node's durable state)."""
    return _event(at_slot, "crash", node=int(node))


def kill(at_slot: float, node: int) -> Event:
    """SIGKILL `node`: unlike `crash` (a power cut whose in-process
    journal object survives by fiat), NOTHING in-process survives a
    kill — the journal object dies with the pipeline, and recovery must
    reopen the on-disk segment journal, repair any torn tail, and
    replay from the snapshot anchor.  Requires `Scenario.durable=True`
    (a non-durable node has nothing to recover from).  The
    slashing-protection guard is still modeled as durable (real
    validators persist it in a separate DB)."""
    return _event(at_slot, "kill", node=int(node))


def recover(at_slot: float, node: int) -> Event:
    """`txn.recover()` the node from its journal — reopened from its
    on-disk segment directory after a `kill` — rebuild the pipeline
    around the durable guard, tick forward, and catch up."""
    return _event(at_slot, "recover", node=int(node))


def join(at_slot: float, node: int) -> Event:
    """Dynamic membership: `node` joins the mesh at runtime.  A node
    whose FIRST membership event is a join starts the scenario ABSENT
    (never spawned); a join after a `leave` is a graceful rejoin over
    the same data dir.  The joiner builds links to its topology
    neighbours, the neighbours learn it through `J` frames, and a
    windowed anti-entropy pass catches it up to the fleet."""
    return _event(at_slot, "join", node=int(node))


def leave(at_slot: float, node: int) -> Event:
    """Dynamic membership: `node` departs GRACEFULLY — its neighbours
    drain and drop their links on `L` frames (no reconnect burn, the
    departure is attributed, not priced as a failure), then the node
    itself drains and exits 0.  Requires `Scenario.durable=True` so a
    later rejoin recovers the journal.  Abrupt departure is `kill` —
    that one rides the quarantine path."""
    return _event(at_slot, "leave", node=int(node))


DEGRADED_FAULTS = ("raise", "shard_dead")


def degraded(at_slot: float, until_slot: float,
             site: str = "gossip.batch_verify",
             node: int | None = None, fault: str = "raise") -> Event:
    """A breaker-open window: a persistent injected fault at `site`
    trips the breaker during the targeted node's dispatches; at
    `until_slot` the fault is lifted and the breaker reset.

    `node=None` degrades the whole fleet (every node gets its own
    seeded plan — breakers are per-node since the fault-isolation PR,
    so N breakers trip, one per book); `node=i` degrades ONLY node i:
    every other node stays on the device path, pinned by the isolation
    tests.  `fault` picks the injected kind: ``raise`` (a dead device
    runtime) or ``shard_dead`` (one seeded mesh member dies — same
    breaker contract, the incident records which shard).  Verdicts
    stay byte-identical throughout (that is the breaker's contract) —
    the window shows up in the targeted node's incidents and fallback
    metrics."""
    return _event(at_slot, "degraded", until_slot=float(until_slot),
                  site=site, node=None if node is None else int(node),
                  fault=fault)


@dataclass(frozen=True)
class Scenario:
    name: str
    nodes: int = 3
    slots: int = 8              # traffic length; the run ends at the
    #                             slot `slots + 1` boundary tick
    fork: str = "altair"
    preset: str = "minimal"
    topology: Topology = field(default_factory=Topology)
    traffic: TrafficSpec = field(default_factory=TrafficSpec)
    events: tuple = ()
    # durable=True gives every node an on-disk segment journal
    # (txn.DurableJournal in a per-run temp dir) — the prerequisite for
    # `kill` events, whose recovery reopens the journal from disk
    durable: bool = False
    # convergence contract: assert byte-identical txn.store_root against
    # the oracle (requires the determinism discipline above).  Scenarios
    # outside the envelope set this False and get head/checkpoint
    # assertions only.
    assert_store_identity: bool = True

    def sorted_events(self) -> tuple:
        return tuple(sorted(self.events, key=lambda e: e.at_slot))

    def validate(self) -> None:
        assert self.nodes >= 1 and self.slots >= 2
        assert self.topology.kind in TOPOLOGY_KINDS, \
            f"unknown topology kind {self.topology.kind!r}"
        down: set = set()
        partitioned = False
        degraded_windows: list = []     # (until_slot, target-or-None)
        # a node whose FIRST membership event is `join` starts absent
        first_membership: dict = {}
        for e in self.sorted_events():
            if e.kind in ("join", "leave"):
                node = e.get("node")
                assert isinstance(node, int) and 0 <= node < self.nodes, \
                    f"membership event targets unknown node: {e}"
                first_membership.setdefault(node, e.kind)
        absent = {n for n, k in first_membership.items() if k == "join"}
        for e in self.sorted_events():
            assert 0.0 <= e.at_slot, f"event before genesis: {e}"
            assert e.at_slot <= self.slots + 1, f"event after end: {e}"
            if e.kind == "partition":
                groups = e.get("groups")
                flat = sorted(n for g in groups for n in g)
                assert flat == sorted(set(range(self.nodes)) - absent), \
                    f"partition groups must cover every present node: {e}"
                partitioned = True
            elif e.kind == "heal":
                partitioned = False
            elif e.kind in ("crash", "kill"):
                node = e.get("node")
                assert 0 <= node < self.nodes and node not in down
                assert node not in absent, f"kill of an absent node: {e}"
                if e.kind == "kill":
                    assert self.durable, \
                        f"kill needs Scenario.durable=True (only the " \
                        f"on-disk journal survives a SIGKILL): {e}"
                down.add(node)
            elif e.kind == "recover":
                node = e.get("node")
                assert node in down, f"recover without crash: {e}"
                down.discard(node)
            elif e.kind == "join":
                node = e.get("node")
                assert node in absent, f"join of a present node: {e}"
                assert node not in down, f"join of a dead node: {e}"
                absent.discard(node)
            elif e.kind == "leave":
                node = e.get("node")
                assert node not in absent and node not in down, \
                    f"leave of an absent or dead node: {e}"
                assert self.durable, \
                    f"leave needs Scenario.durable=True (a rejoin " \
                    f"recovers the drained journal): {e}"
                absent.add(node)
            elif e.kind in ("equivocation_storm", "surround_attack",
                            "long_range_fork"):
                assert 0 <= e.get("origin") < self.nodes
            elif e.kind == "degraded":
                assert e.get("until_slot") > e.at_slot
                target = e.get("node")
                if target is not None:
                    assert 0 <= target < self.nodes, \
                        f"degraded window targets unknown node: {e}"
                assert e.get("fault", "raise") in DEGRADED_FAULTS, \
                    f"degraded window names unknown fault kind: {e}"
                # windows on DIFFERENT nodes may overlap freely (that
                # is the point of per-node isolation); two windows on
                # the same target — or any overlap with a fleet-wide
                # window — would have the second install clobber the
                # first plan and the first end clear the second
                for until, other in degraded_windows:
                    if e.at_slot < until and (target is None
                                              or other is None
                                              or target == other):
                        raise AssertionError(
                            f"overlapping degraded windows on the "
                            f"same target: {e}")
                # the driver injects a persistent fault at this site;
                # an unregistered name would inject at a seam that does
                # not exist and the window would silently test nothing
                from ..resilience import sites
                assert sites.is_registered(e.get("site")), \
                    f"degraded window names unregistered site: {e}"
                degraded_windows.append((e.get("until_slot"), target))
            else:
                raise AssertionError(f"unknown event kind {e.kind!r}")
        assert not down, f"nodes still crashed at scenario end: {down}"
        assert not partitioned, "partition never healed"
        assert not absent, \
            f"nodes still absent at scenario end (every member must " \
            f"rejoin before the convergence check): {absent}"

    def burned_validators_hint(self) -> bool:
        """Whether any event mutes validators from canonical traffic."""
        return any(e.kind in ("equivocation_storm", "surround_attack",
                              "long_range_fork") for e in self.events)


# ---------------------------------------------------------------------------
# the named library (scripts/run_scenario.py and the tests use these)
# ---------------------------------------------------------------------------

# speclint: disable=global-mutable-state -- static scenario registry,
# populated once at import by named() declarations below, read-only at
# run time; scenarios are frozen dataclasses shared safely by value
LIBRARY: dict = {}


def _lib(s: Scenario) -> Scenario:
    LIBRARY[s.name] = s
    return s


# the zero-event baseline: convergence of plain mainnet-shaped traffic
_lib(Scenario(name="smoke", nodes=3, slots=4))

# THE acceptance scenario: seeded partition + equivocation storm + one
# crash-and-recover node, all converging to the oracle head
_lib(Scenario(
    name="battlefield3", nodes=3, slots=8,
    events=(
        partition(2.0, ((0, 1), (2,))),
        equivocation_storm(3.2, origin=0, validators=2),
        crash(4.1, node=1),
        heal(5.0),
        recover(6.1, node=1),
    )))

# surround-vote attack needs two epochs of timeline (minimal preset:
# 8-slot epochs) — light traffic keeps it quick
_lib(Scenario(
    name="surround", nodes=2, slots=10,
    traffic=TrafficSpec(attestation_fraction=0.5, aggregates=False,
                        sync_messages=0),
    events=(surround_attack(9.2, origin=0),)))

# long-range fork: a late-published 2-block fork off slot 2
_lib(Scenario(
    name="longrange", nodes=3, slots=7,
    traffic=TrafficSpec(attestation_fraction=0.5, sync_messages=1),
    events=(long_range_fork(5.4, origin=2, fork_slot=2, length=2),)))

# breaker-open degraded window riding a partition
_lib(Scenario(
    name="degraded_window", nodes=3, slots=6,
    events=(
        degraded(1.5, 3.5),
        partition(2.0, ((0,), (1, 2))),
        heal(4.0),
    )))

# SIGKILL battlefield: durable on-disk journals, one node killed cold
# (in-memory journal object lost) and recovered by reopening its
# segment directory, with a partition riding alongside
_lib(Scenario(
    name="blackout3", nodes=3, slots=8, durable=True,
    events=(
        partition(2.0, ((0, 1), (2,))),
        kill(3.1, node=1),
        heal(4.0),
        recover(4.6, node=1),
    )))

# the bench scenario: 16 nodes at 10x ingress with a partition+heal
# burst in the middle (bench.py asserts convergence + bounded shed)
_lib(Scenario(
    name="mainnet_burst16", nodes=16, slots=6,
    traffic=TrafficSpec(ingress_multiplier=10),
    topology=Topology(link=LinkSpec(delay_s=0.2, jitter_s=0.3)),
    events=(
        partition(2.0, (tuple(range(12)), tuple(range(12, 16)))),
        # origin 1 hosts a slot-1 committee member under the 16-node
        # home mapping (origin 0 does not host one until slot 5, after
        # the cut) — the storm planner needs an established pre-cut vote
        equivocation_storm(2.6, origin=1, validators=4),
        heal(4.0),
    )))


def named(name: str) -> Scenario:
    try:
        return LIBRARY[name]
    except KeyError:
        raise KeyError(
            f"unknown scenario {name!r}; known: {sorted(LIBRARY)}")


def randomized(rng, nodes: int | None = None,
               durable: bool | None = None) -> Scenario:
    """A seeded random scenario inside the convergence envelope: random
    partition/heal pairs (healed within the staleness window), storms,
    crash-or-KILL/recover pairs, *per-node* fault schedules (fleet-wide
    or single-node degraded windows, plus shard_dead windows targeting
    one node while the rest of the fleet stays on the device path), and
    long-range forks.

    `durable` controls the SIGKILL model: True forces on-disk journals
    (the soak runner's setting, making kill draws legal), False never
    deals a kill, and None (the default) lets the draw decide — a dealt
    `kill` sets `Scenario.durable=True`, since `validate()` rejects a
    kill without a disk journal to recover from.  Drives the
    slow-marked scenario-matrix tier and the wall-clock soak runner —
    "as many scenarios as you can imagine" as a generator, not a
    hand-written list."""
    n = nodes if nodes is not None else rng.choice([3, 4, 5])
    slots = rng.choice([6, 7, 8])
    events: list = []
    # partitions start at slot >= 2 so at least block 1 is established
    # fleet-wide before the cut (the storm planner's envelope)
    t = 2.0 + rng.random()
    dealt_partition = False
    dealt_storm = False
    heal_at = 0.0
    if rng.random() < 0.8:      # partition + heal within an epoch
        dealt_partition = True
        ids = list(range(n))
        rng.shuffle(ids)
        cut = rng.randrange(1, n)
        events.append(partition(t, (tuple(ids[:cut]), tuple(ids[cut:]))))
        heal_at = max(min(t + 1.0 + 2.0 * rng.random(), slots - 1.0),
                      t + 0.5)
        events.append(heal(heal_at))
    if rng.random() < 0.8:
        dealt_storm = True
        # storm slot is int(at_slot) - 1 and needs an established
        # parent, so the window starts at slot 3
        events.append(equivocation_storm(
            3.0 + rng.random() * (slots - 4.0),
            origin=rng.randrange(n),
            validators=rng.choice([1, 2, 3])))
    victim = None
    if rng.random() < 0.6 and n > 2:
        victim = rng.randrange(1, n)
        at = 2.0 + rng.random() * (slots - 4.0)
        # SIGKILL model when the journal is (or may become) durable:
        # the in-memory journal object dies with the node and recovery
        # reopens the on-disk segment directory
        deal_kill = durable is not False and rng.random() < 0.4
        events.append((kill if deal_kill else crash)(at, node=victim))
        events.append(recover(
            min(at + 1.0 + rng.random() * 1.5, slots - 0.5),
            node=victim))
    # fault windows: one raise window (fleet-wide or single-node), and
    # maybe a shard_dead window pinned to one node.  A crashed victim
    # is never targeted, and when a partition was dealt the windows
    # ride strictly AFTER the heal: a down — or singleton-partitioned —
    # target sees only single-message windows, so the batch site never
    # dispatches and the window would leave no incident to attribute.
    windows: list = []          # (until, target) dealt so far
    healthy = [i for i in range(n) if i != victim]
    window_lo = max(1.0, heal_at)
    window_hi = max(window_lo + 0.2, slots - 2.0)

    def deal_window(target, fault):
        at = window_lo + rng.random() * (window_hi - window_lo)
        # dodge a conflicting earlier window (same node, or a
        # fleet-wide one): start strictly after it ends
        for until0, target0 in windows:
            if at < until0 and (target0 is None or target0 == target
                                or target is None):
                at = until0 + 0.1
        until = min(at + 1.0 + rng.random(), slots + 0.9)
        if until - at >= 0.5:
            events.append(degraded(at, until, node=target, fault=fault))
            windows.append((until, target))

    if rng.random() < 0.4:
        target = rng.choice(healthy) if victim is not None \
            else rng.choice([None] + healthy)
        deal_window(target, "raise")
    if rng.random() < 0.4:
        deal_window(rng.choice(healthy), "shard_dead")
    if rng.random() < 0.4 and slots >= 6:
        events.append(long_range_fork(
            slots - 1.5 + rng.random(), origin=rng.randrange(n),
            fork_slot=rng.choice([1, 2]), length=rng.choice([1, 2])))
    # the envelope's drop rule (mainnet_burst16 precedent): a drop
    # stall straddling partition onset is upgraded to partition
    # severity, so a pre-cut block can arrive only at heal — and a
    # storm's conflicting same-epoch vote would then win the
    # first-vote-wins LMD race at partitioned nodes while the oracle
    # saw the canonical vote first.  Storm + partition scenarios
    # therefore run lossless links; either alone keeps random drops.
    drop = 0.0 if (dealt_partition and dealt_storm) \
        else rng.choice([0.0, 0.05, 0.15])
    scenario = Scenario(
        name=f"random_{n}n_{slots}s", nodes=n, slots=slots,
        durable=bool(durable) or any(e.kind == "kill" for e in events),
        traffic=TrafficSpec(
            attestation_fraction=rng.choice([0.5, 1.0]),
            aggregates=rng.random() < 0.8,
            sync_messages=rng.choice([0, 1, 2]),
            ingress_multiplier=rng.choice([1, 2, 3])),
        topology=Topology(link=LinkSpec(
            delay_s=0.1 + 0.3 * rng.random(),
            jitter_s=0.3 * rng.random(),
            drop_rate=drop)),
        events=tuple(events))
    scenario.validate()
    return scenario
