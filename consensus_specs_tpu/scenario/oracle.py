"""The omniscient sequential oracle + the convergence/attribution
contract.

The oracle is a SimNode stripped of the network: one scalar-only
admission pipeline (same admission semantics — dedup, equivocation
guard, quotas so generous they never bind — no micro-batching, no
transactions) consuming the ENTIRE canonical feed in publish order at
publish time.  It is what a node with a perfect network would compute.

The contract the driver's report is asserted against:

* convergence — after heal + sync, every node's head and finalized
  checkpoint equal the oracle's, and (for scenarios inside the
  determinism envelope, dsl.py) `txn.store_root(node.store)` is
  byte-identical to the oracle's store root;
* attribution — every adversarial event left a fingerprint in some
  node's OWN incident log: storm/surround/fork -> a
  `gossip.equivocation` quarantine naming each burned validator;
  crash -> that node's `txn.recover` `recovered` incident;
  partition -> a `scenario.sync` catch-up incident on a healed node;
  degraded -> an injected-fault or breaker incident at the window's
  site in some node's log;
* liveness — no node deadlocked or leaked unbounded state
  (SimNode.leak_check, called by the driver before reporting).
"""
from __future__ import annotations

from ..gossip import AdmissionPipeline, GossipConfig
from ..gossip.dedup import EquivocationGuard
from ..resilience.incidents import IncidentLog
from ..sigpipe.metrics import Metrics
from ..test_infra.fork_choice import get_genesis_forkchoice_store
from ..utils import nodectx
from .. import txn

ORACLE_CONFIG = GossipConfig(
    queue_depth=1 << 16, bucket_capacity=float(1 << 30),
    refill_rate=float(1 << 30), max_peers=1 << 12,
    seen_cache_size=1 << 18, history_bound=1 << 18,
    scalar_only=True)


class Oracle:
    """The sequential reference consumer of the canonical feed."""

    def __init__(self, spec, plan, clock):
        self.spec = spec
        self.clock = clock
        self.ctx = nodectx.NodeContext(
            "oracle", metrics=Metrics(node_id="oracle"),
            incidents=IncidentLog(max_entries=1 << 14,
                                  node_id="oracle", clock=clock))
        self.store = get_genesis_forkchoice_store(spec,
                                                  plan.genesis_state)
        self.guard = EquivocationGuard()
        self.pipe = AdmissionPipeline(spec, self.store, ORACLE_CONFIG,
                                      clock, guard=self.guard,
                                      ctx=self.ctx)
        self.accepted: set = set()
        self._seq_digest: dict = {}
        self.retry: list = []

    def deliver(self, topic, payload, digest: bytes,
                peer: str) -> None:
        with nodectx.use(self.ctx):
            seq = self.pipe.submit(topic, payload, peer=peer)
        self._seq_digest[seq] = (digest, topic, payload, peer)

    def tick(self, time: int) -> None:
        if int(self.store.time) < int(time):
            with nodectx.use(self.ctx):
                self.spec.on_tick(self.store, int(time))

    def poll(self) -> None:
        with nodectx.use(self.ctx):
            self.pipe.poll()
        self._harvest()

    def drain(self) -> None:
        with nodectx.use(self.ctx):
            self.pipe.drain()
        self._harvest()

    def pump_retries(self, now: float) -> None:
        """The oracle consumes in publish order, so retries only cover
        same-instant ordering artifacts; normally empty."""
        due = [r for r in self.retry if r[0] <= now]
        self.retry = [r for r in self.retry if r[0] > now]
        for _t, digest, topic, payload, peer in due:
            self.deliver(topic, payload, digest, peer)

    def _harvest(self) -> None:
        done = []
        for seq, (digest, topic, payload, peer) in \
                self._seq_digest.items():
            result = self.pipe.results.get(seq)
            if result is None or not result.final:
                continue
            done.append(seq)
            if result.status == "accepted":
                self.accepted.add(digest)
            elif result.status == "rejected":
                self.retry.append((self.clock.now() + 1.0, digest,
                                   topic, payload, peer))
        for seq in done:
            del self._seq_digest[seq]

    def head_root(self) -> bytes:
        head = self.spec.get_head(self.store)
        return bytes(getattr(head, "root", head))

    def summary(self) -> dict:
        checkpoint = self.store.finalized_checkpoint
        return {
            "node_id": "oracle",
            "store_root": txn.store_root(self.store).hex(),
            "head": self.head_root().hex(),
            "finalized": (int(checkpoint.epoch),
                          bytes(checkpoint.root).hex()),
            "accepted": len(self.accepted),
            "metrics": self.ctx.metrics.snapshot(),
            "incidents": self.ctx.incidents.snapshot(),
        }


def node_summary(node) -> dict:
    checkpoint = node.store.finalized_checkpoint
    journal = node.journal
    return {
        "node_id": node.name,
        "store_root": node.store_root().hex(),
        "head": node.head_root().hex(),
        "finalized": (int(checkpoint.epoch),
                      bytes(checkpoint.root).hex()),
        "accepted": len(node.accepted),
        "crashes": node.crashes,
        "quarantined": sorted(node.guard.quarantined),
        # per-node resilience + journal books (the soak runner's
        # bounded-memory/bounded-disk and fault-accounting signals;
        # deliberately NOT part of the fingerprint projection)
        "breakers": node.breaker_states(),
        "journal_entries": len(journal) if journal is not None else 0,
        "journal_disk_bytes": journal.disk_bytes()
        if hasattr(journal, "disk_bytes") else 0,
        "journal_segments": len(journal.segment_indices())
        if hasattr(journal, "segment_indices") else 0,
        "metrics": node.ctx.metrics.snapshot(),
        "incidents": node.ctx.incidents.snapshot(),
    }


# ---------------------------------------------------------------------------
# assertions
# ---------------------------------------------------------------------------

def assert_converged(report) -> None:
    """Every node reached the oracle: heads and finalized checkpoints
    always; byte-identical store roots when the scenario is inside the
    determinism envelope."""
    oracle = report.oracle
    for node in report.nodes:
        assert node["head"] == oracle["head"], \
            f"{node['node_id']} head {node['head'][:12]}.. != " \
            f"oracle {oracle['head'][:12]}.."
        assert node["finalized"] == oracle["finalized"], \
            f"{node['node_id']} finalized diverged"
        if report.scenario.assert_store_identity:
            assert node["store_root"] == oracle["store_root"], \
                f"{node['node_id']} store_root diverged from oracle"


def _quarantine_incidents(nodes) -> list:
    out = []
    for node in nodes:
        for e in node["incidents"]:
            if e["site"] == "gossip.equivocation" \
                    and e["event"] == "quarantine":
                out.append(e)
    return out


def attribution_report(plan, summaries) -> dict:
    """For every adversarial event: which node-tagged incidents pin it
    (`summaries` are `node_summary` dicts).  Keys are `kind@at_slot`;
    every entry must end up `attributed`."""
    quarantines = _quarantine_incidents(summaries)
    report = {}
    for event in plan.scenario.sorted_events():
        key = f"{event.kind}@{event.at_slot}"
        entry = {"attributed": False, "incidents": []}
        if event.kind in ("equivocation_storm", "surround_attack",
                          "long_range_fork"):
            expected = set(plan.expected[event]["validators"])
            hits = [q for q in quarantines
                    if q.get("validator_index") in expected]
            entry["incidents"] = hits
            entry["attributed"] = \
                {q["validator_index"] for q in hits} == expected
        elif event.kind in ("crash", "kill"):
            name = f"node{event.get('node')}"
            hits = [e for s in summaries if s["node_id"] == name
                    for e in s["incidents"]
                    if e["site"] == "txn.recover"
                    and e["event"] == "recovered"]
            entry["incidents"] = hits
            entry["attributed"] = bool(hits)
        elif event.kind == "partition":
            hits = [e for s in summaries for e in s["incidents"]
                    if e["site"] == "scenario.sync"
                    and e.get("replayed", 0) > 0]
            entry["incidents"] = hits
            entry["attributed"] = bool(hits)
        elif event.kind == "degraded":
            site = event.get("site")
            hits = [e for s in summaries for e in s["incidents"]
                    if e["site"] == site]
            entry["incidents"] = hits
            entry["attributed"] = bool(hits)
        elif event.kind in ("heal", "recover"):
            continue            # remedies, not attacks
        report[key] = entry
    return report


def assert_attributed(report) -> None:
    for key, entry in report.attribution.items():
        assert entry["attributed"], \
            f"adversarial event {key} left no node-tagged incident " \
            f"({len(entry['incidents'])} partial hits)"
