"""Seeded simulated network: per-origin FIFO streams with stall/flush
loss semantics.

The one invariant everything else leans on: **every recipient sees each
origin's messages in publish order**.  LMD-GHOST's `latest_messages` is
first-accepted-wins within an epoch and the equivocation guard is
first-verified-wins, so two nodes that see a conflicting vote pair in
different orders end up with different stores *forever*.  Because every
message carrying a given validator's sole vote originates at one node
(the DSL's home-mapping discipline), per-origin FIFO makes every guard
and latest-message decision identical fleet-wide — the core of the
oracle-convergence theorem (docs/scenario.md).

Loss therefore cannot reorder, only delay: a "dropped" message STALLS
its (origin, dest) stream — it and everything published behind it
queue head-of-line until the next flush point (slot boundary for drop
stalls, heal for partition stalls, recovery for crash stalls), then
deliver in order.  This models what gossipsub redundancy + req/resp
backfill achieve in a real network: messages are late, rarely truly
lost, and a resynced peer replays gaps in order.

Mechanics:

* `publish(time, origin, topic, payload)` assigns a global seq and
  fans the message out to every node (including the origin: a real
  node processes its own proposals) through per-(origin, dest)
  streams.  Primary delivery time = publish + delay + seeded jitter,
  clamped monotonically per stream (FIFO).
* `ingress_multiplier` extra copies are scheduled strictly AFTER the
  primary on each stream — mesh-redundancy duplicates can add load
  (dedup sheds them) but can never flip a first-arrival order.
* partitions stall whole cross-group streams; `heal()` marks them
  flushable.  `pump(now)` returns every (dest, message, peer) due for
  delivery, in (time, seq) order.

Everything is driven by one `random.Random` owned by the driver — no
wall clock, no global state, bit-identical replay from the seed.
"""
from __future__ import annotations

import heapq
from dataclasses import dataclass, field

# delivery epsilon: duplicate copies and FIFO clamps space successive
# deliveries by this much so ordering is strict and reproducible
EPS = 1e-6


@dataclass(order=True)
class _Delivery:
    time: float
    seq: int
    dup: int                    # 0 = primary copy
    dest: int = field(compare=False)
    message: "Publish" = field(compare=False)


@dataclass
class Publish:
    seq: int
    time: float                 # publish time (seconds, sim clock)
    origin: int
    topic: str
    payload: object
    tag: str = "traffic"        # traffic | storm | surround | fork ...

    @property
    def peer(self) -> str:
        return f"node{self.origin}"


class _Stream:
    """One (origin, dest) FIFO lane."""

    __slots__ = ("last_time", "stalled", "stall_kind")

    def __init__(self):
        self.last_time = 0.0    # monotonic delivery clamp
        self.stalled: list = []  # [(sched_time, Publish), ...] in order
        self.stall_kind: str | None = None   # drop|partition|crash


class SimNetwork:
    def __init__(self, nodes: int, link, rng,
                 ingress_multiplier: int = 1):
        self.n = int(nodes)
        self.link = link
        self.rng = rng
        self.multiplier = max(1, int(ingress_multiplier))
        self._heap: list = []
        self._streams = {(o, d): _Stream()
                         for o in range(self.n) for d in range(self.n)}
        self._group_of = {i: 0 for i in range(self.n)}   # partition id
        self._down: set = set()
        self._seq = 0
        self.published: list = []        # the canonical feed, in order
        self.dropped_stalls = 0

    # -- topology state ------------------------------------------------
    def partition(self, groups) -> None:
        for gid, group in enumerate(groups):
            for node in group:
                self._group_of[int(node)] = gid

    def heal(self) -> None:
        for node in self._group_of:
            self._group_of[node] = 0

    def connected(self, a: int, b: int) -> bool:
        return self._group_of[a] == self._group_of[b]

    def node_down(self, node: int, down: bool = True) -> None:
        if down:
            self._down.add(int(node))
        else:
            self._down.discard(int(node))

    # -- publish -------------------------------------------------------
    def publish(self, time: float, origin: int, topic: str, payload,
                tag: str = "traffic") -> Publish:
        self._seq += 1
        msg = Publish(self._seq, float(time), int(origin), topic,
                      payload, tag)
        self.published.append(msg)
        for dest in range(self.n):
            self._schedule(msg, dest)
        return msg

    def _schedule(self, msg: Publish, dest: int) -> None:
        stream = self._streams[(msg.origin, dest)]
        link = self.link
        if msg.origin == dest:
            delay = EPS                  # local publication
            dropped = False
        else:
            delay = (link.delay_s
                     + link.jitter_s * self.rng.random())
            dropped = (link.drop_rate > 0.0
                       and self.rng.random() < link.drop_rate)
        when = msg.time + delay
        blocked = (not self.connected(msg.origin, dest)
                   or dest in self._down)
        if stream.stalled or dropped or blocked:
            # head-of-line: once anything on the stream stalls, every
            # later message queues behind it — loss may delay, never
            # reorder
            if not stream.stalled:
                stream.stall_kind = ("drop" if dropped else
                                     "crash" if dest in self._down
                                     else "partition")
                if dropped:
                    self.dropped_stalls += 1
            stream.stalled.append((when, msg))
            return
        self._push(msg, dest, when)

    def _push(self, msg: Publish, dest: int, when: float) -> None:
        stream = self._streams[(msg.origin, dest)]
        when = max(when, stream.last_time + EPS)     # FIFO clamp
        stream.last_time = when
        heapq.heappush(self._heap, _Delivery(when, msg.seq, 0, dest,
                                             msg))
        for dup in range(1, self.multiplier):
            # redundant mesh copies: strictly after the primary
            extra = when + EPS * dup + 0.01 * self.rng.random()
            heapq.heappush(self._heap,
                           _Delivery(extra, msg.seq, dup, dest, msg))

    # -- stall release -------------------------------------------------
    def flush_stalls(self, now: float, kinds=("drop",)) -> int:
        """Release stalled streams whose blocking condition cleared:
        called with kinds=("drop",) each slot boundary (gossip
        redundancy re-covers plain losses fast), and with
        ("drop", "partition", "crash") at heal / recovery sync points.
        Streams still blocked (cross-partition, dest down) stay
        stalled.  Returns released message count."""
        released = 0
        for (origin, dest), stream in self._streams.items():
            if not stream.stalled or stream.stall_kind not in kinds:
                continue
            if not self.connected(origin, dest) or dest in self._down:
                continue
            # seq order, not arrival-at-stall order: an in-flight
            # message re-stalled at pump time may have been appended
            # after a younger direct-to-stall publish
            for _when, msg in sorted(stream.stalled,
                                     key=lambda p: p[1].seq):
                self._push(msg, dest, now + EPS)
                released += 1
            stream.stalled.clear()
            stream.stall_kind = None
        return released

    def stalled_count(self) -> int:
        return sum(len(s.stalled) for s in self._streams.values())

    # -- delivery ------------------------------------------------------
    def pump(self, now: float) -> list:
        """Every delivery due at or before `now`, in (time, seq, dup)
        order.  Deliveries to crashed nodes are silently re-stalled on
        their stream (the node is not listening; recovery sync replays
        the feed anyway)."""
        due = []
        while self._heap and self._heap[0].time <= now + 1e-12:
            d = heapq.heappop(self._heap)
            if d.dest in self._down:
                continue        # lost with the crash; sync repairs
            if not self.connected(d.message.origin, d.dest):
                # partitioned mid-flight: decided at delivery time
                stream = self._streams[(d.message.origin, d.dest)]
                if d.dup == 0:
                    stream.stalled.append((d.time, d.message))
                    stream.stall_kind = stream.stall_kind or "partition"
                continue
            due.append(d)
        return [(d.dest, d.message) for d in due]

    def idle(self) -> bool:
        return not self._heap and self.stalled_count() == 0
