"""Canonical chain + traffic feed + adversarial message crafting.

The feed is computed UP FRONT from the scenario and the seeded RNG:
one canonical chain (built once, independent of any node's behavior —
empty blocks, so justification stays at the anchor and a long-range
fork can never race a moving finality frontier), plus every message
any node will ever publish, each stamped with its publish time and its
ORIGIN node.  The driver feeds these through the simulated network;
the oracle consumes the same list in publish order.  Pre-computation
is what makes the run a pure function of `(scenario, seed)` — and what
gives the anti-entropy sync a canonical replay order.

Home mapping: validator `v` lives on node `v % nodes`; every message
carrying v's sole vote originates there — except adversarial events,
which pick their validators from the EVENT ORIGIN's population, so the
per-origin FIFO invariant (net.py) still covers every conflicting
pair.

Burned validators — those an adversarial event makes provably
slashable (storm equivocators, the surround voter, long-range-fork
proposers) — are muted from canonical SOLO traffic: their conflicting
votes come from the event itself, so a quarantine decision can never
race an honest same-validator vote published from another origin.
They still propose their canonical blocks (blocks are exempt from the
pre-delivery gate) and still ride committee aggregates (multi-signer
messages are never shed).  This mirrors reality: a slashed validator's
solo voice disappears from the network.
"""
from __future__ import annotations

from dataclasses import dataclass

from ..ssz import hash_tree_root, uint64
from ..test_infra.attestations import (
    build_attestation_data, get_valid_attestation, sign_attestation)
from ..test_infra.blocks import (
    build_empty_block_for_next_slot, state_transition_and_sign_block)
from ..test_infra.genesis import create_genesis_state, default_balances
from ..test_infra.keys import privkey_for_pubkey


@dataclass
class Planned:
    time_s: float
    origin: int
    topic: str
    payload: object
    tag: str


@dataclass
class EventAction:
    """A non-message control point on the timeline (partition, heal,
    crash, recover, degraded open/close)."""
    time_s: float
    kind: str
    params: dict


class TrafficPlan:
    """Everything the driver replays: canonical chain, message feed
    (publish order), control actions, burned validators, and the
    adversarial bookkeeping the attribution report checks against."""

    def __init__(self, spec, scenario, rng):
        self.spec = spec
        self.scenario = scenario
        self.seconds_per_slot = int(spec.config.SECONDS_PER_SLOT)
        self.attest_offset = (self.seconds_per_slot
                              // int(spec.INTERVALS_PER_SLOT))
        self.genesis_state = create_genesis_state(
            spec, default_balances(spec))
        self.genesis_time = int(self.genesis_state.genesis_time)
        # canonical chain: slot -> (root, signed_block, post_state)
        self.chain: dict = {}
        self.block_slots: dict = {}          # proposer bookkeeping
        self.messages: list = []
        self.actions: list = []
        self.burned: set = set()
        self.expected: dict = {}             # event -> attribution facts
        self._build(rng)

    # -- helpers -------------------------------------------------------
    def slot_time(self, slot: float) -> float:
        return float(slot) * self.seconds_per_slot

    def home(self, validator_index: int) -> int:
        return int(validator_index) % self.scenario.nodes

    def _committee_members(self, state, slot):
        spec = self.spec
        members = []
        count = int(spec.get_committee_count_per_slot(
            state, spec.compute_epoch_at_slot(uint64(slot))))
        for index in range(count):
            for v in spec.get_beacon_committee(state, uint64(slot),
                                               uint64(index)):
                members.append((int(v), index))
        return members

    def _solo_attestation(self, state, slot, index, validator,
                          beacon_block_root=None):
        return get_valid_attestation(
            self.spec, state, slot=uint64(slot), index=index,
            filter_participant_set=lambda s, v=validator: {v},
            signed=True, beacon_block_root=beacon_block_root)

    # -- the build -----------------------------------------------------
    def _build(self, rng) -> None:
        spec, scenario = self.spec, self.scenario
        state = self.genesis_state.copy()
        anchor_root = None   # slot 0 lives in the anchor store already

        # 1. canonical chain (deterministic, rng-free)
        for slot in range(1, scenario.slots + 1):
            block = build_empty_block_for_next_slot(spec, state)
            signed = state_transition_and_sign_block(spec, state, block)
            root = bytes(hash_tree_root(signed.message))
            self.chain[slot] = (root, signed, state.copy())
            self.block_slots[slot] = int(signed.message.proposer_index)

        # 2. adversarial events: crafted messages + control actions +
        #    the burned set (computed BEFORE canonical attestations so
        #    muting can apply)
        for event in scenario.sorted_events():
            self._plan_event(event, rng)

        # 3. canonical traffic
        traffic = scenario.traffic
        for slot in range(1, scenario.slots + 1):
            root, signed, post = self.chain[slot]
            proposer = self.block_slots[slot]
            # the block, published at the attesting-interval boundary
            # (untimely by construction: uniform block_timeliness —
            # see dsl.py's determinism discipline)
            self.messages.append(Planned(
                self.slot_time(slot) + self.attest_offset,
                self.home(proposer), "block", signed, "block"))
            # solo attestations for `slot`, published next slot (the
            # handler applies a vote only after its slot has passed)
            base = self.slot_time(slot + 1)
            for validator, index in self._committee_members(post, slot):
                if validator in self.burned:
                    continue
                if rng.random() >= traffic.attestation_fraction:
                    continue
                att = self._solo_attestation(post, slot, index,
                                             validator)
                self.messages.append(Planned(
                    base + 0.8 * rng.random(), self.home(validator),
                    "attestation", att, "attestation"))
            # one aggregate per committee (full participation),
            # published by its aggregator next slot
            if traffic.aggregates:
                count = int(spec.get_committee_count_per_slot(
                    post, spec.compute_epoch_at_slot(uint64(slot))))
                for index in range(count):
                    committee = [int(v) for v in spec.get_beacon_committee(
                        post, uint64(slot), uint64(index))]
                    agg = get_valid_attestation(
                        spec, post, slot=uint64(slot), index=index,
                        signed=True)
                    aggregator = committee[0]
                    sap = self._aggregate_and_proof(post, agg,
                                                    aggregator)
                    self.messages.append(Planned(
                        base + 0.4 + 0.4 * rng.random(),
                        self.home(aggregator), "aggregate", sap,
                        "aggregate"))
            # sync-committee messages for this slot's block
            for k in range(traffic.sync_messages):
                pubkey = bytes(post.current_sync_committee.pubkeys[
                    (slot + k) % len(post.current_sync_committee.pubkeys)])
                validator = next(
                    i for i, v in enumerate(post.validators)
                    if bytes(v.pubkey) == pubkey)
                msg = spec.get_sync_committee_message(
                    post, root, uint64(validator),
                    privkey_for_pubkey(pubkey))
                self.messages.append(Planned(
                    self.slot_time(slot) + self.attest_offset + 1.0
                    + 0.5 * rng.random(),
                    self.home(validator), "sync", msg, "sync"))

        self.messages.sort(key=lambda p: p.time_s)
        self.actions.sort(key=lambda a: a.time_s)

    def _aggregate_and_proof(self, state, attestation, aggregator):
        spec = self.spec
        privkey = privkey_for_pubkey(
            state.validators[int(aggregator)].pubkey)
        proof = spec.get_aggregate_and_proof(
            state, uint64(aggregator), attestation, privkey)
        signature = spec.get_aggregate_and_proof_signature(
            state, proof, privkey)
        return spec.SignedAggregateAndProof(message=proof,
                                            signature=signature)

    # -- adversarial events --------------------------------------------
    def _plan_event(self, event, rng) -> None:
        t = self.slot_time(event.at_slot)
        kind = event.kind
        if kind in ("partition", "heal", "crash", "kill", "recover",
                    "degraded", "join", "leave"):
            self.actions.append(EventAction(
                t, kind, {k: v for k, v in event.params}))
            if kind == "degraded":
                # the end action mirrors the window's target so the
                # driver disarms (and breaker-resets) exactly the
                # node(s) the open action armed
                self.actions.append(EventAction(
                    self.slot_time(event.get("until_slot")),
                    "degraded_end", {"site": event.get("site"),
                                     "node": event.get("node")}))
            return
        if kind == "equivocation_storm":
            self._plan_storm(event, t, rng)
        elif kind == "surround_attack":
            self._plan_surround(event, t)
        elif kind == "long_range_fork":
            self._plan_fork(event, t)
        else:                                # pragma: no cover
            raise AssertionError(f"unplanned event kind {kind!r}")

    def _partition_group_at(self, at_slot: float, node: int):
        """The partition group `node` sits in at `at_slot` (None when
        the mesh is whole) — the planner replays partition/heal
        events."""
        groups = None
        for e in self.scenario.sorted_events():
            if e.at_slot >= at_slot:
                break
            if e.kind == "partition":
                groups = e.get("groups")
            elif e.kind == "heal":
                groups = None
        if groups is None:
            return None
        for g in groups:
            if node in g:
                return frozenset(g)
        return None                          # pragma: no cover

    def _established_storm_slot(self, event) -> int:
        """The attestation slot for a storm: the latest slot whose head
        block is provably deliverable to every node the storm can reach
        BEFORE heal (the origin's partition group) and which has an
        origin-hosted committee member.  Convergence depends on this:
        if some reachable node cannot apply vote1 (missing block), it
        accepts vote2 first and its latest-message entry inverts
        against the fleet — the exact first-wins asymmetry the
        per-origin FIFO discipline exists to prevent."""
        origin = event.get("origin")
        group = self._partition_group_at(event.at_slot, origin)
        link = self.scenario.topology.link
        margin = link.delay_s + link.jitter_s + 0.1
        cut = None
        if group is not None:
            for e in self.scenario.sorted_events():
                if e.kind == "partition" and e.at_slot < event.at_slot:
                    cut = self.slot_time(e.at_slot)
        for slot in range(int(event.at_slot) - 1, 0, -1):
            _root, _signed, post = self.chain[slot]
            if not any(self.home(v) == origin for v, _idx in
                       self._committee_members(post, slot)):
                continue
            if group is not None:
                in_group = self.home(self.block_slots[slot]) in group
                if link.drop_rate > 0.0:
                    # a drop-stalled block stream only flushes at the
                    # NEXT slot boundary — if the cut lands first, the
                    # drop stall becomes a partition stall and the
                    # block is unestablished until heal
                    publish = self.slot_time(slot + 1)
                else:
                    publish = self.slot_time(slot) + self.attest_offset
                pre_cut = publish + margin < cut
                if not (in_group or pre_cut):
                    continue
            return slot
        raise AssertionError(
            f"no established storm slot for {event}: the partition "
            f"predates every block the origin's group could hold")

    def _plan_storm(self, event, t, rng) -> None:
        """Double votes: for each picked validator, the real head vote
        for the established storm slot immediately followed by a
        conflicting same-target vote for its parent — both valid on
        their own, provably slashable together."""
        origin = event.get("origin")
        slot = self._established_storm_slot(event)
        _root, _signed, post = self.chain[slot]
        hosted = [(v, idx) for v, idx in
                  self._committee_members(post, slot)
                  if self.home(v) == origin]
        picks = hosted[:event.get("validators")]
        parent_root = self.chain[slot - 1][0] if slot >= 2 else bytes(
            hash_tree_root(self.spec.BeaconBlock(
                state_root=hash_tree_root(self.genesis_state))))
        victims = []
        for offset, (validator, index) in enumerate(picks):
            vote1 = self._solo_attestation(post, slot, index, validator)
            vote2 = self._solo_attestation(post, slot, index, validator,
                                           beacon_block_root=parent_root)
            at = t + 0.02 * offset
            self.messages.append(Planned(at, origin, "attestation",
                                         vote1, "storm"))
            self.messages.append(Planned(at + 0.005, origin,
                                         "attestation", vote2, "storm"))
            victims.append(validator)
            self.burned.add(validator)
        self.expected[event] = {"validators": victims}

    def _plan_surround(self, event, t) -> None:
        """A verified (source 0, target 1) vote, then a crafted
        (source 1, target 0) vote at an epoch-0 slot: the recorded vote
        surrounds it — the second arm of is_slashable_attestation_data,
        caught by the guard's FFG history."""
        spec = self.spec
        origin = event.get("origin")
        epoch_slots = int(spec.SLOTS_PER_EPOCH)
        assert event.at_slot > epoch_slots + 1, \
            "surround needs an epoch-1 voting slot in the past"
        # v must sit in a committee at an epoch-1 slot that has passed,
        # and (like every validator) in exactly one epoch-0 committee
        pick = None
        for slot1 in range(epoch_slots, int(event.at_slot)):
            _r, _s, post1 = self.chain[slot1]
            for v, idx in self._committee_members(post1, slot1):
                if self.home(v) == origin:
                    pick = (v, idx, slot1, post1)
                    break
            if pick:
                break
        assert pick, "origin hosts no epoch-1 committee member yet"
        validator, index1, slot1, post1 = pick
        vote1 = self._solo_attestation(post1, slot1, index1, validator)
        # the validator's epoch-0 committee slot
        slot0 = index0 = None
        for s in range(1, epoch_slots):
            _r, _sg, post0 = self.chain[s]
            for v, idx in self._committee_members(post0, s):
                if v == validator:
                    slot0, index0, state0 = s, idx, post0
                    break
            if slot0 is not None:
                break
        assert slot0 is not None, "validator missing from epoch 0"
        vote2 = self._solo_attestation(state0, slot0, index0, validator)
        vote2.data.source = spec.Checkpoint(
            epoch=uint64(1), root=vote1.data.target.root)
        sign_attestation(spec, state0, vote2)     # re-sign the mutation
        self.messages.append(Planned(t, origin, "attestation", vote1,
                                     "surround"))
        self.messages.append(Planned(t + 0.005, origin, "attestation",
                                     vote2, "surround"))
        self.burned.add(validator)
        self.expected[event] = {"validators": [validator]}

    def _plan_fork(self, event, t) -> None:
        """A late-published fork off the canonical block at
        `fork_slot`: every fork block is a second proposal for an
        already-proposed slot (empty blocks leave the randao mix and
        balances identical, so the fork proposer IS the canonical
        proposer) — proposer equivocation the guard quarantines
        post-acceptance."""
        spec = self.spec
        origin = event.get("origin")
        fork_slot = event.get("fork_slot")
        length = event.get("length")
        assert fork_slot + length <= self.scenario.slots, \
            "fork must stay within proposed slots"
        state = self.chain[fork_slot][2].copy()
        # perturb the graffiti so the fork block differs from the
        # canonical one even at fork_slot + 1 (parent root already
        # differs from slot +2 on)
        proposers = []
        for slot in range(fork_slot + 1, fork_slot + 1 + length):
            block = build_empty_block_for_next_slot(spec, state)
            block.body.graffiti = b"\x66" * 32    # 'f' is for fork
            signed = state_transition_and_sign_block(spec, state, block)
            proposer = int(signed.message.proposer_index)
            assert proposer == self.block_slots[slot], \
                "fork proposer drifted from canonical (randao changed?)"
            proposers.append(proposer)
            self.burned.add(proposer)
            self.messages.append(Planned(
                t + 0.05 * (slot - fork_slot), origin, "block", signed,
                "fork"))
        self.expected[event] = {"validators": proposers}
