"""Real-process mesh backend for scenario drills.

The in-process scenario driver (driver.py) simulates N nodes inside
one interpreter; this backend runs the SAME scenario timelines against
N real ``scripts/run_node.py`` processes wired over their framed unix
sockets (mesh/service.py) into the scenario's TOPOLOGY — full mesh,
ring, star, or a bridge of two cliques (`topology_peers`).  The driver
here only feeds each message to its ORIGIN node and operates the
control plane — the mesh itself floods admitted gossip peer-to-peer
across however many hops the graph demands (dedup keeps cycles
loop-free, the TTL hop counter backstops), partitions are imposed with
PEERS frames (mesh link block/reset), kills are real SIGKILLs, and
recovery is a real respawn over the surviving segment journal.
Convergence is asserted against the same in-process scalar oracle the
socket drill uses (node/client.py), byte-for-byte on
``txn.store_root``.

Event support is deliberately the recovery-chaos subset: partition /
heal / kill / recover, plus DYNAMIC MEMBERSHIP — ``join`` spawns a
member mid-run (a node whose first membership event is a join was
never spawned at start; its neighbours learn it through `J` frames and
it catches up by windowed anti-entropy) and ``leave`` departs one
gracefully (neighbours drain + drop their links on `L` frames, then
the node drains and exits 0; a later join is a rejoin over the same
data dir).  Adversarial traffic events (storms, surround, long-range
forks) are crafted INTO the plan's message feed by traffic.py and need
no process-level control, but degraded windows and ``crash`` (a
power-cut fiction no real process can perform — SIGKILL is the honest
version) raise ``UnsupportedEvent``.

Determinism note: the mesh floods asynchronously, so mid-run state is
timing-dependent — the contract is the END state.  After the timeline
the driver re-offers every message to its origin (re-offers are
idempotent: duplicates shed, earlier rejects retry), ticks past the
end boundary, runs an anti-entropy pass on every node, and repeats to
a fixpoint that must equal the oracle root on EVERY node.
"""
from __future__ import annotations

import os
import random
import shutil
import signal
import tempfile
import time

from ..node.client import (
    NodeClient, oracle_root, spawn_node)
from ..specs import get_spec
from ..utils.clock import MONOTONIC
from .dsl import (
    LIBRARY, Scenario, Topology, heal, join, kill, leave, partition,
    recover)
from .traffic import TrafficPlan

__all__ = [
    "UnsupportedEvent", "ProcessMesh", "mesh_agenda", "topology_peers",
    "run_scenario_processes", "DRILL_CASES", "drill_case",
]

SUPPORTED_EVENTS = frozenset({"partition", "heal", "kill", "recover",
                              "join", "leave"})

# respawn/connect budget: a fresh run_node.py pays the JAX import
# (~15-30 s on a cold container) before it binds its socket
CONNECT_TIMEOUT_S = 120.0
DRAIN_TIMEOUT_S = 60.0


class UnsupportedEvent(Exception):
    """The scenario uses an event kind the process backend cannot
    impose on a real process (crash, degraded, ...)."""


def topology_peers(scenario: Scenario) -> list:
    """node index -> frozenset of neighbour indices (symmetric), from
    the scenario's topology kind.  The non-complete shapes are the
    multi-hop drills' substrate: a ring of N has diameter N//2, a star
    routes everything through its hub, and a bridge joins two cliques
    through one cut vertex whose death partitions the graph."""
    n = scenario.nodes
    kind = scenario.topology.kind
    peers: list = [set() for _ in range(n)]

    def connect(a: int, b: int) -> None:
        peers[a].add(b)
        peers[b].add(a)

    if kind == "full_mesh":
        for i in range(n):
            for j in range(i + 1, n):
                connect(i, j)
    elif kind == "ring":
        assert n >= 3, "a ring needs >= 3 nodes"
        for i in range(n):
            connect(i, (i + 1) % n)
    elif kind == "star":
        assert n >= 2, "a star needs >= 2 nodes"
        for i in range(1, n):
            connect(0, i)
    elif kind == "bridge":
        assert n >= 3, "a bridge needs >= 3 nodes"
        mid = n // 2                     # the cut vertex
        for clique in (list(range(0, mid + 1)),
                       list(range(mid, n))):
            for x in range(len(clique)):
                for y in range(x + 1, len(clique)):
                    connect(clique[x], clique[y])
    else:                                # pragma: no cover
        raise AssertionError(f"unknown topology kind {kind!r}")
    return [frozenset(p) for p in peers]


def mesh_agenda(plan: TrafficPlan) -> list:
    """Flatten a plan into the process-mesh timeline: a sorted list of
    ("tick", t) | ("msg", topic, payload, origin) | ("event", Event).
    Ticks fall on every integer-second boundary of the publish
    timeline (same boundaries as client.replay_sequence, so the oracle
    feed matches); at equal times a tick sorts before an event, and an
    event before the messages published inside that second."""
    entries = []        # (time, priority, insert-order, item)
    order = 0
    last_tick = None
    for planned in plan.messages:
        t = int(plan.genesis_time + int(planned.time_s))
        if last_tick is None or t > last_tick:
            entries.append((float(t), 0, order, ("tick", t)))
            order += 1
            last_tick = t
        entries.append((plan.genesis_time + float(planned.time_s), 2,
                        order, ("msg", planned.topic, planned.payload,
                                int(planned.origin))))
        order += 1
    end = int(plan.genesis_time + plan.slot_time(plan.scenario.slots + 1))
    if last_tick is None or end > last_tick:
        entries.append((float(end), 0, order, ("tick", end)))
        order += 1
    for event in plan.scenario.sorted_events():
        if event.kind not in SUPPORTED_EVENTS:
            raise UnsupportedEvent(
                f"process mesh cannot impose {event.kind!r} "
                f"(supported: {sorted(SUPPORTED_EVENTS)})")
        t = plan.genesis_time + plan.slot_time(event.at_slot)
        entries.append((float(t), 1, order, ("event", event)))
        order += 1
    entries.sort(key=lambda e: (e[0], e[1], e[2]))
    return [e[3] for e in entries]


class ProcessMesh:
    """N run_node.py processes wired into the scenario's topology,
    driven through one scenario timeline.  Use as a context manager —
    __exit__ reaps every process and removes the work directory even
    on failure."""

    def __init__(self, scenario: Scenario, seed: int = 0,
                 extra_args: dict | None = None,
                 base_dir: str | None = None, clock=MONOTONIC):
        scenario.validate()
        self.scenario = scenario
        self.seed = int(seed)
        self.clock = clock
        self.spec = get_spec(scenario.fork, scenario.preset)
        self.plan = TrafficPlan(self.spec, scenario,
                                random.Random(self.seed))
        self.extra_args = dict(extra_args or {})   # node index -> [argv]
        self.peers_of = topology_peers(scenario)
        self.workdir = tempfile.mkdtemp(prefix="mesh_", dir=base_dir)
        n = scenario.nodes
        self.sockets = [os.path.join(self.workdir, f"node{i}.sock")
                        for i in range(n)]
        self.dirs = [os.path.join(self.workdir, f"node{i}")
                     for i in range(n)]
        self.procs: list = [None] * n
        self.clients: list = [None] * n
        self.up = [False] * n
        # node index -> set of blocked peer ids (current partition view)
        self.blocked = [set() for _ in range(n)]
        # dynamic membership: a node whose FIRST membership event is a
        # join starts absent (never spawned); spawn args exclude
        # currently-absent peers — the join itself introduces the new
        # member to its neighbours via J frames
        first: dict = {}
        for e in scenario.sorted_events():
            if e.kind in ("join", "leave"):
                first.setdefault(e.get("node"), e.kind)
        self.absent = {i for i, k in first.items() if k == "join"}
        self.events_applied: list = []

    # -- lifecycle -----------------------------------------------------

    def __enter__(self) -> "ProcessMesh":
        self.start()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.teardown(force=exc_type is not None)

    def _spawn_args(self, i: int) -> list:
        args = ["--node-id", f"node{i}"]
        for j in sorted(self.peers_of[i]):
            if j not in self.absent:
                args += ["--peer", f"node{j}={self.sockets[j]}"]
        args += [str(a) for a in self.extra_args.get(i, ())]
        return args

    def _spawn(self, i: int) -> None:
        self.procs[i] = spawn_node(self.sockets[i], self.dirs[i],
                                   *self._spawn_args(i))

    def _connect(self, i: int) -> None:
        self.clients[i] = NodeClient(
            self.sockets[i], connect_timeout_s=CONNECT_TIMEOUT_S)
        self.up[i] = True

    def start(self) -> None:
        for i in range(self.scenario.nodes):
            if i not in self.absent:
                self._spawn(i)
        for i in range(self.scenario.nodes):
            if i not in self.absent:
                self._connect(i)

    def up_nodes(self) -> list:
        return [i for i in range(self.scenario.nodes) if self.up[i]]

    # -- the timeline --------------------------------------------------

    def run(self) -> None:
        for item in mesh_agenda(self.plan):
            if item[0] == "tick":
                for i in self.up_nodes():
                    self.clients[i].send_tick(item[1])
                    self.clients[i].drain_responses()
            elif item[0] == "msg":
                _, topic, payload, origin = item
                if self.up[origin]:
                    self.clients[origin].send_message(
                        topic, payload, peer=f"origin{origin}")
                    self.clients[origin].drain_responses()
            else:
                self._apply_event(item[1])

    def _apply_event(self, event) -> None:
        self.events_applied.append((event.kind, dict(event.params)))
        if event.kind == "partition":
            groups = event.get("groups")
            group_of = {n: set(g) for g in groups for n in g}
            for i in range(self.scenario.nodes):
                # absent members are outside every group (validate
                # lets the partition omit them); their view is rebuilt
                # when they join
                if i not in group_of:
                    self.blocked[i] = set()
                    continue
                self.blocked[i] = {f"node{j}"
                                   for j in range(self.scenario.nodes)
                                   if j != i and j not in group_of[i]}
            self._push_partition_view(self.up_nodes())
        elif event.kind == "heal":
            for s in self.blocked:
                s.clear()
            self._push_partition_view(self.up_nodes())
            # reset() fires the links' on_heal auto-sync on each pump;
            # an explicit pass here makes catch-up a synchronous fact
            # before the timeline continues
            for i in self.up_nodes():
                self.clients[i].sync()
        elif event.kind == "kill":
            node = event.get("node")
            # settle the victim first: ROOT drains its pipeline, so the
            # pre-kill state is committed to the journal and recovery is
            # a deterministic fact to assert (mid-WRITE kills are
            # node_drill.py's job — this drill kills the mesh member)
            self.clients[node].root()
            os.kill(self.procs[node].pid, signal.SIGKILL)
            self.procs[node].wait()
            self.clients[node].close()
            self.clients[node] = None
            self.up[node] = False
        elif event.kind == "recover":
            node = event.get("node")
            self._spawn(node)           # same --dir: txn.open_dir +
            self._connect(node)         # recover repair the journal
            # refresh EVERY node's partition view: links the survivors
            # quarantined while the peer was dead reset here, and the
            # restarted node learns any still-open partition
            self._push_partition_view(self.up_nodes())
            self.clients[node].sync()
        elif event.kind == "join":
            node = event.get("node")
            self.absent.discard(node)   # spawn args see current view
            self._spawn(node)
            self._connect(node)
            # the joiner dialed its neighbours from spawn args; the
            # neighbours learn the new member through J frames
            for j in sorted(self.peers_of[node]):
                if self.up[j]:
                    self.clients[j].join(f"node{node}",
                                         self.sockets[node])
            self._push_partition_view(self.up_nodes())
            # windowed anti-entropy catch-up: the joiner's watermark
            # is 0, so one pass pulls exactly what the fleet admitted
            self.clients[node].sync()
        elif event.kind == "leave":
            node = event.get("node")
            # neighbours drain + drop their links FIRST: departure is
            # attributed (`peer_left`), never priced as a failure
            for j in sorted(self.peers_of[node]):
                if self.up[j]:
                    self.clients[j].leave(f"node{node}")
            # then the member itself drains gracefully: ROOT settles
            # the pipeline, DRAIN stops accepts, flushes and exits 0
            self.clients[node].root()
            try:
                self.clients[node].drain()
            except (OSError, ConnectionError, AssertionError):
                pass
            self.clients[node].close()
            self.clients[node] = None
            proc = self.procs[node]
            proc.wait(timeout=DRAIN_TIMEOUT_S)
            if proc.stdout is not None:
                proc.stdout.close()
            if proc.stderr is not None:
                proc.stderr.close()
            self.procs[node] = None
            self.up[node] = False
            self.absent.add(node)

    def _push_partition_view(self, nodes, settle_s: float = 30.0) -> None:
        """Install the current partition view on every node and re-push
        until the links actually settle: a link whose reconnect budget
        expires BETWEEN a respawn and the first refresh quarantines
        itself (sticky by design) a beat after the reset — the control
        plane re-arms until the view sticks.  The deadline rides the
        injected clock (utils/clock.py contract), so tests drive it
        with a ManualClock and slow hosts can widen it without wall-
        clock flake."""
        deadline = self.clock.now() + settle_s
        while True:
            for i in nodes:
                self.clients[i].set_blocked_peers(sorted(self.blocked[i]))
            if self._links_settled() or self.clock.now() >= deadline:
                return
            self.clock.sleep(0.2)

    def _links_settled(self) -> bool:
        for i in self.up_nodes():
            links = self.clients[i].health()["mesh"]["links"]
            for peer_id, state in links.items():
                if not self.up[int(peer_id.removeprefix("node"))]:
                    continue
                if peer_id in self.blocked[i]:
                    if not state["blocked"]:
                        return False
                elif state["blocked"] or state["quarantined"] is not None:
                    return False
        return True

    # -- convergence ---------------------------------------------------

    def converge(self, max_passes: int = 8) -> tuple:
        """Drive every node to the oracle fixpoint: re-offer each
        message to its origin (idempotent), tick past the end, sync
        everyone, compare roots.  Returns (oracle_hex, roots)."""
        oracle = oracle_root(self.spec, self.plan)
        end = int(self.plan.genesis_time
                  + self.plan.slot_time(self.scenario.slots + 1))
        roots = []
        for _ in range(max_passes):
            for planned in self.plan.messages:
                client = self.clients[planned.origin]
                client.send_message(planned.topic, planned.payload,
                                    peer=f"origin{planned.origin}")
                client.drain_responses()
            for i in self.up_nodes():
                self.clients[i].send_tick(end)
                self.clients[i].drain_responses()
            for i in self.up_nodes():
                self.clients[i].sync()
            roots = [self.clients[i].root() for i in self.up_nodes()]
            if all(r == oracle for r in roots):
                break
        return oracle, roots

    # -- reporting -----------------------------------------------------

    def report(self) -> dict:
        nodes = {}
        for i in self.up_nodes():
            client = self.clients[i]
            nodes[f"node{i}"] = {
                "root": client.root(),
                "health": client.health(),
                "incidents": client.incidents(),
            }
        return {"scenario": self.scenario.name, "seed": self.seed,
                "events": list(self.events_applied), "nodes": nodes}

    # -- teardown ------------------------------------------------------

    def teardown(self, force: bool = False) -> dict:
        """Graceful drain of every live node (SIGKILL on `force` or a
        drain that hangs), reap every process, remove the work dir.
        Returns {"orphan_procs": [...], "orphan_sockets": [...]} —
        both empty is the drill's no-leak assertion."""
        for i, client in enumerate(self.clients):
            if client is None:
                continue
            if not force:
                try:
                    client.drain()
                except (OSError, ConnectionError, AssertionError):
                    pass
            client.close()
            self.clients[i] = None
        orphan_procs = []
        for i, proc in enumerate(self.procs):
            if proc is None:
                continue
            try:
                proc.wait(timeout=1.0 if force else DRAIN_TIMEOUT_S)
            except Exception:
                proc.kill()
                try:
                    proc.wait(timeout=10.0)
                except Exception:
                    orphan_procs.append(proc.pid)
            if proc.stdout is not None:
                proc.stdout.close()
            if proc.stderr is not None:
                proc.stderr.close()
            self.up[i] = False
        orphan_sockets = [p for p in self.sockets if os.path.exists(p)]
        shutil.rmtree(self.workdir, ignore_errors=True)
        return {"orphan_procs": orphan_procs,
                "orphan_sockets": orphan_sockets}


def run_scenario_processes(scenario: Scenario, seed: int = 0,
                           extra_args: dict | None = None,
                           max_passes: int = 8) -> dict:
    """One full drill round: spawn the mesh, walk the timeline,
    converge, report, tear down.  The report gains "oracle", "roots",
    "converged", "wall_s" and the teardown's leak lists."""
    t0 = time.perf_counter()
    mesh = ProcessMesh(scenario, seed=seed, extra_args=extra_args)
    try:
        mesh.start()
        mesh.run()
        oracle, roots = mesh.converge(max_passes=max_passes)
        report = mesh.report()
        leaks = mesh.teardown()
    except BaseException:
        mesh.teardown(force=True)
        raise
    report["oracle"] = oracle
    report["roots"] = roots
    report["converged"] = bool(roots) and all(r == oracle for r in roots)
    report["wall_s"] = time.perf_counter() - t0
    report.update(leaks)
    return report


# ---------------------------------------------------------------------------
# the drill matrix (scripts/mesh_drill.py, soak's SOAK_MESH leg and the
# bench mesh tier all draw from here)
# ---------------------------------------------------------------------------

MESH_PART = Scenario(
    name="mesh_part", nodes=3, slots=4,
    events=(partition(2.0, ((0, 1), (2,))), heal(3.0)))

MESH_KILL = Scenario(
    name="mesh_kill", nodes=3, slots=5, durable=True,
    events=(kill(2.2, node=1), recover(3.2, node=1)))

MESH_SMOKE = Scenario(name="mesh_smoke", nodes=3, slots=4)

# a 5-ring: diameter 2, every delivery to a non-neighbour is a real
# multi-hop flood (the bench asserts 100% coverage + >=2-hop depth)
MESH_RING = Scenario(name="mesh_ring", nodes=5, slots=4,
                     topology=Topology(kind="ring"))

# seeded churn on a durable 5-ring: node4 was never spawned and joins
# mid-run (windowed anti-entropy catch-up), node1 leaves gracefully
# and rejoins over its drained journal, node2 is SIGKILLed and
# recovers — membership, graceful departure, and abrupt death all in
# one timeline, converging to the same oracle root
MESH_CHURN = Scenario(
    name="mesh_churn", nodes=5, slots=6, durable=True,
    topology=Topology(kind="ring"),
    events=(join(1.5, node=4), leave(2.5, node=1), kill(3.0, node=2),
            recover(4.0, node=2), join(4.5, node=1)))

# two cliques ({0,1,2} and {2,3,4}) joined through cut vertex 2: kill
# it mid-flood and the graph partitions BY DEATH — the case a static
# full mesh can never express; recovery re-bridges and anti-entropy
# repairs both sides
MESH_BRIDGE = Scenario(
    name="mesh_bridge", nodes=5, slots=6, durable=True,
    topology=Topology(kind="bridge"),
    events=(kill(2.5, node=2), recover(3.5, node=2)))

# node 2 damages its OWN outbound link frames (one flipped bit per
# fire): receivers shed on CRC and quarantine the inbound connection,
# node 2's link layer records the injection — and anti-entropy still
# converges the fleet
# speclint: disable=global-mutable-state -- read-only drill fixture:
# ProcessMesh copies it at construction and nothing writes through it
_CORRUPT_ARGS = {2: ("--fault-site", "mesh.link", "--fault-kind",
                     "corrupt", "--fault-nth", "3", "--fault-fires", "2")}

DRILL_CASES = (
    # (case name, scenario, per-node extra argv)
    ("partition_heal", MESH_PART, None),
    ("kill_recover", MESH_KILL, None),
    ("link_corrupt", MESH_SMOKE, _CORRUPT_ARGS),
    ("blackout3", LIBRARY["blackout3"], None),
    ("churn_storm", MESH_CHURN, None),
    ("bridge_kill", MESH_BRIDGE, None),
)


def drill_case(name: str) -> tuple:
    for case, scenario, extra in DRILL_CASES:
        if case == name:
            return case, scenario, extra
    raise KeyError(f"unknown drill case {name!r}; "
                   f"known: {[c[0] for c in DRILL_CASES]}")
