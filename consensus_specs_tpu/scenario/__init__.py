"""Network-scale adversarial scenario harness.

PRs 1-6 hardened a single node: fused deferred verification (sigpipe/),
a graceful-degradation supervisor (resilience/), bounded gossip
admission (gossip/), a transactional store with crash recovery (txn/),
device G1 sweeps (ops/) and incremental merkleization (ssz/).  This
package composes them into the SYSTEM story: N simulated nodes — each
with its own gossip pipeline, transactional store, and node-tagged
metrics/incident books — driven over a seeded topology through
mainnet-shaped and adversarial traffic, with an omniscient sequential
oracle defining truth.

    from consensus_specs_tpu import scenario
    report = scenario.run_scenario(scenario.named("battlefield3"),
                                   seed=7)
    scenario.assert_converged(report)     # byte-identical store roots
    scenario.assert_attributed(report)    # every attack pinned to a
                                          # node-tagged incident

* dsl.py      — declarative scenarios: topology, traffic mix, and a
                timeline of partitions, equivocation storms,
                surround-vote attacks, long-range forks,
                crash-and-recover nodes, breaker-open windows; plus
                the named LIBRARY and the seeded `randomized()`
                generator.
* net.py      — the simulated network: per-origin FIFO streams with
                stall/flush loss semantics (the determinism invariant
                convergence rests on), seeded delay/jitter/drops,
                mesh-redundancy duplicate copies.
* traffic.py  — one canonical chain + the full message feed + crafted
                adversarial messages, precomputed from
                (scenario, seed).
* node.py     — SimNode: per-node pipeline/store/journal/guard with
                the durable-vs-volatile crash contract.
* driver.py   — the seeded scheduler: agenda loop, event application,
                heal/recovery sync, end-of-run convergence,
                ScenarioReport with a deterministic fingerprint().
* oracle.py   — the sequential omniscient oracle and the
                convergence + attribution assertions.

Every run is a pure function of `(scenario, seed)`; docs/scenario.md
derives why (per-origin FIFO x home-mapping x burned-validator muting
x uniform block timeliness).
"""
from .driver import Driver, ScenarioReport, run_scenario
from .dsl import (
    LIBRARY, LinkSpec, Scenario, Topology, TrafficSpec, crash,
    degraded, equivocation_storm, heal, kill, long_range_fork, named,
    partition, randomized, recover, surround_attack,
)
from .oracle import (
    Oracle, assert_attributed, assert_converged, attribution_report,
)

__all__ = [
    "Driver", "LIBRARY", "LinkSpec", "Oracle", "Scenario",
    "ScenarioReport", "Topology", "TrafficSpec", "assert_attributed",
    "assert_converged", "attribution_report", "crash", "degraded",
    "equivocation_storm", "heal", "kill", "long_range_fork", "named",
    "partition", "randomized", "recover", "run_scenario",
    "surround_attack",
]
