"""The multi-node scenario driver: one seeded scheduler stepping N
nodes, a simulated network, and the omniscient oracle through a
declarative timeline.

Execution model (docs/scenario.md has the diagram):

    TrafficPlan (traffic.py)      one canonical chain + every message,
        |                         precomputed from (scenario, seed)
        v
    agenda = merge(slot ticks, control actions, publishes)
        |                         processed in (time, priority, seq)
        v                         order on ONE ManualClock
    SimNetwork (net.py)           per-origin FIFO streams, seeded
        |                         delay/jitter, stall/flush loss
        v
    SimNode[i] (node.py)          own pipeline + txn store + books
    Oracle     (oracle.py)        same feed, publish order, no network

Sync points — heal, recovery, and the end-of-run convergence loop —
replay the canonical feed to any node missing messages (`catch_up`),
in publish order, until a fixpoint: the simulation's stand-in for
req/resp backfill.  A node that needed one records a `scenario.sync`
incident in its OWN log (that is how a partition is *attributed*: the
node that noticed the gap says so).

Everything runs on the calling thread; the only cross-thread hop is
the resilience watchdog, which inherits the stepped node's context by
construction (see utils/nodectx.py).  `run()` returns a
`ScenarioReport` whose `fingerprint()` is a pure function of
`(scenario, seed)` — the seed-replay determinism pin.
"""
from __future__ import annotations

import os
import random
import shutil
import tempfile
from dataclasses import dataclass, field

from .. import resilience
from ..gossip import GossipConfig
from ..resilience import FaultPlan, FaultSpec
from ..resilience.supervisor import SupervisorConfig
from ..ssz import hash_tree_root
from ..specs import get_spec
from ..utils import nodectx
from ..utils.clock import ManualClock
from .dsl import Scenario
from .net import SimNetwork
from .node import SimNode
from .oracle import Oracle, attribution_report, node_summary
from .traffic import TrafficPlan

MAX_CONVERGENCE_ROUNDS = 6


@dataclass
class ScenarioReport:
    scenario: Scenario
    seed: int
    oracle: dict
    nodes: list = field(default_factory=list)
    attribution: dict = field(default_factory=dict)
    feed_size: int = 0
    sync_replays: int = 0
    convergence_rounds: int = 0
    # durable scenarios: the fleet's on-disk journal high-water mark
    # (bytes, sampled at every slot tick) — the soak runner's
    # bounded-disk signal.  Runtime plumbing, not part of the
    # deterministic fingerprint.
    durable_bytes_hw: int = 0

    def fingerprint(self) -> dict:
        """The deterministic projection: everything here is a pure
        function of (scenario, seed) — no wall-clock timers, no
        transient ids."""
        def node_fp(n):
            return {
                "node_id": n["node_id"],
                "store_root": n["store_root"],
                "head": n["head"],
                "finalized": n["finalized"],
                "accepted": n["accepted"],
                "incidents": [
                    (e["site"], e["event"], round(e["t"], 6))
                    for e in n["incidents"]],
                "counters": {
                    k: v for k, v in sorted(n["metrics"].items())
                    if not k.endswith("_sec")},
            }
        return {
            "scenario": self.scenario.name,
            "seed": self.seed,
            "feed_size": self.feed_size,
            "oracle": {k: self.oracle[k] for k in
                       ("store_root", "head", "finalized", "accepted")},
            "nodes": [node_fp(n) for n in self.nodes],
        }


class Driver:
    def __init__(self, scenario: Scenario, seed: int = 0,
                 node_config: GossipConfig | None = None,
                 snapshot_interval: int = 256,
                 journal_kwargs: dict | None = None,
                 supervisor_overrides: dict | None = None):
        scenario.validate()
        self.scenario = scenario
        self.seed = int(seed)
        self.spec = get_spec(scenario.fork, scenario.preset)
        self.rng = random.Random(self.seed)
        self.clock = ManualClock()
        # the plan consumes the RNG first (fixed draw order), the
        # network and delivery jitter consume it afterwards
        self.plan = TrafficPlan(self.spec, scenario, self.rng)
        self.net = SimNetwork(
            scenario.nodes, scenario.topology.link, self.rng,
            ingress_multiplier=scenario.traffic.ingress_multiplier)
        # durable scenarios: every node journals to its own on-disk
        # segment directory under one per-run temp root (removed at the
        # end of run(); the dirs are runtime plumbing, not part of the
        # deterministic fingerprint)
        self._durable_root = None
        if scenario.durable:
            self._durable_root = tempfile.mkdtemp(
                prefix=f"scenario-{scenario.name}-")
        # every node gets its OWN supervisor (breaker table on the
        # shared ManualClock) and fault-plan slot: a degraded window or
        # shard death on one node is invisible to the others;
        # `supervisor_overrides` tunes the per-node breakers (the soak
        # runner and the isolation tests run trippier thresholds)
        sup_overrides = supervisor_overrides or {}
        self.nodes = [
            SimNode(i, self.spec, self.plan.genesis_state, self.clock,
                    config=node_config,
                    transport=self._transport_for(i),
                    supervisor_config=SupervisorConfig(
                        clock=self.clock, **sup_overrides),
                    snapshot_interval=snapshot_interval,
                    journal_kwargs=journal_kwargs,
                    durable_dir=os.path.join(self._durable_root,
                                             f"node{i}")
                    if self._durable_root else None)
            for i in range(scenario.nodes)]
        self.oracle = Oracle(self.spec, self.plan, self.clock)
        self._digests: dict = {}            # feed seq -> payload digest
        self.sync_replays = 0
        self.convergence_rounds = 0
        self.durable_bytes_hw = 0

    # -- transport seam ------------------------------------------------
    def _transport_for(self, node_id: int):
        def relay(message) -> None:
            # accepted-message forwarding: pure mesh redundancy in this
            # simulation (dedup sheds the copies) — counted so the seam
            # is observable per node
            self.nodes[node_id].ctx.metrics.inc_labeled(
                "gossip_forwarded", message.topic)
        return relay

    # -- time ----------------------------------------------------------
    def _wall(self, sim_s: float) -> int:
        return self.plan.genesis_time + int(sim_s)

    def _advance(self, to_s: float) -> None:
        if to_s > self.clock.now():
            self.clock.advance(to_s - self.clock.now())

    # -- the run -------------------------------------------------------
    def run(self) -> ScenarioReport:
        # the process-global DEFAULT supervisor serves the oracle and
        # any out-of-context work; each SimNode routes to its own
        previous_sup = resilience.supervisor._ACTIVE.default
        resilience.enable(SupervisorConfig(clock=self.clock))
        try:
            return self._run()
        finally:
            for node in self.nodes:
                node.install_fault_plan(None)
            resilience.supervisor._ACTIVE.set_default(previous_sup)
            if self._durable_root is not None:
                for node in self.nodes:
                    if node.journal is not None and \
                            hasattr(node.journal, "close"):
                        node.journal.close()
                shutil.rmtree(self._durable_root, ignore_errors=True)

    def _run(self) -> ScenarioReport:
        scenario = self.scenario
        agenda = []
        end_slot = scenario.slots + 2
        for slot in range(1, end_slot + 1):
            agenda.append((self.plan.slot_time(slot), 0, len(agenda),
                           "tick", slot))
            # the attesting-interval tick: blocks publish AT this
            # boundary, so by the time any delivery flushes, every
            # store's clock is past the timely window — block
            # timeliness is uniformly False at every node AND the
            # oracle, however late a partition or crash delivers the
            # block (dsl.py's determinism discipline)
            agenda.append((self.plan.slot_time(slot)
                           + self.plan.attest_offset, 0, len(agenda),
                           "interval_tick", slot))
        for action in self.plan.actions:
            agenda.append((action.time_s, 1, len(agenda), "action",
                           action))
        for planned in self.plan.messages:
            # stable index keeps equal-time publishes in feed order
            agenda.append((planned.time_s, 2, len(agenda), "publish",
                           planned))
        agenda.sort(key=lambda a: (a[0], a[1], a[2]))

        for time_s, _prio, _idx, kind, item in agenda:
            self._advance(time_s)
            if kind == "tick":
                self._tick(item)
            elif kind == "interval_tick":
                self._tick_stores(time_s)
            elif kind == "action":
                # deliveries already DUE land before the control point
                # mutates topology: a partition cut must not
                # retroactively stall an in-flight message the storm
                # planner's establishment contract (publish + margin <
                # cut => delivered pre-cut) counted as arrived — the
                # agenda can be sparse enough that no pump ran between
                # the due time and the cut
                self._pump()
                self._action(item)
            else:
                self._publish(item)
            self._pump()

        # landing phase: let in-flight deliveries land, flush residual
        # stalls, then converge
        self._advance(self.plan.slot_time(end_slot) + 2.0)
        self.net.flush_stalls(self.clock.now(),
                              kinds=("drop", "partition", "crash"))
        self._pump()
        for node in self.nodes:
            node.drain()
        self.oracle.drain()
        self._converge()
        return self._report()

    # -- agenda steps --------------------------------------------------
    def _tick_stores(self, sim_s: float) -> None:
        wall = self._wall(sim_s)
        for node in self.nodes:
            node.tick(wall)
        self.oracle.tick(wall)

    def _tick(self, slot: int) -> None:
        self._tick_stores(self.plan.slot_time(slot))
        self._sample_disk()
        # slot boundary: gossip redundancy repairs plain drop losses
        self.net.flush_stalls(self.clock.now(), kinds=("drop",))
        for node in self.nodes:
            node.pump_retries(self.clock.now())
        self.oracle.pump_retries(self.clock.now())

    def _action(self, action) -> None:
        now = self.clock.now()
        kind = action.kind
        if kind == "partition":
            self.net.partition(action.params["groups"])
        elif kind == "heal":
            self.net.heal()
            released = self.net.flush_stalls(
                now, kinds=("drop", "partition", "crash"))
            self._pump()
            for node in self.nodes:
                self._catch_up(node, reason="heal",
                               released=released)
        elif kind == "crash":
            node = self.nodes[action.params["node"]]
            node.crash()
            self.net.node_down(node.node_id, True)
        elif kind == "kill":
            node = self.nodes[action.params["node"]]
            node.kill()
            self.net.node_down(node.node_id, True)
        elif kind == "recover":
            node = self.nodes[action.params["node"]]
            self.net.node_down(node.node_id, False)
            node.recover(self._wall(now))
            self.net.flush_stalls(now, kinds=("drop", "crash"))
            self._catch_up(node, reason="recover")
        elif kind == "degraded":
            site = action.params["site"]
            fault = action.params.get("fault") or "raise"
            for node in self._window_targets(action.params.get("node")):
                # one seeded plan PER NODE, installed in that node's
                # own slot: a fleet-wide window still trips N separate
                # breakers (one per book), and a targeted window never
                # draws from — or fires on — any other node's stream
                node.install_fault_plan(FaultPlan(
                    # speclint: disable=seam-dynamic-site -- the site
                    # comes from the scenario DSL; dsl.validate() rejects
                    # any name not in the resilience.sites registry
                    # before a run starts
                    [FaultSpec(site, fault, persistent=True)],
                    seed=self.seed * 1000003 + node.node_id))
        elif kind == "degraded_end":
            site = action.params["site"]
            for node in self._window_targets(action.params.get("node")):
                node.install_fault_plan(None)
                # under the node's context: the reset incident is that
                # node's record, like the trip that preceded it
                with nodectx.use(node.ctx):
                    node.supervisor.reset(site)
        else:                                # pragma: no cover
            raise AssertionError(f"unknown action {kind!r}")

    def _window_targets(self, target) -> list:
        """The nodes a degraded window arms/disarms: all of them for a
        fleet-wide window (target None), else exactly one."""
        return self.nodes if target is None \
            else [self.nodes[int(target)]]

    def _sample_disk(self) -> None:
        """Track the fleet's on-disk journal high-water mark (durable
        scenarios): the soak runner asserts it stays bounded across
        rounds, i.e. snapshot-anchored compaction is really deleting
        superseded segments."""
        if self._durable_root is None:
            return
        total = 0
        for node in self.nodes:
            journal = node.journal
            if journal is not None and hasattr(journal, "disk_bytes"):
                total += journal.disk_bytes()
        if total > self.durable_bytes_hw:
            self.durable_bytes_hw = total

    def _publish(self, planned) -> None:
        digest = bytes(hash_tree_root(planned.payload))
        msg = self.net.publish(planned.time_s, planned.origin,
                               planned.topic, planned.payload,
                               planned.tag)
        self._digests[msg.seq] = digest
        # the oracle consumes the same event stream, in publish order,
        # with no network in the way
        self.oracle.deliver(planned.topic, planned.payload, digest,
                            peer=msg.peer)

    def _pump(self) -> None:
        for dest, msg in self.net.pump(self.clock.now()):
            self.nodes[dest].submit(msg.topic, msg.payload,
                                    self._digests[msg.seq],
                                    peer=msg.peer)
        for node in self.nodes:
            node.poll()
        self.oracle.poll()

    # -- sync / convergence --------------------------------------------
    def _catch_up(self, node: SimNode, reason: str,
                  released: int = 0) -> int:
        """Replay the canonical feed, in publish order, to a node
        missing messages — the req/resp backfill stand-in.  Only
        attempts messages the ORACLE accepted: junk the omniscient
        sequential node rejected can never become acceptable later."""
        if not node.up:
            return 0
        now = self.clock.now()
        replayed = 0
        for msg in self.net.published:
            if msg.time > now:
                break
            digest = self._digests[msg.seq]
            if digest in node.accepted:
                continue
            if digest not in self.oracle.accepted:
                continue
            node.submit(msg.topic, msg.payload, digest, peer=msg.peer)
            replayed += 1
        if replayed:
            node.drain()
            self.sync_replays += replayed
            with node.scope():
                resilience.INCIDENTS.record(
                    "scenario.sync", "catch_up", reason=reason,
                    replayed=replayed, released=released)
        return replayed

    def _converge(self) -> None:
        """End-of-run anti-entropy to fixpoint: first the oracle works
        off its own retry queue (a same-instant ordering artifact can
        transiently reject even with a perfect network), then every
        node is repeatedly offered everything the oracle accepted that
        it has not."""
        for _ in range(MAX_CONVERGENCE_ROUNDS):
            if not self.oracle.retry:
                break
            # retries are scheduled at now+1.0: the clock must move or
            # no retry ever comes due
            self._advance(self.clock.now() + 1.5)
            self.oracle.pump_retries(self.clock.now())
            self.oracle.drain()
        for round_index in range(MAX_CONVERGENCE_ROUNDS):
            progress = 0
            for node in self.nodes:
                before = len(node.accepted)
                self._catch_up(node, reason="final")
                node.drain()
                progress += len(node.accepted) - before
            self.convergence_rounds = round_index + 1
            if progress == 0:
                break

    # -- reporting -----------------------------------------------------
    def _report(self) -> ScenarioReport:
        self._sample_disk()
        report = ScenarioReport(
            scenario=self.scenario, seed=self.seed,
            oracle=self.oracle.summary(),
            feed_size=len(self.net.published),
            sync_replays=self.sync_replays,
            convergence_rounds=self.convergence_rounds,
            durable_bytes_hw=self.durable_bytes_hw)
        for node in self.nodes:
            node.leak_check()
            report.nodes.append(node_summary(node))
        report.attribution = attribution_report(self.plan,
                                                report.nodes)
        return report


def run_scenario(scenario: Scenario, seed: int = 0,
                 node_config: GossipConfig | None = None,
                 snapshot_interval: int = 256,
                 journal_kwargs: dict | None = None,
                 supervisor_overrides: dict | None = None,
                 processes: bool = False):
    """One scenario run.  ``processes=True`` swaps the in-process
    simulated fleet for N real run_node.py processes meshed over their
    framed sockets (scenario/processes.py) — the recovery-chaos
    backend; it supports only the partition/heal/kill/recover event
    subset and ignores the in-process tuning knobs, and returns the
    process backend's report dict instead of a ScenarioReport."""
    if processes:
        from .processes import run_scenario_processes
        return run_scenario_processes(scenario, seed=seed)
    return Driver(scenario, seed, node_config,
                  snapshot_interval=snapshot_interval,
                  journal_kwargs=journal_kwargs,
                  supervisor_overrides=supervisor_overrides).run()
