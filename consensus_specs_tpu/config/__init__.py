"""Two-tier parameter system.

*Presets* (compile-time; define SSZ shapes, trigger type rebuilds) and
*configs* (runtime; swappable per test via with_config_overrides) — the same
split as the reference (/root/reference/setup.py:344-363 bake-in vs
eth2spec/config/config_util.py runtime loader; SURVEY.md §5).
"""
from __future__ import annotations

from .params import PRESETS, CONFIGS


class Config:
    """Attribute-access view over a config dict (runtime tier)."""

    def __init__(self, values: dict):
        object.__setattr__(self, "_values", dict(values))

    def __getattr__(self, name):
        try:
            return self._values[name]
        except KeyError:
            raise AttributeError(name) from None

    def __setattr__(self, name, value):
        raise AttributeError("Config is immutable; use replace()")

    def get(self, name, default=None):
        return self._values.get(name, default)

    def replace(self, **overrides) -> "Config":
        merged = dict(self._values)
        merged.update(overrides)
        return Config(merged)

    def as_dict(self) -> dict:
        return dict(self._values)


def load_preset(preset_name: str) -> dict:
    """Merged preset values across all forks (keys are globally unique)."""
    if preset_name not in PRESETS:
        raise KeyError(f"unknown preset {preset_name!r}")
    merged = {}
    for fork_vals in PRESETS[preset_name].values():
        merged.update(fork_vals)
    return merged


def load_config(config_name: str, overrides: dict | None = None) -> Config:
    if config_name not in CONFIGS:
        raise KeyError(f"unknown config {config_name!r}")
    values = dict(CONFIGS[config_name])
    if overrides:
        values.update(overrides)
    return Config(values)
