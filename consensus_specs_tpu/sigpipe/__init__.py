"""Block-level deferred signature verification pipeline.

Collect every signature check a signed block implies (sets.py), verify
them together in the fewest device dispatches (scheduler.py), isolate
failures by bisection (bisect.py), cache decompressed/aggregated pubkeys
(cache.py), surface counters (metrics.py), and overlap flushes with
host-side work through the async engine (pipeline_async.py,
`ASYNC_FLUSH=0` to disable).  verify.py wires the pipeline into
`state_transition` behind the opt-in `enable()` switch; the inline
scalar path stays the default oracle.
"""
from . import pipeline_async
from .metrics import METRICS
from .sets import (
    SignatureSet, collect_block_sets, collect_pending_deposit_sets,
)
from .verify import (
    block_scope, compute_verdicts, disable, enable, enabled, mode,
    pending_deposit_scope, verify_block_signatures,
)

__all__ = [
    "METRICS", "SignatureSet", "collect_block_sets",
    "collect_pending_deposit_sets", "block_scope", "compute_verdicts",
    "disable", "enable", "enabled", "mode", "pending_deposit_scope",
    "pipeline_async", "verify_block_signatures",
]
