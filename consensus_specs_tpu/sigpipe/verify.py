"""Pipeline driver: collect a block's signature sets, verify them in one
batch, and let the spec consume the verdicts at its own call sites.

Opt-in, like `parallel/mesh_engine.enable(mesh)`:

    from consensus_specs_tpu import sigpipe
    sigpipe.enable()            # or sigpipe.enable(mode="per-set")
    spec.state_transition(state, signed_block)
    sigpipe.disable()

`state_transition` wraps block processing in `block_scope`, which
precomputes a verdict for every signature check the block implies
(sets.collect_block_sets -> scheduler.verify_sets) and installs the map
on the spec instance.  The verification seams (`BaseSpec.bls_verify` /
`bls_fast_aggregate_verify`) look verdicts up by content — (pubkeys,
signing_root, signature) — so a batch verdict substitutes for the scalar
call at EXACTLY the inline call site: an invalid block raises the same
AssertionError at the same operation boundary with the same partial state
mutations, byte-identical to the scalar path.  Any check the collector
failed to predict simply misses the map and falls back to the scalar
backend (counted in metrics), so enabling the pipeline can never change
behavior — only the number of device dispatches.
"""
from __future__ import annotations

from contextlib import contextmanager

from . import pipeline_async, scheduler, sets
from .metrics import METRICS

_enabled = False
_mode = "fused"


def enable(mode: str = "fused") -> None:
    """Route state_transition signature checks through the batch pipeline.
    `mode`: "fused" (one combined pairing dispatch + bisection) or
    "per-set" (VerifyBatch/FastAggregateVerifyBatch grouping)."""
    global _enabled, _mode
    if mode not in ("fused", "per-set"):
        raise ValueError(f"unknown sigpipe mode {mode!r}")
    _enabled = True
    _mode = mode


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def mode() -> str:
    return _mode


class VerdictMap:
    """Content-addressed verdicts: (pubkeys, signing_root, signature) ->
    bool.  The spec seams consult it; misses fall back to scalar."""

    def __init__(self, verdicts: dict):
        self._verdicts = verdicts

    def lookup(self, pubkeys, signing_root, signature):
        v = self._verdicts.get((pubkeys, signing_root, signature))
        if v is None:
            METRICS.inc("seam_misses")
            METRICS.inc_labeled("scalar_fallbacks", "collector_miss")
        else:
            METRICS.inc("seam_hits")
        return v

    def peek(self, key):
        """Verdict for a content key WITHOUT seam-metrics side effects —
        the block scope's window-reuse probe (a probe is not a seam
        consultation; counting it as hit or miss would distort both)."""
        return self._verdicts.get(key)

    def __len__(self) -> int:
        return len(self._verdicts)


class LazyVerdictMap:
    """VerdictMap facade over an in-flight :class:`pipeline_async.
    FlushTicket`: the block scope installs it IMMEDIATELY and the
    engine verifies concurrently with the spec's host-side block work;
    the first seam consultation is the join barrier.  A failed or
    abandoned ticket degrades to an empty map — every lookup then
    misses and the seams fall back to the scalar backend, byte-
    identical to the historical block_scope error path."""

    __slots__ = ("_ticket", "_vm")

    def __init__(self, ticket):
        self._ticket = ticket
        self._vm = None

    def _join(self) -> VerdictMap:
        if self._vm is None:
            by_key = self._ticket.result()
            self._vm = VerdictMap(by_key if by_key is not None else {})
        return self._vm

    def lookup(self, pubkeys, signing_root, signature):
        return self._join().lookup(pubkeys, signing_root, signature)

    def peek(self, key):
        return self._join().peek(key)

    def __len__(self) -> int:
        return len(self._join())


def _batch_verify_unique(collected, mode: str | None = None,
                         reuse: VerdictMap | None = None):
    """Dedup identical checks (same pubkeys/root/signature verify once),
    batch-verify, and return the content-keyed verdict dict.  `mode`
    defaults to the module's enabled mode; the gossip micro-batcher
    passes its own.  `reuse` is an already-installed outer VerdictMap
    (the gossip window's): checks it has a verdict for — the block
    proposer signature the gossip collector predicted — are lifted into
    the result instead of re-verified, so one signature never rides two
    batches."""
    unique: dict = {}
    for s in collected:
        unique.setdefault(s.key(), s)
    dropped = len(collected) - len(unique)
    if dropped:
        METRICS.inc("dedup_saved", dropped)
    by_key: dict = {}
    if reuse is not None:
        for key in list(unique):
            v = reuse.peek(key)
            if v is not None:
                by_key[key] = v
                del unique[key]
        if by_key:
            METRICS.inc("window_verdicts_reused", len(by_key))
    unique_sets = list(unique.values())
    unique_verdicts = scheduler.verify_sets(
        unique_sets, mode=mode if mode is not None else _mode)
    by_key.update(
        {s.key(): v for s, v in zip(unique_sets, unique_verdicts)})
    return by_key


def compute_verdicts(spec, state, signed_block):
    """Collect + batch-verify every signature check in `signed_block`;
    returns (VerdictMap, collected sets, per-set verdict list).  An
    outer verdict map already installed on `spec` (the gossip window's)
    is consulted first — its verdicts are reused, not recomputed."""
    block_sets = sets.collect_block_sets(spec, state, signed_block)
    by_key = _batch_verify_unique(
        block_sets, reuse=getattr(spec, "_sigpipe_verdicts", None))
    return (VerdictMap(by_key), block_sets,
            [by_key[s.key()] for s in block_sets])


def verify_block_signatures(spec, state, signed_block) -> None:
    """Eager API: batch-verify every signature check the block implies;
    None if they all pass, AssertionError naming the first failing
    operation otherwise (deposit sets are valid-or-skip and never raise).
    `state` must be advanced to the block's slot."""
    _vm, block_sets, verdicts = compute_verdicts(spec, state, signed_block)
    for s, ok in zip(block_sets, verdicts):
        assert ok or not s.required, \
            f"sigpipe: invalid {s.kind} signature at {s.origin or s.kind}"


@contextmanager
def block_scope(spec, state, signed_block):
    """Install batch verdicts on `spec` for the duration of one block's
    processing; a pipeline failure degrades to the scalar path.

    With the async flush engine live, collection runs HERE (on the
    calling thread — it reads `state`, which the spec is about to
    mutate) but verification rides a :class:`pipeline_async.FlushTicket`
    whose join barrier is the first seam consultation
    (:class:`LazyVerdictMap`): the proposer-signature check and block
    processing's host-side prefix overlap the flush's device
    dispatches.  An outer gossip-window map is consulted at collect
    time exactly as before (its verdicts are lifted, not recomputed).
    """
    if not _enabled:
        yield
        return
    try:
        if pipeline_async.overlap_live():
            block_sets = sets.collect_block_sets(spec, state, signed_block)
            reuse = getattr(spec, "_sigpipe_verdicts", None)
            ticket = pipeline_async.submit(
                lambda: _batch_verify_unique(block_sets, reuse=reuse),
                "block_scope")
            vm = LazyVerdictMap(ticket)
        else:
            vm, _sets, _verdicts = compute_verdicts(
                spec, state, signed_block)
    except Exception:
        METRICS.inc("pipeline_errors")
        vm = None
    if vm is None:
        yield
        return
    with spec.install_sigpipe_verdicts(vm):
        yield


@contextmanager
def pending_deposit_scope(spec, state):
    """Install batch verdicts for electra's epoch-boundary pending
    deposits (EIP-6110) around `process_pending_deposits`: the per-epoch
    prefix of `state.pending_deposits` is collected and verified as one
    valid-or-skip batch, and `is_valid_deposit_signature`'s seam consumes
    the verdicts at the inline call sites.  Same degradation contract as
    block_scope: any pipeline failure falls back to scalar verification.
    """
    if not _enabled:
        yield
        return
    try:
        dep_sets = sets.collect_pending_deposit_sets(spec, state)
        vm = VerdictMap(_batch_verify_unique(dep_sets)) if dep_sets \
            else None
    except Exception:
        METRICS.inc("pipeline_errors")
        vm = None
    if vm is None:
        yield
        return
    with spec.install_sigpipe_verdicts(vm):
        yield
