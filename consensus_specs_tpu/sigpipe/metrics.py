"""Lightweight pipeline metrics: counters, observations, wall-clock timers.

One module-global `METRICS` registry is shared by the collector, scheduler,
bisection and caches so a single `snapshot()` describes a whole verification
run (batch sizes, dispatch count, bisection depth, cache hit rate) —
dumpable as JSON for `bench.py` and asserted on by tests/test_sigpipe.py.
"""
from __future__ import annotations

import json
import time
from contextlib import contextmanager


class Metrics:
    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self.counters: dict = {}
        self.observations: dict = {}
        self.timers: dict = {}

    # -- counters ------------------------------------------------------
    def inc(self, name: str, by: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + by

    def count(self, name: str) -> int:
        return self.counters.get(name, 0)

    # -- observations (count/total/min/max, no per-sample storage) -----
    def observe(self, name: str, value) -> None:
        o = self.observations.get(name)
        if o is None:
            self.observations[name] = {"count": 1, "total": value,
                                       "min": value, "max": value}
        else:
            o["count"] += 1
            o["total"] += value
            o["min"] = min(o["min"], value)
            o["max"] = max(o["max"], value)

    # -- timers --------------------------------------------------------
    @contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.timers[name] = (self.timers.get(name, 0.0)
                                 + time.perf_counter() - t0)

    # -- export --------------------------------------------------------
    def snapshot(self) -> dict:
        out = dict(self.counters)
        for name, o in self.observations.items():
            out[name] = dict(o)
            if o["count"]:
                out[name]["mean"] = o["total"] / o["count"]
        for name, secs in self.timers.items():
            out[f"{name}_sec"] = round(secs, 6)
        # derived rates the dashboards care about
        hits = self.count("pubkey_cache_hits")
        misses = self.count("pubkey_cache_misses")
        if hits + misses:
            out["pubkey_cache_hit_rate"] = round(hits / (hits + misses), 4)
        return out

    def to_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)


METRICS = Metrics()
