"""Lightweight pipeline metrics: counters, observations, wall-clock timers.

One module-global `METRICS` registry is shared by the collector, scheduler,
bisection, caches and the resilience supervisor, so a single `snapshot()`
describes a whole verification run (batch sizes, dispatch count, bisection
depth, cache hit rate, breaker trips, fallback reasons) — dumpable as JSON
for `bench.py` and asserted on by tests/test_sigpipe.py.

`METRICS` is a *router*, not a bare registry: every call consults the
node-context stack (utils/nodectx.py) and lands in the active node's
own `Metrics` instance when the scenario harness installed one, or in
the process-global default otherwise.  Single-node callers never see
the difference; the multi-node driver gets per-node books (each tagged
with its `node_id`, which `snapshot()` carries) from the exact same
call sites.

Thread-safe: a single re-entrant lock guards every mutation and snapshot.
The gossip-path follow-up (ROADMAP) and the supervisor's watchdog thread
both touch the registry off the main thread; per-counter races would make
degradation counters lie exactly when they matter.

Labeled counters (`inc_labeled`) keep one counter per (name, label) pair —
the `scalar_fallbacks` counter is labeled by degradation reason
(`collector_miss`, `breaker_open`, `dispatch_failed`, `guard_mismatch`,
`disabled`) so a metrics snapshot says not just that the pipeline
degraded but why.

The device-G1-sweep offload (PR 5) is observable through three plain
counters: `g1_aggregate_dispatches` (batched committee-sum calls at the
`ops.g1_aggregate` seam) and `msm_dispatches` (coefficient-weighted
sweep calls at `ops.msm`) count the per-flush device work — exactly one
of each per fused flush — while `host_point_adds` counts every
point add/double the per-set HOST fallback loops perform (cache sums,
weighting ladders, the G2 fold's fallback sum, bisection's oracle
re-derivation): ~0 whenever the device path is healthy, which is what
`make msm-bench` and the sweep tests pin.  All three ride the ordinary
counter path and land in the JSON dump.

The folded pairing product (sigpipe/fold.py) adds the COUNTED perf
invariant the fold bench and tier-1 assert without wall-clock timing:
`miller_loops_per_flush` (an observation — per fused flush, the number
of pairing legs assembled: N+1 folded vs 2N unfolded for an N-set
flush), the labeled `fold_enabled` counter (one `on`/`off` tick per
fused flush, so a snapshot says which assembly every flush used), and
`fold_dispatches` (one `ops.pairing_fold` dispatch per folded flush).
The `scalar_fallbacks` reason vocabulary gains `fold_mismatch`: a
differential-guard trip on the folded path, distinguishable from a
legacy `guard_mismatch` in incident streams.

Incremental merkleization (ssz/incremental.py) reports here too, so one
snapshot covers the whole per-block device story: `merkle_sweep_dispatches`
(one `ssz.merkle_sweep` dispatch per re-rooted tracked view),
`merkle_sweep_levels` (ragged batched level-calls inside those sweeps —
bounded by the state tree height), `merkle_chunks_hashed` (2-to-1 hashes
the sweeps performed — O(diff · log state), the number the merkle bench
asserts scales with diff size), `merkle_dirty_nodes` (dirty leaf chunks
swept) with the power-of-two `merkle_dirty_occupancy` histogram,
`merkle_cache_builds` (first full builds of a tracked view),
`merkle_full_rebuilds` (legacy full re-roots taken as the sweep-site
fallback), `merkle_cached_roots` (re-roots answered from cache with no
hashing), and `merkle_guard_samples` / `merkle_guard_mismatches` for the
differential guard.

The async flush engine (pipeline_async.py) reports overlap here:
`async_flushes` / `inline_flushes` (engine-worker vs caller-inline
submits), `flush_overlap_ns` (worker wall time that ran while the
caller did host work — overlapped flushes only, so scenario replays
stay bit-identical), `device_idle_gaps` (host-sync stalls between a
flush's verify dispatches on the synchronous path; pinned 0 with
overlap on), `abandoned_flushes`, the power-of-two
`flush_inflight_depth` histogram, and
`merkle_device_round_trips` (host<->device transfers per merkle sweep:
1 on the fused device-resident path, one per bulk level otherwise)
with its sibling counters `merkle_sibling_uploads` (literal chunks a
fused sweep actually uploaded) and `merkle_sibling_uploads_skipped`
(clean-sibling level buffers found already device-resident in the
literal pool — the re-uploads the pool exists to skip).

The fused epoch sweep (specs/epoch_fast.py) pins its one-dispatch
contract here: `epoch_sweep_dispatches` counts `ops.epoch_sweep`
seam dispatches — exactly one per `process_epoch` when the device
path is live, which the fork-matrix tests and `make epoch-bench`
assert — while the labeled `epoch_sweep_fallbacks` counter says why
any epoch instead ran the counted numpy twin (`unsupervised`,
`disabled`, `quarantined`, `breaker_open`, `dispatch_failed`).
`epoch_writeback_elems` totals the leaf elements the batched
`bulk_set_basic` writeback pushed into tracked SSZ views (O(1)
Python-level calls per epoch regardless of validator count), and
`epoch_guard_samples` / `epoch_guard_mismatches` record the sampled
lane-level differential guard that quarantines a corrupting device
program before its outputs reach the state.

Histograms (`observe_hist`) bucket integer observations by
power-of-two: the gossip admission layer records batch occupancy per
flush here (`batch_occupancy`: how many signature sets each dispatch
actually fused — the number that decides whether batching pays), and
the window-flush reason rides a labeled counter (`gossip_window_flushes`:
`deadline` vs `size` vs `drain`).  Buckets instead of raw samples keep
the registry O(log max) per series while still answering "mostly
singletons or mostly full windows?".
"""
from __future__ import annotations

import json
import time
from contextlib import contextmanager

from ..utils import nodectx
from ..utils.locks import named_rlock


class Metrics:
    def __init__(self, node_id: str | None = None):
        self._lock = named_rlock("sigpipe.metrics")
        self.node_id = node_id
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.counters: dict = {}
            self.labeled: dict = {}
            self.observations: dict = {}
            self.histograms: dict = {}
            self.timers: dict = {}

    # -- counters ------------------------------------------------------
    def inc(self, name: str, by: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + by

    def count(self, name: str) -> int:
        with self._lock:
            return self.counters.get(name, 0)

    # -- labeled counters (one counter per (name, label) pair) ---------
    def inc_labeled(self, name: str, label: str, by: int = 1) -> None:
        with self._lock:
            series = self.labeled.setdefault(name, {})
            series[label] = series.get(label, 0) + by

    def count_labeled(self, name: str, label: str | None = None) -> int:
        """Count for one label, or the sum across all labels of `name`."""
        with self._lock:
            series = self.labeled.get(name, {})
            if label is not None:
                return series.get(label, 0)
            return sum(series.values())

    # -- observations (count/total/min/max, no per-sample storage) -----
    def observe(self, name: str, value) -> None:
        with self._lock:
            o = self.observations.get(name)
            if o is None:
                self.observations[name] = {"count": 1, "total": value,
                                           "min": value, "max": value}
            else:
                o["count"] += 1
                o["total"] += value
                o["min"] = min(o["min"], value)
                o["max"] = max(o["max"], value)

    # -- histograms (power-of-two buckets over non-negative ints) ------
    @staticmethod
    def _bucket(value: int) -> str:
        if value <= 0:
            return "0"
        return str(1 << (int(value) - 1).bit_length())

    def observe_hist(self, name: str, value: int) -> None:
        """Count `value` into its power-of-two bucket (1,2,4,8,...):
        bucket "8" holds observations in (4, 8]."""
        bucket = self._bucket(value)
        with self._lock:
            series = self.histograms.setdefault(name, {})
            series[bucket] = series.get(bucket, 0) + 1

    def hist_counts(self, name: str) -> dict:
        with self._lock:
            return dict(self.histograms.get(name, {}))

    # -- timers --------------------------------------------------------
    @contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            elapsed = time.perf_counter() - t0
            with self._lock:
                self.timers[name] = self.timers.get(name, 0.0) + elapsed

    # -- export --------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self.counters)
            if self.node_id is not None:
                out["node_id"] = self.node_id
            for name, series in self.labeled.items():
                out[name] = dict(series)
            for name, o in self.observations.items():
                out[name] = dict(o)
                if o["count"]:
                    out[name]["mean"] = o["total"] / o["count"]
            for name, series in self.histograms.items():
                # numeric bucket order so the JSON reads as a histogram
                out[f"{name}_hist"] = {
                    b: series[b]
                    for b in sorted(series, key=lambda s: int(s))}
            for name, secs in self.timers.items():
                out[f"{name}_sec"] = round(secs, 6)
            # derived rates the dashboards care about
            hits = self.counters.get("pubkey_cache_hits", 0)
            misses = self.counters.get("pubkey_cache_misses", 0)
            if hits + misses:
                out["pubkey_cache_hit_rate"] = round(
                    hits / (hits + misses), 4)
            dedup_hits = self.counters.get("gossip_dedup_hits", 0)
            dedup_misses = self.counters.get("gossip_dedup_misses", 0)
            if dedup_hits + dedup_misses:
                out["gossip_dedup_hit_rate"] = round(
                    dedup_hits / (dedup_hits + dedup_misses), 4)
            return out

    def to_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)


METRICS = nodectx.Router(Metrics(), "metrics")
