"""Bisection fallback: isolate failing sets inside a failed batch.

The fused scheduler path verifies a whole group of signature sets with a
single combined pairing check — one device dispatch, one boolean.  When
that boolean is False, the caller still needs *which* sets failed, because
the spec raises at the failing operation's own call site (byte-identical
invalid-block behavior).  `isolate_failures` recursively halves the group,
re-dispatching each half, until the offending singletons are found:
log-many extra dispatches for the (rare) invalid block instead of falling
all the way back to one dispatch per signature.
"""
from __future__ import annotations

from .metrics import METRICS


def isolate_failures(items, group_valid, metrics=METRICS):
    """Indices of invalid items within `items`.

    `group_valid(sub_items) -> bool` must return True iff every item in
    the sub-list verifies (the scheduler's combined pairing check).  The
    caller has already observed `group_valid(items)` == False; this
    function only splits, so a group of one failing item costs no extra
    dispatch.
    """
    bad: list = []
    _split(list(items), 0, group_valid, bad, 1, metrics)
    return bad


def _split(items, base, group_valid, bad, depth, metrics):
    if metrics is not None:
        metrics.observe("bisect_depth", depth)
    if len(items) == 1:
        bad.append(base)
        return
    mid = len(items) // 2
    for lo, sub in ((0, items[:mid]), (mid, items[mid:])):
        if metrics is not None:
            metrics.inc("bisect_dispatches")
        if not group_valid(sub):
            _split(sub, base + lo, group_valid, bad, depth + 1, metrics)
