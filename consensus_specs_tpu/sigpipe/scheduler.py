"""Scheduling: many signature sets -> fewest verification dispatches.

Two strategies, both returning one verdict per set:

* ``fused`` (default) — every *required* set contributes two pairs to ONE
  combined pairing-product check: e(c_i * agg_pk_i, H(root_i)) *
  e(-c_i * g1, sig_i), product over all sets == 1.  One device dispatch
  for the block's gating checks (the batch axis inside the pairing kernel
  is already padded to power-of-two buckets, so XLA recompiles stay
  bounded).  The c_i are 64-bit Fiat-Shamir coefficients derived from a
  length-framed digest of the batch content: without them a block
  carrying two individually-invalid signatures whose errors cancel would
  pass the product check (the classic aggregate-splitting attack); with
  them cancellation requires predicting coefficients that depend on the
  very signatures being chosen, leaving ~2^-64 residual risk — same
  design as production client batch verification.  On a False product
  the bisection fallback (bisect.py) re-dispatches halves to isolate the
  offending sets.  Valid-or-skip sets (``required=False`` — deposit
  proofs of possession, which the spec skips rather than rejects) ride a
  separate per-set dispatch instead: an invalid deposit in an otherwise
  valid block is routine and must not trigger bisection of the product.

* ``per-set`` — homogeneous grouping through the shim's batch entry
  points: single-pubkey sets ride one `VerifyBatch`, aggregate sets one
  `FastAggregateVerifyBatch` (<= 2 dispatches, per-set verdicts
  directly; the batch APIs handle decompression, aggregation and
  decode-failure screening themselves).  The cross-check oracle for the
  fused path, and the mode that keeps the shim's batch APIs exercised
  from the spec layer.

Degenerate sets never reach a dispatch: empty pubkey lists and
undecodable points read as invalid immediately, exactly matching the
scalar API's False-on-DecodeError contract.

DEVICE G1 SWEEP.  The elliptic-curve *preparation* of a flush is
batched onto the accelerator alongside the pairing itself: all cold
committee sums ride one `ops.g1_aggregate` dispatch
(cache.aggregate_many -> ops/g1_sweep.py) and all 2N Fiat-Shamir
weightings one `ops.msm` dispatch (`_weighted_g1` ->
ops/msm.g1_weighted_sweep), so a flush costs O(1) device calls where it
used to cost O(sets x committee) host point ops.  Both sites carry the
per-set host loop as supervised byte-identical fallback (every fallback
add counted in `host_point_adds`), and the bisection path re-derives
its weighted pairs on the host ladder so a corrupt device sweep cannot
flip a verdict through a FAILING product (valid sets survive the host
re-check).  The accept direction is weaker by construction: a sweep
returning all-identity points makes the product vacuously pass and
bisection never runs — that corruption is the differential guard's
case (guard.py), not this path's.

MULTI-CHIP.  With a >1-device verify mesh (parallel/shard_verify.py)
all three dispatches spread over the mesh: the sweeps shard their
padded job axes inside the same `ops.g1_aggregate`/`ops.msm` seams,
and the fused product partitions its pairs axis at the
`ops.pairing_product` seam — per-shard partial Fp12 Miller products,
Fp12-multiply all-reduce, ONE final exponentiation — taken instead of
`bls.pairing_check` only when the tpu backend is active.  One device
(tier-1 CPU) is byte-identical to the unsharded path, and "one shard
of the mesh died" is just another fault (`shard_dead` in
resilience/faults.py: same breaker -> scalar-fallback -> half-open
contract; docs/sigpipe.md "Sharded verify").

FOLDED SIGNATURE LEGS.  The `e(-c_i * g1, sig_i)` legs all share the
base -g1, so by bilinearity they fold to ONE pair `e(-g1, S)` over the
G2 MSM `S = sum_i c_i * sig_i` (sigpipe/fold.py, the
`ops.pairing_fold` seam): an N-set flush pays N+1 Miller loops instead
of 2N — the counted `miller_loops_per_flush` invariant — and the
weighted-G1 MSM halves to N jobs.  On the tpu backend with the fused
pairing mode the ENTIRE folded flush further fuses into one compiled
program per mesh shard (fold.fold_flush -> shard_verify.pairing_fold:
cofactor sweep + weighting + G2 MSM + partial Miller product in one
launch, the log2(D) Fp12 all-reduce unchanged).  Bisection is
untouched either way — probes re-derive both legs per set on the HOST
ladder — and `FOLD_VERIFY=0` restores the 2N-leg flush byte-for-byte
(docs/sigpipe.md "Folded pairing product").
"""
from __future__ import annotations

import hashlib

from ..crypto import curve as cv
from ..crypto.bls12_381 import _load_signature
from ..crypto.curve import DecodeError
from ..utils import bls
from . import bisect as _bisect
from . import fold
from . import pipeline_async
from .cache import AGGREGATES
from .metrics import METRICS


def _hash_roots(roots):
    """hash-to-G2 of every signing root; one device cofactor sweep on the
    tpu backend (supervised, host math as fallback), host math on
    native."""
    def host():
        from ..crypto.hash_to_curve import hash_to_g2
        return [hash_to_g2(r) for r in roots]
    if bls.current_backend() == "tpu":
        from ..ops.bls_tpu import hash_to_g2_batch
        from ..resilience.supervisor import dispatch
        return dispatch("sigpipe.hash_to_g2_batch",
                        lambda: hash_to_g2_batch(roots), host)
    return host()


def _coefficients(entries):
    """64-bit nonzero Fiat-Shamir coefficients, one per entry, bound to a
    length-framed digest of the whole batch (set count, per-set pubkey
    count and field lengths are all hashed, so no two distinct batch
    layouts share a transcript)."""
    h = hashlib.sha256()
    h.update(len(entries).to_bytes(4, "little"))
    for s, _agg, _sig in entries:
        h.update(len(s.pubkeys).to_bytes(4, "little"))
        for pk in s.pubkeys:
            h.update(pk)
        h.update(len(s.signing_root).to_bytes(4, "little"))
        h.update(s.signing_root)
        h.update(s.signature)
    seed = h.digest()
    out = []
    for i in range(len(entries)):
        x = int.from_bytes(
            hashlib.sha256(seed + i.to_bytes(4, "little")).digest()[:8],
            "little")
        out.append(1 + x % (2**64 - 1))
    return out


def _prepare(indices, sets, verdicts):
    """Decode each set's signature and batch-aggregate every G1 side
    through the aggregate cache: all cold committee sums of the flush
    fuse into ONE `ops.g1_aggregate` device dispatch
    (cache.aggregate_many) instead of a per-set Python add loop.  Fills
    `verdicts` with False for sets the scalar API would reject before
    pairing."""
    pending = []
    for i in indices:
        s = sets[i]
        if len(s.pubkeys) == 0:
            verdicts[i] = False      # scalar FastAggregateVerify: False
            continue
        try:
            sig = _load_signature(s.signature)
        except (DecodeError, ValueError):
            verdicts[i] = False
            continue
        pending.append((i, s, sig))
    aggs = AGGREGATES.aggregate_many(
        [(s.pubkeys, s.hint) for _i, s, _sig in pending])
    prepared = []
    for (i, _s, sig), agg in zip(pending, aggs):
        if agg is None:              # a pubkey failed decode/validation
            verdicts[i] = False
            continue
        prepared.append((i, agg, sig))
    return prepared


def _host_scalar_mul(point, k):
    """Host double-and-add ladder with its point-op cost counted — the
    per-set arithmetic the device sweep exists to eliminate (~96 ops
    per 64-bit coefficient)."""
    k = int(k)
    METRICS.inc("host_point_adds",
                max(k.bit_length(), 1) + bin(k).count("1"))
    return point * k


def _pairing_product(pairs):
    """The fused product's single device dispatch.  With a >1-device
    verify mesh and the device pairing kernels active, the pairs axis
    is partitioned over the mesh — per-shard partial Fp12 Miller
    products, all-reduced by Fp12 multiply into one final
    exponentiation — at the `ops.pairing_product` seam
    (parallel/shard_verify.py, host pairing oracle as fallback).
    Otherwise this is exactly the single-device `bls.pairing_check`
    seam, so tier-1 CPU runs are byte-identical."""
    if bls.current_backend() == "tpu":    # cheap gate before the
        from ..parallel import shard_verify   # jax-heavy mesh import
        if shard_verify.pairing_live():
            return shard_verify.pairing_product(pairs)
    return bls.pairing_check(pairs)


def _weighted_g1(points, coeffs):
    """All 2N Fiat-Shamir weightings of a flush as ONE batched dispatch
    (ops/msm.py `g1_weighted_sweep`) behind the `ops.msm` resilience
    seam; the supervised fallback is the byte-identical per-pair host
    ladder."""
    from ..ops import msm as _msm
    from ..resilience.supervisor import dispatch
    METRICS.inc("msm_dispatches")
    return dispatch(
        "ops.msm",
        lambda: _msm.g1_weighted_sweep(points, coeffs),
        lambda: [_host_scalar_mul(p, c)
                 for p, c in zip(points, coeffs)])


def _verify_fused(sets, prepared, verdicts, strict=None, hash_leg=None):
    """`hash_leg` (pipeline_async.Leg over the STRICT indices' roots)
    is the overlapped hash-to-G2 dispatch: launched before `_prepare`'s
    G1 aggregation sweep, joined here AFTER the weighted MSM — so all
    three of a flush's verify dispatches are in flight with no
    host-sync stall between them, and the first forced read is the
    verdict join below.  Without a leg (ASYNC_FLUSH=0, scenario
    fleets) the dispatch order is byte-for-byte the historical one,
    with the host stall it implies counted as a `device_idle_gaps`.

    With folding live (sigpipe/fold.py, the default) the flush emits
    N+1 pairing legs — N weighted aggregate legs plus ONE `e(-g1, S)`
    leg over the folded G2 MSM — instead of 2N; on the one-launch path
    (tpu backend, fused pairing mode) the whole chain collapses into a
    single `ops.pairing_fold` dispatch.  `FOLD_VERIFY=0` restores the
    2N-leg assembly byte-for-byte."""
    entries = [(sets[i], agg, sig) for i, agg, sig in prepared]
    folded = fold.live()
    one_launch = folded and hash_leg is None and fold.one_launch_live()
    roots = [s.signing_root for s, _, _ in entries]
    hashes = None
    if hash_leg is None and not one_launch:
        pipeline_async.sync_gap()
        hashes = _hash_roots(roots)
    coeffs = _coefficients(entries)
    neg_g1 = -cv.g1_generator()
    METRICS.inc_labeled("fold_enabled", "on" if folded else "off")
    if one_launch:
        # ONE launch per shard: hash cofactor sweep + G1 weighting +
        # G2 signature MSM + partial Miller products fused into a
        # single `ops.pairing_fold` dispatch (the pairs-axis all-reduce
        # and final exponentiation unchanged)
        METRICS.inc("dispatches")
        ok = fold.fold_flush(
            [agg for _s, agg, _sig in entries], coeffs, roots,
            [sig for _s, _agg, sig in entries])
    else:
        if folded:
            # N weightings instead of 2N: the signature legs need no
            # G1 weighting — their coefficients ride the G2 fold
            bases = [agg for _s, agg, _sig in entries]
            scalars = list(coeffs)
        else:
            bases, scalars = [], []
            for (_s, agg, _sig), c in zip(entries, coeffs):
                bases.extend((agg, neg_g1))
                scalars.extend((c, c))
        weighted_flat = _weighted_g1(bases, scalars)
        if folded:
            S = fold.fold_signatures(
                [sig for _s, _agg, sig in entries], coeffs)
        if hash_leg is not None:
            # join as late as the data flow allows: hash-to-G2 of every
            # strict root ran concurrently with prepare/aggregate/MSM
            # (and the G2 fold); a set `_prepare` screened out (bad
            # signature, cold decode failure) simply leaves its hash
            # unused — per-root outputs are independent, so the subset
            # is byte-identical to hashing only the surviving roots
            all_hashes = hash_leg.get()
            pos = {i: k for k, i in enumerate(strict)}
            hashes = [all_hashes[pos[i]] for i, _agg, _sig in prepared]
        if folded:
            pairs = [(weighted_flat[k], h)
                     for k, h in enumerate(hashes)]
            pairs.append((neg_g1, S))
        else:
            pairs = []
            for k, ((_s, _agg, sig), h) in enumerate(
                    zip(entries, hashes)):
                pairs.append((weighted_flat[2 * k], h))
                pairs.append((weighted_flat[2 * k + 1], sig))
        METRICS.observe("miller_loops_per_flush", len(pairs))
        METRICS.inc("dispatches")
        ok = _pairing_product(pairs)

    def group_valid(sub_groups):
        # bisection probe: re-derive each group's weighted pairs on the
        # HOST ladder, so invalid-set isolation never trusts a possibly
        # corrupt device sweep OR a corrupt folded MSM — a lying device
        # answer degrades to one failed product plus an oracle-weighted
        # re-check, not to wrong per-set verdicts.  Probes always carry
        # both legs per set (the folded product cannot attribute, so
        # isolation re-derives the unfolded algebra)
        METRICS.inc("dispatches")
        probe_pairs = []
        for agg, c, h, sig in sub_groups:
            probe_pairs.append((_host_scalar_mul(agg, c), h))
            probe_pairs.append((_host_scalar_mul(neg_g1, c), sig))
        return bls.pairing_check(probe_pairs)

    if ok:
        bad_local = set()
    else:
        METRICS.inc("fused_batch_failures")
        if hashes is None:
            # one-launch failure: the per-set hashes never existed on
            # the host — derive them now for the probes (the same
            # supervised hash seam the staged chain crosses)
            hashes = _hash_roots(roots)
        groups = [(agg, c, h, sig) for (_s, agg, sig), h, c in zip(
            entries, hashes, coeffs)]
        if len(groups) == 1:
            # isolate_failures condemns a singleton without re-probing
            # (its contract assumes the caller's failing check is
            # trusted) — but OUR failing product used device-weighted
            # points, so a one-set flush must re-check on the host
            # ladder or a corrupt sweep could flip the verdict
            bad_local = set() if group_valid(groups) else {0}
        else:
            bad_local = set(_bisect.isolate_failures(groups, group_valid))
    for rank, (i, _agg, _sig) in enumerate(prepared):
        verdicts[i] = rank not in bad_local


def _verify_per_set(indices, sets, verdicts):
    """Per-set verdicts through the shim's batch APIs (which screen empty
    lists and decode failures themselves — no preparation needed)."""
    singles = [i for i in indices if len(sets[i].pubkeys) == 1]
    multis = [i for i in indices if len(sets[i].pubkeys) != 1]
    if singles:
        METRICS.inc("dispatches")
        for i, v in zip(singles, bls.VerifyBatch(
                [sets[i].pubkeys[0] for i in singles],
                [sets[i].signing_root for i in singles],
                [sets[i].signature for i in singles])):
            verdicts[i] = bool(v)
    if multis:
        # the multi-pubkey leg: every job's committee sum rides the one
        # batched aggregation dispatch, and the batch API receives the
        # pre-aggregated point (the aggregate of one point is itself).
        # Jobs whose pubkeys fail decode keep their original list — the
        # batch API's own screening reads them as invalid — and so does
        # the identity aggregate (a pubkey list summing to infinity
        # must reach the scalar check undisturbed: compressed-infinity
        # pubkeys are rejected at decode, which a substitution would
        # wrongly trigger).
        aggs = AGGREGATES.aggregate_many(
            [(sets[i].pubkeys, sets[i].hint) for i in multis])
        pk_lists = [
            [agg] if agg is not None and not agg.is_infinity()
            else list(sets[i].pubkeys)
            for i, agg in zip(multis, aggs)]
        METRICS.inc("dispatches")
        for i, v in zip(multis, bls.FastAggregateVerifyBatch(
                pk_lists,
                [sets[i].signing_root for i in multis],
                [sets[i].signature for i in multis])):
            verdicts[i] = bool(v)


def _guard_verdicts(sets, verdicts, reason_for=None):
    """Differential guard (resilience/guard.py): cross-check a sample of
    batch verdicts against the scalar oracle; on mismatch the backend is
    quarantined and EVERY verdict is recomputed on the trusted path —
    silent corruption degrades to the oracle instead of deciding.
    `reason_for(i)` labels the fallback (and the quarantine) by the
    path that produced the MISMATCHING verdict: `fold_mismatch` for a
    folded fused leg, `guard_mismatch` otherwise — so incident streams
    attribute a folded-path trip precisely, and a corruption in an
    unrelated leg (a lax per-set batch of the same flush) never points
    operators at the fold."""
    from ..resilience import guard
    g = guard.active()
    if g is None:
        return verdicts
    mismatch = g.check(sets, list(range(len(sets))), verdicts,
                       reason_for=reason_for)
    if mismatch is None:
        return verdicts
    METRICS.inc_labeled("scalar_fallbacks", mismatch)
    return [guard.oracle_verdict(s) for s in sets]


def verify_sets(sets, mode: str = "fused"):
    """Verdict per SignatureSet.  `mode` is "fused" or "per-set"."""
    n = len(sets)
    if n == 0:
        return []       # an empty window is not a batch: no dispatch,
        # no stub counting, no occupancy sample
    METRICS.observe("batch_size", n)
    METRICS.observe_hist("batch_occupancy", n)
    METRICS.inc("signatures_scheduled", n)
    if not bls.bls_active:
        # stub-True contract, zero dispatches (matches the scalar API)
        METRICS.inc("stubbed_batches")
        return [True] * n
    verdicts: list = [None] * n
    guard_reason_for = None
    with METRICS.timer("verify_sets"):
        if mode == "per-set":
            _verify_per_set(list(range(n)), sets, verdicts)
        elif mode == "fused":
            if fold.live():
                # only the strict (required) sets ride the folded
                # product; lax sets take the per-set batch APIs, so a
                # mismatch there keeps the legacy label
                guard_reason_for = (
                    lambda i: "fold_mismatch" if sets[i].required
                    else "guard_mismatch")
            strict = [i for i, s in enumerate(sets) if s.required]
            lax = [i for i, s in enumerate(sets) if not s.required]
            hash_leg = None
            if strict and pipeline_async.overlap_live() \
                    and not fold.one_launch_live():
                # overlapped leg: hash-to-G2 needs only the signing
                # roots, so it launches BEFORE the G1 aggregation sweep
                # and runs concurrently with the whole prepare chain.
                # The one-launch folded path owns the cofactor sweep
                # inside its single fused program — nothing to overlap
                roots = [sets[i].signing_root for i in strict]
                hash_leg = pipeline_async.launch_leg(
                    lambda: _hash_roots(roots), "hash_to_g2")
            prepared = _prepare(strict, sets, verdicts)
            if prepared:
                _verify_fused(sets, prepared, verdicts, strict, hash_leg)
            elif hash_leg is not None:
                # every strict set screened out pre-pairing: drain the
                # leg so nothing is left in flight past this flush
                hash_leg.get()
            if lax:
                _verify_per_set(lax, sets, verdicts)
        else:
            raise ValueError(f"unknown sigpipe mode {mode!r}")
        verdicts = _guard_verdicts(sets, verdicts,
                                   reason_for=guard_reason_for)
    return verdicts
