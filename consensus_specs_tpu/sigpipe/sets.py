"""Signature-set collection: one block in, every signature check out.

A `SignatureSet` is one BLS verification job — (pubkeys, signing_root,
signature) plus the kind/origin of the spec operation it came from.  The
collectors walk a `SignedBeaconBlock` against the post-`process_slots`
pre-block state and emit the same checks the inline spec path performs,
site by site:

  proposer signature, randao reveal, each attestation's aggregate, both
  headers of each proposer slashing, both indexed attestations of each
  attester slashing, each voluntary exit, each new-pubkey deposit (the
  valid-or-skip check of phase0 `apply_deposit`), capella+'s
  bls_to_execution_changes, altair+'s sync aggregate, and eip7732's signed
  execution payload header + payload attestations.

Collection is read-only and *best-effort*: any operation whose inputs are
malformed (bad indices, failing pre-asserts) is skipped here — the inline
spec path raises its own exception before ever reaching the signature
check, so nothing is lost, and the scalar fallback at the verification
seam keeps behavior identical for any set we fail to predict.

Semantics mirrored precisely:

* deposits are `required=False` — the spec skips invalid deposit
  signatures instead of raising (phase0 `apply_deposit`); a deposit set
  is only emitted for pubkeys not already in the registry, and for EVERY
  such deposit in the block (an earlier invalid deposit of the same
  pubkey leaves the registry unchanged, so the inline path re-checks).
* altair's `eth_fast_aggregate_verify` returns True for an empty
  participant set with the infinity signature — no set is emitted.
* phase0's `is_valid_indexed_attestation` returns False for empty or
  unsorted indices without touching BLS — no set is emitted.
* whisk's proposer comes from the opened tracker, not the shuffle: the
  randao collector uses `block.proposer_index` there (the value the
  post-header inline check reads).  Whisk's shuffle / registration /
  opening proofs are *intentionally not collected*: they are
  curdleproofs-style arguments (crypto/whisk_proofs.py), not BLS
  (pubkeys, root, signature) triples, so they never reach the bls
  seams and cannot ride the pairing-product batch.  The per-fork audit
  (tests/test_sigpipe.py::test_whisk_block_pipeline) pins that a whisk
  block's *BLS* surface is fully collected — zero `collector_miss`
  fallbacks — with the proof checks running inline as before.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from ..ssz import uint64
from .metrics import METRICS


@dataclass(frozen=True)
class SignatureSet:
    pubkeys: tuple          # tuple of compressed 48-byte pubkeys
    signing_root: bytes
    signature: bytes
    kind: str               # "proposer" | "randao" | "attestation" | ...
    origin: tuple = ()      # e.g. ("attestation", 3)
    required: bool = True   # False: valid-or-skip (deposit semantics)
    hint: tuple = field(default=(), compare=False)  # aggregate-cache label

    def key(self):
        """Content identity — what the verification seam looks up."""
        return (self.pubkeys, self.signing_root, self.signature)


def _set(pubkeys, signing_root, signature, kind, origin=(),
         required=True, hint=()):
    return SignatureSet(
        pubkeys=tuple(bytes(pk) for pk in pubkeys),
        signing_root=bytes(signing_root), signature=bytes(signature),
        kind=kind, origin=tuple(origin), required=required, hint=hint)


def _guarded(out, kind, fn):
    """Run one collector; a failure means the inline path raises before
    its signature check, so skip the set and count it."""
    try:
        fn(out)
    except Exception:
        METRICS.inc("collect_skipped")
        METRICS.inc(f"collect_skipped_{kind}")


# -- per-operation collectors ----------------------------------------------

def _proposer(spec, state, signed_block, out):
    proposer = state.validators[signed_block.message.proposer_index]
    root = spec.compute_signing_root(
        signed_block.message,
        spec.get_domain(state, spec.DOMAIN_BEACON_PROPOSER))
    out.append(_set([proposer.pubkey], root, signed_block.signature,
                    "proposer"))


def _randao(spec, state, signed_block, out):
    body = signed_block.message.body
    epoch = spec.get_current_epoch(state)
    if spec.is_post("whisk"):
        # whisk replaces the computed proposer index with whoever opened
        # the tracker: get_beacon_proposer_index reads the block header,
        # which is not processed yet at collection time.  The inline
        # path verifies randao AFTER process_block_header pinned the
        # proposer to block.proposer_index, so that field is exactly the
        # index the scalar check will use.
        proposer_index = signed_block.message.proposer_index
    else:
        proposer_index = spec.get_beacon_proposer_index(state)
    proposer = state.validators[proposer_index]
    root = spec.compute_signing_root(
        uint64(epoch), spec.get_domain(state, spec.DOMAIN_RANDAO))
    out.append(_set([proposer.pubkey], root, body.randao_reveal, "randao"))


def indexed_attestation_parts(spec, state, indexed):
    """(indices, pubkeys, signing_root) that
    `is_valid_indexed_attestation` will feed into BLS, or None when the
    inline check returns False before touching BLS (empty or unsorted
    indices).  THE single mirror of that derivation — the block
    collector below and the gossip collector (gossip/collect.py) both
    ride it, so a fork that changes indexed-attestation validity only
    has one place to update."""
    indices = [int(i) for i in indexed.attesting_indices]
    if len(indices) == 0 or indices != sorted(set(indices)):
        return None
    pubkeys = [state.validators[i].pubkey for i in indices]
    domain = spec.get_domain(state, spec.DOMAIN_BEACON_ATTESTER,
                             indexed.data.target.epoch)
    root = spec.compute_signing_root(indexed.data, domain)
    return indices, pubkeys, root


def _indexed_attestation_set(spec, state, indexed, kind, origin):
    parts = indexed_attestation_parts(spec, state, indexed)
    if parts is None:
        return None     # inline is_valid_indexed_attestation: False, no BLS
    _indices, pubkeys, root = parts
    return _set(pubkeys, root, indexed.signature, kind, origin)


def _attestations(spec, state, body, out):
    for i, attestation in enumerate(body.attestations):
        def one(out, i=i, attestation=attestation):
            indexed = spec.get_indexed_attestation(state, attestation)
            s = _indexed_attestation_set(
                spec, state, indexed, "attestation", ("attestation", i))
            if s is not None:
                data = attestation.data
                out.append(_set(
                    s.pubkeys, s.signing_root, s.signature, s.kind,
                    s.origin,
                    hint=("att", int(data.target.epoch), int(data.index))))
        _guarded(out, "attestation", one)


def _proposer_slashings(spec, state, body, out):
    for i, slashing in enumerate(body.proposer_slashings):
        def one(out, i=i, slashing=slashing):
            proposer = state.validators[
                slashing.signed_header_1.message.proposer_index]
            for j, signed_header in enumerate(
                    (slashing.signed_header_1, slashing.signed_header_2)):
                domain = spec.get_domain(
                    state, spec.DOMAIN_BEACON_PROPOSER,
                    spec.compute_epoch_at_slot(signed_header.message.slot))
                root = spec.compute_signing_root(
                    signed_header.message, domain)
                out.append(_set([proposer.pubkey], root,
                                signed_header.signature,
                                "proposer_slashing",
                                ("proposer_slashing", i, j)))
        _guarded(out, "proposer_slashing", one)


def _attester_slashings(spec, state, body, out):
    for i, slashing in enumerate(body.attester_slashings):
        for j, indexed in enumerate((slashing.attestation_1,
                                     slashing.attestation_2)):
            def one(out, i=i, j=j, indexed=indexed):
                s = _indexed_attestation_set(
                    spec, state, indexed, "attester_slashing",
                    ("attester_slashing", i, j))
                if s is not None:
                    out.append(s)
            _guarded(out, "attester_slashing", one)


def _deposits(spec, state, body, out):
    if not len(body.deposits):
        return      # skip the O(registry) pubkey snapshot below
    registry = {bytes(v.pubkey) for v in state.validators}
    for i, deposit in enumerate(body.deposits):
        def one(out, i=i, deposit=deposit):
            pubkey = bytes(deposit.data.pubkey)
            if pubkey in registry:
                return      # top-up: the inline path never checks it
            message = spec.DepositMessage(
                pubkey=deposit.data.pubkey,
                withdrawal_credentials=deposit.data.withdrawal_credentials,
                amount=deposit.data.amount)
            domain = spec.compute_domain(spec.DOMAIN_DEPOSIT)
            root = spec.compute_signing_root(message, domain)
            out.append(_set([deposit.data.pubkey], root,
                            deposit.data.signature, "deposit",
                            ("deposit", i), required=False))
        _guarded(out, "deposit", one)


def _voluntary_exits(spec, state, body, out):
    for i, signed_exit in enumerate(body.voluntary_exits):
        def one(out, i=i, signed_exit=signed_exit):
            exit_msg = signed_exit.message
            validator = state.validators[exit_msg.validator_index]
            domain = spec.voluntary_exit_domain(state, exit_msg)
            root = spec.compute_signing_root(exit_msg, domain)
            out.append(_set([validator.pubkey], root,
                            signed_exit.signature, "voluntary_exit",
                            ("voluntary_exit", i)))
        _guarded(out, "voluntary_exit", one)


def _bls_changes(spec, state, body, out):
    for i, signed_change in enumerate(body.bls_to_execution_changes):
        def one(out, i=i, signed_change=signed_change):
            change = signed_change.message
            domain = spec.compute_domain(
                spec.DOMAIN_BLS_TO_EXECUTION_CHANGE,
                genesis_validators_root=state.genesis_validators_root)
            root = spec.compute_signing_root(change, domain)
            out.append(_set([change.from_bls_pubkey], root,
                            signed_change.signature,
                            "bls_to_execution_change",
                            ("bls_to_execution_change", i)))
        _guarded(out, "bls_to_execution_change", one)


def _sync_aggregate(spec, state, body, out):
    aggregate = body.sync_aggregate
    committee_pubkeys = state.current_sync_committee.pubkeys
    participants = [pk for pk, bit in zip(
        committee_pubkeys, aggregate.sync_committee_bits) if bit]
    signature = aggregate.sync_committee_signature
    if not participants and bytes(signature) == bytes(
            spec.G2_POINT_AT_INFINITY):
        return      # inline eth_fast_aggregate_verify: True, no BLS
    previous_slot = uint64(max(int(state.slot), 1) - 1)
    domain = spec.get_domain(state, spec.DOMAIN_SYNC_COMMITTEE,
                             spec.compute_epoch_at_slot(previous_slot))
    root = spec.compute_signing_root(
        spec.get_block_root_at_slot(state, previous_slot), domain)
    epoch = int(spec.get_current_epoch(state))
    period = epoch // int(spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD)
    out.append(_set(participants, root, signature, "sync_aggregate",
                    hint=("sync", period)))


def _payload_header(spec, state, body, out):
    signed_header = body.signed_execution_payload_header
    builder = state.validators[signed_header.message.builder_index]
    root = spec.compute_signing_root(
        signed_header.message,
        spec.get_domain(state, spec.DOMAIN_BEACON_BUILDER))
    out.append(_set([builder.pubkey], root, signed_header.signature,
                    "payload_header"))


def _payload_attestations(spec, state, body, out):
    for i, payload_attestation in enumerate(body.payload_attestations):
        def one(out, i=i, payload_attestation=payload_attestation):
            indexed = spec.get_indexed_payload_attestation(
                state, payload_attestation.data.slot, payload_attestation)
            indices = [int(x) for x in indexed.attesting_indices]
            if len(indices) == 0 or indices != sorted(set(indices)):
                return
            pubkeys = [state.validators[x].pubkey for x in indices]
            domain = spec.get_domain(state, spec.DOMAIN_PTC_ATTESTER, None)
            root = spec.compute_signing_root(indexed.data, domain)
            out.append(_set(pubkeys, root, indexed.signature,
                            "payload_attestation",
                            ("payload_attestation", i)))
        _guarded(out, "payload_attestation", one)


def collect_pending_deposit_sets(spec, state):
    """Every deposit signature check electra's `process_pending_deposits`
    MAY perform this epoch (EIP-6110: deposits are queued on-block and
    applied during epoch processing, outside the block window), as
    valid-or-skip SignatureSets — the spec skips an invalid pending
    deposit exactly like a block deposit.

    Only unknown-pubkey deposits reach `is_valid_deposit_signature` (a
    registered pubkey takes the top-up branch), and the loop stops at the
    first deposit past the finalized slot / eth1-bridge drain point / the
    per-epoch cap — all statically decidable here.  The churn-limit break
    depends on registry state mutated mid-loop, so collection
    over-approximates it: an unused verdict is one wasted pairing inside
    an already-batched dispatch, never a semantic difference.  A deposit
    whose pubkey an *earlier in-batch deposit* registers is collected too
    and simply never looked up.
    """
    out: list = []
    pending = getattr(state, "pending_deposits", None)
    if pending is None or not len(pending):
        return out
    registry = {bytes(v.pubkey) for v in state.validators}
    finalized_slot = spec.compute_start_slot_at_epoch(
        state.finalized_checkpoint.epoch)
    for i, deposit in enumerate(pending):
        if i >= int(spec.MAX_PENDING_DEPOSITS_PER_EPOCH):
            break
        if (deposit.slot > spec.GENESIS_SLOT
                and state.eth1_deposit_index
                < state.deposit_requests_start_index):
            break
        if deposit.slot > finalized_slot:
            break

        def one(out, i=i, deposit=deposit):
            if bytes(deposit.pubkey) in registry:
                return      # top-up: the inline path never checks it
            message = spec.DepositMessage(
                pubkey=deposit.pubkey,
                withdrawal_credentials=deposit.withdrawal_credentials,
                amount=deposit.amount)
            domain = spec.compute_domain(spec.DOMAIN_DEPOSIT)
            root = spec.compute_signing_root(message, domain)
            out.append(_set([deposit.pubkey], root, deposit.signature,
                            "pending_deposit", ("pending_deposit", i),
                            required=False))
        _guarded(out, "pending_deposit", one)
    METRICS.observe("pending_deposit_sets", len(out))
    return out


def collect_block_sets(spec, state, signed_block):
    """Every signature check `state_transition(state, signed_block)` will
    perform, as SignatureSets.  `state` must already be advanced to the
    block's slot (post-`process_slots`), exactly where the inline path
    verifies; collection never mutates it."""
    out: list = []
    body = signed_block.message.body
    _guarded(out, "proposer",
             lambda o: _proposer(spec, state, signed_block, o))
    _guarded(out, "randao",
             lambda o: _randao(spec, state, signed_block, o))
    if spec.is_post("eip7732"):
        _guarded(out, "payload_header",
                 lambda o: _payload_header(spec, state, body, o))
    _proposer_slashings(spec, state, body, out)
    _attester_slashings(spec, state, body, out)
    _attestations(spec, state, body, out)
    _deposits(spec, state, body, out)
    _voluntary_exits(spec, state, body, out)
    if spec.is_post("capella"):
        _bls_changes(spec, state, body, out)
    if spec.is_post("eip7732"):
        _payload_attestations(spec, state, body, out)
    if spec.is_post("altair"):
        _guarded(out, "sync_aggregate",
                 lambda o: _sync_aggregate(spec, state, body, o))
    METRICS.observe("sets_per_block", len(out))
    return out
