"""Async pipelined flush engine: overlapped verify dispatches and
double-buffered flush state.

A flush used to run strictly back-to-back on the calling thread:
collect -> G1 sweep -> hash-to-G2 -> MSM -> pairing product -> merkle
re-root, each stage waiting on the previous and the caller idle while
the device worked.  This module supplies the two overlap mechanisms the
scheduler and the gossip pipeline now ride:

* **flush double-buffering** (`submit` / :class:`FlushTicket`) — the
  whole batch-verify of flush N runs on ONE long-lived engine worker
  while the submitting thread goes on to host-side work: the gossip
  drainer collects and stages window N+1 (hash_tree_root digests,
  committee prediction, Fiat-Shamir transcripts) and delivers window
  N-1's handlers while N's device dispatches are in flight.  The ticket
  is the explicit join handle — `ticket.result()` is the ONLY way a
  verdict leaves the engine, so the join barrier is a visible call
  site, not an accident of data flow.
* **intra-flush legs** (`launch_leg` / :class:`Leg`) — the one verify
  dispatch with no data dependency on the G1 chain (the hash-to-G2
  cofactor sweep: it needs only the signing roots) launches on a leg
  worker concurrently with prepare + G1 aggregation + Fiat-Shamir
  derivation, and joins at the pairing-product assembly — the verdict
  join barrier (sigpipe/scheduler.py `_verify_fused`).

DRAIN SEMANTICS.  The engine adds NO new failure modes: every device
dispatch inside a submitted flush still crosses its own
`resilience.dispatch` seam, so a breaker trip, watchdog abandon, or
bisection probe inside an in-flight flush degrades on the worker
exactly as it would inline — the ticket then simply delivers the
byte-identical scalar-fallback verdicts.  A ticket the CALLER abandons
(`ticket.abandon()`, or a `result(timeout)` that expires) keeps running
on the worker but its outcome is discarded at the join and, from the
abandonment on, the flush may no longer write shared caches
(`writes_allowed` — sigpipe/cache.py consults it before every insert)
— the same purity discipline as the abandoned merkle sweep
(ssz/incremental.py `_commit`, pinned by test_merkle_inc.py).

SCOPE.  The engine is process-global and deliberately SYNCHRONOUS in
two situations: `ASYNC_FLUSH=0` (the escape hatch — every submit runs
inline on the caller, byte-identical by construction since the worker
would execute the very same closure), and whenever a TRANSIENT node
context is installed (utils/nodectx.py): the context stack is
process-global, so overlapping two simulated nodes' flushes would
interleave push/pop and mis-attribute exactly the incidents the
scenario tier asserts on — fleet simulations therefore run inline.  A
RESIDENT context (`nodectx.pin`, the real node process's one-process/
one-node wiring) is exempt: it sits at the base of the stack for the
process's whole lifetime, every worker thread resolves to the same
context with no push/pop to interleave, so the node process's device
verifies genuinely pipeline (the mesh PR lifted the old blanket
restriction; tests/test_node.py pins async-on/off byte parity of the
served roots).

Observability (sigpipe metrics): `async_flushes` / `inline_flushes`,
`flush_overlap_ns` (wall nanoseconds of worker device work that
overlapped caller-side host work), `device_idle_gaps` (host-sync
stalls between a flush's verify dispatches that the async path would
have overlapped — 0 on the async path, what `make pipeline-bench`
pins), `abandoned_flushes`, and the power-of-two `flush_inflight_depth`
histogram (tickets in flight at each submit).
"""
from __future__ import annotations

import os
import queue
import threading
import time

from ..utils import nodectx
from ..utils.locks import named_condition, named_lock
from .metrics import METRICS

# states a ticket moves through (monotonic)
PENDING = "pending"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
ABANDONED = "abandoned"

_FORCED: bool | None = None     # enable()/disable() override; None = env


def enabled() -> bool:
    """Whether flushes are submitted to the engine worker at all.
    `ASYNC_FLUSH=0` (or `off`) is the escape hatch; `enable()` /
    `disable()` override the environment for tests and benches."""
    if _FORCED is not None:
        return _FORCED
    return os.environ.get("ASYNC_FLUSH", "") not in ("0", "off")


def enable() -> None:
    global _FORCED
    _FORCED = True


def disable() -> None:
    global _FORCED
    _FORCED = False


def reset() -> None:
    """Back to the environment default (test teardown)."""
    global _FORCED
    _FORCED = None


def overlap_live() -> bool:
    """True when a submit would actually overlap: async on AND either
    no node context installed or the active context is process-RESIDENT
    (`nodectx.pin` — the real node process).  A transient context (a
    scenario SimNode's `use()` push) still forces inline: the stack is
    process-global, and overlapping two simulated nodes' flushes would
    interleave its push/pop and mis-attribute their records."""
    if not enabled():
        return False
    ctx = nodectx.current()
    return ctx is None or getattr(ctx, "resident", False)


class FlushTicket:
    """Join handle for one in-flight flush.  `result()` blocks for the
    outcome and re-raises nothing: a flush that failed (or that this
    caller abandoned) answers None, which every consumer already treats
    as "no batch verdicts — deliver scalar" (the degradation ladder).
    """

    __slots__ = ("label", "_done", "_state", "_value", "_error", "_lock",
                 "_overlapped", "_submitted_ns", "_started_ns",
                 "_finished_ns")

    def __init__(self, label: str):
        self.label = label
        self._done = threading.Event()
        self._state = PENDING
        self._value = None
        self._error = None
        self._lock = named_lock("sigpipe.ticket")
        self._overlapped = False    # ran on a worker (submit sets it)
        self._submitted_ns = time.perf_counter_ns()
        self._started_ns = None
        self._finished_ns = None

    # -- worker side ---------------------------------------------------
    def _start(self) -> None:
        with self._lock:
            if self._state == PENDING:
                self._state = RUNNING
            self._started_ns = time.perf_counter_ns()

    def _finish(self, value, error) -> None:
        with self._lock:
            self._finished_ns = time.perf_counter_ns()
            if self._state == ABANDONED:
                # late completion of an abandoned flush: the outcome is
                # dropped on the floor — never installed, never cached
                return
            self._value = value
            self._error = error
            self._state = FAILED if error is not None else DONE
        self._done.set()

    # -- caller side ---------------------------------------------------
    def done(self) -> bool:
        return self._done.is_set()

    def state(self) -> str:
        with self._lock:
            return self._state

    def abandoned(self) -> bool:
        with self._lock:
            return self._state == ABANDONED

    def abandon(self) -> None:
        """Give up on this flush: the worker keeps running it (an XLA
        dispatch cannot be cancelled) but its result is discarded and
        its remaining cache writes are suppressed (`writes_allowed`)."""
        with self._lock:
            if self._state in (DONE, FAILED):
                return
            self._state = ABANDONED
        METRICS.inc("abandoned_flushes")
        self._done.set()    # wake any joiner: the answer is None now

    def result(self, timeout: float | None = None):
        """THE join barrier.  Returns the flush's value, or None when
        the flush failed, was abandoned, or `timeout` expired (the
        ticket is then abandoned — late completion is discarded)."""
        if not self._done.wait(timeout):
            self.abandon()
            return None
        join_ns = time.perf_counter_ns()
        with self._lock:
            if self._state != DONE:
                if self._error is not None:
                    METRICS.inc("pipeline_errors")
                return None
            # overlap = worker wall time that ran while the caller was
            # away doing host work (clamped to the submit->join window).
            # Inline flushes record nothing: a wall-clock sample in the
            # per-node counters would break the scenario tier's
            # bit-identical (scenario, seed) replay fingerprint
            if self._overlapped and self._started_ns is not None and \
                    self._finished_ns is not None:
                overlap = min(self._finished_ns, join_ns) \
                    - max(self._started_ns, self._submitted_ns)
                if overlap > 0:
                    METRICS.inc("flush_overlap_ns", overlap)
            return self._value


# speclint: disable=global-mutable-state -- thread-local slot carrying
# the worker's OWN in-flight ticket; by construction never shared
# between threads, so fleet isolation cannot be breached through it
_TL = threading.local()         # .ticket — set on engine/leg workers


def current_ticket() -> FlushTicket | None:
    """The ticket the CURRENT thread is executing (engine/leg workers
    only; None on ordinary threads)."""
    return getattr(_TL, "ticket", None)


def writes_allowed() -> bool:
    """Whether flush-side shared-cache writes may proceed: False only
    on a worker whose ticket the caller has abandoned — from the
    watchdog deadline on, a zombie flush must leave no trace
    (sigpipe/cache.py consults this before every insert)."""
    t = current_ticket()
    return t is None or not t.abandoned()


def bind_current_ticket(fn):
    """Wrap `fn` to execute under the CALLING thread's in-flight ticket
    (identity when there is none).  The resilience supervisor's
    watchdog runs dispatches on per-site worker threads
    (supervisor._SiteWorker) — a plain thread-local would lose the
    flush identity across that hop and an abandoned flush could write
    caches again from the site worker, so the supervisor binds every
    watchdog'd device fn through this before the hand-off."""
    ticket = current_ticket()
    if ticket is None:
        return fn

    def bound():
        prev = getattr(_TL, "ticket", None)
        _TL.ticket = ticket
        try:
            return fn()
        finally:
            _TL.ticket = prev
    return bound


class _Worker:
    """One long-lived daemon worker draining a FIFO queue of (ticket,
    fn) jobs.  FIFO is the determinism contract: tickets complete in
    submit order, so a seeded run's flushes verify in the same order
    the sync path would have."""

    def __init__(self, name: str):
        self._jobs: queue.Queue = queue.Queue()
        self._pending = 0               # queued + running jobs
        self._cv = named_condition("sigpipe.worker_cv")
        self._thread = threading.Thread(
            target=self._loop, name=name, daemon=True)
        self._thread.start()

    def depth(self) -> int:
        with self._cv:
            return self._pending

    def put(self, ticket: FlushTicket, fn) -> None:
        with self._cv:
            self._pending += 1
        self._jobs.put((ticket, fn))

    def join_idle(self, timeout: float) -> bool:
        with self._cv:
            return self._cv.wait_for(lambda: self._pending == 0, timeout)

    def _loop(self) -> None:
        while True:
            ticket, fn = self._jobs.get()
            ticket._start()
            _TL.ticket = ticket
            try:
                ticket._finish(fn(), None)
            except Exception as e:          # shipped across the join
                ticket._finish(None, e)
            except BaseException as e:      # KeyboardInterrupt/SystemExit:
                # finish the ticket so joiners never hang, then let the
                # interrupt kill this thread (never silently convert it
                # into a scalar-fallback window); _worker() respawns
                ticket._finish(None, e)
                raise
            finally:
                _TL.ticket = None
                with self._cv:
                    self._pending -= 1
                    self._cv.notify_all()


# the flush worker (double-buffering) and the leg worker (intra-flush
# dispatch overlap) are separate on purpose: a flush RUNNING on the
# flush worker launches its hash leg on the leg worker, so one thread
# for both would deadlock the leg behind its own flush
_ENGINE_LOCK = named_lock("sigpipe.engine")
_FLUSH_WORKER: _Worker | None = None
_LEG_WORKER: _Worker | None = None


def _worker(which: str) -> _Worker:
    global _FLUSH_WORKER, _LEG_WORKER
    with _ENGINE_LOCK:
        if which == "flush":
            if _FLUSH_WORKER is None or \
                    not _FLUSH_WORKER._thread.is_alive():
                _FLUSH_WORKER = _Worker("sigpipe-flush-engine")
            return _FLUSH_WORKER
        if _LEG_WORKER is None or not _LEG_WORKER._thread.is_alive():
            _LEG_WORKER = _Worker("sigpipe-flush-leg")
        return _LEG_WORKER


def submit(fn, label: str = "flush") -> FlushTicket:
    """Submit one flush's batch-verify closure; returns its ticket.
    Inline (executed on the caller before returning, ticket already
    done) when overlap is off — byte-identical by construction: the
    worker would run the exact same closure."""
    ticket = FlushTicket(label)
    if not overlap_live():
        METRICS.inc("inline_flushes")
        ticket._start()
        try:
            ticket._finish(fn(), None)
        except Exception as e:
            # Exception only: a Ctrl-C mid-flush must propagate exactly
            # as the pre-engine direct call would have, not degrade the
            # window to scalar delivery
            ticket._finish(None, e)
        return ticket
    worker = _worker("flush")
    ticket._overlapped = True
    METRICS.inc("async_flushes")
    METRICS.observe_hist("flush_inflight_depth", worker.depth() + 1)
    worker.put(ticket, fn)
    return ticket


class Leg:
    """Join handle for one intra-flush dispatch leg.  Unlike a ticket,
    `get()` RE-RAISES the leg's exception: a leg stands in for an
    inline call (the scheduler's hash-to-G2 dispatch), so its errors
    must surface at the join with the same types the inline call would
    have raised there."""

    __slots__ = ("_ticket",)

    def __init__(self, ticket: FlushTicket):
        self._ticket = ticket

    def get(self):
        self._ticket._done.wait()
        with self._ticket._lock:
            if self._ticket._error is not None:
                raise self._ticket._error
            return self._ticket._value


def launch_leg(fn, label: str) -> Leg:
    """Run `fn` on the leg worker concurrently with the caller's own
    dispatch chain; join with `Leg.get()` at the verdict barrier.
    Inline when overlap is off."""
    ticket = FlushTicket(label)
    if not overlap_live():
        ticket._start()
        try:
            ticket._finish(fn(), None)
        except Exception as e:      # Leg.get() re-raises at the join
            ticket._finish(None, e)
        return Leg(ticket)
    _worker("leg").put(ticket, fn)
    return Leg(ticket)


def sync_gap() -> None:
    """Record one host-sync stall between a flush's verify dispatches —
    a point where the caller blocked on a device result that the async
    path overlaps instead.  The pipeline bench pins this at 0 with the
    engine on."""
    METRICS.inc("device_idle_gaps")


def drain(timeout: float = 30.0) -> bool:
    """Block until every submitted flush and leg has completed (the
    breaker-trip / shutdown discipline: nothing may still be in flight
    when the caller re-reads shared state).  Returns False on timeout.
    """
    deadline = time.perf_counter() + timeout
    with _ENGINE_LOCK:
        # snapshot under the engine lock: _worker() may be respawning a
        # dead worker concurrently, and a torn read here would join an
        # orphaned instance while jobs land on its replacement
        workers = (_FLUSH_WORKER, _LEG_WORKER)
    for w in workers:
        if w is None:
            continue
        if not w.join_idle(max(deadline - time.perf_counter(), 0.0)):
            return False
    return True
