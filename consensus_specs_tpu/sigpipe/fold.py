"""G2-leg folding: every signature leg of a fused flush as ONE pair.

The fused product (scheduler.py) historically paid TWO Miller loops per
signature set — e(c_i * agg_i, H(root_i)) * e(-c_i * g1, sig_i) — even
though the second legs all share the base point -g1 and therefore fold
algebraically to a single pair:

    prod_i e(-c_i * g1, sig_i)  ==  e(-g1, S),   S = sum_i c_i * sig_i

(bilinearity moves the Fiat-Shamir coefficient from the G1 side onto
the signature, and the shared-base pairs collapse through the G2
multi-scalar sum).  An N-set flush therefore needs N+1 Miller loops
instead of 2N — the counted `miller_loops_per_flush` invariant — and
the win composes multiplicatively with mesh sharding: each device's
slice of the pairs axis halves too.

This module owns the ``ops.pairing_fold`` resilience seam, with the
standard breaker -> bisect -> scalar-fallback contract:

* :func:`fold_signatures` — S via a batched device G2 MSM
  (ops/msm.g2_multi_exp, its 64-bit ladder axis mesh-sharded), the
  vectorized host oracle standing in on CPU hosts (the
  g1_sweep.G1_SWEEP_MODE platform split); the supervised fallback is
  the per-set host ladder with every point op counted in
  `host_point_adds`.
* :func:`fold_kzg_lincombs` — the KZG batch verifier's three
  shared-base G1 lincombs (crypto/kzg.verify_kzg_proof_batch) as one
  dispatch on the same seam: the RLC batch is the same algebra — N
  untrusted points under Fiat-Shamir weights folding to a 2-leg
  pairing — so it shares the breaker, the bisect policy, and the
  counted host-ladder fallback.
* :func:`fold_flush` — the ONE-LAUNCH path (tpu backend, fused pairing
  mode): hash-to-G2's cofactor sweep, the Fiat-Shamir G1 weighting, the
  G2 signature MSM and the per-shard partial Miller product all fused
  into one compiled program per mesh device
  (parallel/shard_verify.pairing_fold -> ops/pairing_jax
  fold_partial_products), so an entire flush is literally one launch
  per shard plus the unchanged log2(D) Fp12 all-reduce.  The
  supervised fallback derives the same N+1-leg product entirely on the
  host oracle, byte-identical verdict.

Bisection and fallback semantics are untouched: probes re-derive every
weighted pair on the HOST ladder (scheduler.group_valid), so a lying
fold — a corrupt S, a garbage fused program — degrades to one failed
product plus an oracle-weighted re-check, never to a flipped per-set
verdict.  The accept direction (a corruption that makes the product
vacuously pass) stays the differential guard's case, now labeled
`fold_mismatch` so folded-path trips are distinguishable in incident
streams.

``FOLD_VERIFY=0`` (or ``off``) is the escape hatch: the scheduler then
emits today's 2N-leg flush byte-for-byte.  Resolved LAZILY like
MSM_MODE / G1_SWEEP_MODE: the env var is read at first use, direct
assignment wins, and reset_mode() forgets a cached choice.
"""
from __future__ import annotations

import os as _os

from ..crypto import curve as cv
from .metrics import METRICS

FOLD_MODE = None        # None = unresolved; "on" | "off" once resolved


def reset_mode() -> None:
    """Forget the cached folding choice: the next flush re-reads the
    FOLD_VERIFY env var."""
    global FOLD_MODE
    FOLD_MODE = None


def _resolve_mode() -> str:
    global FOLD_MODE
    if FOLD_MODE is None:
        FOLD_MODE = ("off"
                     if _os.environ.get("FOLD_VERIFY", "") in ("0", "off")
                     else "on")
    return FOLD_MODE


def live() -> bool:
    """Whether the scheduler's fused flush folds its signature legs."""
    return _resolve_mode() == "on"


def one_launch_live() -> bool:
    """Whether the WHOLE folded flush rides one compiled program per
    mesh device: folding on, device pairing kernels active (tpu
    backend) and the fused single-program pairing mode resolved — on
    CPU hosts the staged kernels win and the folded flush runs its
    staged chain instead (hash sweep + weighting MSM + G2 fold + shard
    product), byte-identical verdicts either way."""
    if not live():
        return False
    from ..utils import bls
    if bls.current_backend() != "tpu":
        return False
    from ..ops import pairing_jax as pj
    return pj._resolve_mode() == "fused"


def _host_ladder_mul(point, c):
    """Host double-and-add with its point-op cost counted — the per-set
    arithmetic the folded device MSM exists to eliminate."""
    c = int(c)
    METRICS.inc("host_point_adds",
                max(c.bit_length(), 1) + bin(c).count("1"))
    return point * c


def _host_fold(sigs, coeffs):
    """The supervised fallback: per-set host ladder + running sum, every
    point op counted in `host_point_adds` (the degradation the metric
    makes visible)."""
    acc = cv.g2_infinity()
    for sig, c in zip(sigs, coeffs):
        acc = acc + _host_ladder_mul(sig, c)
    if sigs:
        METRICS.inc("host_point_adds", len(sigs))
    return acc


def _fold_sweep(sigs, coeffs):
    """The device fn of the staged fold: engine-split like the G1
    sweeps (g1_sweep.G1_SWEEP_MODE — jax limb kernels off-CPU with the
    ladder axis mesh-sharded, one vectorized host-oracle call on CPU
    hosts), so the call shape the scheduler sees is always one batched
    invocation per flush."""
    from ..ops.g1_sweep import _resolve_mode as _sweep_mode
    if _sweep_mode() == "jax":
        from ..ops import msm as _msm
        return _msm.g2_multi_exp(sigs, coeffs, label="ops.pairing_fold")
    acc = cv.g2_infinity()
    for sig, c in zip(sigs, coeffs):
        acc = acc + sig * int(c)
    return acc


def fold_signatures(sigs, coeffs):
    """All signature legs of a flush folded to ONE aggregate G2 point
    S = sum_i c_i * sig_i, behind the `ops.pairing_fold` seam (one
    dispatch per flush; the per-set host ladder as counted
    byte-identical fallback)."""
    from ..resilience.supervisor import dispatch
    METRICS.inc("fold_dispatches")
    return dispatch(
        "ops.pairing_fold",
        lambda: _fold_sweep(sigs, coeffs),
        lambda: _host_fold(sigs, coeffs))


def _host_kzg_lincombs(proof_points, c_minus_ys, r_powers, r_times_z):
    """The supervised fallback for the KZG fold: each lincomb on the
    per-point host ladder, every point op counted in
    `host_point_adds` — the same visible degradation `_host_fold`
    prices for signature legs."""
    def lincomb(points, coeffs):
        acc = cv.g1_infinity()
        for point, c in zip(points, coeffs):
            acc = acc + _host_ladder_mul(point, c)
        if points:
            METRICS.inc("host_point_adds", len(points))
        return acc
    return (lincomb(proof_points, r_powers),
            lincomb(proof_points, r_times_z),
            lincomb(c_minus_ys, r_powers))


def _kzg_lincombs_sweep(proof_points, c_minus_ys, r_powers, r_times_z):
    """Device fn of the KZG fold: the three lincombs as batched G1
    MSMs (ops/msm.g1_multi_exp) when the limb kernels are live, the
    vectorized host oracle on CPU hosts — the same engine split as
    `_fold_sweep`."""
    from ..ops.g1_sweep import _resolve_mode as _sweep_mode
    if _sweep_mode() == "jax":
        from ..ops import msm as _msm
        return (_msm.g1_multi_exp(proof_points, r_powers),
                _msm.g1_multi_exp(proof_points, r_times_z),
                _msm.g1_multi_exp(c_minus_ys, r_powers))
    from ..crypto.curve import msm as _host_msm
    return (_host_msm(proof_points, r_powers),
            _host_msm(proof_points, r_times_z),
            _host_msm(c_minus_ys, r_powers))


def fold_kzg_lincombs(proof_points, c_minus_ys, r_powers, r_times_z):
    """The KZG batch verifier's three shared-base G1 lincombs —
    sum r_i * proof_i, sum (r_i z_i) * proof_i, sum r_i * (C_i - y_i)
    — as ONE supervised `ops.pairing_fold` dispatch, the exact
    shared-base shape the signature fold rides: N untrusted points
    weighted by Fiat-Shamir coefficients collapsing to the two legs
    of one pairing.  Returns (proof_lincomb, proof_z_lincomb,
    c_minus_y_lincomb); the counted host ladder is the byte-identical
    fallback."""
    from ..resilience.supervisor import dispatch
    METRICS.inc("fold_dispatches")
    return dispatch(
        "ops.pairing_fold",
        lambda: _kzg_lincombs_sweep(proof_points, c_minus_ys,
                                    r_powers, r_times_z),
        lambda: _host_kzg_lincombs(proof_points, c_minus_ys,
                                   r_powers, r_times_z))


def _host_fold_flush(aggs, coeffs, roots, sigs) -> bool:
    """The one-launch path's supervised fallback: the identical
    N+1-leg folded product derived entirely on the host oracle —
    hash-to-G2, Fiat-Shamir weighting and the G2 fold on host ints,
    one native pairing check."""
    from ..crypto import bls12_381 as native
    from ..crypto.hash_to_curve import hash_to_g2
    hashes = [hash_to_g2(bytes(r)) for r in roots]
    S = _host_fold(sigs, coeffs)
    pairs = [(_host_ladder_mul(agg, c), h)
             for agg, c, h in zip(aggs, coeffs, hashes)]
    pairs.append((-cv.g1_generator(), S))
    return native.pairing_check(pairs)


def fold_flush(aggs, coeffs, roots, sigs) -> bool:
    """THE one-launch folded flush: one `ops.pairing_fold` dispatch
    whose device fn runs one compiled program per mesh shard — cofactor
    sweep + G1 weighting + local G2 MSM + partial Miller product —
    followed by the unchanged log2(D) Fp12 all-reduce and one final
    exponentiation (parallel/shard_verify.pairing_fold).  Returns the
    product verdict; on any failure the supervisor degrades to the
    byte-identical host folded derivation."""
    from ..resilience.supervisor import dispatch
    METRICS.inc("fold_dispatches")
    used_fallback = False

    def device():
        from ..parallel import shard_verify
        return shard_verify.pairing_fold(aggs, coeffs, roots, sigs)

    def host():
        nonlocal used_fallback
        used_fallback = True
        return _host_fold_flush(aggs, coeffs, roots, sigs)

    ok = bool(dispatch("ops.pairing_fold", device, host))
    # observed HERE, once per flush, for the path that actually decided
    # it — observing inside the supervised fns would double-count a
    # watchdog-abandoned dispatch plus its fallback.  The host
    # derivation assembles N+1 legs; the device program pays one local
    # S_d leg per shard (N+D — N+1 at width 1)
    if used_fallback:
        legs = len(aggs) + 1
    else:
        from ..parallel import shard_verify
        legs = len(aggs) + (shard_verify.mesh_devices()
                            if shard_verify.get_mesh() is not None else 1)
    METRICS.observe("miller_loops_per_flush", legs)
    return ok
