"""Pubkey caches for the signature pipeline.

Two layers, both bounded (FIFO eviction, same discipline as the spec's
shuffle-permutation LRU):

* `PubkeyCache` — compressed 48-byte pubkey -> validated decompressed G1
  Point.  Decompression + subgroup check is the per-key host cost of every
  verification; real clients cache it across blocks, so do we.
* `AggregatePubkeyCache` — participant-set digest -> aggregated G1 Point.
  The committee/sync-aggregate G1 sums are O(committee) point adds per set;
  re-verifying the same participant set (oracle cross-checks, repeated
  dispatch of one block, fork-choice replays) hits the cache instead.
  Entries carry a human-readable hint like ``("att", epoch,
  committee_index)`` for debugging, but the KEY is a content digest of the
  participant pubkeys — a label collision can therefore never return the
  wrong aggregate.

Hit/miss counters land in sigpipe.metrics.METRICS.

Both caches are thread-safe (one lock each around lookup/insert/evict):
the supervisor's watchdog runs dispatches on worker threads, and the
gossip-path follow-up (ROADMAP) will share these caches across
verification threads.  Point decompression runs OUTSIDE the lock — it is
the expensive part and needs no cache state.
"""
from __future__ import annotations

import hashlib
import threading

from ..crypto import curve as cv
from ..crypto.bls12_381 import _load_pubkey
from .metrics import METRICS


class PubkeyCache:
    def __init__(self, max_size: int = 1 << 16, metrics=METRICS):
        self._cache: dict = {}
        self._max = max_size
        self._metrics = metrics
        self._lock = threading.RLock()

    def get(self, pubkey) -> cv.Point:
        """Decompressed, validated G1 point for compressed bytes; raises
        DecodeError/ValueError exactly like the scalar `_load_pubkey`."""
        key = bytes(pubkey)
        with self._lock:
            point = self._cache.get(key)
        if point is not None:
            self._metrics.inc("pubkey_cache_hits")
            return point
        self._metrics.inc("pubkey_cache_misses")
        point = _load_pubkey(key)   # DecodeError / ValueError propagate
        with self._lock:
            if len(self._cache) >= self._max:
                self._cache.pop(next(iter(self._cache)))
            self._cache[key] = point
        return point

    def clear(self) -> None:
        with self._lock:
            self._cache.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._cache)


class AggregatePubkeyCache:
    def __init__(self, pubkeys: PubkeyCache, max_size: int = 1 << 12,
                 metrics=METRICS):
        self._pubkeys = pubkeys
        self._cache: dict = {}
        self._max = max_size
        self._metrics = metrics
        self._lock = threading.RLock()
        self._track_stack: list = []    # open insert-tracking scopes

    # -- insert tracking (txn/ rollback invalidation) -------------------
    # A transaction that rolls back must be able to evict exactly the
    # aggregates it inserted (prewarms and verification-miss inserts):
    # a rolled-back block's participant sets would otherwise linger in
    # the cache as warm state the store never accepted.  Content
    # addressing keeps them CORRECT, but crash-only discipline says a
    # rolled-back operation leaves no trace.

    def begin_track(self) -> set:
        """Start recording digests inserted from now on; returns the
        live set (hand it to `evict` on rollback, `end_track` always)."""
        tracked: set = set()
        with self._lock:
            self._track_stack.append(tracked)
        return tracked

    def end_track(self, tracked: set) -> None:
        with self._lock:
            self._track_stack = [t for t in self._track_stack
                                 if t is not tracked]

    def evict(self, digests) -> int:
        """Drop the given digests; returns how many were present."""
        with self._lock:
            evicted = sum(1 for d in digests
                          if self._cache.pop(d, None) is not None)
        if evicted:
            self._metrics.inc("aggregate_cache_evictions", evicted)
        return evicted

    @staticmethod
    def _digest(pubkey_bytes_list) -> bytes:
        return hashlib.sha256(
            b"".join(bytes(pk) for pk in pubkey_bytes_list)).digest()

    def aggregate(self, pubkey_bytes_list, hint=None) -> cv.Point:
        """Sum of the (decompressed) pubkeys; cached by content digest."""
        digest = self._digest(pubkey_bytes_list)
        with self._lock:
            entry = self._cache.get(digest)
        if entry is not None:
            self._metrics.inc("aggregate_cache_hits")
            return entry[0]
        self._metrics.inc("aggregate_cache_misses")
        agg = self._compute_and_insert(digest, pubkey_bytes_list, hint)
        return agg

    def warm(self, pubkey_bytes_list, hint=None) -> bool:
        """Pre-compute an aggregate OUTSIDE a verification (the
        fork-choice on_block pre-warm, gossip/prewarm.py): inserts like
        `aggregate` but counts `aggregate_cache_prewarms` instead of a
        hit or a miss, so warm-up work never distorts the hit rate the
        dashboards track.  Returns True when the entry was actually cold
        (work done), False when it was already cached."""
        digest = self._digest(pubkey_bytes_list)
        with self._lock:
            if digest in self._cache:
                return False
        self._metrics.inc("aggregate_cache_prewarms")
        self._compute_and_insert(digest, pubkey_bytes_list, hint)
        return True

    def _compute_and_insert(self, digest, pubkey_bytes_list,
                            hint) -> cv.Point:
        agg = cv.g1_infinity()
        for pk in pubkey_bytes_list:
            agg = agg + self._pubkeys.get(pk)
        with self._lock:
            if len(self._cache) >= self._max:
                self._cache.pop(next(iter(self._cache)))
            self._cache[digest] = (agg, hint)
            for tracked in self._track_stack:
                tracked.add(digest)
        return agg

    def clear(self) -> None:
        with self._lock:
            self._cache.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._cache)


PUBKEYS = PubkeyCache()
AGGREGATES = AggregatePubkeyCache(PUBKEYS)


def clear() -> None:
    PUBKEYS.clear()
    AGGREGATES.clear()
