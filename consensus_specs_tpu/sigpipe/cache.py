"""Pubkey caches for the signature pipeline.

Two layers, both bounded (FIFO eviction, same discipline as the spec's
shuffle-permutation LRU):

* `PubkeyCache` — compressed 48-byte pubkey -> validated decompressed G1
  Point.  Decompression + subgroup check is the per-key host cost of every
  verification; real clients cache it across blocks, so do we.
* `AggregatePubkeyCache` — participant-set digest -> aggregated G1 Point.
  The committee/sync-aggregate G1 sums are O(committee) point adds per set;
  re-verifying the same participant set (oracle cross-checks, repeated
  dispatch of one block, fork-choice replays) hits the cache instead.
  Entries carry a human-readable hint like ``("att", epoch,
  committee_index)`` for debugging, but the KEY is a content digest of the
  participant pubkeys — a label collision can therefore never return the
  wrong aggregate.

COLD sums run on the accelerator, not the host: every compute path
(`aggregate`, `aggregate_many`, `warm_many`) funnels into
`_sum_batch`, which fuses all cold sets of a call into ONE
`ops/g1_sweep.g1_add_sweep` ragged-segment reduction behind the
`ops.g1_aggregate` resilience dispatch seam — the scheduler's flush and
the gossip prewarm therefore cost one batched dispatch each instead of
O(sets x committee) Python point adds.  The supervised fallback is the
byte-identical per-set host loop, and every add it performs lands in
the `host_point_adds` counter (the number the device offload exists to
drive to ~0); `g1_aggregate_dispatches` counts the batched calls.

Hit/miss counters land in sigpipe.metrics.METRICS.

Both caches are thread-safe (one lock each around lookup/insert/evict):
the supervisor's watchdog runs dispatches on worker threads, and the
gossip-path follow-up (ROADMAP) will share these caches across
verification threads.  Point decompression runs OUTSIDE the lock — it is
the expensive part and needs no cache state.
"""
from __future__ import annotations

import hashlib

from ..crypto import curve as cv
from ..crypto.bls12_381 import _load_pubkey
from ..crypto.curve import DecodeError
from ..utils.locks import named_rlock
from . import pipeline_async
from .metrics import METRICS


class PubkeyCache:
    def __init__(self, max_size: int = 1 << 16, metrics=METRICS):
        self._cache: dict = {}
        self._max = max_size
        self._metrics = metrics
        self._lock = named_rlock("sigpipe.pubkey_cache")

    def get(self, pubkey) -> cv.Point:
        """Decompressed, validated G1 point for compressed bytes; raises
        DecodeError/ValueError exactly like the scalar `_load_pubkey`."""
        key = bytes(pubkey)
        with self._lock:
            point = self._cache.get(key)
        if point is not None:
            self._metrics.inc("pubkey_cache_hits")
            return point
        self._metrics.inc("pubkey_cache_misses")
        point = _load_pubkey(key)   # DecodeError / ValueError propagate
        if not pipeline_async.writes_allowed():
            return point    # abandoned in-flight flush: leave no trace
        with self._lock:
            if len(self._cache) >= self._max:
                self._cache.pop(next(iter(self._cache)))
            self._cache[key] = point
        return point

    def clear(self) -> None:
        with self._lock:
            self._cache.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._cache)


class AggregatePubkeyCache:
    def __init__(self, pubkeys: PubkeyCache, max_size: int = 1 << 12,
                 metrics=METRICS):
        self._pubkeys = pubkeys
        self._cache: dict = {}
        self._max = max_size
        self._metrics = metrics
        self._lock = named_rlock("sigpipe.aggregate_cache")
        self._track_stack: list = []    # open insert-tracking scopes

    # -- insert tracking (txn/ rollback invalidation) -------------------
    # A transaction that rolls back must be able to evict exactly the
    # aggregates it inserted (prewarms and verification-miss inserts):
    # a rolled-back block's participant sets would otherwise linger in
    # the cache as warm state the store never accepted.  Content
    # addressing keeps them CORRECT, but crash-only discipline says a
    # rolled-back operation leaves no trace.

    def begin_track(self) -> set:
        """Start recording digests inserted from now on; returns the
        live set (hand it to `evict` on rollback, `end_track` always)."""
        tracked: set = set()
        with self._lock:
            self._track_stack.append(tracked)
        return tracked

    def end_track(self, tracked: set) -> None:
        with self._lock:
            self._track_stack = [t for t in self._track_stack
                                 if t is not tracked]

    def evict(self, digests) -> int:
        """Drop the given digests; returns how many were present."""
        with self._lock:
            evicted = sum(1 for d in digests
                          if self._cache.pop(d, None) is not None)
        if evicted:
            self._metrics.inc("aggregate_cache_evictions", evicted)
        return evicted

    @staticmethod
    def _digest(pubkey_bytes_list) -> bytes:
        return hashlib.sha256(
            b"".join(bytes(pk) for pk in pubkey_bytes_list)).digest()

    def aggregate(self, pubkey_bytes_list, hint=None) -> cv.Point:
        """Sum of the (decompressed) pubkeys; cached by content digest."""
        digest = self._digest(pubkey_bytes_list)
        with self._lock:
            entry = self._cache.get(digest)
        if entry is not None:
            self._metrics.inc("aggregate_cache_hits")
            return entry[0]
        self._metrics.inc("aggregate_cache_misses")
        agg = self._compute_and_insert(digest, pubkey_bytes_list, hint)
        return agg

    def _collect_cold(self, jobs, hit_counter, miss_counter):
        """Shared cold-collection for the batch entry points: digest
        each (pubkey_bytes_list, hint) job, count cache hits/misses
        under the given metric names (None skips the count), decode the
        cold sets — a job whose pubkeys fail decode is dropped, the
        per-job stand-in for the scalar path's DecodeError/ValueError —
        and dedup by content digest within the call.  Returns
        (hits: job index -> cached Point,
         cold: digest -> (decompressed points, hint),
         slots: digest -> job indices awaiting that cold sum)."""
        hits: dict = {}
        cold: dict = {}
        slots: dict = {}
        for k, (pks, hint) in enumerate(jobs):
            digest = self._digest(pks)
            with self._lock:
                entry = self._cache.get(digest)
            if entry is not None:
                if hit_counter:
                    self._metrics.inc(hit_counter)
                hits[k] = entry[0]
                continue
            if digest in cold:
                # an intra-call duplicate reads as a HIT, matching the
                # sequential scalar path (first call misses and
                # computes, the second hits the fresh entry)
                if hit_counter:
                    self._metrics.inc(hit_counter)
                slots[digest].append(k)
                continue
            if miss_counter:
                self._metrics.inc(miss_counter)
            try:
                pts = [self._pubkeys.get(pk) for pk in pks]
            except (DecodeError, ValueError):
                continue
            cold[digest] = (pts, hint)
            slots[digest] = [k]
        return hits, cold, slots

    def _sum_and_insert(self, cold) -> list:
        """ONE batched `_sum_batch` dispatch over every cold set, each
        sum inserted under its digest; returns the sums in `cold`
        iteration order."""
        digests = list(cold)
        sums = self._sum_batch([cold[d][0] for d in digests])
        for digest, agg in zip(digests, sums):
            self._insert(digest, agg, cold[digest][1])
        return sums

    def aggregate_many(self, jobs) -> list:
        """Batch form of `aggregate` for a whole scheduler flush: `jobs`
        is a list of (pubkey_bytes_list, hint) pairs; returns one
        aggregated Point per job, or None where a pubkey failed
        decode/validation (the per-job stand-in for the scalar path's
        DecodeError/ValueError).  Hits come straight from the cache; ALL
        cold jobs' committee sums fuse into one `_sum_batch` device
        dispatch, deduplicated by content digest within the call."""
        hits, cold, slots = self._collect_cold(
            jobs, "aggregate_cache_hits", "aggregate_cache_misses")
        results = [None] * len(jobs)
        for k, agg in hits.items():
            results[k] = agg
        if cold:
            for digest, agg in zip(cold, self._sum_and_insert(cold)):
                for k in slots[digest]:
                    results[k] = agg
        return results

    def warm_many(self, jobs) -> int:
        """Pre-compute aggregates OUTSIDE a verification (the on_block
        prewarm sweep, gossip/prewarm.py): inserts every cold
        participant set of `jobs` via one `_sum_batch` dispatch,
        counting `aggregate_cache_prewarms` instead of hits/misses so
        warm-up work never distorts the hit rate the dashboards track;
        returns how many sets were actually cold.  Best-effort like the
        prewarm path itself — a set whose pubkeys fail decode is
        skipped, never an error."""
        _hits, cold, _slots = self._collect_cold(jobs, None, None)
        if not cold:
            return 0
        self._metrics.inc("aggregate_cache_prewarms", len(cold))
        self._sum_and_insert(cold)
        return len(cold)

    def _compute_and_insert(self, digest, pubkey_bytes_list,
                            hint) -> cv.Point:
        # decompression (the expensive per-key host step, cached in
        # PubkeyCache) raises DecodeError/ValueError exactly like the
        # scalar path; the sum itself rides the batched dispatch seam
        pts = [self._pubkeys.get(pk) for pk in pubkey_bytes_list]
        agg = self._sum_batch([pts])[0]
        self._insert(digest, agg, hint)
        return agg

    def _sum_batch(self, point_lists) -> list:
        """THE cold-sum path: one `ops.g1_aggregate` dispatch for every
        cold participant set of a call (ops/g1_sweep.py padded
        ragged-segment reduction); the supervised fallback is the
        byte-identical per-set host loop, its adds counted."""
        from ..resilience.supervisor import dispatch
        self._metrics.inc("g1_aggregate_dispatches")

        def device():
            from ..ops.g1_sweep import g1_add_sweep
            return g1_add_sweep(point_lists)

        return dispatch("ops.g1_aggregate", device,
                        lambda: [self._host_sum(pts)
                                 for pts in point_lists])

    def _host_sum(self, pts) -> cv.Point:
        agg = cv.g1_infinity()
        for p in pts:
            agg = agg + p
        if pts:
            self._metrics.inc("host_point_adds", len(pts))
        return agg

    def _insert(self, digest, agg, hint) -> None:
        if not pipeline_async.writes_allowed():
            # a flush the caller abandoned past its watchdog deadline
            # keeps computing on the engine worker but may no longer
            # warm shared state: same purity pin as the abandoned
            # merkle sweep (values would be content-correct, but
            # crash-only discipline says a zombie leaves no trace)
            return
        with self._lock:
            if len(self._cache) >= self._max:
                self._cache.pop(next(iter(self._cache)))
            self._cache[digest] = (agg, hint)
            for tracked in self._track_stack:
                tracked.add(digest)

    def clear(self) -> None:
        with self._lock:
            self._cache.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._cache)


# speclint: disable=global-mutable-state -- content-addressed cache:
# pubkey bytes -> decompressed Point, identical whichever node computes
# it, so fleet-wide sharing is sound (and what makes SimNode fleets cheap)
PUBKEYS = PubkeyCache()
# speclint: disable=global-mutable-state -- keyed by participant-set
# digest, values node-independent; txn rollback evicts only entries the
# aborted transaction itself inserted (begin_track/end_track)
AGGREGATES = AggregatePubkeyCache(PUBKEYS)


def clear() -> None:
    PUBKEYS.clear()
    AGGREGATES.clear()
