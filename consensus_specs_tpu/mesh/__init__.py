"""Process mesh: real node processes gossiping to each other over the
framed unix-socket wire, with fault-injecting peer links (link.py) and
digest-keyed anti-entropy repair (service.py).  The scenario driver's
``processes=True`` backend (scenario/processes.py) runs the DSL's
partition/kill timelines against this mesh; `scripts/mesh_drill.py`
is the drill."""
from .link import LinkConfig, PeerLink, backoff_delay
from .service import MeshConfig, MeshNodeService

__all__ = ["LinkConfig", "PeerLink", "backoff_delay",
           "MeshConfig", "MeshNodeService"]
