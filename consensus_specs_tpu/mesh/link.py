"""Peer links: the mesh's outbound half.

One :class:`PeerLink` per peer owns a bounded outbound frame queue and
a single worker thread (role ``mesh-link``) that connects to the
peer's unix socket and streams frames at it.  The failure model is the
whole point:

* **bounded reconnect + exponential backoff + jitter** — a dead peer
  costs `reconnect_max` connect attempts spaced by
  ``min(base·2^n, cap)·(1 + jitter·U[0,1))``; past the budget the link
  QUARANTINES itself (sticky, incident-logged) instead of spinning.
* **send timeouts** — a half-open peer (accepted but never reads)
  stalls `sendall` for at most `send_timeout_s` before the link drops
  the connection and retries through the same backoff budget.
* **shed-oldest backpressure** — `offer()` never blocks the pump: a
  full queue evicts its oldest frame (incident + metric); the
  anti-entropy pass repairs whatever a shed frame would have carried.
* **registered fault boundary** — every send consults the active
  `FaultPlan` at the ``mesh.link`` dispatch site (raise = the frame
  and the connection are lost, timeout = the wire stalls, corrupt =
  one on-wire bit flips so the RECEIVER's CRC check sheds it) and
  crosses the ``mesh.send`` barrier, so the seeded injector faults
  real socket traffic exactly like it faults device dispatches.
* **quarantine, never crash** — damage in the peer's response stream
  (a `WireError` from the deframer) quarantines THIS link; the node
  keeps serving.  `reset()` (a `B` peers frame, or the drill healing a
  partition) clears quarantine and re-arms the reconnect budget.

Attribution: the owning process pins its `NodeContext` as resident
(service construction), so the worker's incident/metric records — and
the fault injector's own ``injected`` records — land in the right
node's books without any per-thread context push.
"""
from __future__ import annotations

import random
import socket
import threading
import time
from collections import deque
from dataclasses import dataclass

from ..node import wire
from ..resilience import faults
from ..utils.locks import named_condition

LINK_SITE = "mesh.link"
SEND_SITE = "mesh.send"


@dataclass
class LinkConfig:
    queue_bound: int = 1024          # outbound frames kept per peer
    send_timeout_s: float = 5.0      # half-open peer stall budget
    connect_timeout_s: float = 2.0
    reconnect_max: int = 8           # consecutive failures -> quarantine
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    backoff_jitter: float = 0.25     # +0..25% per wait, seeded


def backoff_delay(config: LinkConfig, attempt: int,
                  rng: random.Random) -> float:
    """Wait before reconnect attempt ``attempt`` (0-based): exponential
    growth capped at `backoff_max_s`, stretched by up to `+jitter` so a
    restarted peer is not hit by every link in lockstep."""
    base = min(config.backoff_base_s * (2 ** attempt),
               config.backoff_max_s)
    return base * (1.0 + config.backoff_jitter * rng.random())


def _flip_byte(data: bytes, rng: random.Random) -> bytes:
    """On-wire corruption: one flipped bit anywhere in the framed
    bytes.  The receiver's magic/CRC check turns it into a
    malformed-frame shed + connection close — never a crash."""
    out = bytearray(data)
    j = rng.randrange(len(out))
    out[j] ^= 1 << rng.randrange(8)
    return bytes(out)


class PeerLink:
    """Outbound link to one peer.  Thread shape: any thread may
    `offer()`/`block()`/`reset()`; one ``mesh-link`` worker sends."""

    def __init__(self, peer_id: str, socket_path: str, ctx,
                 config: LinkConfig | None = None,
                 rng: random.Random | None = None, on_heal=None):
        self.peer_id = str(peer_id)
        self.socket_path = socket_path
        self.ctx = ctx                  # owning node's NodeContext
        self.config = config or LinkConfig()
        self.on_heal = on_heal          # called after quarantine/block lift
        self._rng = rng or random.Random(0)
        self._cond = named_condition("mesh.link")
        self._queue = deque()           # guarded by _cond (handoff)
        self._blocked = False           # partition control (B frames)
        self._quarantined = None        # sticky reason string
        self._closing = False
        self._sent = 0
        self._shed = 0                  # evicted by backpressure
        self._dropped = 0               # lost to block/quarantine
        self._connects = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"mesh-link-{self.peer_id}",
            daemon=True)

    def start(self) -> None:
        self._thread.start()

    # -- any-thread surface ---------------------------------------------

    def offer(self, data: bytes) -> bool:
        """Enqueue one framed message; never blocks.  Returns False when
        the link is down (blocked/quarantined/closing) — the frame is
        dropped and the anti-entropy pass owns the repair."""
        evicted = False
        with self._cond:
            if (self._closing or self._blocked
                    or self._quarantined is not None):
                self._dropped += 1
                return False
            if len(self._queue) >= self.config.queue_bound:
                self._queue.popleft()       # shed-OLDEST
                self._shed += 1
                evicted = True
            self._queue.append(data)
            self._cond.notify()
        if evicted:
            self.ctx.incidents.record(LINK_SITE, "link_shed",
                                      peer=self.peer_id)
            self.ctx.metrics.inc("mesh_link_shed")
        return True

    def block(self) -> None:
        """Partition control: drop everything queued and everything
        offered until `reset()`."""
        with self._cond:
            if self._blocked:
                return
            self._blocked = True
            self._dropped += len(self._queue)
            self._queue.clear()
            self._cond.notify()
        self.ctx.incidents.record(LINK_SITE, "link_blocked",
                                  peer=self.peer_id)

    def reset(self) -> None:
        """Heal: lift a partition block AND a sticky quarantine (the
        peer restarted, or the drill healed the cut), re-arming the
        reconnect budget.  Fires `on_heal` so the owner can schedule an
        anti-entropy pass."""
        healed = False
        with self._cond:
            if self._blocked or self._quarantined is not None:
                healed = True
            self._blocked = False
            self._quarantined = None
            self._cond.notify()
        if healed:
            self.ctx.incidents.record(LINK_SITE, "link_healed",
                                      peer=self.peer_id)
            if self.on_heal is not None:
                self.on_heal(self.peer_id)

    def quarantine(self, reason: str) -> None:
        """Sticky failure isolation: the LINK goes dark (queue dropped,
        offers refused) until `reset()`; the node keeps serving."""
        with self._cond:
            if self._quarantined is not None or self._closing:
                return
            self._quarantined = str(reason)
            self._dropped += len(self._queue)
            self._queue.clear()
            self._cond.notify()
        self.ctx.incidents.record(LINK_SITE, "link_quarantined",
                                  peer=self.peer_id, detail=str(reason))
        self.ctx.metrics.inc("mesh_link_quarantined")

    def healthy(self) -> bool:
        with self._cond:
            return (not self._blocked and self._quarantined is None
                    and not self._closing)

    def state(self) -> dict:
        with self._cond:
            return {"peer": self.peer_id,
                    "depth": len(self._queue),
                    "blocked": self._blocked,
                    "quarantined": self._quarantined,
                    "sent": self._sent,
                    "shed": self._shed,
                    "dropped": self._dropped,
                    "connects": self._connects}

    def close(self, timeout_s: float = 10.0) -> None:
        with self._cond:
            self._closing = True
            self._cond.notify()
        self._stop.set()
        if self._thread.ident is not None:
            self._thread.join(timeout=timeout_s)

    # -- the mesh-link worker -------------------------------------------

    def _run(self) -> None:
        sock = None
        reader = None
        attempts = 0
        while True:
            with self._cond:
                while not self._queue and not self._closing:
                    self._cond.wait(timeout=0.1)
                if self._closing:
                    break
                if self._blocked or self._quarantined is not None:
                    # a control thread downed the link between the
                    # notify and this pop: drop what raced in
                    self._dropped += len(self._queue)
                    self._queue.clear()
                    continue
                data = self._queue.popleft()
            # the registered fault boundary: the injector models real
            # wire damage on this hop
            spec = None
            plan = faults.active_plan()
            if plan is not None:
                spec = plan.decide(LINK_SITE)
            if spec is not None:
                if spec.kind in ("raise", "shard_dead"):
                    # frame AND connection lost: a peer hangup mid-send
                    self.ctx.metrics.inc("mesh_link_injected_drops")
                    sock, reader = self._hangup(sock), None
                    attempts += 1
                    continue
                if spec.kind == "timeout":
                    time.sleep(spec.sleep_s)
                elif spec.kind == "corrupt":
                    data = _flip_byte(data, self._rng)
            while data is not None:
                if sock is None:
                    sock, reader, attempts = self._connect(attempts)
                    if sock is None:
                        break           # quarantined / downed / closing
                try:
                    faults.fire(SEND_SITE)
                except faults.DeviceFault as exc:
                    self.ctx.incidents.record(
                        SEND_SITE, "send_fault", peer=self.peer_id,
                        detail=str(exc))
                    self.ctx.metrics.inc("mesh_send_faults")
                    break               # frame shed at the barrier
                try:
                    sock.settimeout(self.config.send_timeout_s)
                    sock.sendall(data)
                except OSError:
                    sock, reader = self._hangup(sock), None
                    attempts += 1
                    continue            # reconnect, resend this frame
                with self._cond:
                    self._sent += 1
                attempts = 0
                data = None
                if not self._drain_responses(sock, reader):
                    sock, reader = self._hangup(sock), None
        self._hangup(sock)

    def _connect(self, attempts: int):
        """(sock, reader, attempts) or (None, None, attempts): bounded
        reconnect with jittered exponential backoff; budget exhaustion
        quarantines the link."""
        while True:
            with self._cond:
                if (self._closing or self._blocked
                        or self._quarantined is not None):
                    return None, None, attempts
            if attempts > self.config.reconnect_max:
                self.quarantine(
                    f"reconnect budget exhausted "
                    f"({self.config.reconnect_max} retries)")
                return None, None, attempts
            if attempts > 0 and self._stop.wait(
                    backoff_delay(self.config, attempts - 1, self._rng)):
                return None, None, attempts
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(self.config.connect_timeout_s)
            try:
                sock.connect(self.socket_path)
            except OSError:
                sock.close()
                attempts += 1
                continue
            with self._cond:
                self._connects += 1
            return sock, wire.FrameReader(), attempts

    def _drain_responses(self, sock, reader) -> bool:
        """Read whatever the peer answered without blocking.  The
        forward path is fire-and-forget, but the response stream must
        be drained (a never-read socket would eventually wedge the
        peer's responder) and VERIFIED: framing damage quarantines the
        link, never the node."""
        try:
            sock.settimeout(0.0)
            while True:
                buf = sock.recv(1 << 16)
                if not buf:
                    return False        # peer hung up
                reader.feed(buf)        # CRC-checked; bodies discarded
        except (BlockingIOError, InterruptedError):
            return True
        except wire.WireError as exc:
            self.quarantine(f"corrupt response frame: {exc}")
            return False
        except OSError:
            return False
        finally:
            try:
                sock.settimeout(None)
            except OSError:
                pass

    def _hangup(self, sock):
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        return None
