"""The mesh node: a `NodeService` that floods admitted gossip to real
peer processes and repairs itself by anti-entropy.

Topology starts as config — `MeshConfig.peers` names each neighbour's
(id, socket path) and every neighbour gets one :class:`PeerLink` — but
membership is DYNAMIC: a `J` join frame builds a live link to a new
member at runtime and an `L` leave frame drains and removes one, both
under the registered ``mesh.links`` lock, so a fleet can churn without
respawning survivors.  The flood rides the admission pipeline's
``transport`` seam — a message fires `_forward` only AFTER local
validation accepts it, and the content-addressed `SeenCache` dedup at
each hop (duplicates shed before transport fires) keeps an arbitrary
cyclic topology loop-free.  Split horizon: a message is never
forwarded back to the peer it arrived from (peers identify themselves
as ``mesh:<node_id>``).  Mesh-forwarded frames additionally carry a
hop counter in the `M` frame's msg_id slot: each forward increments
it, accepted hop depths land in the ``mesh_hops`` pow-2 histogram, and
a frame arriving past ``MeshConfig.ttl`` hops is shed with a
``ttl_exhausted`` incident — a backstop on top of dedup, priced and
observable.

Anti-entropy (the ``scenario.sync`` contract, realized over sockets):
every accepted message's digest -> (topic, origin peer, payload,
accept slot) is kept in a bounded replay log.  `S`/`P` frames serve
the log INLINE on conn threads (lock-guarded, no pump involvement —
two nodes can sync each other concurrently without deadlock); the `Y`
sync frame queues a control item so the PULL + re-submit side runs on
the pump, the only thread allowed to touch the pipeline.  Summaries
are SLOT-WINDOWED: the syncing node tracks the slot through which it
believes itself complete (`_synced_through`, advanced only when a pass
reached every configured peer) and asks each peer for digests accepted
at or after that watermark, so repair cost after a W-slot outage is
O(W), not O(history); the bare full-set summary stays available as the
counted fallback (``mesh_sync_full_fallbacks``).  A healed link
(quarantine or partition block lifted by a `B` peers frame) schedules
an automatic sync on the pump via the `_pump_extra` hook.

Fault surface: peer-forwarded messages cross the registered
``mesh.recv`` barrier before admission; membership changes cross
``mesh.join`` / ``mesh.leave``; each link's sends consult ``mesh.link``
and cross ``mesh.send`` (link.py).  The `I` incidents frame exposes
the node's incident book so the drill can assert every injected fault
and SIGKILL is attributed in the right process.
"""
from __future__ import annotations

import json
import random
import threading
import zlib
from collections import OrderedDict
from dataclasses import dataclass, field

from ..node import wire
from ..node.client import NodeClient
from ..node.service import NodeConfig, NodeService
from ..resilience import faults
from ..ssz import hash_tree_root
from ..utils.clock import MONOTONIC
from ..utils.locks import named_lock
from .link import LinkConfig, PeerLink

RECV_SITE = "mesh.recv"
SYNC_SITE = "mesh.sync"          # incident site (scenario.sync's twin)
JOIN_SITE = "mesh.join"          # barrier: before admitting a member
LEAVE_SITE = "mesh.leave"        # barrier: before draining a member out
PEER_PREFIX = "mesh:"            # how mesh nodes identify to each other
HOPS_BOUND = 4096                # inbound hop counts awaiting acceptance


@dataclass
class MeshConfig(NodeConfig):
    node_id: str = "node0"
    peers: tuple = ()            # ((peer_id, socket_path), ...)
    link: LinkConfig = field(default_factory=LinkConfig)
    replay_bound: int = 1 << 14  # digests kept for anti-entropy
    sync_page: int = 64          # digests per PULL page
    link_seed: int = 0           # seeds per-link backoff jitter
    ttl: int = 16                # max hops a forwarded frame may travel


class MeshNodeService(NodeService):
    def __init__(self, config: MeshConfig, clock=MONOTONIC):
        super().__init__(config, clock)
        self._replay_lock = named_lock("mesh.replay")
        # digest -> (topic, peer, payload, accept slot)
        self._replay = OrderedDict()
        self._sync_wanted = threading.Event()
        # runtime peer-table mutation (J/L frames) vs pump/conn readers
        self._links_lock = named_lock("mesh.links")
        # pump-only: inbound hop counts keyed by digest, consumed when
        # acceptance fires the transport seam; slot watermark the
        # windowed anti-entropy pass believes itself complete through
        self._hops = OrderedDict()
        self._max_slot = 0
        self._synced_through = 0
        seeder = random.Random(config.link_seed)
        self.links = {}
        for peer_id, path in config.peers:
            self.links[str(peer_id)] = PeerLink(
                peer_id, path, self.ctx, config.link,
                rng=random.Random(seeder.randrange(1 << 30)),
                on_heal=self._on_heal)
        # admitted messages flood through the pipeline's transport seam
        self.pipe.transport = self._forward
        for link in self.links.values():
            link.start()

    def _link_rng(self, peer_id: str) -> random.Random:
        """Deterministic per-link jitter seed that does not depend on
        join ORDER — dynamic membership must replay under a seed."""
        return random.Random(
            (int(self.config.link_seed) << 32)
            ^ zlib.crc32(str(peer_id).encode("utf-8")))

    # -- the flood (pump thread, under scope) ---------------------------

    def _forward(self, message) -> None:
        """Transport seam: record the accepted message for anti-entropy,
        then offer it to every link except the sender's."""
        slot = int(self.spec.get_current_slot(self.store))
        self._max_slot = max(self._max_slot, slot)
        hops = int(self._hops.pop(message.digest, 0))
        self.ctx.metrics.observe_hist("mesh_hops", hops)
        with self._replay_lock:
            if message.digest not in self._replay:
                if len(self._replay) >= self.config.replay_bound:
                    self._replay.popitem(last=False)
                self._replay[message.digest] = (
                    message.topic, message.peer, message.payload, slot)
        data = wire.encode_message(
            hops + 1, message.topic,
            PEER_PREFIX + self.config.node_id, message.payload)
        with self._links_lock:
            targets = list(self.links.items())
        for peer_id, link in targets:
            if message.peer == PEER_PREFIX + peer_id:
                continue                # split horizon
            link.offer(data)
        self.ctx.metrics.inc("mesh_forwarded")

    # -- conn-thread surface --------------------------------------------

    def handle(self, kind: str, value, respond) -> None:
        if (kind == wire.KIND_MESSAGE
                and isinstance(value, (tuple, list)) and len(value) == 4
                and isinstance(value[2], str)
                and value[2].startswith(PEER_PREFIX)):
            # the msg_id slot of a mesh-forwarded frame is its hop count
            hops = value[0] if isinstance(value[0], int) else 0
            if hops >= max(1, int(self.config.ttl)):
                self.ctx.incidents.record(RECV_SITE, "ttl_exhausted",
                                          hops=int(hops),
                                          peer=str(value[2]))
                self.ctx.metrics.inc("mesh_ttl_exhausted")
                respond({"id": value[0], "status": "shed",
                         "detail": "ttl exhausted"})
                return
            # peer-forwarded gossip crosses the registered recv barrier
            # before admission: the injector drops/delays it here
            try:
                faults.fire(RECV_SITE)
            except faults.DeviceFault as exc:
                self.ctx.incidents.record(RECV_SITE, "recv_fault",
                                          detail=str(exc))
                self.ctx.metrics.inc("mesh_recv_faults")
                respond({"id": value[0], "status": "shed",
                         "detail": "recv fault"})
                return
        if kind == wire.KIND_SUMMARY:
            window = None
            if isinstance(value, (tuple, list)) and len(value) == 3 \
                    and all(isinstance(v, int) for v in value):
                rid, lo, hi = value
                window = (lo, hi)
            elif isinstance(value, int):
                rid = value
                self.ctx.metrics.inc("mesh_summary_full")
            else:
                self._shed_frame(respond, None, "bad summary request")
                return
            with self._replay_lock:
                if window is None:
                    digests = list(self._replay.keys())
                else:
                    lo, hi = window
                    digests = [d for d, e in self._replay.items()
                               if e[3] >= lo and (hi < 0 or e[3] < hi)]
            if window is not None:
                self.ctx.metrics.inc("mesh_summary_windowed")
            respond({"id": rid, "status": "ok", "digests": digests})
            return
        if kind == wire.KIND_PULL:
            if (not isinstance(value, (tuple, list)) or len(value) != 2
                    or not isinstance(value[0], int)
                    or not isinstance(value[1], (tuple, list))):
                self._shed_frame(respond, None, "bad pull request")
                return
            rid, wanted = value
            out = []
            with self._replay_lock:
                for digest in wanted:
                    entry = self._replay.get(digest)
                    if entry is not None:
                        out.append(entry[:3])
            respond({"id": rid, "status": "ok", "messages": out})
            return
        if kind == wire.KIND_JOIN:
            if (not isinstance(value, (tuple, list)) or len(value) != 3
                    or not isinstance(value[0], int)
                    or not isinstance(value[1], str)
                    or not isinstance(value[2], str)):
                self._shed_frame(respond, None, "bad join request")
                return
            rid, peer_id, path = value
            try:
                faults.fire(JOIN_SITE)
            except faults.DeviceFault as exc:
                self.ctx.incidents.record(JOIN_SITE, "join_fault",
                                          peer=peer_id, detail=str(exc))
                respond({"id": rid, "status": "shed",
                         "detail": "join fault"})
                return
            added = self._add_link(peer_id, path)
            respond({"id": rid, "status": "ok", "added": added,
                     "peers": self._peer_ids()})
            return
        if kind == wire.KIND_LEAVE:
            if (not isinstance(value, (tuple, list)) or len(value) != 2
                    or not isinstance(value[0], int)
                    or not isinstance(value[1], str)):
                self._shed_frame(respond, None, "bad leave request")
                return
            rid, peer_id = value
            try:
                faults.fire(LEAVE_SITE)
            except faults.DeviceFault as exc:
                self.ctx.incidents.record(LEAVE_SITE, "leave_fault",
                                          peer=peer_id, detail=str(exc))
                respond({"id": rid, "status": "shed",
                         "detail": "leave fault"})
                return
            removed = self._remove_link(peer_id)
            respond({"id": rid, "status": "ok", "removed": removed,
                     "peers": self._peer_ids()})
            return
        if kind == wire.KIND_SYNC:
            if not isinstance(value, int):
                self._shed_frame(respond, None, "bad sync request")
                return
            # the pull+resubmit side must run on the pump
            self._enqueue(("sync", value, respond), respond, control=True)
            return
        if kind == wire.KIND_PEERS:
            if (not isinstance(value, (tuple, list)) or len(value) != 2
                    or not isinstance(value[0], int)
                    or not isinstance(value[1], (tuple, list))):
                self._shed_frame(respond, None, "bad peers request")
                return
            rid, blocked = value
            blocked = {str(b) for b in blocked}
            with self._links_lock:
                targets = list(self.links.items())
            for peer_id, link in targets:
                if peer_id in blocked:
                    link.block()
                else:
                    link.reset()
            respond({"id": rid, "status": "ok",
                     "blocked": sorted(blocked)})
            return
        if kind == wire.KIND_INCIDENTS:
            if not isinstance(value, int):
                self._shed_frame(respond, None, "bad incidents request")
                return
            # JSON string like health: incident detail values may be
            # floats, which the wire codec (deliberately) refuses
            respond({"id": value, "status": "ok",
                     "incidents": json.dumps(self.ctx.incidents.snapshot(),
                                             default=str)})
            return
        super().handle(kind, value, respond)

    # -- dynamic membership (conn threads) ------------------------------

    def _peer_ids(self) -> list:
        with self._links_lock:
            return sorted(self.links)

    def _add_link(self, peer_id: str, path: str) -> bool:
        """Admit a member at runtime: build, register and start a link.
        Idempotent on (peer_id, path); a peer re-joining on a NEW
        socket replaces its old link.  The link starts outside the
        table lock — `start`/`close` may wait on worker threads."""
        peer_id = str(peer_id)
        stale = None
        with self._links_lock:
            old = self.links.get(peer_id)
            if old is not None and old.socket_path == path:
                old.reset()             # re-join on the same socket
                return False
            link = PeerLink(peer_id, path, self.ctx, self.config.link,
                            rng=self._link_rng(peer_id),
                            on_heal=self._on_heal)
            stale, self.links[peer_id] = old, link
        if stale is not None:
            stale.close()
        link.start()
        self.ctx.incidents.record(JOIN_SITE, "peer_joined",
                                  peer=peer_id)
        self.ctx.metrics.inc("mesh_joins")
        return True

    def _remove_link(self, peer_id: str) -> bool:
        """Drain a member out: unregister its link, then close it —
        the worker flushes what it can before the socket drops, and
        anything still queued is priced as `link_shed`/`dropped`
        rather than silently lost (anti-entropy owns the repair if the
        peer ever returns)."""
        peer_id = str(peer_id)
        with self._links_lock:
            link = self.links.pop(peer_id, None)
        if link is None:
            return False
        link.close()
        self.ctx.incidents.record(LEAVE_SITE, "peer_left",
                                  peer=peer_id)
        self.ctx.metrics.inc("mesh_leaves")
        return True

    # -- anti-entropy (pump thread, under scope) ------------------------

    def _on_heal(self, peer_id: str) -> None:
        self._sync_wanted.set()

    def _pump_extra(self) -> None:
        if self._sync_wanted.is_set():
            self._sync_wanted.clear()
            self._sync()

    def _process(self, item) -> None:
        if item[0] == "sync":
            _, rid, respond = item
            respond({"id": rid, "status": "ok",
                     "replayed": self._sync()})
            return
        if (item[0] == "msg" and isinstance(item[3], str)
                and item[3].startswith(PEER_PREFIX)
                and isinstance(item[1], int) and item[1] > 0):
            # stash the inbound hop count by content digest so the
            # transport seam (which fires at ACCEPTANCE, possibly a
            # later flush) forwards with hops+1 and histograms the
            # depth.  Pump-thread only; FIFO-bounded because shed or
            # rejected messages never consume their entry.
            digest = bytes(hash_tree_root(item[4]))
            if digest not in self._hops:
                while len(self._hops) >= HOPS_BOUND:
                    self._hops.popitem(last=False)
                self._hops[digest] = int(item[1])
        super()._process(item)

    def _sync(self) -> int:
        """One anti-entropy pass: for every reachable peer, fetch its
        digest summary, PULL what this node has not admitted, and
        re-submit the misses through the pipeline under their original
        origin — the mesh twin of the scenario driver's catch-up
        replay.  Failures are per-peer and non-fatal.

        Summaries are windowed on the node's own completeness
        watermark: digests accepted before `_synced_through` were
        already compared in a pass that reached EVERY peer, so only
        the missed window crosses the wire — O(W) repair after a
        W-slot outage.  A peer that rejects the windowed request gets
        the full-set exchange as counted fallback."""
        replayed = 0
        reached_all = True
        lo = int(self._synced_through)
        with self._links_lock:
            targets = list(self.links.items())
        for peer_id, link in targets:
            if not link.healthy():
                reached_all = False
                continue
            try:
                client = NodeClient(link.socket_path,
                                    connect_timeout_s=2.0,
                                    resolver=self._resolver)
            except OSError:
                reached_all = False
                continue
            try:
                try:
                    remote = client.summary(lo=lo, hi=-1)
                except (OSError, ConnectionError, wire.WireError,
                        AssertionError):
                    # an old or damaged peer: full-set fallback, counted
                    self.ctx.metrics.inc("mesh_sync_full_fallbacks")
                    remote = client.summary()
                self.ctx.metrics.inc("mesh_sync_digests", len(remote))
                missing = [d for d in remote
                           if not self.pipe.seen.seen_before(d)]
                for start in range(0, len(missing),
                                   self.config.sync_page):
                    page = missing[start:start + self.config.sync_page]
                    for topic, peer, payload in client.pull(page):
                        if topic not in self.pipe.topics:
                            continue
                        self.pipe.submit(topic, payload, peer=peer)
                        replayed += 1
                    self.pipe.drain()
            except (OSError, ConnectionError, wire.WireError,
                    AssertionError):
                reached_all = False
                continue                # peer died mid-sync: next pass
            finally:
                client.close()
        if replayed:
            self.pipe.drain()
            self._harvest()
        if reached_all:
            # complete through everything we have now admitted; the
            # NEXT pass only repairs what lands after this watermark.
            # One slot of overlap absorbs tick skew between nodes (a
            # peer may still be a slot behind when it accepts).
            self._synced_through = max(0, int(self._max_slot) - 1)
        self.ctx.incidents.record(SYNC_SITE, "catch_up",
                                  replayed=replayed)
        self.ctx.metrics.inc("mesh_syncs")
        return replayed

    # -- health / lifecycle ---------------------------------------------

    def health(self) -> dict:
        report = super().health()
        with self._replay_lock:
            log_size = len(self._replay)
        with self._links_lock:
            links = list(self.links.items())
        report["mesh"] = {
            "node_id": self.config.node_id,
            "forwarded": self.ctx.metrics.count("mesh_forwarded"),
            "syncs": self.ctx.metrics.count("mesh_syncs"),
            "joins": self.ctx.metrics.count("mesh_joins"),
            "leaves": self.ctx.metrics.count("mesh_leaves"),
            "sync_digests": self.ctx.metrics.count("mesh_sync_digests"),
            "summary_windowed":
                self.ctx.metrics.count("mesh_summary_windowed"),
            "summary_full": self.ctx.metrics.count("mesh_summary_full"),
            "sync_full_fallbacks":
                self.ctx.metrics.count("mesh_sync_full_fallbacks"),
            "ttl_exhausted": self.ctx.metrics.count("mesh_ttl_exhausted"),
            "hops": self.ctx.metrics.hist_counts("mesh_hops"),
            "replay_log": log_size,
            "links": {pid: link.state() for pid, link in links},
        }
        return report

    def _close_links(self) -> None:
        with self._links_lock:
            links = list(self.links.values())
        for link in links:
            link.close()

    def _shutdown(self) -> None:
        self._close_links()
        super()._shutdown()

    def close(self) -> None:
        self._close_links()
        super().close()
