"""The mesh node: a `NodeService` that floods admitted gossip to real
peer processes and repairs itself by anti-entropy.

Topology is static config: `MeshConfig.peers` names each neighbour's
(id, socket path); every neighbour gets one :class:`PeerLink`.  The
flood rides the admission pipeline's ``transport`` seam — a message
fires `_forward` only AFTER local validation accepts it, and the
content-addressed `SeenCache` dedup at each hop (duplicates shed
before transport fires) keeps an arbitrary cyclic topology loop-free.
Split horizon: a message is never forwarded back to the peer it
arrived from (peers identify themselves as ``mesh:<node_id>``).

Anti-entropy (the ``scenario.sync`` contract, realized over sockets):
every accepted message's digest -> (topic, origin peer, payload) is
kept in a bounded replay log.  `S`/`P` frames serve the log INLINE on
conn threads (lock-guarded, no pump involvement — two nodes can sync
each other concurrently without deadlock); the `Y` sync frame queues a
control item so the PULL + re-submit side runs on the pump, the only
thread allowed to touch the pipeline.  A healed link (quarantine or
partition block lifted by a `B` peers frame) schedules an automatic
sync on the pump via the `_pump_extra` hook.

Fault surface: peer-forwarded messages cross the registered
``mesh.recv`` barrier before admission; each link's sends consult
``mesh.link`` and cross ``mesh.send`` (link.py).  The `I` incidents
frame exposes the node's incident book so the drill can assert every
injected fault and SIGKILL is attributed in the right process.
"""
from __future__ import annotations

import json
import random
import threading
from collections import OrderedDict
from dataclasses import dataclass, field

from ..node import wire
from ..node.client import NodeClient
from ..node.service import NodeConfig, NodeService
from ..resilience import faults
from ..utils.clock import MONOTONIC
from ..utils.locks import named_lock
from .link import LinkConfig, PeerLink

RECV_SITE = "mesh.recv"
SYNC_SITE = "mesh.sync"          # incident site (scenario.sync's twin)
PEER_PREFIX = "mesh:"            # how mesh nodes identify to each other


@dataclass
class MeshConfig(NodeConfig):
    node_id: str = "node0"
    peers: tuple = ()            # ((peer_id, socket_path), ...)
    link: LinkConfig = field(default_factory=LinkConfig)
    replay_bound: int = 1 << 14  # digests kept for anti-entropy
    sync_page: int = 64          # digests per PULL page
    link_seed: int = 0           # seeds per-link backoff jitter


class MeshNodeService(NodeService):
    def __init__(self, config: MeshConfig, clock=MONOTONIC):
        super().__init__(config, clock)
        self._replay_lock = named_lock("mesh.replay")
        self._replay = OrderedDict()    # digest -> (topic, peer, payload)
        self._sync_wanted = threading.Event()
        seeder = random.Random(config.link_seed)
        self.links = {}
        for peer_id, path in config.peers:
            self.links[str(peer_id)] = PeerLink(
                peer_id, path, self.ctx, config.link,
                rng=random.Random(seeder.randrange(1 << 30)),
                on_heal=self._on_heal)
        # admitted messages flood through the pipeline's transport seam
        self.pipe.transport = self._forward
        for link in self.links.values():
            link.start()

    # -- the flood (pump thread, under scope) ---------------------------

    def _forward(self, message) -> None:
        """Transport seam: record the accepted message for anti-entropy,
        then offer it to every link except the sender's."""
        with self._replay_lock:
            if message.digest not in self._replay:
                if len(self._replay) >= self.config.replay_bound:
                    self._replay.popitem(last=False)
                self._replay[message.digest] = (
                    message.topic, message.peer, message.payload)
        data = wire.encode_message(
            0, message.topic, PEER_PREFIX + self.config.node_id,
            message.payload)
        for peer_id, link in self.links.items():
            if message.peer == PEER_PREFIX + peer_id:
                continue                # split horizon
            link.offer(data)
        self.ctx.metrics.inc("mesh_forwarded")

    # -- conn-thread surface --------------------------------------------

    def handle(self, kind: str, value, respond) -> None:
        if (kind == wire.KIND_MESSAGE
                and isinstance(value, (tuple, list)) and len(value) == 4
                and isinstance(value[2], str)
                and value[2].startswith(PEER_PREFIX)):
            # peer-forwarded gossip crosses the registered recv barrier
            # before admission: the injector drops/delays it here
            try:
                faults.fire(RECV_SITE)
            except faults.DeviceFault as exc:
                self.ctx.incidents.record(RECV_SITE, "recv_fault",
                                          detail=str(exc))
                self.ctx.metrics.inc("mesh_recv_faults")
                respond({"id": value[0], "status": "shed",
                         "detail": "recv fault"})
                return
        if kind == wire.KIND_SUMMARY:
            if not isinstance(value, int):
                self._shed_frame(respond, None, "bad summary request")
                return
            with self._replay_lock:
                digests = list(self._replay.keys())
            respond({"id": value, "status": "ok", "digests": digests})
            return
        if kind == wire.KIND_PULL:
            if (not isinstance(value, (tuple, list)) or len(value) != 2
                    or not isinstance(value[0], int)
                    or not isinstance(value[1], (tuple, list))):
                self._shed_frame(respond, None, "bad pull request")
                return
            rid, wanted = value
            out = []
            with self._replay_lock:
                for digest in wanted:
                    entry = self._replay.get(digest)
                    if entry is not None:
                        out.append(entry)
            respond({"id": rid, "status": "ok", "messages": out})
            return
        if kind == wire.KIND_SYNC:
            if not isinstance(value, int):
                self._shed_frame(respond, None, "bad sync request")
                return
            # the pull+resubmit side must run on the pump
            self._enqueue(("sync", value, respond), respond, control=True)
            return
        if kind == wire.KIND_PEERS:
            if (not isinstance(value, (tuple, list)) or len(value) != 2
                    or not isinstance(value[0], int)
                    or not isinstance(value[1], (tuple, list))):
                self._shed_frame(respond, None, "bad peers request")
                return
            rid, blocked = value
            blocked = {str(b) for b in blocked}
            for peer_id, link in self.links.items():
                if peer_id in blocked:
                    link.block()
                else:
                    link.reset()
            respond({"id": rid, "status": "ok",
                     "blocked": sorted(blocked)})
            return
        if kind == wire.KIND_INCIDENTS:
            if not isinstance(value, int):
                self._shed_frame(respond, None, "bad incidents request")
                return
            # JSON string like health: incident detail values may be
            # floats, which the wire codec (deliberately) refuses
            respond({"id": value, "status": "ok",
                     "incidents": json.dumps(self.ctx.incidents.snapshot(),
                                             default=str)})
            return
        super().handle(kind, value, respond)

    # -- anti-entropy (pump thread, under scope) ------------------------

    def _on_heal(self, peer_id: str) -> None:
        self._sync_wanted.set()

    def _pump_extra(self) -> None:
        if self._sync_wanted.is_set():
            self._sync_wanted.clear()
            self._sync()

    def _process(self, item) -> None:
        if item[0] == "sync":
            _, rid, respond = item
            respond({"id": rid, "status": "ok",
                     "replayed": self._sync()})
            return
        super()._process(item)

    def _sync(self) -> int:
        """One anti-entropy pass: for every reachable peer, fetch its
        digest summary, PULL what this node has not admitted, and
        re-submit the misses through the pipeline under their original
        origin — the mesh twin of the scenario driver's catch-up
        replay.  Failures are per-peer and non-fatal."""
        replayed = 0
        for peer_id, link in self.links.items():
            if not link.healthy():
                continue
            try:
                client = NodeClient(link.socket_path,
                                    connect_timeout_s=2.0,
                                    resolver=self._resolver)
            except OSError:
                continue
            try:
                missing = [d for d in client.summary()
                           if not self.pipe.seen.seen_before(d)]
                for start in range(0, len(missing),
                                   self.config.sync_page):
                    page = missing[start:start + self.config.sync_page]
                    for topic, peer, payload in client.pull(page):
                        if topic not in self.pipe.topics:
                            continue
                        self.pipe.submit(topic, payload, peer=peer)
                        replayed += 1
                    self.pipe.drain()
            except (OSError, ConnectionError, wire.WireError,
                    AssertionError):
                continue                # peer died mid-sync: next pass
            finally:
                client.close()
        if replayed:
            self.pipe.drain()
            self._harvest()
        self.ctx.incidents.record(SYNC_SITE, "catch_up",
                                  replayed=replayed)
        self.ctx.metrics.inc("mesh_syncs")
        return replayed

    # -- health / lifecycle ---------------------------------------------

    def health(self) -> dict:
        report = super().health()
        with self._replay_lock:
            log_size = len(self._replay)
        report["mesh"] = {
            "node_id": self.config.node_id,
            "forwarded": self.ctx.metrics.count("mesh_forwarded"),
            "syncs": self.ctx.metrics.count("mesh_syncs"),
            "replay_log": log_size,
            "links": {pid: link.state()
                      for pid, link in self.links.items()},
        }
        return report

    def _shutdown(self) -> None:
        for link in self.links.values():
            link.close()
        super()._shutdown()

    def close(self) -> None:
        for link in self.links.values():
            link.close()
        super().close()
