"""ctypes bindings for the native host tier (native/libconsensus_native.so).

The framework's counterpart of the reference's C-backed host packages
(milagro / python-snappy / pycryptodome, SURVEY.md §2.2).  Everything here
degrades gracefully: `available()` is False when the library isn't built
and callers keep their pure-Python paths.

Build with: python scripts/build_native.py
"""
from __future__ import annotations

import ctypes
import os

_LIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "native", "libconsensus_native.so")

_lib = None
if os.path.exists(_LIB_PATH):
    try:
        _lib = ctypes.CDLL(_LIB_PATH)
        _lib.sha256_2to1_batch.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t]
        _lib.crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        _lib.crc32c.restype = ctypes.c_uint32
        _lib.snappy_max_compressed.argtypes = [ctypes.c_size_t]
        _lib.snappy_max_compressed.restype = ctypes.c_size_t
        _lib.snappy_compress_block.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p]
        _lib.snappy_compress_block.restype = ctypes.c_size_t
        _lib.snappy_decompress_block.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
            ctypes.c_size_t, ctypes.POINTER(ctypes.c_size_t)]
        _lib.snappy_decompress_block.restype = ctypes.c_int
    except OSError:
        _lib = None


def available() -> bool:
    return _lib is not None


def sha256_2to1_batch(data: bytes) -> bytes:
    """n 64-byte blocks -> n 32-byte digests."""
    assert len(data) % 64 == 0
    n = len(data) // 64
    out = ctypes.create_string_buffer(32 * n)
    _lib.sha256_2to1_batch(data, out, n)
    return out.raw


def crc32c(data: bytes) -> int:
    return int(_lib.crc32c(bytes(data), len(data)))


def snappy_compress_block(data: bytes) -> bytes:
    cap = _lib.snappy_max_compressed(len(data))
    out = ctypes.create_string_buffer(cap)
    n = _lib.snappy_compress_block(bytes(data), len(data), out)
    return out.raw[:n]


def snappy_decompress_block(data: bytes, max_out: int) -> bytes:
    out = ctypes.create_string_buffer(max_out)
    out_len = ctypes.c_size_t(0)
    rc = _lib.snappy_decompress_block(bytes(data), len(data), out,
                                      max_out, ctypes.byref(out_len))
    if rc != 0:
        raise ValueError(f"malformed snappy block (native rc={rc})")
    return out.raw[:out_len.value]
