"""Merge-transition test infra: stub PoW chain views and pre-merge
states (reference helpers/pow_block.py + helpers/execution_payload.py
:360 build_state_with_incomplete_transition)."""
from __future__ import annotations

import contextlib
from random import Random

from ..ssz import hash_tree_root


def prepare_random_pow_block(spec, rng):
    """A PowBlock with random hashes and zero difficulty fields —
    callers set total_difficulty around the TTD as the case needs.

    `rng` is required and must be ONE per-case Random instance shared
    by all of a case's blocks: per-case seeding keeps emitted vectors
    identical between full and incremental generator runs, while the
    shared stream keeps successive hashes distinct."""
    return spec.PowBlock(
        block_hash=bytes(rng.getrandbits(8) for _ in range(32)),
        parent_hash=bytes(rng.getrandbits(8) for _ in range(32)),
        total_difficulty=0)


@contextlib.contextmanager
def pow_chain_patch(spec, pow_blocks):
    """Expose `pow_blocks` through spec.get_pow_block for the duration
    of the test (spec instances are cached across tests — restore)."""
    saved = dict(spec.pow_chain)
    try:
        for block in pow_blocks:
            spec.pow_chain[bytes(block.block_hash)] = block
        yield
    finally:
        spec.pow_chain.clear()
        spec.pow_chain.update(saved)


class PowChain:
    """A linked list of PowBlocks, newest last (reference
    helpers/pow_block.py::PowChain): head(-1) is the parent of head()."""

    def __init__(self, blocks):
        self.blocks = list(blocks)

    def __iter__(self):
        return iter(self.blocks)

    def head(self, offset=0):
        assert offset <= 0
        return self.blocks[-1 + offset]


def prepare_random_pow_chain(spec, length, rng=None) -> PowChain:
    rng = rng or Random(3131)
    blocks = []
    for _ in range(length):
        block = prepare_random_pow_block(spec, rng)
        if blocks:
            block.parent_hash = blocks[-1].block_hash
        blocks.append(block)
    return PowChain(blocks)


def build_state_with_complete_transition(spec, state):
    """A state that already merged: non-empty latest payload header."""
    state = state.copy()
    if spec.is_merge_transition_complete(state):
        return state
    header = spec.ExecutionPayloadHeader()
    header.block_hash = b"\x11" * 32
    header.block_number = 1
    state.latest_execution_payload_header = header
    return state


def build_state_with_incomplete_transition(spec, state):
    """Zero the latest execution payload header: the merge has not
    happened yet from this state's point of view."""
    state = state.copy()
    state.latest_execution_payload_header = spec.ExecutionPayloadHeader()
    return state


def recompute_payload_block_hash(spec, payload) -> None:
    """Re-derive the deterministic fake block hash after mutating
    payload fields (same convention as
    blocks.build_empty_execution_payload)."""
    payload.block_hash = b"\x00" * 32
    payload.block_hash = spec.hash(
        bytes(hash_tree_root(payload)) + b"FAKE RLP HASH")
