"""Mock genesis state construction for tests.

Capability parity with the reference harness's genesis fixtures
(/root/reference/tests/core/pyspec/eth2spec/test/helpers/genesis.py:16-47):
validators are built directly (no deposit proofs) from the deterministic
key table, then the state is assembled exactly as the genesis function
would have left it.
"""
from __future__ import annotations

from ..ssz import hash_tree_root, uint64
from .keys import pubkeys


def build_mock_validator(spec, i: int, balance: int):
    pubkey = pubkeys[i]
    if spec.is_post("electra"):
        if balance > spec.MIN_ACTIVATION_BALANCE:
            # compounding credentials above the min activation balance
            withdrawal_credentials = (
                spec.COMPOUNDING_WITHDRAWAL_PREFIX + b"\x00" * 11
                + bytes(spec.hash(pubkey))[12:])
        else:
            withdrawal_credentials = (
                spec.BLS_WITHDRAWAL_PREFIX + bytes(spec.hash(pubkey))[1:])
        max_effective_balance = spec.MAX_EFFECTIVE_BALANCE_ELECTRA
    else:
        # BLS-prefixed withdrawal credentials derived from the pubkey
        withdrawal_credentials = (
            spec.BLS_WITHDRAWAL_PREFIX + bytes(spec.hash(pubkey))[1:])
        max_effective_balance = spec.MAX_EFFECTIVE_BALANCE
    return spec.Validator(
        pubkey=pubkey,
        withdrawal_credentials=withdrawal_credentials,
        activation_eligibility_epoch=spec.FAR_FUTURE_EPOCH,
        activation_epoch=spec.FAR_FUTURE_EPOCH,
        exit_epoch=spec.FAR_FUTURE_EPOCH,
        withdrawable_epoch=spec.FAR_FUTURE_EPOCH,
        effective_balance=uint64(min(
            int(balance) - int(balance) % spec.EFFECTIVE_BALANCE_INCREMENT,
            max_effective_balance)))


# Built genesis states keyed by (spec instance, balances, threshold):
# building one costs ~2 s (sync-committee pubkey aggregation dominates)
# while a COW copy costs ~0.5 ms, and the quick tier builds hundreds of
# identical ones.  Keying on the spec OBJECT (not its name) makes
# custom-config specs miss instead of aliasing; the FIFO bound keeps
# those misses from accumulating states forever.
_STATE_CACHE: dict = {}
_STATE_CACHE_MAX = 64


def create_genesis_state(spec, validator_balances, activation_threshold=None):
    if activation_threshold is None:
        activation_threshold = spec.MAX_EFFECTIVE_BALANCE
    key = (id(spec), tuple(int(b) for b in validator_balances),
           int(activation_threshold))
    cached = _STATE_CACHE.get(key)
    if cached is not None and cached[0] is spec:
        return cached[1].copy()
    state = _build_genesis_state(spec, validator_balances,
                                 activation_threshold)
    if len(_STATE_CACHE) >= _STATE_CACHE_MAX:
        _STATE_CACHE.pop(next(iter(_STATE_CACHE)))
    # the cached entry keeps a strong ref to `spec`, so the id() in the
    # key can never be recycled onto a different live spec
    _STATE_CACHE[key] = (spec, state.copy())
    return state


def _build_genesis_state(spec, validator_balances, activation_threshold):
    deposit_root = b"\x42" * 32
    eth1_block_hash = b"\xda" * 32
    state = spec.BeaconState(
        genesis_time=spec.config.MIN_GENESIS_TIME,
        eth1_deposit_index=len(validator_balances),
        eth1_data=spec.Eth1Data(
            deposit_root=deposit_root,
            deposit_count=len(validator_balances),
            block_hash=eth1_block_hash),
        latest_block_header=spec.BeaconBlockHeader(
            body_root=hash_tree_root(spec.BeaconBlockBody())),
        randao_mixes=[eth1_block_hash] * spec.EPOCHS_PER_HISTORICAL_VECTOR)

    previous_version, current_version = spec.genesis_fork_versions()
    state.fork = spec.Fork(previous_version=previous_version,
                           current_version=current_version,
                           epoch=spec.GENESIS_EPOCH)

    for index, balance in enumerate(validator_balances):
        validator = build_mock_validator(spec, index, balance)
        if validator.effective_balance >= activation_threshold:
            validator.activation_eligibility_epoch = spec.GENESIS_EPOCH
            validator.activation_epoch = spec.GENESIS_EPOCH
        state.validators.append(validator)
        state.balances.append(balance)

    state.genesis_validators_root = hash_tree_root(state.validators)

    if spec.is_post("altair"):
        n = len(validator_balances)
        state.previous_epoch_participation = [0] * n
        state.current_epoch_participation = [0] * n
        state.inactivity_scores = [0] * n
        state.current_sync_committee = spec.get_next_sync_committee(state)
        state.next_sync_committee = spec.get_next_sync_committee(state)

    if spec.fork == "eip7732":
        # ePBS: the header is a builder bid; genesis commits to an empty
        # kzg list, the last full slot is genesis itself
        empty_kzgs = spec.ExecutionPayloadEnvelope.fields()[
            "blob_kzg_commitments"]()
        state.latest_execution_payload_header.blob_kzg_commitments_root = \
            hash_tree_root(empty_kzgs)
        state.latest_execution_payload_header.block_hash = eth1_block_hash
        state.latest_block_hash = eth1_block_hash
        state.latest_full_slot = spec.GENESIS_SLOT
    elif spec.is_post("bellatrix"):
        # post-bellatrix mock genesis is post-merge: sample payload header
        state.latest_execution_payload_header = \
            sample_genesis_execution_payload_header(spec, eth1_block_hash)

    if spec.is_post("electra"):
        state.deposit_requests_start_index = \
            spec.UNSET_DEPOSIT_REQUESTS_START_INDEX

    if spec.fork == "whisk":
        # mirror the whisk fork upgrade: initial per-validator trackers +
        # two candidate selections and one proposer selection
        for i in range(len(validator_balances)):
            k = spec.get_unique_whisk_k(state, i)
            state.whisk_trackers.append(spec.get_initial_tracker(k))
            state.whisk_k_commitments.append(spec.get_k_commitment(k))
        epoch = spec.GENESIS_EPOCH
        spec.select_whisk_candidate_trackers(state, epoch)
        spec.select_whisk_proposer_trackers(state, epoch)

    return state


def sample_genesis_execution_payload_header(spec, eth1_block_hash):
    header = spec.ExecutionPayloadHeader(
        parent_hash=b"\x30" * 32,
        fee_recipient=b"\x42" * 20,
        state_root=b"\x20" * 32,
        receipts_root=b"\x20" * 32,
        logs_bloom=b"\x35" * spec.BYTES_PER_LOGS_BLOOM,
        prev_randao=eth1_block_hash,
        block_number=0,
        gas_limit=30000000,
        gas_used=0,
        timestamp=0,
        base_fee_per_gas=1000000000,
        block_hash=eth1_block_hash,
        transactions_root=spec.hash_tree_root(
            spec.ExecutionPayload.fields()["transactions"]()))
    if spec.is_post("capella"):
        header.withdrawals_root = spec.hash_tree_root(
            spec.ExecutionPayload.fields()["withdrawals"]())
    return header


def default_balances(spec):
    """Enough full-balance validators for a healthy committee structure."""
    num_validators = spec.SLOTS_PER_EPOCH * 8
    return [spec.MAX_EFFECTIVE_BALANCE] * num_validators
