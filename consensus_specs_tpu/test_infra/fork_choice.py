"""Step-emitting fork-choice test harness.

Capability counterpart of the reference's helpers/fork_choice.py:53-235 —
the mechanism by which multi-node behavior is tested without a network:
each peer's view is a sequence of store events (`on_tick`, `on_block`,
`on_attestation`, `checks`), recorded as a steps list that the fork_choice
vector format (tests/formats/fork_choice/README.md:30-80) serializes to
steps.yaml plus one ssz file per object.

Usage inside a dual-mode test:

    store, steps, anchor = start_fork_choice_test(spec, state)
    ...
    yield from tick_and_add_block(spec, store, signed_block, steps)
    output_store_checks(spec, store, steps)
    yield from emit_steps(steps)
"""
from __future__ import annotations

from ..ssz import hash_tree_root


def get_genesis_forkchoice_store(spec, state):
    """Bare anchor store for unit tests (no steps artifact)."""
    anchor_block = spec.BeaconBlock(state_root=hash_tree_root(state))
    return spec.get_forkchoice_store(state, anchor_block)


def start_fork_choice_test(spec, state):
    """Build the anchor store and the initial artifacts.

    Returns (store, steps, emit_parts) where emit_parts are the
    anchor_state / anchor_block artifacts to yield first."""
    anchor_block = spec.BeaconBlock(state_root=hash_tree_root(state))
    store = spec.get_forkchoice_store(state, anchor_block)
    parts = [("anchor_state", state.copy()),
             ("anchor_block", anchor_block)]
    return store, [], parts


def on_tick_and_append_step(spec, store, time, steps) -> None:
    spec.on_tick(store, int(time))
    steps.append({"tick": int(time)})


def get_head_root(spec, store):
    """get_head as a root (eip7732 returns a ChildNode; unwrap)."""
    head = spec.get_head(store)
    return getattr(head, "root", head)


def tick_to_state_slot(spec, store, state, steps) -> None:
    """Tick the store to the wall-clock time of `state`'s slot."""
    on_tick_and_append_step(
        spec, store,
        int(store.genesis_time)
        + int(state.slot) * int(spec.config.SECONDS_PER_SLOT), steps)


def tick_to_slot(spec, store, slot, steps) -> None:
    time = (int(store.genesis_time)
            + int(slot) * int(spec.config.SECONDS_PER_SLOT))
    on_tick_and_append_step(spec, store, time, steps)


def tick_to_attesting_interval(spec, store, slot, steps) -> None:
    """Tick just past `slot`'s attesting interval: blocks applied now
    are untimely (no proposer boost)."""
    time = (int(store.genesis_time)
            + int(slot) * int(spec.config.SECONDS_PER_SLOT)
            + int(spec.config.SECONDS_PER_SLOT)
            // int(spec.INTERVALS_PER_SLOT))
    on_tick_and_append_step(spec, store, time, steps)


def add_block(spec, store, signed_block, steps, valid=True):
    """Apply a signed block to the store, recording the step and the block
    artifact.  Returns the artifact list to yield.

    As in the reference harness (helpers/fork_choice.py::add_block), an
    on_block step implies receiving the block's attestations and attester
    slashings — without this the justified checkpoint state never lands
    in store.checkpoint_states and get_weight cannot score branches."""
    root = hash_tree_root(signed_block.message)
    name = f"block_{root.hex()[:16]}"
    parts = [(name, signed_block)]
    step = {"block": name, "valid": bool(valid)}
    if not valid:
        try:
            spec.on_block(store, signed_block)
        except (AssertionError, ValueError, KeyError):
            steps.append(step)
            return parts
        raise AssertionError("block unexpectedly valid in fork choice")
    spec.on_block(store, signed_block)
    steps.append(step)
    for attestation in signed_block.message.body.attestations:
        spec.on_attestation(store, attestation, is_from_block=True)
    for attester_slashing in signed_block.message.body.attester_slashings:
        spec.on_attester_slashing(store, attester_slashing)
    return parts


def tick_and_add_block(spec, store, signed_block, steps, valid=True):
    """Advance time to the block's slot, then apply it."""
    slot = int(signed_block.message.slot)
    time = (int(store.genesis_time)
            + slot * int(spec.config.SECONDS_PER_SLOT))
    if int(store.time) < time:
        on_tick_and_append_step(spec, store, time, steps)
    return add_block(spec, store, signed_block, steps, valid=valid)


def add_attestation(spec, store, attestation, steps, valid=True):
    root = hash_tree_root(attestation)
    name = f"attestation_{root.hex()[:16]}"
    parts = [(name, attestation)]
    step = {"attestation": name, "valid": bool(valid)}
    if not valid:
        try:
            spec.on_attestation(store, attestation)
        except (AssertionError, ValueError, KeyError):
            steps.append(step)
            return parts
        raise AssertionError("attestation unexpectedly valid")
    spec.on_attestation(store, attestation)
    steps.append(step)
    return parts


def add_attester_slashing(spec, store, attester_slashing, steps,
                          valid=True):
    """Apply an attester slashing to the store (format README
    'attester_slashing' step — equivocation discard)."""
    root = hash_tree_root(attester_slashing)
    name = f"attester_slashing_{root.hex()[:16]}"
    parts = [(name, attester_slashing)]
    step = {"attester_slashing": name, "valid": bool(valid)}
    if not valid:
        try:
            spec.on_attester_slashing(store, attester_slashing)
        except (AssertionError, ValueError, KeyError):
            steps.append(step)
            return parts
        raise AssertionError("attester slashing unexpectedly valid")
    spec.on_attester_slashing(store, attester_slashing)
    steps.append(step)
    return parts


def apply_next_epoch_with_attestations(spec, state, store, steps,
                                       fill_cur_epoch=True,
                                       fill_prev_epoch=False):
    """Advance `state` one epoch with attestation-filled blocks and feed
    every block through the store (reference
    helpers/fork_choice.py::apply_next_epoch_with_attestations shape).

    Returns (parts, signed_blocks): artifacts to yield and the blocks
    applied."""
    from .attestations import next_epoch_with_attestations
    signed_blocks, _post = next_epoch_with_attestations(
        spec, state, fill_cur_epoch, fill_prev_epoch)
    parts = []
    for signed_block in signed_blocks:
        parts.extend(
            tick_and_add_block(spec, store, signed_block, steps))
    return parts, signed_blocks


def tick_and_run_on_attestation(spec, store, attestation, steps,
                                is_from_block=False):
    """Tick past the attestation's slot if needed, then apply it."""
    min_time = (int(store.genesis_time)
                + (int(attestation.data.slot) + 1)
                * int(spec.config.SECONDS_PER_SLOT))
    if int(store.time) < min_time:
        on_tick_and_append_step(spec, store, min_time, steps)
    root = hash_tree_root(attestation)
    name = f"attestation_{root.hex()[:16]}"
    spec.on_attestation(store, attestation, is_from_block=is_from_block)
    steps.append({"attestation": name})
    return [(name, attestation)]


def apply_next_slots_with_attestations(spec, state, store, slots, steps,
                                       fill_cur_epoch=True,
                                       fill_prev_epoch=False):
    """Advance `slots` slots with attestation-filled blocks fed through
    the store (reference helpers/fork_choice.py::
    apply_next_slots_with_attestations).  Returns (parts, last_block)."""
    from .attestations import state_transition_with_full_block
    parts = []
    last_signed = None
    for _ in range(slots):
        last_signed = state_transition_with_full_block(
            spec, state, fill_cur_epoch, fill_prev_epoch)
        parts.extend(
            tick_and_add_block(spec, store, last_signed, steps))
    return parts, last_signed


def add_pow_block(spec, store, pow_block, steps):
    """Record a PoW-chain block artifact (fork_choice format
    'pow_block' step).  The block is made visible to get_pow_block via
    test_infra.pow_block.pow_chain_patch."""
    name = f"pow_block_{bytes(pow_block.block_hash).hex()}"
    steps.append({"pow_block": name})
    return [(name, pow_block)]


def add_attestations(spec, store, attestations, steps, valid=True):
    """Apply a batch of attestations; returns the artifacts to yield."""
    parts = []
    for attestation in attestations:
        parts.extend(
            add_attestation(spec, store, attestation, steps, valid=valid))
    return parts


def is_ready_to_justify(spec, state) -> bool:
    """Would the epoch-boundary justification pass bump the justified
    checkpoint, given the votes already in `state`?  (reference
    helpers/fork_choice.py:349)."""
    temp = state.copy()
    spec.process_justification_and_finalization(temp)
    return int(temp.current_justified_checkpoint.epoch) \
        > int(state.current_justified_checkpoint.epoch)


def find_next_justifying_slot(spec, state, fill_cur_epoch,
                              fill_prev_epoch, participation_fn=None):
    """Extend a throwaway copy of `state` with attestation-filled blocks
    until the pending votes suffice to justify at the next boundary
    (reference helpers/fork_choice.py:358).  Returns (signed_blocks,
    justifying_slot)."""
    from .attestations import state_transition_with_full_block
    temp = state.copy()
    signed_blocks = []
    while True:
        signed_blocks.append(state_transition_with_full_block(
            spec, temp, fill_cur_epoch, fill_prev_epoch,
            participation_fn))
        if is_ready_to_justify(spec, temp):
            return signed_blocks, int(temp.slot)


def fill_epochs_with_attestations(spec, state, store, steps, n):
    """Advance `n` fully-attested epochs through the store; returns the
    accumulated artifacts to yield."""
    parts = []
    for _ in range(n):
        more, _ = apply_next_epoch_with_attestations(
            spec, state, store, steps, fill_cur_epoch=True,
            fill_prev_epoch=True)
        parts.extend(more)
    return parts


def output_store_checks(spec, store, steps) -> None:
    """Record the observable store state (format README 'checks' step)."""
    head = spec.get_head(store)
    # eip7732 returns a ChildNode; the on-disk checks use the root
    head = getattr(head, "root", head)
    steps.append({"checks": {
        "time": int(store.time),
        "head": {"slot": int(store.blocks[head].slot),
                 "root": "0x" + bytes(head).hex()},
        "justified_checkpoint": {
            "epoch": int(store.justified_checkpoint.epoch),
            "root": "0x" + bytes(store.justified_checkpoint.root).hex()},
        "finalized_checkpoint": {
            "epoch": int(store.finalized_checkpoint.epoch),
            "root": "0x" + bytes(store.finalized_checkpoint.root).hex()},
        "proposer_boost_root":
            "0x" + bytes(store.proposer_boost_root).hex(),
    }})


def emit_steps(steps):
    """Final artifact of a fork-choice case: the steps script."""
    yield "steps", "data", steps
