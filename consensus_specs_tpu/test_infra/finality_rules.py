"""Finality-rule scenario builders for
process_justification_and_finalization (the reference's
test_process_justification_and_finalization.py mechanism: mock
checkpoints in block_roots, preset justification bits, inject target
attestations/participation at a chosen support level, then run the pass
and check which FFG rule fired).

Rules (fork-choice nomenclature): 234 and 23 finalize via the previous
justified checkpoint; 123 and 12 via the current one.
"""
from __future__ import annotations

from ..ssz import Bitvector, uint64
from .blocks import transition_to


def mock_checkpoints(spec, epoch):
    """Checkpoints for 1..5 epochs ago with distinct mock roots."""
    roots = [b"\xaa", b"\xbb", b"\xcc", b"\xdd", b"\xee"]
    return [spec.Checkpoint(epoch=uint64(int(epoch) - k),
                            root=roots[k - 1] * 32)
            if int(epoch) >= k else None
            for k in range(1, 6)]


def put_checkpoints_in_block_roots(spec, state, checkpoints) -> None:
    for c in checkpoints:
        slot = int(spec.compute_start_slot_at_epoch(c.epoch))
        state.block_roots[slot % int(spec.SLOTS_PER_HISTORICAL_ROOT)] = \
            c.root


def add_mock_target_attestations(spec, state, epoch, source, target,
                                 sufficient_support=True,
                                 messed_up_target=False) -> None:
    """Inject target votes worth just over (or under) 2/3 of the active
    balance for `epoch` (must be the previous or current epoch)."""
    assert (int(state.slot) + 1) % int(spec.SLOTS_PER_EPOCH) == 0
    previous_epoch = spec.get_previous_epoch(state)
    current_epoch = spec.get_current_epoch(state)
    assert int(epoch) in (int(previous_epoch), int(current_epoch))

    total_balance = int(spec.get_total_active_balance(state))
    remaining = total_balance * 2 // 3

    if spec.is_post("altair"):
        participation = (state.current_epoch_participation
                         if int(epoch) == int(current_epoch)
                         else state.previous_epoch_participation)
    else:
        attestations = (state.current_epoch_attestations
                        if int(epoch) == int(current_epoch)
                        else state.previous_epoch_attestations)

    start_slot = int(spec.compute_start_slot_at_epoch(epoch))
    committees_per_slot = int(
        spec.get_committee_count_per_slot(state, epoch))
    for slot in range(start_slot, start_slot + int(spec.SLOTS_PER_EPOCH)):
        for index in range(committees_per_slot):
            if remaining < 0:
                return
            committee = spec.get_beacon_committee(
                state, uint64(slot), uint64(index))
            bits = [0] * len(committee)
            for v in range(len(committee) * 2 // 3 + 1):
                if remaining > 0:
                    remaining -= int(
                        state.validators[committee[v]].effective_balance)
                    bits[v] = 1
                else:
                    break
            if not sufficient_support:
                for i in range(max(len(committee) // 5, 1)):
                    bits[i] = 0
            if spec.is_post("altair"):
                for i, vindex in enumerate(committee):
                    if not bits[i]:
                        continue
                    flags = int(participation[int(vindex)])
                    flags |= 1 << int(spec.TIMELY_HEAD_FLAG_INDEX)
                    flags |= 1 << int(spec.TIMELY_SOURCE_FLAG_INDEX)
                    if not messed_up_target:
                        flags |= 1 << int(spec.TIMELY_TARGET_FLAG_INDEX)
                    participation[int(vindex)] = flags
            else:
                data = spec.AttestationData(
                    slot=uint64(slot), index=uint64(index),
                    beacon_block_root=b"\xff" * 32,
                    source=source, target=target)
                if messed_up_target:
                    data.target.root = b"\x99" * 32
                attestations.append(spec.PendingAttestation(
                    aggregation_bits=bits, data=data,
                    inclusion_delay=uint64(1)))


def _start(spec, state, epoch) -> None:
    transition_to(
        spec, state,
        uint64(int(spec.SLOTS_PER_EPOCH) * int(epoch) - 1))


def _set_bits(spec, state, indices) -> None:
    state.justification_bits = Bitvector[
        int(spec.JUSTIFICATION_BITS_LENGTH)]()
    for i in indices:
        state.justification_bits[i] = True


def finalize_on_234(spec, state, epoch, sufficient_support):
    """Rule 234: bits[1:3] justified; justifying epoch-2 with epoch-4
    source finalizes the old previous-justified (epoch-4)."""
    assert int(epoch) > 4
    _start(spec, state, epoch)
    c1, c2, c3, c4, _ = mock_checkpoints(spec, epoch)
    put_checkpoints_in_block_roots(spec, state, [c1, c2, c3, c4])
    old_finalized = state.finalized_checkpoint.copy()
    state.previous_justified_checkpoint = c4
    state.current_justified_checkpoint = c3
    _set_bits(spec, state, [1, 2])
    add_mock_target_attestations(spec, state, uint64(int(epoch) - 2),
                                 c4, c2, sufficient_support)
    yield from _run(spec, state)
    assert state.previous_justified_checkpoint == c3
    if sufficient_support:
        assert state.current_justified_checkpoint == c2
        assert state.finalized_checkpoint == c4
    else:
        assert state.current_justified_checkpoint == c3
        assert state.finalized_checkpoint == old_finalized


def finalize_on_23(spec, state, epoch, sufficient_support):
    """Rule 23: bit[1] justified; justifying epoch-2 with epoch-3
    source finalizes epoch-3."""
    assert int(epoch) > 3
    _start(spec, state, epoch)
    c1, c2, c3, _, _ = mock_checkpoints(spec, epoch)
    put_checkpoints_in_block_roots(spec, state, [c1, c2, c3])
    old_finalized = state.finalized_checkpoint.copy()
    state.previous_justified_checkpoint = c3
    state.current_justified_checkpoint = c3
    _set_bits(spec, state, [1])
    add_mock_target_attestations(spec, state, uint64(int(epoch) - 2),
                                 c3, c2, sufficient_support)
    yield from _run(spec, state)
    assert state.previous_justified_checkpoint == c3
    if sufficient_support:
        assert state.current_justified_checkpoint == c2
        assert state.finalized_checkpoint == c3
    else:
        assert state.current_justified_checkpoint == c3
        assert state.finalized_checkpoint == old_finalized


def finalize_on_123(spec, state, epoch, sufficient_support):
    """Rule 123: epoch-3 pre-justified (bit 1); epochs 2 and 1 justify
    in THIS pass, making bits[0:3] contiguous — finalizes the old
    current-justified (epoch-3)."""
    assert int(epoch) > 5
    _start(spec, state, epoch)
    c1, c2, c3, c4, c5 = mock_checkpoints(spec, epoch)
    put_checkpoints_in_block_roots(spec, state, [c1, c2, c3, c4, c5])
    old_finalized = state.finalized_checkpoint.copy()
    state.previous_justified_checkpoint = c5
    state.current_justified_checkpoint = c3
    _set_bits(spec, state, [1])
    add_mock_target_attestations(spec, state, uint64(int(epoch) - 2),
                                 c5, c2, sufficient_support)
    add_mock_target_attestations(spec, state, uint64(int(epoch) - 1),
                                 c3, c1, sufficient_support)
    yield from _run(spec, state)
    assert state.previous_justified_checkpoint == c3
    if sufficient_support:
        assert state.current_justified_checkpoint == c1
        assert state.finalized_checkpoint == c3
    else:
        assert state.current_justified_checkpoint == c3
        assert state.finalized_checkpoint == old_finalized


def finalize_on_12(spec, state, epoch, sufficient_support,
                   messed_up_target=False):
    """Rule 12: epoch 2 justified; justifying epoch-1 with epoch-2
    source finalizes epoch-2."""
    assert int(epoch) > 2
    _start(spec, state, epoch)
    c1, c2, _, _, _ = mock_checkpoints(spec, epoch)
    put_checkpoints_in_block_roots(spec, state, [c1, c2])
    old_finalized = state.finalized_checkpoint.copy()
    state.previous_justified_checkpoint = c2
    state.current_justified_checkpoint = c2
    _set_bits(spec, state, [0])
    add_mock_target_attestations(spec, state, uint64(int(epoch) - 1),
                                 c2, c1, sufficient_support,
                                 messed_up_target)
    yield from _run(spec, state)
    assert state.previous_justified_checkpoint == c2
    if sufficient_support and not messed_up_target:
        assert state.current_justified_checkpoint == c1
        assert state.finalized_checkpoint == c2
    else:
        assert state.current_justified_checkpoint == c2
        assert state.finalized_checkpoint == old_finalized


def _run(spec, state):
    from .epoch_processing import run_epoch_processing_with
    yield from run_epoch_processing_with(
        spec, state, "process_justification_and_finalization")
