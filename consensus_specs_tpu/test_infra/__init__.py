"""Test infrastructure: keys, genesis fixtures, block/attestation builders,
decorator engine, and BLS toggling — the counterpart of the reference's
eth2spec.test harness (SURVEY.md §2.4).
"""
from contextlib import contextmanager

from ..utils import bls as _bls


@contextmanager
def disable_bls():
    """Stub BLS inside the block — the reference's --disable-bls semantics
    for bulk trajectory tests where signature crypto is not under test."""
    previous = _bls.bls_active
    _bls.bls_active = False
    try:
        yield
    finally:
        _bls.bls_active = previous


@contextmanager
def enable_bls():
    previous = _bls.bls_active
    _bls.bls_active = True
    try:
        yield
    finally:
        _bls.bls_active = previous
