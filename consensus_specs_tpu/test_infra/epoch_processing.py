"""Epoch-processing test driver.

Counterpart of the reference harness's helpers/epoch_processing.py: run the
epoch passes preceding a target pass, then yield pre/post around the target
— the shape of every `epoch_processing` conformance vector.
"""
from __future__ import annotations

from ..ssz import uint64
from .blocks import transition_to


def epoch_pass_order(spec) -> list:
    """Sub-pass order of process_epoch for this fork (mirrors the
    per-fork process_epoch bodies; phase0 beacon-chain.md:1302, altair
    :564, electra :800)."""
    if not spec.is_post("altair"):
        return [
            "process_justification_and_finalization",
            "process_rewards_and_penalties",
            "process_registry_updates",
            "process_slashings",
            "process_eth1_data_reset",
            "process_effective_balance_updates",
            "process_slashings_reset",
            "process_randao_mixes_reset",
            "process_historical_roots_update",
            "process_participation_record_updates",
        ]
    order = [
        "process_justification_and_finalization",
        "process_inactivity_updates",
        "process_rewards_and_penalties",
        "process_registry_updates",
        "process_slashings",
        "process_eth1_data_reset",
    ]
    if spec.is_post("electra"):
        order += ["process_pending_deposits",
                  "process_pending_consolidations"]
    order += ["process_effective_balance_updates",
              "process_slashings_reset",
              "process_randao_mixes_reset"]
    if spec.is_post("capella"):
        order += ["process_historical_summaries_update"]
    else:
        order += ["process_historical_roots_update"]
    order += ["process_participation_flag_updates",
              "process_sync_committee_updates"]
    return order


def run_epoch_processing_to(spec, state, pass_name: str) -> None:
    """Advance to the final slot of the epoch, then run every pass that
    precedes `pass_name`."""
    order = epoch_pass_order(spec)
    if pass_name not in order:        # validate BEFORE mutating the state
        raise ValueError(
            f"unknown epoch pass {pass_name!r} for fork {spec.fork}")
    slot = uint64(state.slot + spec.SLOTS_PER_EPOCH
                  - state.slot % spec.SLOTS_PER_EPOCH - 1)
    transition_to(spec, state, slot)
    for name in order:
        if name == pass_name:
            return
        getattr(spec, name)(state)


def run_epoch_processing_with(spec, state, pass_name: str):
    """Yield-protocol driver: pre, run `pass_name`, post."""
    run_epoch_processing_to(spec, state, pass_name)
    yield "pre", state.copy()
    getattr(spec, pass_name)(state)
    yield "post", state
