"""Step-driven light-client sync harness (reference:
test/helpers/light_client_sync.py — the step-emitting mechanism behind
the light_client/sync vector format, tests/formats/light_client/sync.md:
a bootstrap plus a steps.yaml of process_update / force_update events
with per-step store checks).
"""
from __future__ import annotations

from ..ssz import Bytes32, hash_tree_root, uint64
from ..utils import bls as bls_utils
from .blocks import (
    build_empty_block_for_next_slot, state_transition_and_sign_block)
from .context import _forced_bls
from .keys import privkey_for_pubkey


def build_chain(spec, n_blocks, state):
    """n empty signed blocks from `state`; returns (states, blocks) with
    states[i] the post-state of blocks[i] (signatures stubbed — LC
    verification only touches the sync-committee signatures we add)."""
    states, blocks = [], []
    with _forced_bls(False):
        for _ in range(n_blocks):
            block = build_empty_block_for_next_slot(spec, state)
            signed = state_transition_and_sign_block(spec, state, block)
            states.append(state.copy())
            blocks.append(signed)
    return states, blocks


def build_sync_aggregate(spec, state, signature_slot, attested_root,
                         participation=1.0):
    """A real SyncAggregate over `attested_root` signed by the leading
    `participation` fraction of the committee."""
    committee = state.current_sync_committee.pubkeys
    n_sign = int(len(committee) * participation)
    previous_slot = uint64(int(signature_slot) - 1)
    domain = spec.get_domain(state, spec.DOMAIN_SYNC_COMMITTEE,
                             spec.compute_epoch_at_slot(previous_slot))
    signing_root = spec.compute_signing_root(
        Bytes32(attested_root), domain)
    sigs = [bls_utils.Sign(privkey_for_pubkey(pk), signing_root)
            for pk in list(committee)[:n_sign]]
    bits = [i < n_sign for i in range(len(committee))]
    signature = bls_utils.Aggregate(sigs) if sigs \
        else spec.G2_POINT_AT_INFINITY
    return spec.SyncAggregate(sync_committee_bits=bits,
                              sync_committee_signature=signature)


def make_update(spec, states, blocks, signature_index,
                finalized_index=None, participation=1.0):
    """LightClientUpdate whose signature block (at signature_index)
    attests blocks[signature_index - 1]."""
    att_index = signature_index - 1
    attested_root = hash_tree_root(blocks[att_index].message)
    aggregate = build_sync_aggregate(
        spec, states[signature_index],
        blocks[signature_index].message.slot, attested_root,
        participation)
    with _forced_bls(False):
        pre = states[att_index].copy()
        block = build_empty_block_for_next_slot(spec, pre)
        block.body.sync_aggregate = aggregate
        signed = state_transition_and_sign_block(spec, pre, block)
    finalized_block = None if finalized_index is None \
        else blocks[finalized_index]
    update = spec.create_light_client_update(
        pre, signed, states[att_index], blocks[att_index],
        finalized_block)
    return update


def store_checks(spec, store) -> dict:
    """The per-step check object of the sync format."""
    def header_checks(header):
        out = {
            "slot": int(header.beacon.slot),
            "beacon_root": "0x" + bytes(
                hash_tree_root(header.beacon)).hex(),
        }
        if spec.is_post("capella"):
            out["execution_root"] = "0x" + bytes(
                spec.get_lc_execution_root(header)).hex()
        return out
    return {
        "finalized_header": header_checks(store.finalized_header),
        "optimistic_header": header_checks(store.optimistic_header),
    }


class LightClientSyncTest:
    """Accumulates steps + artifacts in the on-disk sync format; drive
    with process_update / force_update, then yield_parts() in a
    dual-mode test."""

    def __init__(self, spec, trusted_block, bootstrap):
        self.spec = spec
        self.trusted_block_root = hash_tree_root(trusted_block.message)
        self.bootstrap = bootstrap
        self.store = spec.initialize_light_client_store(
            self.trusted_block_root, bootstrap)
        self.steps = []
        self.artifacts = []

    def process_update(self, update, current_slot,
                       genesis_validators_root):
        name = f"update_{len(self.steps)}"
        self.spec.process_light_client_update(
            self.store, update, uint64(current_slot),
            genesis_validators_root)
        self.artifacts.append((name, update))
        self.steps.append({"process_update": {
            "update": name,
            "current_slot": int(current_slot),
            "checks": store_checks(self.spec, self.store),
        }})

    def force_update(self, current_slot):
        self.spec.process_light_client_store_force_update(
            self.store, uint64(current_slot))
        self.steps.append({"force_update": {
            "current_slot": int(current_slot),
            "checks": store_checks(self.spec, self.store),
        }})

    def yield_parts(self, state):
        yield "meta", {
            "genesis_validators_root": "0x" + bytes(
                state.genesis_validators_root).hex(),
            "trusted_block_root": "0x" + bytes(
                self.trusted_block_root).hex(),
        }
        yield "bootstrap", self.bootstrap
        for name, obj in self.artifacts:
            yield name, obj
        yield "steps", self.steps
