"""Fork-transition test harness.

Counterpart of the reference harness's helpers/fork_transition.py
(transition_until_fork / do_fork): advance a pre-fork state to the fork
boundary under the pre spec, apply the post spec's state upgrade, and
optionally apply the first post-fork block at the boundary slot.
"""
from __future__ import annotations

from ..ssz import hash_tree_root, uint64
from .blocks import build_empty_block, sign_block

# canonical mainnet fork ladder (spec class MRO order)
FORK_ORDER = ["phase0", "altair", "bellatrix", "capella", "deneb",
              "electra", "fulu"]


def transition_until_fork(pre_spec, state, fork_epoch: int) -> None:
    """Advance to the last slot before the fork boundary, then process
    the boundary epoch under the pre spec (the upgrade happens after the
    pre-fork epoch processing, fork.md 'Fork trigger')."""
    boundary_slot = uint64(fork_epoch * pre_spec.SLOTS_PER_EPOCH)
    assert state.slot <= boundary_slot
    if state.slot < boundary_slot:
        pre_spec.process_slots(state, boundary_slot)


def do_fork(pre_spec, post_spec, state, with_block: bool = True,
            block_mutator=None):
    """Upgrade `state` (sitting at an epoch boundary) to the post fork,
    optionally applying an empty post-fork block at the boundary slot.
    `block_mutator(post_spec, post_state, block)` can inject operations
    into that first post-fork block before it is signed (reference
    run_transition_with_operation's is_right_after_fork arm).
    Returns (post_state, signed_block_or_None)."""
    assert state.slot % pre_spec.SLOTS_PER_EPOCH == 0
    post_state = post_spec.upgrade_from(state)
    assert post_state.fork.previous_version == state.fork.current_version

    if not with_block:
        return post_state, None

    block = build_empty_block(post_spec, post_state, slot=post_state.slot)
    if block_mutator is not None:
        block_mutator(post_spec, post_state, block)
    # apply directly (process_slots already ran under the pre spec)
    temp = post_state.copy()
    post_spec.process_block(temp, block)
    block.state_root = hash_tree_root(temp)
    signed = sign_block(post_spec, post_state, block)
    post_spec.process_block(post_state, block)
    return post_state, signed


def transition_across(pre_spec, post_spec, state, fork_epoch: int,
                      with_block: bool = True, block_mutator=None):
    """transition_until_fork + do_fork in one step."""
    transition_until_fork(pre_spec, state, fork_epoch)
    return do_fork(pre_spec, post_spec, state, with_block=with_block,
                   block_mutator=block_mutator)
