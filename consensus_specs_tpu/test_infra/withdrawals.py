"""Withdrawal test helpers (capella+).

Counterpart of the reference harness's helpers/withdrawals.py: set
execution/compounding withdrawal credentials and stage validators so the
sweep (reference specs/capella/beacon-chain.md:345-420) produces full or
partial withdrawals on demand.
"""
from __future__ import annotations

from ..ssz import Bytes32, uint64


def set_eth1_withdrawal_credentials(spec, state, index, address=None):
    """Give validator `index` 0x01 (eth1) withdrawal credentials."""
    if address is None:
        address = b"\xaa" * 20
    validator = state.validators[index]
    validator.withdrawal_credentials = Bytes32(
        bytes(spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX) + b"\x00" * 11 + address)


def set_compounding_withdrawal_credentials(spec, state, index,
                                           address=None):
    """Electra 0x02 compounding credentials."""
    if address is None:
        address = b"\xaa" * 20
    validator = state.validators[index]
    validator.withdrawal_credentials = Bytes32(
        bytes(spec.COMPOUNDING_WITHDRAWAL_PREFIX) + b"\x00" * 11 + address)


def prepare_fully_withdrawable_validator(spec, state, index,
                                         balance=None):
    """Make validator `index` fully withdrawable at the current epoch."""
    set_eth1_withdrawal_credentials(spec, state, index)
    validator = state.validators[index]
    epoch = spec.get_current_epoch(state)
    validator.exit_epoch = uint64(max(int(epoch) - 1, 0))
    validator.withdrawable_epoch = epoch
    if balance is not None:
        state.balances[index] = uint64(balance)


def prepare_partially_withdrawable_validator(spec, state, index,
                                             excess=1000000000):
    """Make validator `index` partially withdrawable: max effective
    balance with an excess on top."""
    set_eth1_withdrawal_credentials(spec, state, index)
    validator = state.validators[index]
    validator.effective_balance = spec.MAX_EFFECTIVE_BALANCE
    state.balances[index] = uint64(
        int(spec.MAX_EFFECTIVE_BALANCE) + excess)


def prepare_pending_withdrawal(spec, state, validator_index,
                               effective_balance=32_000_000_000,
                               amount=1_000_000_000,
                               withdrawable_epoch=None):
    """Electra: queue a PendingPartialWithdrawal for a compounding
    validator holding `effective_balance + amount` (reference
    helpers/withdrawals.py:110)."""
    assert spec.is_post("electra")
    if withdrawable_epoch is None:
        withdrawable_epoch = spec.get_current_epoch(state)
    set_compounding_withdrawal_credentials(spec, state, validator_index)
    state.validators[validator_index].effective_balance = \
        uint64(effective_balance)
    state.balances[validator_index] = uint64(
        int(effective_balance) + int(amount))
    withdrawal = spec.PendingPartialWithdrawal(
        validator_index=validator_index, amount=amount,
        withdrawable_epoch=withdrawable_epoch)
    state.pending_partial_withdrawals.append(withdrawal)
    return withdrawal


def get_expected_withdrawals(spec, state):
    """Fork-agnostic expected-withdrawals list (electra returns a
    (withdrawals, processed_partial_count) pair)."""
    result = spec.get_expected_withdrawals(state)
    return result[0] if spec.is_post("electra") else result


def payload_with_expected_withdrawals(spec, state):
    """An execution payload carrying exactly the expected withdrawals."""
    from .blocks import build_empty_execution_payload
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals = get_expected_withdrawals(spec, state)
    return payload


def run_withdrawals_processing(spec, state, payload, valid=True):
    """Dual-mode runner around process_withdrawals (operations-runner
    withdrawals handler: vector format carries the payload)."""
    yield "pre", state.copy()
    yield "execution_payload", payload
    if not valid:
        try:
            spec.process_withdrawals(state, payload)
        except (AssertionError, ValueError, IndexError):
            yield "post", None
            return
        raise AssertionError("withdrawals unexpectedly valid")
    spec.process_withdrawals(state, payload)
    yield "post", state
