"""Deterministic test keypairs.

Same convention as the reference harness (privkeys 1..N,
/root/reference/tests/core/pyspec/eth2spec/test/helpers/keys.py:1-6) but
pubkeys are derived lazily through our own BLS (no external key table) and
memoized — deriving all of them eagerly would cost seconds of scalar mults.
"""
from __future__ import annotations

from ..crypto import bls12_381 as _native

KEY_COUNT = 8192

privkeys = [i + 1 for i in range(KEY_COUNT)]

_pubkey_cache: dict[int, bytes] = {}


def pubkey_of(privkey: int) -> bytes:
    pk = _pubkey_cache.get(privkey)
    if pk is None:
        pk = _native.SkToPk(privkey)
        _pubkey_cache[privkey] = pk
    return pk


class _PubkeyTable:
    """List-like lazy pubkey table: pubkeys[i] == SkToPk(privkeys[i])."""

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [pubkey_of(pk) for pk in privkeys[i]]
        return pubkey_of(privkeys[i])

    def __len__(self):
        return KEY_COUNT

    def index(self, pubkey) -> int:
        pubkey = bytes(pubkey)
        for i, pk in list(_pubkey_cache.items()):
            if pk == pubkey:
                return privkeys.index(i)
        for i in range(KEY_COUNT):  # fall back to deriving
            if pubkey_of(privkeys[i]) == pubkey:
                return i
        raise ValueError("unknown pubkey")


pubkeys = _PubkeyTable()


def privkey_for_pubkey(pubkey) -> int:
    return privkeys[pubkeys.index(bytes(pubkey))]
