"""Test-harness context: global defaults set by pytest CLI flags.

Mirrors the reference harness's context defaults
(/root/reference/tests/core/pyspec/eth2spec/test/context.py and
conftest.py:30-99).  The decorator engine builds on these.
"""

DEFAULT_TEST_PRESET = "minimal"
DEFAULT_PYTEST_FORKS = None  # None = all forks
