"""Decorator engine: fork/preset/BLS/state orchestration for spec tests.

Capability parity with the reference harness's context machinery
(/root/reference/tests/core/pyspec/eth2spec/test/context.py:282-783 —
`@spec_state_test`, fork filters, `@with_presets`, `@always_bls`,
`with_custom_state`, `with_config_overrides`) plus the dual-mode yield
protocol (test/utils/utils.py:6-74), re-designed for the class-based spec
registry: decorators attach metadata, the runner wrapper iterates the
selected (fork x preset) targets, builds LRU-cached genesis states, and
either drains the test body's yields (pytest mode) or streams them as a
vector TestCase (generator mode via `make_vector_cases`).

Usage:

    @with_all_phases
    @spec_state_test
    def test_something(spec, state):
        yield "pre", state.copy()
        ...
        yield "post", state
"""
from __future__ import annotations

import functools
from contextlib import contextmanager

from ..specs import get_spec
from ..utils import bls as bls_utils

# set by tests/conftest.py from CLI flags
DEFAULT_TEST_PRESET = "minimal"
DEFAULT_PYTEST_FORKS = None  # None = all mainline forks
# Quick-tier fork thinning (set by tests/conftest.py): when True, each
# pytest spec test runs only the ENDPOINTS of its selected fork span —
# earliest + latest — instead of every fork in between.  The middle
# forks are the redundant rows of the matrix (the bodies branch on
# is_post_fork, so the endpoints exercise both sides of every guard);
# the full matrix still runs under --kernel-tiers (`make test-kernels`)
# and generator mode (make_vector_cases) never thins.
QUICK_FORK_SPAN = False

MAINLINE_FORKS = ["phase0", "altair", "bellatrix", "capella", "deneb",
                  "electra", "fulu"]
# feature forks run only when explicitly named by @with_phases
FEATURE_FORKS = ["whisk", "eip7732", "eip6800"]
ALL_FORKS = MAINLINE_FORKS + FEATURE_FORKS


def is_post_fork(a: str, b: str) -> bool:
    """True if mainline fork `a` is `b` or later."""
    return MAINLINE_FORKS.index(a) >= MAINLINE_FORKS.index(b)


# ---------------------------------------------------------------------------
# balance shapers (reference context.py:103-238)
# ---------------------------------------------------------------------------

from .genesis import default_balances  # noqa: E402 (single source of truth)


def low_balances(spec):
    # low but above EJECTION_BALANCE, so validators stay active
    # (reference context.py low_balances: 18 ETH)
    low = 18 * 10**9
    return [low] * (spec.SLOTS_PER_EPOCH * 8)


def misc_balances(spec):
    n = spec.SLOTS_PER_EPOCH * 8
    return [spec.MAX_EFFECTIVE_BALANCE * (i % 5) // 4 or
            spec.config.EJECTION_BALANCE for i in range(n)]


def default_activation_threshold(spec):
    return spec.MAX_EFFECTIVE_BALANCE


def zero_activation_threshold(spec):
    return 0


# ---------------------------------------------------------------------------
# cached genesis states
# ---------------------------------------------------------------------------

_state_cache: dict = {}


def _genesis_state(spec, balances_fn, threshold_fn, cfg_key):
    key = (spec.fork, spec.preset_name, cfg_key,
           f"{balances_fn.__module__}.{balances_fn.__qualname__}",
           f"{threshold_fn.__module__}.{threshold_fn.__qualname__}")
    if key not in _state_cache:
        from .genesis import create_genesis_state
        with _forced_bls(False):
            _state_cache[key] = create_genesis_state(
                spec, balances_fn(spec), threshold_fn(spec))
    return _state_cache[key].copy()


@contextmanager
def _forced_bls(active: bool):
    prev = bls_utils.bls_active
    bls_utils.bls_active = active
    try:
        yield
    finally:
        bls_utils.bls_active = prev


# ---------------------------------------------------------------------------
# metadata decorators
# ---------------------------------------------------------------------------

def _meta(fn) -> dict:
    if not hasattr(fn, "_spec_meta"):
        fn._spec_meta = {}
    return fn._spec_meta


def with_phases(forks):
    def deco(fn):
        _meta(fn)["forks"] = list(forks)
        return fn
    return deco


def with_all_phases(fn):
    _meta(fn)["forks"] = list(MAINLINE_FORKS)
    return fn


def with_all_phases_from(fork, to=None):
    i = MAINLINE_FORKS.index(fork)
    j = MAINLINE_FORKS.index(to) + 1 if to else len(MAINLINE_FORKS)

    def deco(fn):
        _meta(fn)["forks"] = MAINLINE_FORKS[i:j]
        return fn
    return deco


def with_all_phases_except(excluded):
    def deco(fn):
        _meta(fn)["forks"] = [f for f in MAINLINE_FORKS
                              if f not in excluded]
        return fn
    return deco


def no_vectors(fn):
    """Mark a test as pytest-only (a unit/consistency check, not a
    conformance case) — the reference's check_mods exclusion for
    unittests.  make_vector_cases returns no cases for it."""
    _meta(fn)["no_vectors"] = True
    return fn


def with_pytest_fork_subset(forks):
    """Restrict the PYTEST run to `forks` without narrowing the
    generator: expensive real-signature suites keep CI inside budget on
    a representative subset while conformance vectors still cover every
    fork the test applies to."""
    def deco(fn):
        _meta(fn)["pytest_forks"] = list(forks)
        return fn
    return deco


def with_presets(presets, reason: str | None = None):
    def deco(fn):
        _meta(fn)["presets"] = list(presets)
        _meta(fn)["preset_reason"] = reason
        return fn
    return deco


def always_bls(fn):
    _meta(fn)["bls"] = "always"
    return fn


def never_bls(fn):
    _meta(fn)["bls"] = "never"
    return fn


def with_custom_state(balances_fn, threshold_fn=default_activation_threshold):
    def deco(fn):
        _meta(fn)["balances_fn"] = balances_fn
        _meta(fn)["threshold_fn"] = threshold_fn
        return fn
    return deco


def with_config_overrides(overrides: dict):
    """Run against a spec whose runtime config has `overrides` applied
    (reference context.py:600-665; configs are the runtime tier, so no
    recompile — a fresh spec instance is built per overridden config)."""
    def deco(fn):
        _meta(fn)["config_overrides"] = dict(overrides)
        return fn
    return deco


# ---------------------------------------------------------------------------
# the runner wrapper
# ---------------------------------------------------------------------------

def _selected_targets(meta, forks=None, presets=None):
    """Yield (fork, preset, spec) for every applicable target."""
    from ..config import load_config

    presets = presets or [DEFAULT_TEST_PRESET]
    test_forks = meta.get("forks") or list(MAINLINE_FORKS)
    if forks is not None:
        test_forks = [f for f in test_forks if f in forks]
    if DEFAULT_PYTEST_FORKS is not None:
        # the --fork CLI filter applies on top of any explicit subset
        # (pytest_forks must not resurrect forks the user filtered out)
        test_forks = [f for f in test_forks if f in DEFAULT_PYTEST_FORKS]
    overrides = meta.get("config_overrides")
    for preset in presets:
        if meta.get("presets") and preset not in meta["presets"]:
            continue
        for fork in test_forks:
            if overrides:
                config = load_config(preset).replace(**overrides)
                yield fork, preset, get_spec(fork, preset, config)
            else:
                yield fork, preset, get_spec(fork, preset)


@contextmanager
def _bls_mode(meta, generator_mode: bool):
    mode = meta.get("bls", "optional")
    if generator_mode:
        # emitted vectors must carry real signatures unless the test
        # explicitly opts out
        with _forced_bls(mode != "never"):
            yield
    elif mode == "always":
        with _forced_bls(True):
            yield
    elif mode == "never":
        with _forced_bls(False):
            yield
    else:
        yield  # follow the session default (--disable-bls)


def _cfg_key(meta) -> str:
    ov = meta.get("config_overrides")
    return "" if not ov else repr(sorted(ov.items()))


def _run_single(fn, meta, spec, needs_state, collect):
    kwargs = {"spec": spec}
    if needs_state:
        kwargs["state"] = _genesis_state(
            spec,
            meta.get("balances_fn", default_balances),
            meta.get("threshold_fn", default_activation_threshold),
            _cfg_key(meta))
    gen = fn(**kwargs)
    if gen is None:
        return []
    if collect:
        from ..gen.vector_test import run_yields
        return run_yields(lambda: gen)
    for _ in gen:
        pass
    return []


def _span_endpoints(targets):
    """Keep the earliest and latest fork of each preset's span."""
    by_preset: dict = {}
    for t in targets:
        by_preset.setdefault(t[1], []).append(t)
    kept = []
    for group in by_preset.values():
        kept.extend(group if len(group) <= 2
                    else [group[0], group[-1]])
    return kept


def _make_runner(fn, needs_state: bool):
    @functools.wraps(fn)
    def runner():
        from ..gen.vector_test import SkippedTest
        meta = _meta(runner)
        ran = 0
        # pytest-only narrowing; make_vector_cases ignores this so the
        # generator keeps full fork coverage
        targets = list(_selected_targets(
            meta, forks=meta.get("pytest_forks")))
        if QUICK_FORK_SPAN:
            targets = _span_endpoints(targets)
        for _fork, _preset, spec in targets:
            try:
                with _bls_mode(meta, generator_mode=False):
                    _run_single(fn, meta, spec, needs_state,
                                collect=False)
            except SkippedTest:
                continue  # inapplicable for this target only
            ran += 1
        if ran == 0:
            import pytest
            pytest.skip("no applicable (fork, preset) target")

    # pytest resolves fixture names through __wrapped__/signature; this
    # wrapper takes none — hide the inner (spec, state) signature
    import inspect
    runner.__signature__ = inspect.Signature()
    if hasattr(runner, "__wrapped__"):
        del runner.__wrapped__
    runner._spec_meta = _meta(fn)
    runner._spec_inner = fn
    runner._needs_state = needs_state

    def make_vector_cases(runner_name, handler_name, suite_name="pyspec",
                          forks=None, presets=None, case_name=None):
        """Reflect this test into generator TestCases, one per target —
        the reference's gen_from_tests capability (gen.py:18-61)."""
        from ..gen.typing import TestCase
        meta = _meta(runner)
        if meta.get("no_vectors"):
            return []
        name = case_name or (fn.__name__[5:]
                             if fn.__name__.startswith("test_")
                             else fn.__name__)
        cases = []
        for fork, preset, spec in _selected_targets(
                meta, forks=forks, presets=presets or ["minimal"]):
            def case_fn(spec=spec, meta=meta):
                with _bls_mode(meta, generator_mode=True):
                    for part in _run_single(fn, meta, spec, needs_state,
                                            collect=True):
                        yield part
            cases.append(TestCase(
                fork_name=fork, preset_name=preset,
                runner_name=runner_name, handler_name=handler_name,
                suite_name=suite_name, case_name=name, case_fn=case_fn))
        return cases

    runner.make_vector_cases = make_vector_cases
    return runner


def spec_state_test(fn):
    """Test body gets (spec, state); state is a fresh copy of the cached
    mock genesis for the target (fork, preset, balances)."""
    return _make_runner(fn, needs_state=True)


def spec_test(fn):
    """Test body gets (spec) only."""
    return _make_runner(fn, needs_state=False)
