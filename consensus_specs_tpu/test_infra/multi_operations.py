"""Build blocks carrying a full mix of operations (reference:
test/helpers/multi_operations.py capability): one block exercising every
operation channel the fork supports, each constructed against the same
pre-state so they stay mutually valid.
"""
from __future__ import annotations

from ..ssz import uint64
from .attestations import get_valid_attestation
from .blocks import build_empty_block_for_next_slot, transition_to
from .deposits import prepare_state_and_deposit
from .slashings import (
    get_valid_attester_slashing, get_valid_proposer_slashing,
    get_valid_voluntary_exit)


def build_block_with_operations(spec, state, *,
                                with_attestation: bool = True,
                                with_deposit: bool = True,
                                with_proposer_slashing: bool = True,
                                with_attester_slashing: bool = True,
                                with_voluntary_exit: bool = True):
    """(block, expectations) for the advanced `state`.

    Mutually-exclusive victims: the proposer slashing takes validator
    well past the committee window, the attester slashing a committee
    from a past slot, the exit another index — so every op applies in
    one process_operations pass."""
    # age the chain so exits pass the SHARD_COMMITTEE_PERIOD gate
    period_slots = (int(spec.config.SHARD_COMMITTEE_PERIOD) + 1) * \
        int(spec.SLOTS_PER_EPOCH)
    if int(state.slot) < period_slots:
        transition_to(spec, state, uint64(period_slots))

    deposit = None
    if with_deposit:
        deposit = prepare_state_and_deposit(
            spec, state, len(state.validators),
            spec.MAX_EFFECTIVE_BALANCE, signed=True)

    attestations = []
    if with_attestation:
        attestations.append(
            get_valid_attestation(spec, state, signed=True))
    transition_to(spec, state,
                  state.slot + spec.MIN_ATTESTATION_INCLUSION_DELAY)

    block = build_empty_block_for_next_slot(spec, state)
    block.body.attestations = attestations

    expectations = {"slashed": set(), "exited": set()}
    if with_proposer_slashing:
        victim = len(state.validators) - 1
        ps = get_valid_proposer_slashing(spec, state,
                                         proposer_index=victim)
        block.body.proposer_slashings = [ps]
        expectations["slashed"].add(victim)
    if with_attester_slashing:
        aslash = get_valid_attester_slashing(spec, state)
        block.body.attester_slashings = [aslash]
        for idx in aslash.attestation_1.attesting_indices:
            expectations["slashed"].add(int(idx))
    if with_voluntary_exit:
        exit_index = len(state.validators) - 2
        if exit_index not in expectations["slashed"]:
            sve = get_valid_voluntary_exit(spec, state, exit_index,
                                           signed=True)
            block.body.voluntary_exits = [sve]
            expectations["exited"].add(exit_index)
    if deposit is not None:
        block.body.deposits = [deposit]
    return block, expectations
