"""Attestation-building test helpers.

Counterpart of the reference harness's helpers/attestations.py
(get_valid_attestation / sign_attestation / build_attestation_data).
"""
from __future__ import annotations

from ..ssz import hash_tree_root, uint64
from ..utils import bls
from .keys import privkey_for_pubkey
from .blocks import build_empty_block_for_next_slot


def build_attestation_data(spec, state, slot, index,
                           beacon_block_root=None):
    assert state.slot >= slot

    if beacon_block_root is not None:
        pass  # explicit LMD vote (e.g. voting the parent over the head)
    elif slot == state.slot:
        beacon_block_root = build_empty_block_for_next_slot(
            spec, state).parent_root
    else:
        beacon_block_root = spec.get_block_root_at_slot(state, slot)

    current_epoch_start_slot = spec.compute_start_slot_at_epoch(
        spec.get_current_epoch(state))
    if slot < current_epoch_start_slot:
        epoch_boundary_root = spec.get_block_root(
            state, spec.get_previous_epoch(state))
    elif slot == current_epoch_start_slot:
        epoch_boundary_root = beacon_block_root
    else:
        epoch_boundary_root = spec.get_block_root(
            state, spec.get_current_epoch(state))

    if slot < current_epoch_start_slot:
        source = state.previous_justified_checkpoint
    else:
        source = state.current_justified_checkpoint

    return spec.AttestationData(
        slot=uint64(slot),
        index=uint64(index),
        beacon_block_root=beacon_block_root,
        source=source,
        target=spec.Checkpoint(
            epoch=spec.compute_epoch_at_slot(slot),
            root=epoch_boundary_root))


def sign_aggregate_attestation(spec, state, attestation_data,
                               participants) -> bytes:
    signatures = []
    for validator_index in participants:
        privkey = privkey_for_pubkey(
            state.validators[validator_index].pubkey)
        signatures.append(
            spec.get_attestation_signature(state, attestation_data, privkey))
    return bls.Aggregate(signatures)


def sign_attestation(spec, state, attestation) -> None:
    participants = spec.get_attesting_indices(state, attestation)
    attestation.signature = sign_aggregate_attestation(
        spec, state, attestation.data, participants)


def get_valid_attestation(spec, state, slot=None, index=None,
                          filter_participant_set=None, signed=False,
                          beacon_block_root=None):
    # No slot/index implies the current slot's first committee
    if slot is None:
        slot = state.slot
    if index is None:
        index = 0

    if spec.is_post("electra"):
        # EIP-7549: committee index moves to committee_bits; data.index == 0
        attestation_data = build_attestation_data(
            spec, state, slot, 0, beacon_block_root=beacon_block_root)
        committee = spec.get_beacon_committee(
            state, attestation_data.slot, index)
    else:
        attestation_data = build_attestation_data(
            spec, state, slot, index, beacon_block_root=beacon_block_root)
        committee = spec.get_beacon_committee(
            state, attestation_data.slot, attestation_data.index)

    participants = set(committee)
    if filter_participant_set is not None:
        participants = filter_participant_set(participants)

    aggregation_bits = [validator_index in participants
                        for validator_index in committee]
    if spec.is_post("electra"):
        committee_bits = [i == index
                          for i in range(spec.MAX_COMMITTEES_PER_SLOT)]
        attestation = spec.Attestation(
            aggregation_bits=aggregation_bits, data=attestation_data,
            committee_bits=committee_bits)
    else:
        attestation = spec.Attestation(
            aggregation_bits=aggregation_bits, data=attestation_data)
    if signed and participants:
        sign_attestation(spec, state, attestation)
    return attestation


def get_empty_eip7549_aggregation_bits(spec, state, committee_bits, slot):
    """All-zero aggregation bits sized for the committees selected by
    `committee_bits` (reference helpers/attestations.py:436)."""
    participants_count = 0
    for index in spec.get_committee_indices(committee_bits):
        participants_count += len(
            spec.get_beacon_committee(state, slot, index))
    att_type = spec.Attestation
    bits_type = att_type._field_types[
        att_type._field_names.index("aggregation_bits")]
    return bits_type([False] * participants_count)


def get_valid_attestations_at_slot(state, spec, slot_to_attest,
                                   participation_fn=None,
                                   beacon_block_root=None):
    """One signed single-committee attestation per committee of the slot."""
    epoch = spec.compute_epoch_at_slot(slot_to_attest)
    committees_per_slot = spec.get_committee_count_per_slot(state, epoch)
    for index in range(committees_per_slot):
        def participants_filter(comm):
            if participation_fn is None:
                return comm
            return participation_fn(slot_to_attest, index, comm)
        yield get_valid_attestation(
            spec, state, slot_to_attest, index=index,
            filter_participant_set=participants_filter, signed=True,
            beacon_block_root=beacon_block_root)


def get_valid_attestation_at_slot(state, spec, slot_to_attest,
                                  participation_fn=None):
    """Post-electra on-chain aggregate spanning every committee of the
    slot (reference helpers/attestations.py:228)."""
    assert spec.is_post("electra")
    attestations = list(get_valid_attestations_at_slot(
        state, spec, slot_to_attest, participation_fn=participation_fn))
    assert attestations, "no valid attestations found"
    return spec.compute_on_chain_aggregate(attestations)


def compute_max_inclusion_slot(spec, attestation):
    """Latest slot the attestation may be included at (reference
    helpers/attestations.py:152): EIP-7045 (deneb) extends inclusion to
    the end of the epoch after the attestation's."""
    if spec.is_post("deneb"):
        next_epoch = spec.compute_epoch_at_slot(attestation.data.slot) + 1
        return spec.compute_start_slot_at_epoch(uint64(next_epoch + 1)) - 1
    return attestation.data.slot + spec.SLOTS_PER_EPOCH


def add_attestations_to_state(spec, state, attestations, slot) -> None:
    from .blocks import transition_to
    transition_to(spec, state, slot)
    for attestation in attestations:
        spec.process_attestation(state, attestation)


def add_valid_attestations_to_block(spec, state, block, slot_to_attest,
                                    participation_fn=None) -> None:
    """Attach every committee's attestation for `slot_to_attest` to the
    block — one on-chain aggregate post-electra, per-committee otherwise
    (reference helpers/attestations.py::_add_valid_attestations)."""
    if spec.is_post("electra"):
        block.body.attestations.append(get_valid_attestation_at_slot(
            state, spec, slot_to_attest, participation_fn))
    else:
        for attestation in get_valid_attestations_at_slot(
                state, spec, slot_to_attest, participation_fn):
            block.body.attestations.append(attestation)


def state_transition_with_full_block(spec, state, fill_cur_epoch,
                                     fill_prev_epoch,
                                     participation_fn=None,
                                     sync_aggregate=None, block=None):
    """Build + apply ONE block carrying the attestations for the
    current and/or previous epoch's computed attesting slot (reference
    helpers/attestations.py:306).  Returns the signed block."""
    from .blocks import build_empty_block_for_next_slot, \
        state_transition_and_sign_block
    if block is None:
        block = build_empty_block_for_next_slot(spec, state)
    if fill_cur_epoch and \
            state.slot >= spec.MIN_ATTESTATION_INCLUSION_DELAY:
        slot_to_attest = uint64(
            state.slot - spec.MIN_ATTESTATION_INCLUSION_DELAY + 1)
        if slot_to_attest >= spec.compute_start_slot_at_epoch(
                spec.get_current_epoch(state)):
            add_valid_attestations_to_block(
                spec, state, block, slot_to_attest,
                participation_fn=participation_fn)
    if fill_prev_epoch and state.slot >= spec.SLOTS_PER_EPOCH:
        slot_to_attest = uint64(state.slot - spec.SLOTS_PER_EPOCH + 1)
        add_valid_attestations_to_block(
            spec, state, block, slot_to_attest,
            participation_fn=participation_fn)
    if sync_aggregate is not None:
        block.body.sync_aggregate = sync_aggregate
    return state_transition_and_sign_block(spec, state, block)


def next_epoch_with_attestations(spec, state, fill_cur_epoch: bool,
                                 fill_prev_epoch: bool):
    """Advance one epoch, attaching full attestations via empty blocks.

    Returns (attestations_in_blocks, post_state) trajectory pieces like the
    reference helper (helpers/attestations.py:289) — used by finality tests.
    """
    from .blocks import build_empty_block_for_next_slot, \
        state_transition_and_sign_block

    signed_blocks = []
    for _ in range(spec.SLOTS_PER_EPOCH):
        block = build_empty_block_for_next_slot(spec, state)
        if fill_cur_epoch and state.slot >= spec.MIN_ATTESTATION_INCLUSION_DELAY:
            slot_to_attest = uint64(
                state.slot - spec.MIN_ATTESTATION_INCLUSION_DELAY + 1)
            if slot_to_attest >= spec.compute_start_slot_at_epoch(
                    spec.get_current_epoch(state)):
                committees_per_slot = spec.get_committee_count_per_slot(
                    state, spec.compute_epoch_at_slot(slot_to_attest))
                for index in range(committees_per_slot):
                    attestation = get_valid_attestation(
                        spec, state, slot_to_attest, index, signed=True)
                    block.body.attestations.append(attestation)
        if fill_prev_epoch:
            slot_to_attest = uint64(state.slot - spec.SLOTS_PER_EPOCH + 1)
            committees_per_slot = spec.get_committee_count_per_slot(
                state, spec.compute_epoch_at_slot(slot_to_attest))
            for index in range(committees_per_slot):
                attestation = get_valid_attestation(
                    spec, state, slot_to_attest, index, signed=True)
                block.body.attestations.append(attestation)
        signed_blocks.append(
            state_transition_and_sign_block(spec, state, block))
    return signed_blocks, state
