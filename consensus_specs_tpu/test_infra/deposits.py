"""Deposit-building test helpers.

Counterpart of the reference harness's helpers/deposits.py (468 LoC):
build DepositData with a real signature, assemble the incremental deposit
tree, and produce merkle proofs that satisfy process_deposit's
is_valid_merkle_branch check (phase0 beacon-chain.md:1900).
"""
from __future__ import annotations

from ..ssz import hash_tree_root, uint64
from ..ssz.merkle import get_merkle_proof, merkleize_chunks, mix_in_length
from ..utils import bls
from .keys import privkeys, pubkeys


def build_deposit_data(spec, pubkey, privkey, amount,
                       withdrawal_credentials, signed=False):
    data = spec.DepositData(
        pubkey=pubkey,
        withdrawal_credentials=withdrawal_credentials,
        amount=uint64(amount))
    if signed:
        sign_deposit_data(spec, data, privkey)
    return data


def sign_deposit_data(spec, deposit_data, privkey) -> None:
    """Deposits are signed over the genesis-version domain with a zeroed
    validators root (they predate the chain)."""
    deposit_message = spec.DepositMessage(
        pubkey=deposit_data.pubkey,
        withdrawal_credentials=deposit_data.withdrawal_credentials,
        amount=deposit_data.amount)
    domain = spec.compute_domain(spec.DOMAIN_DEPOSIT)
    signing_root = spec.compute_signing_root(deposit_message, domain)
    deposit_data.signature = bls.Sign(privkey, signing_root)


def deposit_tree(spec, deposit_data_list):
    """Leaves (hash_tree_root per DepositData) of the deposit contract
    tree, padded to depth DEPOSIT_CONTRACT_TREE_DEPTH with a mixed-in
    count — returns (root, leaves)."""
    leaves = [bytes(hash_tree_root(d)) for d in deposit_data_list]
    limit = 2 ** spec.DEPOSIT_CONTRACT_TREE_DEPTH
    root = mix_in_length(merkleize_chunks(leaves, limit=limit), len(leaves))
    return root, leaves


def build_deposit(spec, deposit_data_list, pubkey, privkey, amount,
                  withdrawal_credentials, signed):
    """Append a new deposit to `deposit_data_list` and return
    (deposit_with_proof, root, deposit_data_list)."""
    data = build_deposit_data(spec, pubkey, privkey, amount,
                              withdrawal_credentials, signed=signed)
    deposit_data_list.append(data)
    index = len(deposit_data_list) - 1
    root, leaves = deposit_tree(spec, deposit_data_list)
    limit = 2 ** spec.DEPOSIT_CONTRACT_TREE_DEPTH
    proof = get_merkle_proof(leaves, index, limit=limit) + [
        int(len(leaves)).to_bytes(32, "little")]
    deposit = spec.Deposit(proof=proof, data=data)
    return deposit, root, deposit_data_list


def prepare_state_and_deposit(spec, state, validator_index, amount,
                              withdrawal_credentials=None, signed=False):
    """Mutate state's eth1 data to commit to a one-deposit tree and return
    the matching Deposit (reference helpers/deposits.py
    prepare_state_and_deposit)."""
    pubkey = pubkeys[validator_index]
    privkey = privkeys[validator_index]
    if withdrawal_credentials is None:
        withdrawal_credentials = (
            spec.BLS_WITHDRAWAL_PREFIX + bytes(spec.hash(pubkey))[1:])
    deposit, root, _ = build_deposit(
        spec, [], pubkey, privkey, amount, withdrawal_credentials, signed)
    state.eth1_deposit_index = 0
    state.eth1_data.deposit_root = root
    state.eth1_data.deposit_count = 1
    return deposit


def run_deposit_processing(spec, state, deposit, validator_index,
                           valid=True, effective=True):
    """Yield-protocol driver for a deposit operation case.

    Pre-electra, effects land immediately; electra (EIP-6110) queues a
    PendingDeposit and defers balance/registry effects."""
    pre_validator_count = len(state.validators)
    pre_balance = 0
    is_top_up = validator_index < pre_validator_count
    if is_top_up:
        pre_balance = int(state.balances[validator_index])
    pre_pending = (len(state.pending_deposits)
                   if spec.is_post("electra") else 0)

    yield "pre", state.copy()
    yield "deposit", deposit

    if not valid:
        try:
            spec.process_deposit(state, deposit)
        except (AssertionError, ValueError, IndexError):
            yield "post", None
            return
        raise AssertionError("expected invalid deposit")

    spec.process_deposit(state, deposit)
    yield "post", state

    if spec.is_post("electra"):
        # EIP-6110: the balance is queued as a PendingDeposit; a new valid
        # pubkey still lands in the registry immediately (with 0 balance).
        # An invalid-signature NEW deposit queues nothing (effective=False).
        assert len(state.pending_deposits) == \
            pre_pending + (1 if effective else 0)
        if not effective:
            assert len(state.validators) == pre_validator_count
    elif not effective:
        assert len(state.validators) == pre_validator_count
    elif is_top_up:
        assert state.balances[validator_index] == \
            pre_balance + deposit.data.amount
    else:
        assert len(state.validators) == pre_validator_count + 1
