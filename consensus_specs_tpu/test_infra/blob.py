"""Blob test infra (deneb+): sample blobs with real KZG artifacts and
the retrieval monkeypatch driving blob data availability (reference
helpers/blob.py + helpers/fork_choice.py::with_blob_data)."""
from __future__ import annotations

import contextlib
from random import Random


def get_sample_blob(spec, rng=None):
    """A mostly-sparse blob (valid field elements; sparse keeps the
    pure-Python KZG oracle fast while remaining non-trivial)."""
    rng = rng or Random(5566)
    n = int(spec.FIELD_ELEMENTS_PER_BLOB)
    values = [0] * n
    for _ in range(4):
        values[rng.randrange(n)] = rng.randrange(
            int(spec.BLS_MODULUS))
    return b"".join(v.to_bytes(32, "big") for v in values)


def get_sample_blob_tx(spec, blob_count=1, rng=None):
    """(opaque_tx, blobs, commitments, proofs) — the transaction bytes
    are opaque to the consensus layer (noop engine); the KZG artifacts
    are real and verify against the baked trusted setup."""
    rng = rng or Random(5566)
    blobs, commitments, proofs = [], [], []
    for _ in range(blob_count):
        blob = get_sample_blob(spec, rng=rng)
        commitment = spec.blob_to_kzg_commitment(blob)
        proofs.append(spec.compute_blob_kzg_proof(blob, commitment))
        blobs.append(blob)
        commitments.append(spec.KZGCommitment(bytes(commitment)))
    opaque_tx = bytes([0x03]) + bytes(
        rng.getrandbits(8) for _ in range(31))
    return opaque_tx, blobs, commitments, proofs


class BlobData:
    """The sidecar data a node 'retrieved' for a block."""

    def __init__(self, blobs, proofs):
        self.blobs = list(blobs)
        self.proofs = list(proofs)


@contextlib.contextmanager
def blob_data_patch(spec, blob_data: BlobData):
    """Route spec.retrieve_blobs_and_proofs to `blob_data` for the
    duration (spec instances are cached across tests — restore)."""
    # save/restore the INSTANCE slot (the spec object is cached across
    # tests; nesting must unwind to the previous patch, not the class
    # stub)
    sentinel = object()
    saved = spec.__dict__.get("retrieve_blobs_and_proofs", sentinel)
    try:
        # instance attribute shadows the class-level stub
        spec.retrieve_blobs_and_proofs = \
            lambda beacon_block_root: (blob_data.blobs, blob_data.proofs)
        yield
    finally:
        if saved is sentinel:
            spec.__dict__.pop("retrieve_blobs_and_proofs", None)
        else:
            spec.retrieve_blobs_and_proofs = saved
