"""Randomized-scenario helpers (reference: test/helpers/random.py +
test/utils/randomized_block_tests.py capability).

Seeded mutators randomize state fields within spec-legal ranges, and a
seeded block builder assembles blocks with a random mix of operations.
Determinism contract: the same (spec, seed) always produces the same
trajectory, so randomized vectors are replay-exact.
"""
from __future__ import annotations

import random as _random

from ..ssz import uint64
from .attestations import get_valid_attestation
from .blocks import (
    build_empty_block_for_next_slot, next_slot,
    state_transition_and_sign_block, transition_to)
from .slashings import (
    get_valid_attester_slashing, get_valid_proposer_slashing,
    get_valid_voluntary_exit)


def rng_for(spec, seed: int) -> _random.Random:
    return _random.Random(f"{spec.fork}:{spec.preset_name}:{seed}")


def randomize_inactivity_scores(spec, state, rng) -> None:
    state.inactivity_scores = [
        uint64(rng.randrange(0, 50)) for _ in state.validators]


def randomize_balances(spec, state, rng) -> None:
    max_eb = int(spec.MAX_EFFECTIVE_BALANCE)
    state.balances = [
        uint64(rng.randrange(max_eb // 2, max_eb + max_eb // 8))
        for _ in state.validators]


def randomize_participation(spec, state, rng) -> None:
    if spec.is_post("altair"):
        full = (1 << len(spec.PARTICIPATION_FLAG_WEIGHTS)) - 1
        state.previous_epoch_participation = [
            rng.randrange(0, full + 1) for _ in state.validators]
        state.current_epoch_participation = [
            rng.randrange(0, full + 1) for _ in state.validators]


def randomize_state(spec, state, rng) -> None:
    randomize_balances(spec, state, rng)
    randomize_participation(spec, state, rng)
    if spec.is_post("altair"):
        randomize_inactivity_scores(spec, state, rng)


def random_block(spec, state, rng):
    """An empty-to-busy block for the next slot: each op class included
    with some probability, always consistent with the state."""
    block = build_empty_block_for_next_slot(spec, state)
    if rng.random() < 0.6:
        # attestation for a prior slot (satisfies inclusion delay)
        target = int(state.slot) + 1 - int(
            spec.MIN_ATTESTATION_INCLUSION_DELAY)
        if target >= 0:
            attestation = get_valid_attestation(
                spec, state, slot=uint64(max(target, 0)), signed=True)
            block.body.attestations = [attestation]
    if rng.random() < 0.2:
        block.body.proposer_slashings = [
            get_valid_proposer_slashing(spec, state)]
    elif rng.random() < 0.2:
        block.body.attester_slashings = [
            get_valid_attester_slashing(spec, state)]
    return block


def _skip_slashed_proposers(spec, state) -> None:
    """Advance past slots whose proposer is slashed — such slots can
    only ever be empty (process_block_header rejects the proposer), so
    the trajectory leaves them blockless."""
    for _ in range(2 * int(spec.SLOTS_PER_EPOCH)):
        look = state.copy()
        spec.process_slots(look, uint64(int(state.slot) + 1))
        proposer = look.validators[
            spec.get_beacon_proposer_index(look)]
        if not proposer.slashed:
            return
        next_slot(spec, state)
    raise AssertionError("no proposable slot within two epochs")


def apply_random_block(spec, state, rng):
    """Build and apply one random block; if the op mix turns out
    illegal in context, deterministically fall back to an empty
    block."""
    _skip_slashed_proposers(spec, state)
    scratch = state.copy()
    try:
        block = random_block(spec, scratch, rng)
        signed = state_transition_and_sign_block(spec, scratch, block)
    except (AssertionError, ValueError, IndexError):
        block = build_empty_block_for_next_slot(spec, state)
        return state_transition_and_sign_block(spec, state, block)
    # replay the known-good block on the real state
    spec.state_transition(state, signed)
    return signed


def trajectory_blocks(spec, state, seed: int, slots: int):
    """THE trajectory definition: warm past the genesis epoch, scramble
    the state (eagerly, so callers can snapshot the pre-blocks state),
    then return a generator of `slots` random signed blocks (mutating
    `state`).  Both the pytest determinism check and the vector-emitting
    tests drive this one path, so they cannot drift apart."""
    rng = rng_for(spec, seed)
    transition_to(spec, state,
                  uint64(int(spec.SLOTS_PER_EPOCH) * 2))
    randomize_state(spec, state, rng)

    def blocks():
        for _ in range(slots):
            if rng.random() < 0.25:
                next_slot(spec, state)  # empty slot
            yield apply_random_block(spec, state, rng)
    return blocks()


def run_random_trajectory(spec, state, seed: int, slots: int = 8):
    """Apply `slots` random blocks; returns the signed blocks.  All
    blocks are valid by construction (illegal op mixes degrade to empty
    blocks, deterministically per seed)."""
    return list(trajectory_blocks(spec, state, seed, slots))
