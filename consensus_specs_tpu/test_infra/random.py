"""Randomized-scenario helpers (reference: test/helpers/random.py +
test/utils/randomized_block_tests.py capability).

Seeded mutators randomize state fields within spec-legal ranges, and a
seeded block builder assembles blocks with a random mix of operations.
Determinism contract: the same (spec, seed) always produces the same
trajectory, so randomized vectors are replay-exact.
"""
from __future__ import annotations

import itertools as _itertools
import random as _random

from ..ssz import uint64
from .attestations import get_valid_attestation
from .blocks import (
    build_empty_block_for_next_slot, next_slot,
    state_transition_and_sign_block, transition_to)
from .slashings import (
    get_valid_attester_slashing, get_valid_proposer_slashing,
    get_valid_voluntary_exit)


def rng_for(spec, seed: int) -> _random.Random:
    return _random.Random(f"{spec.fork}:{spec.preset_name}:{seed}")


def randomize_inactivity_scores(spec, state, rng) -> None:
    state.inactivity_scores = [
        uint64(rng.randrange(0, 50)) for _ in state.validators]


def randomize_balances(spec, state, rng) -> None:
    max_eb = int(spec.MAX_EFFECTIVE_BALANCE)
    state.balances = [
        uint64(rng.randrange(max_eb // 2, max_eb + max_eb // 8))
        for _ in state.validators]


def randomize_participation(spec, state, rng) -> None:
    if spec.is_post("altair"):
        full = (1 << len(spec.PARTICIPATION_FLAG_WEIGHTS)) - 1
        state.previous_epoch_participation = [
            rng.randrange(0, full + 1) for _ in state.validators]
        state.current_epoch_participation = [
            rng.randrange(0, full + 1) for _ in state.validators]


def randomize_state(spec, state, rng) -> None:
    randomize_balances(spec, state, rng)
    randomize_participation(spec, state, rng)
    if spec.is_post("altair"):
        randomize_inactivity_scores(spec, state, rng)


def random_block(spec, state, rng):
    """An empty-to-busy block for the next slot: each op class included
    with some probability, always consistent with the state."""
    block = build_empty_block_for_next_slot(spec, state)
    if rng.random() < 0.6:
        # attestation for a prior slot (satisfies inclusion delay)
        target = int(state.slot) + 1 - int(
            spec.MIN_ATTESTATION_INCLUSION_DELAY)
        if target >= 0:
            attestation = get_valid_attestation(
                spec, state, slot=uint64(max(target, 0)), signed=True)
            block.body.attestations = [attestation]
    if rng.random() < 0.2:
        block.body.proposer_slashings = [
            get_valid_proposer_slashing(spec, state)]
    elif rng.random() < 0.2:
        block.body.attester_slashings = [
            get_valid_attester_slashing(spec, state)]
    return block


def _skip_slashed_proposers(spec, state) -> None:
    """Advance past slots whose proposer is slashed — such slots can
    only ever be empty (process_block_header rejects the proposer), so
    the trajectory leaves them blockless."""
    for _ in range(2 * int(spec.SLOTS_PER_EPOCH)):
        look = state.copy()
        spec.process_slots(look, uint64(int(state.slot) + 1))
        proposer = look.validators[
            spec.get_beacon_proposer_index(look)]
        if not proposer.slashed:
            return
        next_slot(spec, state)
    raise AssertionError("no proposable slot within two epochs")


def apply_random_block(spec, state, rng, block_fn=None):
    """Build and apply one random block; if the op mix turns out
    illegal in context, deterministically fall back to an empty
    block."""
    if block_fn is None:
        block_fn = random_block
    _skip_slashed_proposers(spec, state)
    scratch = state.copy()
    try:
        block = block_fn(spec, scratch, rng)
        signed = state_transition_and_sign_block(spec, scratch, block)
    except (AssertionError, ValueError, IndexError):
        block = build_empty_block_for_next_slot(spec, state)
        return state_transition_and_sign_block(spec, state, block)
    # replay the known-good block on the real state
    spec.state_transition(state, signed)
    return signed


def trajectory_blocks(spec, state, seed: int, slots: int):
    """THE trajectory definition: warm past the genesis epoch, scramble
    the state (eagerly, so callers can snapshot the pre-blocks state),
    then return a generator of `slots` random signed blocks (mutating
    `state`).  Both the pytest determinism check and the vector-emitting
    tests drive this one path, so they cannot drift apart."""
    rng = rng_for(spec, seed)
    transition_to(spec, state,
                  uint64(int(spec.SLOTS_PER_EPOCH) * 2))
    randomize_state(spec, state, rng)

    def blocks():
        for _ in range(slots):
            if rng.random() < 0.25:
                next_slot(spec, state)  # empty slot
            yield apply_random_block(spec, state, rng)
    return blocks()


def run_random_trajectory(spec, state, seed: int, slots: int = 8):
    """Apply `slots` random blocks; returns the signed blocks.  All
    blocks are valid by construction (illegal op mixes degrade to empty
    blocks, deterministically per seed)."""
    return list(trajectory_blocks(spec, state, seed, slots))


# ── scenario-matrix machinery ─────────────────────────────────────────
# Reference capability: tests/generators/random/generate.py code-gens 16
# scenarios per fork = {no-leak, leak} × 8 shuffled (epoch-skip,
# slot-position) combos, each with two random-block rounds
# (test/utils/randomized_block_tests.py drives them).  Same matrix
# shape here, original engine.

SLOT_MODES = ("epoch_first", "immediate", "mid_epoch", "epoch_last")


def scenario_matrix():
    """16 deterministic scenarios: {no-leak, leak} × 8 paired
    (epochs_to_skip, slot-position) combos.  The pairing across the two
    rounds comes from two fixed-seed shuffles, so every combo appears in
    each round exactly once and the matrix is stable across runs."""
    combos = list(_itertools.product((0, 1), SLOT_MODES))
    rng = _random.Random(20260730)
    round1 = rng.sample(combos, len(combos))
    round2 = rng.sample(combos, len(combos))
    return [
        {"leak": leak,
         "rounds": ({"epochs": round1[i][0], "slot_mode": round1[i][1]},
                    {"epochs": round2[i][0], "slot_mode": round2[i][1]})}
        for leak in (False, True)
        for i in range(len(combos))
    ]


def transition_to_leaking(spec, state) -> None:
    """Advance through empty epochs (no attestations included) until
    the inactivity leak engages (finality delay >
    MIN_EPOCHS_TO_INACTIVITY_PENALTY)."""
    spe = int(spec.SLOTS_PER_EPOCH)
    for _ in range(16):
        if spec.is_in_inactivity_leak(state):
            return
        spec.process_slots(state, uint64(int(state.slot) + spe))
    raise AssertionError("inactivity leak never engaged")


def _skip_to_block_pos(spec, state, mode: str, rng) -> None:
    """Process empty slots so the NEXT block (built for state.slot+1)
    lands at the requested position within an epoch: its first slot,
    its last slot, strictly inside, or wherever we already are."""
    if mode == "immediate":
        return
    spe = int(spec.SLOTS_PER_EPOCH)
    target_pos = {"epoch_first": 0, "epoch_last": spe - 1}.get(mode)
    if target_pos is None:                      # mid_epoch
        target_pos = rng.randrange(1, spe - 1)
    next_pos = (int(state.slot) + 1) % spe
    skip = (target_pos - next_pos) % spe
    if skip:
        spec.process_slots(state, uint64(int(state.slot) + skip))


def _random_address_change(spec, state, rng):
    """A signed BLSToExecutionChange for a validator whose credentials
    are still the BLS (0x00) form derived from the shared test key
    table.  Never mutates state — validity on a scratch copy must
    imply validity on the state the block is replayed onto (a prior
    round may already have rotated some validators' credentials)."""
    from .keys import privkeys, pubkeys
    from ..utils import bls as _bls
    candidates = [
        i for i in range(len(state.validators))
        if bytes(state.validators[i].withdrawal_credentials)
        == bytes(spec.BLS_WITHDRAWAL_PREFIX)
        + bytes(spec.hash(pubkeys[i]))[1:]]
    assert candidates, "no BLS-credentialed validators left"
    index = rng.choice(candidates)
    from_pubkey = pubkeys[index]
    change = spec.BLSToExecutionChange(
        validator_index=uint64(index),
        from_bls_pubkey=from_pubkey,
        to_execution_address=bytes([rng.randrange(256)] * 20))
    domain = spec.compute_domain(
        spec.DOMAIN_BLS_TO_EXECUTION_CHANGE,
        genesis_validators_root=state.genesis_validators_root)
    signature = _bls.Sign(privkeys[index],
                          spec.compute_signing_root(change, domain))
    return spec.SignedBLSToExecutionChange(message=change,
                                           signature=signature)


def random_block_for(spec, state, rng):
    """Fork-aware random block: the phase0 op mix plus, per fork,
    sync aggregates with cycling participation (altair+), BLS→execution
    address changes (capella+), and blob commitments (deneb+)."""
    block = random_block(spec, state, rng)
    if spec.is_post("altair") and rng.random() < 0.7:
        from .sync_committee import get_sync_aggregate
        frac = rng.choice((1.0, 0.5, 0.0))       # cycling participation
        committee_rng = _random.Random(rng.randrange(1 << 30))
        # sign from a lookahead at the block's slot so the message is
        # the block root process_sync_aggregate will verify (the root
        # at block.slot-1 under that slot's domain), matching the
        # op-test call sites that transition before signing
        look = state.copy()
        spec.process_slots(look, uint64(block.slot))
        block.body.sync_aggregate = get_sync_aggregate(
            spec, look,
            participation_fn=lambda _p: committee_rng.random() < frac)
    if spec.is_post("capella") and rng.random() < 0.25:
        block.body.bls_to_execution_changes = [
            _random_address_change(spec, state, rng)]
    if spec.is_post("deneb") and rng.random() < 0.3:
        from .keys import pubkeys
        n = rng.randrange(1, int(spec.max_blobs_per_block()) + 1)
        block.body.blob_kzg_commitments = [
            bytes(pubkeys[rng.randrange(64)]) for _ in range(n)]
    return block


def run_randomized_scenario(spec, state, scenario, seed: int):
    """Drive one matrix scenario end to end and yield the standard
    sanity-blocks vector shape (pre, blocks_<i>, post).  Warm past the
    genesis epoch, scramble the state, optionally engage the leak, then
    run the two (epoch-skip, slot-position, random block) rounds."""
    rng = rng_for(spec, seed)
    transition_to(spec, state, uint64(int(spec.SLOTS_PER_EPOCH) * 2))
    randomize_state(spec, state, rng)
    if scenario["leak"]:
        transition_to_leaking(spec, state)
    yield "pre", state.copy()
    signed = []
    spe = int(spec.SLOTS_PER_EPOCH)
    for rnd in scenario["rounds"]:
        if rnd["epochs"]:
            boundary = (int(state.slot) // spe + rnd["epochs"]) * spe
            spec.process_slots(state, uint64(boundary))
        _skip_to_block_pos(spec, state, rnd["slot_mode"], rng)
        signed.append(apply_random_block(spec, state, rng,
                                         block_fn=random_block_for))
    for i, sb in enumerate(signed):
        yield f"blocks_{i}", sb
    yield "blocks_count", "meta", len(signed)
    yield "post", state
