"""Sync-committee test helpers (altair+).

Counterpart of the reference harness's helpers/sync_committee.py: build
real (or deliberately broken) SyncAggregates for a state by signing the
previous slot's block root with the current committee's keys, matching
process_sync_aggregate's verification path
(reference specs/altair/beacon-chain.md:534-568).
"""
from __future__ import annotations

from ..ssz import uint64
from ..utils import bls
from .keys import privkey_for_pubkey


def compute_sync_committee_signing_root(spec, state, signature_slot=None):
    if signature_slot is None:
        signature_slot = state.slot
    previous_slot = uint64(max(int(signature_slot), 1) - 1)
    domain = spec.get_domain(state, spec.DOMAIN_SYNC_COMMITTEE,
                             spec.compute_epoch_at_slot(previous_slot))
    return spec.compute_signing_root(
        spec.get_block_root_at_slot(state, previous_slot), domain)


def compute_aggregate_sync_committee_signature(spec, state, participants,
                                               signature_slot=None,
                                               privkey_override=None):
    """Aggregate signature of the committee members whose *positions*
    (indices into current_sync_committee.pubkeys) are `participants`."""
    if not participants:
        return spec.G2_POINT_AT_INFINITY
    signing_root = compute_sync_committee_signing_root(
        spec, state, signature_slot)
    signatures = []
    for pos in participants:
        pubkey = state.current_sync_committee.pubkeys[pos]
        privkey = (privkey_override if privkey_override is not None
                   else privkey_for_pubkey(pubkey))
        signatures.append(bls.Sign(privkey, signing_root))
    return bls.Aggregate(signatures)


def get_sync_aggregate(spec, state, participation_fn=None,
                       signature_slot=None):
    """A valid SyncAggregate for `state`.  participation_fn filters the
    committee positions (default: everyone participates)."""
    size = int(spec.SYNC_COMMITTEE_SIZE)
    positions = list(range(size))
    if participation_fn is not None:
        positions = [p for p in positions if participation_fn(p)]
    bits = [False] * size
    for p in positions:
        bits[p] = True
    return spec.SyncAggregate(
        sync_committee_bits=bits,
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, positions, signature_slot))


def run_sync_committee_processing(spec, state, block, valid=True):
    """Dual-mode runner: yields pre/block/post around
    process_sync_aggregate (the operations-runner sync_aggregate
    handler)."""
    yield "pre", state.copy()
    yield "sync_aggregate", block.body.sync_aggregate
    if not valid:
        try:
            spec.process_sync_aggregate(state,
                                        block.body.sync_aggregate)
        except (AssertionError, ValueError, IndexError):
            yield "post", None
            return
        raise AssertionError("sync aggregate unexpectedly valid")
    spec.process_sync_aggregate(state, block.body.sync_aggregate)
    yield "post", state
