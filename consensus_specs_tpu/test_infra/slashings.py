"""Proposer/attester-slashing and voluntary-exit test helpers.

Counterpart of the reference harness's helpers/{proposer_slashings,
attester_slashings,voluntary_exits}.py: build conflicting signed headers,
conflicting attestations, and signed exits for operation tests.
"""
from __future__ import annotations

from ..ssz import hash_tree_root, uint64
from ..utils import bls
from .attestations import get_valid_attestation, sign_attestation
from .keys import privkey_for_pubkey


def sign_block_header(spec, state, header, privkey):
    domain = spec.get_domain(state, spec.DOMAIN_BEACON_PROPOSER,
                             spec.compute_epoch_at_slot(header.slot))
    signing_root = spec.compute_signing_root(header, domain)
    return spec.SignedBeaconBlockHeader(
        message=header, signature=bls.Sign(privkey, signing_root))


def get_valid_proposer_slashing(spec, state, signed_1=True, signed_2=True,
                                proposer_index=None):
    if proposer_index is None:
        proposer_index = spec.get_beacon_proposer_index(state)
    privkey = privkey_for_pubkey(state.validators[proposer_index].pubkey)
    slot = state.slot

    header_1 = spec.BeaconBlockHeader(
        slot=slot, proposer_index=proposer_index,
        parent_root=b"\x33" * 32, state_root=b"\x44" * 32,
        body_root=b"\x55" * 32)
    header_2 = header_1.copy()
    header_2.state_root = b"\x99" * 32

    if signed_1:
        signed_header_1 = sign_block_header(spec, state, header_1, privkey)
    else:
        signed_header_1 = spec.SignedBeaconBlockHeader(message=header_1)
    if signed_2:
        signed_header_2 = sign_block_header(spec, state, header_2, privkey)
    else:
        signed_header_2 = spec.SignedBeaconBlockHeader(message=header_2)
    return spec.ProposerSlashing(signed_header_1=signed_header_1,
                                 signed_header_2=signed_header_2)


def get_valid_attester_slashing(spec, state, slot=None, signed_1=True,
                                signed_2=True):
    """Two attestations with the same data except beacon_block_root — a
    double vote."""
    att_1 = get_valid_attestation(spec, state, slot=slot, signed=signed_1)
    att_2 = att_1.copy()
    att_2.data.beacon_block_root = b"\x01" * 32
    if signed_2:
        sign_attestation(spec, state, att_2)
    return spec.AttesterSlashing(
        attestation_1=spec.get_indexed_attestation(state, att_1),
        attestation_2=spec.get_indexed_attestation(state, att_2))


def sign_voluntary_exit(spec, state, voluntary_exit, privkey):
    if spec.is_post("deneb"):
        # EIP-7044: exits sign over the capella fork domain permanently
        domain = spec.compute_domain(
            spec.DOMAIN_VOLUNTARY_EXIT,
            spec.config.CAPELLA_FORK_VERSION,
            state.genesis_validators_root)
    else:
        domain = spec.get_domain(state, spec.DOMAIN_VOLUNTARY_EXIT,
                                 voluntary_exit.epoch)
    signing_root = spec.compute_signing_root(voluntary_exit, domain)
    return spec.SignedVoluntaryExit(
        message=voluntary_exit, signature=bls.Sign(privkey, signing_root))


def get_valid_attester_slashing_by_indices(spec, state, indices,
                                           signed_1=True, signed_2=True):
    """Double-vote slashing whose indexed attestations cover exactly
    `indices` (reference helpers/attester_slashings.py equivalent):
    builds the data from a live attestation, then replaces the index
    sets and re-signs per set."""
    att = get_valid_attestation(spec, state, signed=False)
    indices = sorted(int(i) for i in indices)
    indexed_1 = spec.IndexedAttestation(
        attesting_indices=indices, data=att.data)
    data_2 = att.data.copy()
    data_2.beacon_block_root = b"\x01" * 32
    indexed_2 = spec.IndexedAttestation(
        attesting_indices=indices, data=data_2)

    def _sign(indexed):
        domain = spec.get_domain(state, spec.DOMAIN_BEACON_ATTESTER,
                                 indexed.data.target.epoch)
        root = spec.compute_signing_root(indexed.data, domain)
        sigs = [bls.Sign(privkey_for_pubkey(
            state.validators[i].pubkey), root)
            for i in indexed.attesting_indices]
        indexed.signature = bls.Aggregate(sigs) if sigs \
            else spec.G2_POINT_AT_INFINITY
    if signed_1:
        _sign(indexed_1)
    if signed_2:
        _sign(indexed_2)
    return spec.AttesterSlashing(attestation_1=indexed_1,
                                 attestation_2=indexed_2)


def get_valid_voluntary_exit(spec, state, validator_index, signed=True):
    voluntary_exit = spec.VoluntaryExit(
        epoch=spec.get_current_epoch(state),
        validator_index=uint64(validator_index))
    if signed:
        privkey = privkey_for_pubkey(
            state.validators[validator_index].pubkey)
        return sign_voluntary_exit(spec, state, voluntary_exit, privkey)
    return spec.SignedVoluntaryExit(message=voluntary_exit)


def sign_indexed_attestation(spec, state, indexed) -> None:
    """(Re)build the aggregate signature over indexed.attesting_indices
    — used after index-set surgery in slashing edge tests."""
    domain = spec.get_domain(state, spec.DOMAIN_BEACON_ATTESTER,
                            indexed.data.target.epoch)
    root = spec.compute_signing_root(indexed.data, domain)
    sigs = [bls.Sign(privkey_for_pubkey(
                state.validators[int(i)].pubkey), root)
            for i in indexed.attesting_indices]
    indexed.signature = bls.Aggregate(sigs) if sigs \
        else spec.G2_POINT_AT_INFINITY


def get_surround_attester_slashing(spec, state):
    """att_1 surrounds att_2: source_1 < source_2 and
    target_1 > target_2 (the second slashable relation)."""
    att_1 = get_valid_attestation(spec, state, signed=False)
    indexed_1 = spec.get_indexed_attestation(state, att_1)
    indexed_2 = indexed_1.copy()
    # craft epochs: source 0 / target T for att_1, source 1 /
    # target T-1 for att_2 (both <= current epoch)
    cur = int(spec.get_current_epoch(state))
    assert cur >= 3, "surround slashing needs >= 3 epochs of history"
    indexed_1.data.source.epoch = uint64(0)
    indexed_1.data.target.epoch = uint64(cur)
    indexed_2.data.source.epoch = uint64(1)
    indexed_2.data.target.epoch = uint64(cur - 1)
    indexed_2.data.beacon_block_root = b"\x01" * 32
    sign_indexed_attestation(spec, state, indexed_1)
    sign_indexed_attestation(spec, state, indexed_2)
    return spec.AttesterSlashing(attestation_1=indexed_1,
                                 attestation_2=indexed_2)
