"""Staging helpers for electra EL-request operation tests (EIP-7002
withdrawal requests, EIP-6110 deposit requests, EIP-7251 consolidation
requests).

Counterpart of the staging done inline by the reference suites
(test/electra/block_processing/test_process_{withdrawal,deposit,
consolidation}_request.py): age validators past the exit gate, scale the
registry so the consolidation churn limit clears MIN_ACTIVATION_BALANCE,
and run a request through the no-fault processors while asserting
whether the state moved.
"""
from __future__ import annotations

from ..ssz import uint64

DEFAULT_ADDRESS = b"\xaa" * 20
WRONG_ADDRESS = b"\xbb" * 20


def age_past_exit_gate(spec, state):
    """Advance the chain past SHARD_COMMITTEE_PERIOD so exits and
    consolidations clear the activation-age gate
    (electra/beacon-chain.md:1511,1654)."""
    state.slot = uint64(
        int(state.slot)
        + int(spec.config.SHARD_COMMITTEE_PERIOD)
        * int(spec.SLOTS_PER_EPOCH))


def scale_churn(spec, state, factor=64):
    """Scale every balance so get_consolidation_churn_limit exceeds
    MIN_ACTIVATION_BALANCE (otherwise every consolidation is a no-op)."""
    state.balances = [uint64(int(b) * factor) for b in state.balances]
    for v in state.validators:
        v.effective_balance = uint64(int(v.effective_balance) * factor)


def run_request_processing(spec, state, kind, request, mutates=True):
    """Yield the operation vector and process; request processing is
    no-fault, so ignored requests assert an untouched state root."""
    pre = state.copy()
    yield "pre", pre
    yield kind, request
    getattr(spec, f"process_{kind}")(state, request)
    if not mutates:
        assert spec.hash_tree_root(state) == spec.hash_tree_root(pre)
    yield "post", state


def make_exited(spec, state, index):
    state.validators[index].exit_epoch = uint64(
        int(spec.get_current_epoch(state)) + 4)


def make_inactive(spec, state, index):
    state.validators[index].activation_epoch = uint64(
        int(spec.get_current_epoch(state)) + 8)


def add_pending_partial_withdrawal(spec, state, index, amount=None):
    if amount is None:
        amount = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    state.pending_partial_withdrawals.append(
        spec.PendingPartialWithdrawal(
            validator_index=index,
            amount=uint64(amount),
            withdrawable_epoch=uint64(
                int(spec.get_current_epoch(state)) + 1)))
