"""Block-building test helpers.

Counterpart of the reference harness's helpers/block.py and state.py:
build/sign empty blocks, advance slots/epochs, full
state_transition_and_sign_block.
"""
from __future__ import annotations

from ..ssz import Bytes32, hash_tree_root, uint64
from ..utils import bls
from .keys import privkey_for_pubkey


def transition_to(spec, state, slot) -> None:
    """Advance state to `slot` (no-op if already there)."""
    assert state.slot <= slot
    if state.slot < slot:
        spec.process_slots(state, uint64(slot))


def next_slot(spec, state) -> None:
    spec.process_slots(state, uint64(state.slot + 1))


def next_epoch(spec, state) -> None:
    slot = uint64(state.slot + spec.SLOTS_PER_EPOCH
                  - state.slot % spec.SLOTS_PER_EPOCH)
    spec.process_slots(state, slot)


def proposer_privkey(spec, state, proposer_index) -> int:
    return privkey_for_pubkey(state.validators[proposer_index].pubkey)


def build_empty_block(spec, state, slot=None):
    """An empty block at `slot` consistent with (an advanced copy of) state."""
    if slot is None:
        slot = state.slot
    if slot < state.slot:
        raise ValueError("cannot build a block for a past slot")
    lookahead = state
    if state.slot < slot:
        lookahead = state.copy()
        spec.process_slots(lookahead, uint64(slot))
    proposer_index = spec.get_beacon_proposer_index(lookahead)
    header = lookahead.latest_block_header.copy()
    if header.state_root == Bytes32():
        header.state_root = hash_tree_root(lookahead)
    block = spec.BeaconBlock(
        slot=uint64(slot),
        proposer_index=proposer_index,
        parent_root=hash_tree_root(header))
    block.body.eth1_data.deposit_count = lookahead.eth1_deposit_index
    # randao reveal for the block's epoch, signed by the proposer
    privkey = proposer_privkey(spec, lookahead, proposer_index)
    block.body.randao_reveal = spec.get_epoch_signature(
        lookahead, block, privkey)
    if spec.is_post("altair"):
        # empty sync aggregate carries the point-at-infinity signature
        block.body.sync_aggregate.sync_committee_signature = \
            spec.G2_POINT_AT_INFINITY
    if spec.is_post("capella") or (
            spec.is_post("bellatrix")
            and spec.is_merge_transition_complete(lookahead)):
        # capella+ processes payloads unconditionally (even pre-merge)
        block.body.execution_payload = build_empty_execution_payload(
            spec, lookahead)
    return block


def build_empty_execution_payload(spec, state):
    """A payload consistent with `state` at its current slot: satisfies the
    spec asserts (parent hash, randao, timestamp, expected withdrawals);
    execution-layer contents are vacuous under the noop engine."""
    latest = state.latest_execution_payload_header
    payload = spec.ExecutionPayload(
        parent_hash=latest.block_hash,
        fee_recipient=b"\x00" * 20,
        state_root=latest.state_root,
        receipts_root=b"\x1d\xcc\x4d\xe8\xde\xc7\x5d\x7a\xab\x85\xb5\x67"
                      b"\xb6\xcc\xd4\x1a\xd3\x12\x45\x1b\x94\x8a\x74\x13"
                      b"\xf0\xa1\x42\xfd\x40\xd4\x93\x47",
        logs_bloom=b"\x00" * spec.BYTES_PER_LOGS_BLOOM,
        prev_randao=spec.get_randao_mix(state,
                                        spec.get_current_epoch(state)),
        block_number=uint64(latest.block_number + 1),
        gas_limit=latest.gas_limit,
        gas_used=0,
        timestamp=spec.compute_timestamp_at_slot(state, state.slot),
        base_fee_per_gas=latest.base_fee_per_gas)
    if spec.is_post("electra"):
        # electra returns (withdrawals, processed_partial_count)
        payload.withdrawals = spec.get_expected_withdrawals(state)[0]
    elif spec.is_post("capella"):
        payload.withdrawals = spec.get_expected_withdrawals(state)
    # a deterministic fake block hash binding the payload contents
    payload.block_hash = spec.hash(
        bytes(spec.hash_tree_root(payload)) + b"FAKE RLP HASH")
    return payload


def build_empty_block_for_next_slot(spec, state):
    return build_empty_block(spec, state, uint64(state.slot + 1))


def sign_block(spec, state, block):
    privkey = proposer_privkey(spec, state, block.proposer_index)
    domain = spec.get_domain(state, spec.DOMAIN_BEACON_PROPOSER,
                             spec.compute_epoch_at_slot(block.slot))
    signing_root = spec.compute_signing_root(block, domain)
    return spec.SignedBeaconBlock(
        message=block, signature=bls.Sign(privkey, signing_root))


def state_transition_and_sign_block(spec, state, block,
                                    expect_fail=False):
    """Fill block.state_root, sign, and apply to `state`; returns the
    signed block (the harness's standard way to extend a chain).

    `expect_fail` mirrors the reference helper (helpers/state.py:94):
    the transition must raise, and the block is still signed over the
    slot-advanced state root so invalid vectors carry a real block."""
    temp = state.copy()
    if temp.slot < block.slot:
        spec.process_slots(temp, block.slot)
    if expect_fail:
        try:
            spec.process_block(temp, block)
        except (AssertionError, ValueError, IndexError):
            pass
        else:
            raise AssertionError("block unexpectedly valid")
        block.state_root = hash_tree_root(temp)
        return sign_block(spec, state, block)
    spec.process_block(temp, block)
    block.state_root = hash_tree_root(temp)
    signed_block = sign_block(spec, state, block)
    spec.state_transition(state, signed_block)
    return signed_block


def apply_empty_block(spec, state, slot=None):
    """Apply an empty block at `slot` (default: the next slot)."""
    if slot is None:
        slot = uint64(state.slot + 1)
    block = build_empty_block(spec, state, slot)
    return state_transition_and_sign_block(spec, state, block)


def transition_to_slot_via_block(spec, state, slot):
    """Advance to `slot` by applying one empty block there (reference
    helpers/state.py:36)."""
    assert state.slot < slot
    apply_empty_block(spec, state, uint64(slot))
    assert state.slot == slot


def next_epoch_via_block(spec, state):
    """Advance to the start of the next epoch via an empty block
    (reference helpers/state.py:71)."""
    return apply_empty_block(
        spec, state,
        uint64(state.slot + spec.SLOTS_PER_EPOCH
               - state.slot % spec.SLOTS_PER_EPOCH))
