"""Block-building test helpers.

Counterpart of the reference harness's helpers/block.py and state.py:
build/sign empty blocks, advance slots/epochs, full
state_transition_and_sign_block.
"""
from __future__ import annotations

from ..ssz import Bytes32, hash_tree_root, uint64
from ..utils import bls
from .keys import privkey_for_pubkey


def transition_to(spec, state, slot) -> None:
    """Advance state to `slot` (no-op if already there)."""
    assert state.slot <= slot
    if state.slot < slot:
        spec.process_slots(state, uint64(slot))


def next_slot(spec, state) -> None:
    spec.process_slots(state, uint64(state.slot + 1))


def next_epoch(spec, state) -> None:
    slot = uint64(state.slot + spec.SLOTS_PER_EPOCH
                  - state.slot % spec.SLOTS_PER_EPOCH)
    spec.process_slots(state, slot)


def proposer_privkey(spec, state, proposer_index) -> int:
    return privkey_for_pubkey(state.validators[proposer_index].pubkey)


def build_empty_block(spec, state, slot=None):
    """An empty block at `slot` consistent with (an advanced copy of) state."""
    if slot is None:
        slot = state.slot
    if slot < state.slot:
        raise ValueError("cannot build a block for a past slot")
    lookahead = state
    if state.slot < slot:
        lookahead = state.copy()
        spec.process_slots(lookahead, uint64(slot))
    proposer_index = spec.get_beacon_proposer_index(lookahead)
    header = lookahead.latest_block_header.copy()
    if header.state_root == Bytes32():
        header.state_root = hash_tree_root(lookahead)
    block = spec.BeaconBlock(
        slot=uint64(slot),
        proposer_index=proposer_index,
        parent_root=hash_tree_root(header))
    block.body.eth1_data.deposit_count = lookahead.eth1_deposit_index
    # randao reveal for the block's epoch, signed by the proposer
    privkey = proposer_privkey(spec, lookahead, proposer_index)
    block.body.randao_reveal = spec.get_epoch_signature(
        lookahead, block, privkey)
    return block


def build_empty_block_for_next_slot(spec, state):
    return build_empty_block(spec, state, uint64(state.slot + 1))


def sign_block(spec, state, block):
    privkey = proposer_privkey(spec, state, block.proposer_index)
    domain = spec.get_domain(state, spec.DOMAIN_BEACON_PROPOSER,
                             spec.compute_epoch_at_slot(block.slot))
    signing_root = spec.compute_signing_root(block, domain)
    return spec.SignedBeaconBlock(
        message=block, signature=bls.Sign(privkey, signing_root))


def state_transition_and_sign_block(spec, state, block):
    """Fill block.state_root, sign, and apply to `state`; returns the
    signed block (the harness's standard way to extend a chain)."""
    temp = state.copy()
    if temp.slot < block.slot:
        spec.process_slots(temp, block.slot)
    spec.process_block(temp, block)
    block.state_root = hash_tree_root(temp)
    signed_block = sign_block(spec, state, block)
    spec.state_transition(state, signed_block)
    return signed_block


def apply_empty_block(spec, state, slot=None):
    """Apply an empty block at `slot` (default: the next slot)."""
    if slot is None:
        slot = uint64(state.slot + 1)
    block = build_empty_block(spec, state, slot)
    return state_transition_and_sign_block(spec, state, block)
