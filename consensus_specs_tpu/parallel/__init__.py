"""Distributed layer: device meshes and XLA-collective reductions.

The TPU-native communication backend (SURVEY.md §2.6): where the
reference's scale-out is process pools over CPU cores, this framework
shards its data-parallel axes — validators, merkle chunks, G1 point sets,
generator cases — over a jax.sharding.Mesh and reduces with lax
collectives (psum / all_gather) riding ICI.  Host-level fan-out across
DCN stays at the generator layer (scripts/gen_vectors.py --shard).

shard_verify.py is the verify hot path's slice of this layer: the
fused pairing product, committee-aggregation sweep, and Fiat–Shamir
weighted MSM partitioned over the mesh behind their resilience seams
(docs/sigpipe.md "Sharded verify").
"""
from .mesh import get_mesh, device_count  # noqa: F401
from .collectives import (  # noqa: F401
    make_balance_total, make_merkle_root, make_g1_sum, shard_array)
