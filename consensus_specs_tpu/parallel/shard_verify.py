"""Multi-chip sharded verify path: mesh-partitioned pairing product,
G1 sweep, and weighted MSM.

The verify hot path is fully batched (O(1) device dispatches per
gossip flush — PRs 1, 5) but each dispatch ran on ONE chip while the
repo's device mesh (parallel/mesh.py, the MULTICHIP_r0* 8-device
history) sat idle.  This module is the layer that spreads those
dispatches over the mesh:

* **job-axis sharding** (`shard_jobs`) — the padded segment/pair axis
  of `ops/g1_sweep.g1_add_sweep` and `ops/msm.g1_weighted_sweep`, and
  the padded message axis of `ops/bls_tpu.hash_to_g2_batch`'s cofactor
  sweep (the last per-flush device call to go multi-chip — async-flush
  PR), is placed with a `NamedSharding(mesh, P(AXIS, ...))`; the
  existing limb kernels then run GSPMD-partitioned, each device
  reducing its own slice with ZERO cross-device traffic (the
  SNIPPETS.md pjit-with-explicit-shardings pattern).  A flush of
  thousands of signature sets scales near-linearly with chip count.
* **pairing-product sharding** (`pairing_product`) — the scheduler's
  fused Fiat–Shamir product partitions its pairs axis over the mesh:
  each shard computes the partial Fp12 Miller product of its slice
  (`pairing_jax.miller_partial_products`), the partials are all-reduced
  by Fp12 multiply (a log2(mesh) halving tree over the sharded axis),
  and ONE final exponentiation decides the whole product
  (`pairing_jax.fq12_product_is_one`).  Fp12 multiplication is exact
  integer math and commutative, so the verdict is bit-identical to the
  single-device product whatever the partition.

Resilience contract: the sharded pairing product is its own seam —
ONE ``resilience.dispatch("ops.pairing_product", ...)`` per flush with
the host pairing oracle as byte-identical fallback — and the sharded
sweeps stay INSIDE the existing ``ops.g1_aggregate`` / ``ops.msm``
dispatches (sharding changes where the device fn runs, never the seam
shape).  "One shard of the mesh died" is just another fault: the
``shard_dead`` kind raises ``resilience.ShardDead`` (a ``DeviceFault``;
the XLA runtime surfaces a dead mesh device as a
failed collective launch), tripping the same breaker → scalar-fallback
→ half-open contract as every other fault, and :func:`poison_shard`
lets the kernel-tier tests model the returns-garbage flavor with real
data (a garbage partial fails the product — it can never validate a
set, because bisection re-derives probes on the host ladder).

Degradation: with one device (`jax.device_count() == 1`, or
``SHARD_VERIFY=0``, or ``configure(max_devices=1)``) every entry point
is byte-identical to the unsharded path — tier-1 CPU runs never change.
The mesh width is the largest power of two ≤ the device count, so a
power-of-two-padded job axis always divides evenly.
"""
from __future__ import annotations

import os as _os
from contextlib import contextmanager

AXIS = "shard"

_MAX_DEVICES: int | None = None     # configure() cap; None = all devices
_MESH = None                        # cached Mesh (one per configuration)
_MESH_WIDTH: int | None = None      # cached mesh_devices() result
_POISONED: int | None = None        # poison_shard() test hook


def configure(max_devices: int | None = None) -> None:
    """Cap the verify mesh at `max_devices` (None: use every device).
    The bench's 1/2/4/8 scan uses this; tests use it to force the
    single-device degrade path in-process."""
    global _MAX_DEVICES
    _MAX_DEVICES = max_devices
    reset()


def reset() -> None:
    """Drop the cached mesh (after device/backend reconfiguration)."""
    global _MESH, _MESH_WIDTH
    _MESH = None
    _MESH_WIDTH = None


def mesh_devices() -> int:
    """Devices the verify mesh would use: the largest power of two ≤
    jax.device_count() (capped by configure()/SHARD_VERIFY env); 1
    means sharding is off."""
    global _MESH_WIDTH
    if _MESH_WIDTH is None:
        if _os.environ.get("SHARD_VERIFY", "") in ("0", "off"):
            _MESH_WIDTH = 1
        else:
            import jax
            n = jax.device_count()
            if _MAX_DEVICES is not None:
                n = min(n, max(_MAX_DEVICES, 1))
            _MESH_WIDTH = 1 << (max(n, 1).bit_length() - 1)
    return _MESH_WIDTH


def enabled() -> bool:
    return mesh_devices() > 1


def get_mesh():
    """The (cached) verify mesh, or None when sharding is off."""
    global _MESH
    if not enabled():
        return None
    if _MESH is None:
        from .mesh import get_mesh as _build
        _MESH = _build(mesh_devices(), axis_name=AXIS)
    return _MESH


# ---------------------------------------------------------------------------
# shard-fault hooks
# ---------------------------------------------------------------------------

@contextmanager
def poison_shard(idx: int):
    """Model 'one mesh device returns garbage' with REAL data: while
    active, the sharded pairing product replaces shard `idx`'s partial
    Fp12 product with a deterministic garbage value before the
    all-reduce.  The product then fails (never falsely passes): the
    fail-safe the kernel-tier suite pins."""
    global _POISONED
    previous = _POISONED
    _POISONED = int(idx)
    try:
        yield
    finally:
        _POISONED = previous


def _apply_poison(partials):
    """Replace the poisoned shard's [12, 32] partial with garbage limbs
    (a fixed pattern, so a poisoned run replays deterministically)."""
    if _POISONED is None:
        return partials
    import jax.numpy as jnp
    idx = _POISONED % partials.shape[0]
    garbage = (jnp.arange(12 * partials.shape[-1], dtype=jnp.uint32)
               .reshape(12, partials.shape[-1])
               * jnp.uint32(2654435761) + jnp.uint32(97))
    return partials.at[idx].set(garbage & jnp.uint32(0xFFFF))


# ---------------------------------------------------------------------------
# job-axis sharding (g1_add_sweep / g1_weighted_sweep)
# ---------------------------------------------------------------------------

def shard_jobs(arrays, label: str):
    """Place each array with its leading (job) axis partitioned over
    the verify mesh; returns the arrays unchanged when sharding is off
    or the axis is smaller than the mesh.  The callers' job axes are
    already power-of-two padded, so a live mesh (power-of-two wide by
    construction) always divides them evenly.  `label` names the owning
    dispatch site in the `sharded_dispatches` metric."""
    mesh = get_mesh()
    n = int(arrays[0].shape[0])
    n_dev = mesh_devices()
    if mesh is None or n < n_dev or n % n_dev:
        return arrays
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..sigpipe.metrics import METRICS
    METRICS.inc_labeled("sharded_dispatches", label)
    out = []
    for a in arrays:
        spec = P(AXIS, *([None] * (a.ndim - 1)))
        out.append(jax.device_put(a, NamedSharding(mesh, spec)))
    return tuple(out)


# ---------------------------------------------------------------------------
# the sharded pairing product (ops.pairing_product seam)
# ---------------------------------------------------------------------------

def pairing_live() -> bool:
    """Whether the scheduler's fused product should ride the sharded
    seam: a >1-device mesh AND the device pairing kernels active (on
    the native backend the product is host math — nothing to shard)."""
    if not enabled():
        return False
    from ..utils import bls
    return bls.current_backend() == "tpu"


def _host_pairing_product(pairs) -> bool:
    """The supervised fallback: the same native pairing oracle
    `bls.pairing_check` falls back to."""
    from ..crypto import bls12_381 as native
    return native.pairing_check(pairs)


def _device_pairing_product(pairs) -> bool:
    """Mesh-partitioned pairing product: pack the pairs axis, shard it
    over the mesh, per-shard partial Miller products, Fp12-multiply
    all-reduce, one final exponentiation."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..ops import fq, fq_tower as ft, pairing_jax as pj
    from ..ops.bls_tpu import _affine_or_skip_g1, _affine_or_skip_g2
    from ..crypto import curve as cv

    mesh = get_mesh()
    if mesh is None:            # mesh vanished (breaker probe after a
        from ..ops import bls_tpu   # reconfigure): single-device kernel
        return bool(bls_tpu.pairing_check_points(pairs))
    from ..sigpipe.metrics import METRICS
    METRICS.inc_labeled("sharded_dispatches", "ops.pairing_product")
    n_dev = mesh_devices()
    k = len(pairs)
    k_local = max(-(-k // n_dev), 1)
    k_local = 1 << (k_local - 1).bit_length() if k_local > 1 else 1
    rows = list(pairs) + [(cv.g1_infinity(), cv.g2_infinity())] \
        * (n_dev * k_local - k)
    x1s, y1s, x2s, y2s, sks = [], [], [], [], []
    for p, q in rows:
        x1, y1, s1 = _affine_or_skip_g1(p)
        x2, y2, s2 = _affine_or_skip_g2(q)
        x1s.append(x1)
        y1s.append(y1)
        x2s.append(x2)
        y2s.append(y2)
        sks.append(s1 or s2)
    xp = np.asarray(fq.pack_mont(x1s)).reshape(n_dev, k_local, fq.LIMBS)
    yp = np.asarray(fq.pack_mont(y1s)).reshape(n_dev, k_local, fq.LIMBS)
    xq = np.asarray(ft.fq2_pack_mont(x2s)).reshape(
        n_dev, k_local, 2, fq.LIMBS)
    yq = np.asarray(ft.fq2_pack_mont(y2s)).reshape(
        n_dev, k_local, 2, fq.LIMBS)
    sk = np.asarray(sks).reshape(n_dev, k_local)

    def put(a):
        spec = P(AXIS, *([None] * (a.ndim - 1)))
        return jax.device_put(jnp.asarray(a), NamedSharding(mesh, spec))

    partials = pj.miller_partial_products(
        put(xp), put(yp), put(xq), put(yq), put(sk))  # [n_dev, 12, 32]
    partials = _apply_poison(partials)
    return bool(np.asarray(pj.fq12_product_is_one(partials)))


def pairing_product(pairs) -> bool:
    """THE sharded fused-product entry point: ONE dispatch per flush at
    the `ops.pairing_product` seam, host pairing oracle as supervised
    byte-identical fallback (sigpipe/scheduler.py routes here instead
    of `bls.pairing_check` when :func:`pairing_live`)."""
    pairs = list(pairs)
    if not pairs:
        return True
    from ..resilience.supervisor import dispatch
    # `sharded_dispatches` is counted inside _device_pairing_product
    # AFTER the mesh check (matching shard_jobs): a breaker-open flush
    # riding the host fallback, or a degraded 1-device mesh, must not
    # read as sharded activity
    return bool(dispatch(
        "ops.pairing_product",
        lambda: _device_pairing_product(pairs),
        lambda: _host_pairing_product(pairs)))


# ---------------------------------------------------------------------------
# the one-launch folded flush (device fn of the ops.pairing_fold seam)
# ---------------------------------------------------------------------------

def pairing_fold(aggs, coeffs, roots, sigs) -> bool:
    """ONE compiled program per mesh shard for an ENTIRE folded flush
    (sigpipe/fold.py `fold_flush`'s device fn): the hash-to-G2 cofactor
    ladder, the Fiat–Shamir G1 weighting ladder, the shard-local G2
    signature MSM and the partial Miller product all run inside one
    fused launch per device (ops/pairing_jax.fold_partial_products;
    staged per-piece kernels on CPU hosts — identical math).  Each
    shard's partial covers its k weighted-aggregate legs PLUS one
    `e(-g1, S_d)` leg over its local MSM partial — sound because the
    final exponentiation restores bilinearity, so the all-reduced
    product equals the folded `e(-g1, sum_d S_d)` check at any width.
    Only the host hash-to-field/SSWU/isogeny prep (cheap int math, the
    same split as `ops/bls_tpu.hash_to_g2_batch`) and the final
    Fp12-is-one verdict read touch the host: ONE np.asarray per flush
    (this function is a registered HOST_SYNC_BARRIERS join)."""
    import numpy as np
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..crypto import curve as cv
    from ..crypto import hash_to_curve as h2c
    from ..ops import curve_jax as cj, pairing_jax as pj
    from ..sigpipe.metrics import METRICS

    n = len(aggs)
    if n == 0:
        return True
    mesh = get_mesh()
    n_dev = mesh_devices() if mesh is not None else 1
    k_local = max(-(-n // n_dev), 1)
    k_local = 1 << (k_local - 1).bit_length() if k_local > 1 else 1
    rows = n_dev * k_local
    pre = []
    for root in roots:
        u0, u1 = h2c.hash_to_field_fq2(bytes(root), 2)
        pre.append(h2c.iso_map(*h2c.sswu_map(u0))
                   + h2c.iso_map(*h2c.sswu_map(u1)))
    pad = rows - n
    aggs = list(aggs) + [cv.g1_infinity()] * pad
    coeffs = [int(c) for c in coeffs] + [0] * pad
    pre = pre + [pre[0]] * pad          # padded rows are skip-masked
    sigs = list(sigs) + [cv.g2_infinity()] * pad

    def shape(a, trailing):
        return a.reshape((n_dev, k_local) + trailing)

    aggP = tuple(shape(c, (32,)) for c in cj.g1_pack(aggs))
    cbits = shape(cj.scalars_to_bits(coeffs, n_bits=64), (64,))
    hP = tuple(shape(c, (2, 32)) for c in cj.g2_pack(pre))
    sP = tuple(shape(c, (2, 32)) for c in cj.g2_pack(sigs))
    if mesh is not None:
        METRICS.inc_labeled("sharded_dispatches", "ops.pairing_fold")

        def put(a):
            spec = P(AXIS, *([None] * (a.ndim - 1)))
            return jax.device_put(a, NamedSharding(mesh, spec))

        aggP = tuple(put(c) for c in aggP)
        cbits = put(cbits)
        hP = tuple(put(c) for c in hP)
        sP = tuple(put(c) for c in sP)
    partials = pj.fold_partial_products(aggP, cbits, hP, sP)
    partials = _apply_poison(partials)
    # leg accounting (N aggregate legs + one local-MSM leg per shard)
    # is observed by the CALLER (fold.fold_flush) after the dispatch
    # returns — observing here would double-count a watchdog-abandoned
    # dispatch alongside its host fallback
    return bool(np.asarray(pj.fq12_product_is_one(partials)))
