"""Mesh construction helpers.

One place decides how devices are laid out; everything else takes a Mesh.
On real hardware the axis rides ICI; under
--xla_force_host_platform_device_count it rides host memory, which is how
the test suite and the driver's dry-run exercise multi-chip code paths
without a pod (SURVEY.md environment notes).
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh


def device_count() -> int:
    return len(jax.devices())


def get_mesh(n_devices: int | None = None,
             axis_name: str = "data") -> Mesh:
    devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    if len(devices) < n_devices:
        raise RuntimeError(
            f"need {n_devices} devices, have {len(devices)} "
            "(set --xla_force_host_platform_device_count)")
    return Mesh(np.array(devices[:n_devices]), axis_names=(axis_name,))
