"""Mesh construction helpers.

One place decides how devices are laid out; everything else takes a Mesh.
On real hardware the axis rides ICI; under
--xla_force_host_platform_device_count it rides host memory, which is how
the test suite and the driver's dry-run exercise multi-chip code paths
without a pod (SURVEY.md environment notes).
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh


def shard_map(f, mesh: Mesh, in_specs, out_specs, check_vma: bool = False):
    """`jax.shard_map` across jax versions: new jax exposes it at the
    top level (with the replication check spelled `check_vma`); jax <
    0.5 ships it as `jax.experimental.shard_map.shard_map` with the
    same flag spelled `check_rep`.  Every shard_map in the repo routes
    through here so the collectives run on both."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def axis_size(axis_name: str) -> int:
    """Static mesh-axis size from inside a shard_map body.  jax < 0.5
    has no `jax.lax.axis_size`; there, `psum(1, axis)` of a static
    value folds to the concrete axis size at trace time (the ring
    permutations below need a Python int, not a tracer)."""
    if hasattr(jax.lax, "axis_size"):
        return int(jax.lax.axis_size(axis_name))
    return int(jax.lax.psum(1, axis_name))


def enable_x64():
    """`jax.enable_x64` across jax versions (jax < 0.5 keeps the
    context manager under jax.experimental)."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64()
    from jax.experimental import enable_x64 as _enable_x64
    return _enable_x64()


def device_count() -> int:
    return len(jax.devices())


def get_mesh(n_devices: int | None = None,
             axis_name: str = "data") -> Mesh:
    devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    if len(devices) < n_devices:
        raise RuntimeError(
            f"need {n_devices} devices, have {len(devices)} "
            "(set --xla_force_host_platform_device_count)")
    return Mesh(np.array(devices[:n_devices]), axis_names=(axis_name,))
