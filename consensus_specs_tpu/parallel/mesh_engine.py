"""Mesh engine mode: multi-chip as a PRODUCTION engine, not a demo.

`enable(mesh)` routes two hot production paths through shard_map
collectives over the device mesh (SURVEY §2.6 "TPU-native equivalent"
column):

- SSZ merkleization: `hash_tree_root` of any large chunk tree (the
  BeaconState validator registry, balances, roots histories) flows
  through `ssz.merkle.set_subtree_hasher` — each device sweeps its
  local subtree, per-device roots all_gather over ICI, the replicated
  top closes the tree.

Epoch processing no longer hooks through here: the fused
`ops.epoch_sweep` program shards its validator axis via
`parallel/shard_verify.shard_jobs` against the SAME verify mesh, so a
live mesh partitions the one-dispatch epoch sweep with no
engine-specific monkey-patching (the old `flag_set_batch` /
`slashings_batch` per-pass hooks are retired into that seam).

Everything stays byte-identical to the host engine; the CPU-mesh suite
(tests/test_mesh_engine.py) and the driver's dryrun_multichip both
assert it.
"""
from __future__ import annotations

import numpy as np
import jax

from .collectives import shard_array
from jax.sharding import Mesh


class MeshEngine:
    """Compiled-callable cache for one mesh; install with .enable()."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self.n_dev = int(np.prod(list(mesh.shape.values())))
        self._merkle_cache: dict = {}
        self._msm_fn = None
        self._prev_kzg_msm = None
        self._threshold = 1 << 14

    # ------------------------------------------------------------------
    # sharded merkleization (ssz.merkle subtree hook)
    # ------------------------------------------------------------------
    def subtree_root(self, level_bytes: bytes, depth: int) -> bytes:
        from ..ops.sha256 import bytes_to_words, words_to_bytes
        from .collectives import make_merkle_root
        n = 1 << depth
        per_dev = n // self.n_dev
        if per_dev < 2 or self.n_dev & (self.n_dev - 1):
            # tree smaller than the mesh, or a non-power-of-two mesh
            # (which cannot divide a power-of-two tree): single-device
            # fallback
            from ..ops.sha256 import merkle_root_jax
            return merkle_root_jax(level_bytes)
        fn = self._merkle_cache.get(per_dev)
        if fn is None:
            fn = make_merkle_root(self.mesh, per_dev)
            self._merkle_cache[per_dev] = fn
        words = bytes_to_words(level_bytes)
        root = fn(shard_array(self.mesh, words))
        return words_to_bytes(np.asarray(jax.device_get(root))[None])

    # ------------------------------------------------------------------
    # sharded MSM (kzg.g1_lincomb device-MSM hook)
    # ------------------------------------------------------------------
    def g1_msm(self, points, scalars):
        """sum_i scalars[i]*points[i] with per-device partials + a ring
        reduction over ICI (collectives.sharded_msm) — the in-path
        engine for deneb's g1_lincomb (polynomial-commitments.md:268)
        when the mesh is enabled.  Pads to a multiple of the mesh with
        infinity*0 lanes; returns an oracle Point."""
        from ..crypto import curve as cv
        from ..ops import curve_jax as cj
        from .collectives import AXIS, make_msm, shard_array
        from jax.sharding import PartitionSpec as P
        n = len(points)
        if n == 0:
            return cv.g1_infinity()
        pad = (-n) % self.n_dev
        pts = list(points) + [cv.g1_infinity()] * pad
        sc = [int(s) for s in scalars] + [0] * pad
        if self._msm_fn is None:
            self._msm_fn = make_msm(self.mesh)
        X, Y, Z = cj.g1_pack(pts)
        bits = cj.scalars_to_bits(sc)
        spec2d = P(AXIS, None)
        rx, ry, rz = self._msm_fn(
            shard_array(self.mesh, np.asarray(X), spec2d),
            shard_array(self.mesh, np.asarray(Y), spec2d),
            shard_array(self.mesh, np.asarray(Z), spec2d),
            shard_array(self.mesh, np.asarray(bits), spec2d))
        return cj.g1_unpack((np.asarray(jax.device_get(rx))[:1],
                             np.asarray(jax.device_get(ry))[:1],
                             np.asarray(jax.device_get(rz))[:1]))[0]

    # ------------------------------------------------------------------
    def enable(self, merkle_threshold: int | None = None,
               msm_threshold: int = 128) -> None:
        from ..crypto import kzg as kzg_mod
        from ..ssz import merkle as ssz_merkle
        if merkle_threshold is not None:
            self._threshold = merkle_threshold
        ssz_merkle.set_subtree_hasher(self.subtree_root, self._threshold)
        # don't snapshot our own hook on re-enable — disable() would
        # then "restore" it and leave the engine live after teardown
        if getattr(kzg_mod._device_msm, "__self__", None) is not self:
            self._prev_kzg_msm = (kzg_mod._device_msm,
                                  kzg_mod._device_msm_threshold)
        kzg_mod.set_device_msm(self.g1_msm, msm_threshold)

    def disable(self) -> None:
        from ..crypto import kzg as kzg_mod
        from ..ssz import merkle as ssz_merkle
        # only uninstall our own hooks — a later-enabled engine owns
        # the globals now and must not be silently reverted.  NB: bound
        # methods are re-created per attribute access, so identity must
        # be checked via __self__, never `is` on the method itself
        installed = getattr(ssz_merkle._subtree_hasher, "__self__", None)
        if installed is self:
            ssz_merkle.set_subtree_hasher(None)
        if getattr(kzg_mod._device_msm, "__self__", None) is self:
            prev_fn, prev_thr = self._prev_kzg_msm or (None, 128)
            kzg_mod.set_device_msm(prev_fn, prev_thr)


def enable(mesh: Mesh, merkle_threshold: int = 1 << 14,
           msm_threshold: int = 128) -> MeshEngine:
    engine = MeshEngine(mesh)
    engine.enable(merkle_threshold, msm_threshold=msm_threshold)
    return engine


def enable_single_device(merkle_threshold: int = 1 << 14,
                         msm_threshold: int = 128) -> MeshEngine:
    """The SAME compiled programs the multi-chip mesh runs, on a
    1-device mesh over the default accelerator: psums collapse to
    no-ops, everything else is identical XLA.  This is the single-chip
    production path for the merkle/MSM hooks; epoch processing no
    longer needs it — the fused ops.epoch_sweep program is device-run
    (and mesh-sharded) by default."""
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    return enable(mesh, merkle_threshold, msm_threshold=msm_threshold)
