"""Mesh engine mode: multi-chip as a PRODUCTION engine, not a demo.

`enable(mesh)` routes two hot production paths through shard_map
collectives over the device mesh (SURVEY §2.6 "TPU-native equivalent"
column):

- SSZ merkleization: `hash_tree_root` of any large chunk tree (the
  BeaconState validator registry, balances, roots histories) flows
  through `ssz.merkle.set_subtree_hasher` — each device sweeps its
  local subtree, per-device roots all_gather over ICI, the replicated
  top closes the tree.
- Epoch processing: `epoch_fast.altair_delta_sets`' per-flag
  reward/penalty passes run as validator-axis shard_map bodies whose
  two global reductions (active and participating increments) are
  psums (collectives.sharded_flag_set — bit-exact to the host pass).

Everything stays byte-identical to the host engine; the CPU-mesh suite
(tests/test_mesh_engine.py) and the driver's dryrun_multichip both
assert it.
"""
from __future__ import annotations

import numpy as np
import jax

from .collectives import make_flag_set, make_slashings, shard_array
from jax.sharding import Mesh


class MeshEngine:
    """Compiled-callable cache for one mesh; install with .enable()."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self.n_dev = int(np.prod(list(mesh.shape.values())))
        self._merkle_cache: dict = {}
        self._flag_cache: dict = {}
        self._slash_cache: dict = {}
        self._msm_fn = None
        self._prev_kzg_msm = None
        self._threshold = 1 << 14

    # ------------------------------------------------------------------
    # sharded merkleization (ssz.merkle subtree hook)
    # ------------------------------------------------------------------
    def subtree_root(self, level_bytes: bytes, depth: int) -> bytes:
        from ..ops.sha256 import bytes_to_words, words_to_bytes
        from .collectives import make_merkle_root
        n = 1 << depth
        per_dev = n // self.n_dev
        if per_dev < 2 or self.n_dev & (self.n_dev - 1):
            # tree smaller than the mesh, or a non-power-of-two mesh
            # (which cannot divide a power-of-two tree): single-device
            # fallback
            from ..ops.sha256 import merkle_root_jax
            return merkle_root_jax(level_bytes)
        fn = self._merkle_cache.get(per_dev)
        if fn is None:
            fn = make_merkle_root(self.mesh, per_dev)
            self._merkle_cache[per_dev] = fn
        words = bytes_to_words(level_bytes)
        root = fn(shard_array(self.mesh, words))
        return words_to_bytes(np.asarray(jax.device_get(root))[None])

    # ------------------------------------------------------------------
    # sharded epoch flag pass (epoch_fast hook)
    # ------------------------------------------------------------------
    def _pad_shard(self, arr):
        n = len(arr)
        pad = (-n) % self.n_dev
        if pad:
            arr = np.concatenate([arr, np.zeros(pad, arr.dtype)])
        return shard_array(self.mesh, arr)

    def flag_set_batch(self, eff_incr, active_cur, eligible, flags,
                       base_per_incr: int, leak: bool):
        """All per-flag altair reward/penalty passes for one epoch:
        the invariant arrays (balances, active, eligible) pad + shard
        ONCE; each flag adds only its participation mask.  `flags` is a
        list of (weight, wd, unsl_mask, head_flag).  Padding lanes (eff
        0, masks False) contribute nothing to the psums."""
        n = len(eff_incr)
        padded = n + (-n) % self.n_dev
        eff_s = self._pad_shard(eff_incr.astype(np.int64))
        act_s = self._pad_shard(active_cur)
        elig_s = self._pad_shard(eligible)
        out = []
        for weight, wd, unsl, head_flag in flags:
            key = (padded, weight, wd, head_flag)
            fn = self._flag_cache.get(key)
            if fn is None:
                fn = make_flag_set(self.mesh, weight, wd, head_flag)
                self._flag_cache[key] = fn
            rewards, penalties = fn(
                eff_s, act_s, elig_s, self._pad_shard(unsl),
                base_per_incr, leak)
            out.append(
                (np.asarray(jax.device_get(rewards))[:n].astype(np.int64),
                 np.asarray(jax.device_get(penalties))[:n]
                 .astype(np.int64)))
        return out

    def slashings_batch(self, eff_incr, mask, adjusted_total: int,
                        total_balance: int, increment: int,
                        electra: bool):
        """The slashing-penalty sweep as a compiled validator-axis
        program (collectives.sharded_slashings — bit-exact to the host
        lane in epoch_fast.slashings_pass)."""
        n = len(eff_incr)
        padded = n + (-n) % self.n_dev
        key = (padded, electra)
        fn = self._slash_cache.get(key)
        if fn is None:
            fn = make_slashings(self.mesh, electra)
            self._slash_cache[key] = fn
        pen = fn(self._pad_shard(eff_incr.astype(np.int64)),
                 self._pad_shard(mask), adjusted_total, total_balance,
                 increment)
        return np.asarray(jax.device_get(pen))[:n].astype(np.int64)

    # ------------------------------------------------------------------
    # sharded MSM (kzg.g1_lincomb device-MSM hook)
    # ------------------------------------------------------------------
    def g1_msm(self, points, scalars):
        """sum_i scalars[i]*points[i] with per-device partials + a ring
        reduction over ICI (collectives.sharded_msm) — the in-path
        engine for deneb's g1_lincomb (polynomial-commitments.md:268)
        when the mesh is enabled.  Pads to a multiple of the mesh with
        infinity*0 lanes; returns an oracle Point."""
        from ..crypto import curve as cv
        from ..ops import curve_jax as cj
        from .collectives import AXIS, make_msm, shard_array
        from jax.sharding import PartitionSpec as P
        n = len(points)
        if n == 0:
            return cv.g1_infinity()
        pad = (-n) % self.n_dev
        pts = list(points) + [cv.g1_infinity()] * pad
        sc = [int(s) for s in scalars] + [0] * pad
        if self._msm_fn is None:
            self._msm_fn = make_msm(self.mesh)
        X, Y, Z = cj.g1_pack(pts)
        bits = cj.scalars_to_bits(sc)
        spec2d = P(AXIS, None)
        rx, ry, rz = self._msm_fn(
            shard_array(self.mesh, np.asarray(X), spec2d),
            shard_array(self.mesh, np.asarray(Y), spec2d),
            shard_array(self.mesh, np.asarray(Z), spec2d),
            shard_array(self.mesh, np.asarray(bits), spec2d))
        return cj.g1_unpack((np.asarray(jax.device_get(rx))[:1],
                             np.asarray(jax.device_get(ry))[:1],
                             np.asarray(jax.device_get(rz))[:1]))[0]

    # ------------------------------------------------------------------
    def enable(self, merkle_threshold: int | None = None,
               msm_threshold: int = 128) -> None:
        from ..crypto import kzg as kzg_mod
        from ..ssz import merkle as ssz_merkle
        from ..specs import epoch_fast
        if merkle_threshold is not None:
            self._threshold = merkle_threshold
        ssz_merkle.set_subtree_hasher(self.subtree_root, self._threshold)
        epoch_fast.MESH_ENGINE = self
        # don't snapshot our own hook on re-enable — disable() would
        # then "restore" it and leave the engine live after teardown
        if getattr(kzg_mod._device_msm, "__self__", None) is not self:
            self._prev_kzg_msm = (kzg_mod._device_msm,
                                  kzg_mod._device_msm_threshold)
        kzg_mod.set_device_msm(self.g1_msm, msm_threshold)

    def disable(self) -> None:
        from ..crypto import kzg as kzg_mod
        from ..ssz import merkle as ssz_merkle
        from ..specs import epoch_fast
        # only uninstall our own hooks — a later-enabled engine owns
        # the globals now and must not be silently reverted.  NB: bound
        # methods are re-created per attribute access, so identity must
        # be checked via __self__, never `is` on the method itself
        installed = getattr(ssz_merkle._subtree_hasher, "__self__", None)
        if installed is self:
            ssz_merkle.set_subtree_hasher(None)
        if epoch_fast.MESH_ENGINE is self:
            epoch_fast.MESH_ENGINE = None
        if getattr(kzg_mod._device_msm, "__self__", None) is self:
            prev_fn, prev_thr = self._prev_kzg_msm or (None, 128)
            kzg_mod.set_device_msm(prev_fn, prev_thr)


def enable(mesh: Mesh, merkle_threshold: int = 1 << 14,
           msm_threshold: int = 128) -> MeshEngine:
    engine = MeshEngine(mesh)
    engine.enable(merkle_threshold, msm_threshold=msm_threshold)
    return engine


def enable_single_device(merkle_threshold: int = 1 << 14,
                         msm_threshold: int = 128) -> MeshEngine:
    """The SAME compiled programs the multi-chip mesh runs, on a
    1-device mesh over the default accelerator: psums collapse to
    no-ops, everything else is identical XLA.  This is the single-chip
    production path — 'TPU-native epoch processing' on one chip, not
    only on the mesh (bench.py's epoch tier enables it)."""
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    return enable(mesh, merkle_threshold, msm_threshold=msm_threshold)
