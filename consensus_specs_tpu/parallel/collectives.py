"""Sharded reductions over the device mesh.

The reduction shapes covering the framework's hot paths (SURVEY.md §2.6):

* sharded_balance_total — the epoch-processing scalar reduction
  (get_total_active_balance and friends): local sum + psum.
* sharded_merkle_root — hash_tree_root over a chunk tree sharded on the
  leaf axis: local subtree sweep, all_gather of subtree roots, replicated
  top sweep (the BeaconState merkleization layout).
* sharded_g1_sum — aggregate-pubkey / MSM-partial reduction: each device
  tree-sums its shard of G1 points, partial sums are all_gathered and the
  small replicated tail is tree-added.  G1 addition is the reduction op
  the ICI ring carries for big-batch BLS aggregation.

All functions are shard_map bodies over a 1-D mesh axis "data"; callers
jit them via `make_*` builders that close over the mesh.
"""
from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import axis_size, enable_x64, shard_map

from ..ops import curve_jax as cj
from ..ops.sha256 import sha256_64byte


AXIS = "data"


# ---------------------------------------------------------------------------
# balances
# ---------------------------------------------------------------------------

def sharded_balance_total(local_balances):
    """Body: sum the local balance shard, psum across the mesh."""
    return jax.lax.psum(jnp.sum(local_balances), AXIS)


def make_balance_total(mesh: Mesh):
    return jax.jit(shard_map(
        sharded_balance_total, mesh=mesh,
        in_specs=P(AXIS), out_specs=P(), check_vma=False))


# ---------------------------------------------------------------------------
# merkle
# ---------------------------------------------------------------------------

def _tree_levels(level, depth: int):
    for _ in range(depth):
        n = level.shape[0] // 2
        level = sha256_64byte(level.reshape(n, 16))
    return level


def sharded_merkle_root(local_chunks, local_depth: int):
    """Body: local subtree root, all_gather, replicated top sweep."""
    local_root = _tree_levels(local_chunks, local_depth)     # [1, 8]
    roots = jax.lax.all_gather(local_root.reshape(8), AXIS)  # [n_dev, 8]
    top_depth = int(np.log2(roots.shape[0]))
    return _tree_levels(roots, top_depth)[0]


def make_merkle_root(mesh: Mesh, chunks_per_device: int):
    local_depth = int(np.log2(chunks_per_device))
    return jax.jit(shard_map(
        partial(sharded_merkle_root, local_depth=local_depth), mesh=mesh,
        in_specs=P(AXIS, None), out_specs=P(), check_vma=False))


# ---------------------------------------------------------------------------
# G1 point-set reduction
# ---------------------------------------------------------------------------

def sharded_g1_sum(X, Y, Z):
    """Body: tree-sum the local shard of Jacobian points, all_gather the
    per-device partials, tree-add the replicated tail."""
    lx, ly, lz = cj.point_sum_tree(cj.F1, (X, Y, Z))
    gx = jax.lax.all_gather(lx, AXIS)        # [n_dev, 32]
    gy = jax.lax.all_gather(ly, AXIS)
    gz = jax.lax.all_gather(lz, AXIS)
    return cj.point_sum_tree(cj.F1, (gx, gy, gz))


def make_g1_sum(mesh: Mesh):
    return jax.jit(shard_map(
        sharded_g1_sum, mesh=mesh,
        in_specs=(P(AXIS, None),) * 3, out_specs=(P(),) * 3,
        check_vma=False))


def sharded_g1_ring_sum(X, Y, Z):
    """Body: RING reduction of per-device partial sums over ICI.

    Each device tree-sums its local shard, then the partials travel the
    ring with lax.ppermute: after n_dev-1 hops every device has added
    every partial, with each hop moving only one point (3x32 limb
    words) over a single neighbor link — the bandwidth shape of a ring
    all-reduce, vs all_gather's n_dev-wide fan-in.  This is the "ring
    all-gather of per-chip partial MSM buckets" pattern of SURVEY §2.6;
    big MSMs shard their buckets exactly like this.
    """
    n_dev = axis_size(AXIS)
    local = cj.point_sum_tree(cj.F1, (X, Y, Z))   # local partial
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def hop(_i, carry):
        acc, incoming = carry
        incoming = tuple(
            jax.lax.ppermute(c, AXIS, perm) for c in incoming)
        return cj.point_add(cj.F1, acc, incoming), incoming

    # fori_loop keeps ONE hop body in the graph (an unrolled ring
    # compiles n_dev-1 point-adds inline — minutes of XLA on small
    # hosts)
    acc, _ = jax.lax.fori_loop(0, n_dev - 1, hop, (local, local))
    # [1, 32] per device -> callers see [n_dev, 32] rows, all equal
    return tuple(c[None] for c in acc)


def make_g1_ring_sum(mesh: Mesh):
    return jax.jit(shard_map(
        sharded_g1_ring_sum, mesh=mesh,
        in_specs=(P(AXIS, None),) * 3,
        out_specs=(P(AXIS, None),) * 3, check_vma=False))


def sharded_msm(X, Y, Z, bits):
    """Body: the PRODUCTION sharded multi-scalar multiplication.

    Each device scalar-multiplies its local (points, scalars) shard
    with the double-and-add lanes, tree-sums the local products into
    one per-chip partial (the bucket-partial of a sharded Pippenger),
    and the partials ring-reduce over ICI exactly like
    sharded_g1_ring_sum.  This is the in-path shape g1_lincomb uses
    when the mesh engine is enabled (deneb
    polynomial-commitments.md:268 over a device mesh)."""
    n_dev = axis_size(AXIS)
    prods = cj.g1_scalar_mul((X, Y, Z), bits)
    local = cj.point_sum_tree(cj.F1, prods)
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def hop(_i, carry):
        acc, incoming = carry
        incoming = tuple(
            jax.lax.ppermute(c, AXIS, perm) for c in incoming)
        return cj.point_add(cj.F1, acc, incoming), incoming

    acc, _ = jax.lax.fori_loop(0, n_dev - 1, hop, (local, local))
    return tuple(c[None] for c in acc)


def make_msm(mesh: Mesh):
    """Compiled sharded MSM: points sharded over the mesh's device
    axis, scalar bit-planes alongside, one replicated-sum row per
    device out."""
    return jax.jit(shard_map(
        sharded_msm, mesh=mesh,
        in_specs=(P(AXIS, None), P(AXIS, None), P(AXIS, None),
                  P(AXIS, None)),
        out_specs=(P(AXIS, None),) * 3, check_vma=False))


# ---------------------------------------------------------------------------
# device placement helper
# ---------------------------------------------------------------------------

def shard_array(mesh: Mesh, arr, spec=None):
    if spec is None:
        spec = P(AXIS) if np.ndim(arr) == 1 else P(AXIS, *([None] * (np.ndim(arr) - 1)))
    return jax.device_put(jnp.asarray(arr), NamedSharding(mesh, spec))


# ---------------------------------------------------------------------------
# epoch deltas (validator-axis data parallelism)
# ---------------------------------------------------------------------------

def sharded_flag_set(local_eff_incr, local_active_cur, local_eligible,
                     local_unsl, base_per_increment, leak,
                     weight: int, weight_denominator: int,
                     head_flag: bool):
    """Standalone altair flag pass (bit-exact to the per-flag lanes
    inside ops.epoch_sweep's fused program): distinct active/eligible/
    unslashed-participating masks, the max(1, .) clamps, the leak and
    head-flag switches.  The two global reductions ride the mesh as
    psums; the reward/penalty lanes stay local.  `base_per_increment`
    and `leak` are traced (they change every epoch — baking them would
    recompile per epoch); weight/denominator/head_flag are per-flag
    constants.  Production epoch processing now runs the single fused
    sweep instead; this pass remains the mesh-collective reference the
    CPU-mesh suite pins against it."""
    eff64 = local_eff_incr.astype(jnp.int64)
    active_incr = jax.lax.psum(
        jnp.sum(jnp.where(local_active_cur, eff64, 0)), AXIS)
    active_incr = jnp.maximum(active_incr, 1)
    part_incr = jax.lax.psum(
        jnp.sum(jnp.where(local_unsl, eff64, 0)), AXIS)
    part_incr = jnp.maximum(part_incr, 1)
    base = eff64 * base_per_increment
    rewards = jnp.where(
        local_eligible & local_unsl & ~leak,
        base * weight * part_incr
        // (active_incr * weight_denominator), 0)
    if head_flag:
        penalties = jnp.zeros_like(base)
    else:
        penalties = jnp.where(
            local_eligible & ~local_unsl,
            base * weight // weight_denominator, 0)
    return rewards, penalties


def make_flag_set(mesh: Mesh, weight: int, weight_denominator: int,
                  head_flag: bool):
    """Compiled flag pass over a validator axis sharded on `mesh`
    (reference collective; production epoch flags ride the fused
    ops.epoch_sweep dispatch)."""
    jfn = jax.jit(shard_map(
        partial(sharded_flag_set, weight=weight,
                weight_denominator=weight_denominator,
                head_flag=head_flag),
        mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(), P()),
        out_specs=(P(AXIS), P(AXIS)), check_vma=False))

    def call(eff_incr, active_cur, eligible, unsl, base_per_incr, leak):
        with enable_x64():
            return jfn(eff_incr, active_cur, eligible, unsl,
                       jnp.int64(base_per_incr), jnp.bool_(leak))
    return call


def make_flag_deltas(mesh: Mesh, weight: int, weight_denominator: int,
                     base_per_increment: int):
    """Demo-shaped wrapper over the production pass: eligible == active,
    unslashed-participating == part & active, no leak, penalties on."""
    inner = make_flag_set(mesh, weight, weight_denominator,
                          head_flag=False)

    def call(eff_incr, active, part):
        return inner(eff_incr, active, active, part & active,
                     base_per_increment, False)
    return call


def sharded_slashings(local_eff_incr, local_mask, adjusted_total,
                      total_balance, increment, electra: bool):
    """Standalone slashing-penalty sweep (bit-exact to the slashings
    lane inside ops.epoch_sweep): the correlation penalty for every
    validator whose withdrawable epoch sits at the slashing-window
    midpoint.  Penalty lanes are local; the inputs that need global
    agreement (adjusted total, total balance) are traced scalars the
    caller derives once — electra factors the increment out before the
    multiply, pre-electra divides afterwards."""
    eff64 = local_eff_incr.astype(jnp.int64)
    if electra:
        per_incr = adjusted_total // (total_balance // increment)
        pen = eff64 * per_incr
    else:
        pen = eff64 * adjusted_total // total_balance * increment
    return jnp.where(local_mask, pen, 0)


def make_slashings(mesh: Mesh, electra: bool):
    """Compiled slashing sweep over a validator axis sharded on `mesh`
    (reference collective; production slashings ride the fused
    ops.epoch_sweep dispatch)."""
    jfn = jax.jit(shard_map(
        partial(sharded_slashings, electra=electra),
        mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(), P(), P()),
        out_specs=P(AXIS), check_vma=False))

    def call(eff_incr, mask, adjusted_total, total_balance, increment):
        with enable_x64():
            return jfn(eff_incr, mask, jnp.int64(adjusted_total),
                       jnp.int64(total_balance), jnp.int64(increment))
    return call
